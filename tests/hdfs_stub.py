"""In-process WebHDFS stub — a namenode+datanode pair in one HTTP
server with a REAL filesystem tree and the protocol's two-step
redirect: CREATE/OPEN/APPEND against the namenode role answer 307 to a
datanode URL (same server, ``datanode=true`` marker); only the
datanode role accepts/serves bytes, so a client that skips the
redirect dance fails.  RemoteException error bodies match the real
wire shape.
"""

from __future__ import annotations

import http.server
import json
import threading
from urllib.parse import parse_qs, unquote, urlsplit


class _Node:
    def __init__(self, is_dir: bool, data: bytes = b""):
        self.is_dir = is_dir
        self.data = data
        self.mtime = 1722400000000        # ms, fixed-ish for tests
        self.children: dict[str, _Node] = {} if is_dir else None


class HDFSStubServer:
    def __init__(self):
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, status, doc=None, raw: bytes | None = None,
                       location: str | None = None):
                body = raw if raw is not None else (
                    json.dumps(doc).encode() if doc is not None else b"")
                self.send_response(status)
                if location:
                    self.send_header("Location", location)
                if doc is not None:
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _exc(self, status, exception, message):
                self._reply(status, {"RemoteException": {
                    "exception": exception,
                    "javaClassName": f"org.apache.hadoop.{exception}",
                    "message": message}})

            def _route(self):
                u = urlsplit(self.path)
                if not u.path.startswith("/webhdfs/v1"):
                    return self._exc(404, "FileNotFoundException",
                                     u.path)
                path = unquote(u.path[len("/webhdfs/v1"):]) or "/"
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                op = q.get("op", "").upper()
                if "user.name" not in q:
                    return self._exc(401, "SecurityException",
                                     "authentication required")
                ln = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(ln) if ln else b""
                is_dn = q.get("datanode") == "true"
                try:
                    return stub._op(self, op, path, q, body, is_dn)
                except KeyError:
                    return self._exc(404, "FileNotFoundException",
                                     f"File does not exist: {path}")

            do_GET = do_PUT = do_POST = do_DELETE = _route

        self._http = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.port = self._http.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self.root = _Node(True)
        self.redirects = 0            # proves the two-step dance ran
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True)

    # -- tree helpers -----------------------------------------------------

    def _resolve(self, path: str) -> _Node:
        node = self.root
        for part in [p for p in path.split("/") if p]:
            if not node.is_dir:
                raise KeyError(path)
            node = node.children[part]
        return node

    def _parent(self, path: str, create: bool = False):
        parts = [p for p in path.split("/") if p]
        node = self.root
        for part in parts[:-1]:
            if part not in node.children:
                if not create:
                    raise KeyError(path)
                node.children[part] = _Node(True)
            node = node.children[part]
            if not node.is_dir:
                raise KeyError(path)
        return node, (parts[-1] if parts else "")

    @staticmethod
    def _status_doc(name: str, node: _Node) -> dict:
        return {"pathSuffix": name,
                "type": "DIRECTORY" if node.is_dir else "FILE",
                "length": 0 if node.is_dir else len(node.data),
                "modificationTime": node.mtime,
                "replication": 1, "blockSize": 134217728,
                "owner": "minio-tpu", "group": "supergroup",
                "permission": "755"}

    # -- op dispatch ------------------------------------------------------

    def _op(self, h, op, path, q, body, is_dn):
        if op == "MKDIRS":
            parent, leaf = self._parent(path, create=True)
            if leaf:
                parent.children.setdefault(leaf, _Node(True))
            return h._reply(200, {"boolean": True})
        if op == "GETFILESTATUS":
            node = self._resolve(path)
            return h._reply(200, {"FileStatus":
                                  self._status_doc("", node)})
        if op == "LISTSTATUS":
            node = self._resolve(path)
            if not node.is_dir:
                docs = [self._status_doc("", node)]
            else:
                docs = [self._status_doc(n, c)
                        for n, c in sorted(node.children.items())]
            return h._reply(200, {"FileStatuses": {"FileStatus": docs}})
        if op == "DELETE":
            parent, leaf = self._parent(path)
            node = parent.children.get(leaf)
            if node is None:
                return h._reply(200, {"boolean": False})
            if node.is_dir and node.children and \
                    q.get("recursive") != "true":
                return self._exc_of(h, 403, "PathIsNotEmptyDirectory",
                                    path)
            del parent.children[leaf]
            return h._reply(200, {"boolean": True})
        if op == "RENAME":
            parent, leaf = self._parent(path)
            node = parent.children.pop(leaf)
            dparent, dleaf = self._parent(q["destination"], create=True)
            dparent.children[dleaf] = node
            return h._reply(200, {"boolean": True})
        if op in ("CREATE", "APPEND", "OPEN"):
            if not is_dn:
                # namenode role: redirect to the "datanode" (us).
                # Real namenodes never read a write body in step 1 —
                # reject one outright so a client that ships bytes
                # early (doubling every upload) fails conformance.
                if body:
                    return self._exc_of(
                        h, 400, "IllegalArgumentException",
                        "data sent to namenode; expected empty "
                        "request before redirect")
                self.redirects += 1
                sep = "&" if h.path.find("?") >= 0 else "?"
                return h._reply(307, location=self.endpoint + h.path
                                + sep + "datanode=true")
            if op == "CREATE":
                parent, leaf = self._parent(path, create=True)
                if leaf in parent.children and \
                        q.get("overwrite") != "true":
                    return self._exc_of(
                        h, 403, "FileAlreadyExistsException", path)
                parent.children[leaf] = _Node(False, body)
                return h._reply(201)
            if op == "APPEND":
                node = self._resolve(path)
                if node.is_dir:
                    raise KeyError(path)
                node.data += body
                return h._reply(200)
            node = self._resolve(path)
            if node.is_dir:
                raise KeyError(path)
            off = int(q.get("offset", 0) or 0)
            ln = q.get("length")
            data = node.data[off:off + int(ln)] if ln else \
                node.data[off:]
            return h._reply(200, raw=data)
        return self._exc_of(h, 400, "IllegalArgumentException",
                            f"unknown op {op}")

    @staticmethod
    def _exc_of(h, status, exception, message):
        return h._reply(status, {"RemoteException": {
            "exception": exception, "message": str(message)}})

    def start(self) -> "HDFSStubServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
