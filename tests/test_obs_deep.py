"""Deep tracing plane (obs smoke tier): subsystem spans, cluster-wide
request correlation, last-minute latency stats, slow-drive detection,
TPU-kernel metrics, and the idle-overhead contract.

Reference tier: `mc admin trace -a` (cmd/admin-handlers.go TraceHandler
type filters + peerRESTMethodTrace), cmd/last-minute.go, and the Dapper
span-with-propagated-context model (request IDs crossing the internode
boundary in an X-Request-ID header).
"""

import json
import re
import threading
import time

import pytest

from minio_tpu.obs import lastminute, trace
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.s3.client import S3Client
from minio_tpu.s3.server import S3Server
from minio_tpu.storage import health
from minio_tpu.storage.xl_storage import XLStorage


# -- idle-overhead contract -------------------------------------------------

def test_idle_storage_ops_build_no_spans(tmp_path, monkeypatch):
    """With zero trace subscribers and an idle ring, the storage hot
    path's tracing overhead is a single predicate — no span dict is
    constructed, nothing is published."""
    assert not trace.active(), "leaked subscriber/ring from another test"
    calls = {"make": 0, "publish": 0}
    real_make = trace.make_span
    monkeypatch.setattr(
        trace, "make_span",
        lambda *a, **k: (calls.__setitem__("make", calls["make"] + 1),
                         real_make(*a, **k))[1])
    monkeypatch.setattr(
        trace, "publish_span",
        lambda s: calls.__setitem__("publish", calls["publish"] + 1))
    d = tmp_path / "d0"
    d.mkdir()
    x = XLStorage(str(d))
    x.make_vol("vol")
    for i in range(50):
        x.write_all("vol", f"o{i}", b"payload")
        assert x.read_all("vol", f"o{i}") == b"payload"
    assert calls == {"make": 0, "publish": 0}
    # the always-on last-minute window still accumulated
    totals = x.latency.totals()
    assert totals["read_all"][0] == 50
    assert totals["write_all"][0] == 50
    assert totals["write_all"][2] == 50 * len(b"payload")
    # with a subscriber the same ops DO publish
    with trace.HTTP_TRACE.subscribe():
        x.read_all("vol", "o0")
    assert calls["publish"] >= 1


def test_nested_storage_ops_record_once(tmp_path):
    """Traced ops that call other traced ops internally (write_metadata
    -> write_all, read_version -> read_all) record ONE op per logical
    call — the outermost — so drive latency is never double-counted."""
    from minio_tpu.storage.datatypes import FileInfo
    d = tmp_path / "d0"
    d.mkdir()
    x = XLStorage(str(d))
    x.make_vol("vol")
    fi = FileInfo(volume="vol", name="obj", version_id="",
                  mod_time=123, size=0)
    x.write_metadata("vol", "obj", fi)
    x.read_version("vol", "obj")
    totals = x.latency.totals()
    assert totals["write_metadata"][0] == 1
    assert totals["read_version"][0] == 1
    # the nested write_all/read_all must not have been recorded
    assert "write_all" not in totals
    assert "read_all" not in totals


# -- last-minute windows ----------------------------------------------------

def test_window_slides_and_reports():
    w = lastminute.Window()
    w.record(1000, 10, now_s=100)
    w.record(3000, 20, now_s=130)
    assert w.total(now_s=130) == (2, 4000, 30)
    # 61s later the first sample aged out
    assert w.total(now_s=161) == (1, 3000, 20)
    # a slot is reclaimed when its second comes around again
    w.record(7000, 5, now_s=160)      # same slot index as 100
    assert w.total(now_s=161) == (2, 10000, 25)
    # p50 only reflects live samples
    assert w.p50(now_s=161) == 7000
    assert w.p50(now_s=300) == 0      # idle window reads 0


def test_opwindows_p50_and_top():
    ow = lastminute.OpWindows("drv")
    for _ in range(10):
        ow.record("read", 1_000_000, 100, now_s=50)
    for _ in range(3):
        ow.record("write", 9_000_000, 10, now_s=50)
    assert ow.p50_all(now_s=50) == 1_000_000
    rows = lastminute.top_entries(ow, now_s=50)
    assert rows[0]["name"] == "read" and rows[0]["count"] == 10
    assert rows[1]["name"] == "write" and rows[1]["avg_ns"] == 9_000_000


def test_slow_drive_flagged_not_ejected():
    class FakeDisk:
        def __init__(self, label, p50_ns, samples=20):
            self.latency = lastminute.OpWindows(label)
            for _ in range(samples):
                self.latency.record("read", p50_ns, 0)

    disks = [FakeDisk("d0", 1_000_000), FakeDisk("d1", 1_100_000),
             FakeDisk("d2", 900_000), FakeDisk("d3", 50_000_000)]
    out = health.slow_drives(disks, multiple=4.0, min_samples=10)
    assert out["d3"]["slow"] is True
    assert not any(out[d]["slow"] for d in ("d0", "d1", "d2"))
    # below min_samples the outlier is not flagged (too little signal)
    thin = [FakeDisk("t0", 1_000_000, samples=20),
            FakeDisk("t1", 1_000_000, samples=20),
            FakeDisk("t2", 50_000_000, samples=3)]
    out = health.slow_drives(thin, multiple=4.0, min_samples=10)
    assert out["t2"]["slow"] is False
    # leave-one-out median: in a 2-drive set the outlier must not drag
    # the comparison median up to its own p50 and escape detection
    pair = [FakeDisk("p0", 1_000_000), FakeDisk("p1", 100_000_000)]
    out = health.slow_drives(pair, multiple=4.0, min_samples=10)
    assert out["p1"]["slow"] is True
    assert out["p0"]["slow"] is False
    # knobs resolve from the kvconfig `drive` subsystem (env override)
    mult, min_s = health.slow_drive_knobs()
    assert mult == 4.0 and min_s == 10


def test_slow_drives_grouped_per_set(tmp_path):
    """Detection compares a drive against its SET peers: a slow pool
    must not mask a relatively-failing drive in a fast pool."""
    class FakeDisk:
        def __init__(self, label, p50_ns):
            self.latency = lastminute.OpWindows(label)
            for _ in range(20):
                self.latency.record("read", p50_ns, 0)

        def is_online(self):
            return True

    class FakeSet:
        def __init__(self, disks):
            self.disks = disks

    class FakeLayer:
        def __init__(self, sets):
            self.sets = sets

    hdd = [FakeDisk(f"hdd{i}", 10_000_000) for i in range(4)]
    nvme = [FakeDisk(f"nvme{i}", 100_000) for i in range(3)]
    nvme.append(FakeDisk("nvme3", 5_000_000))   # 50x its set median
    layer = FakeLayer([FakeSet(hdd), FakeSet(nvme)])
    out = health.slow_drives_for_layer(layer, multiple=4.0,
                                       min_samples=10)
    assert out["nvme3"]["slow"] is True, \
        "fast-pool outlier masked by the slow pool"
    assert not any(out[f"hdd{i}"]["slow"] for i in range(4))


# -- served spans + correlation (single node) -------------------------------

@pytest.fixture
def served(tmp_path):
    disks = []
    for i in range(4):
        d = tmp_path / f"d{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=64 * 1024,
                           backend="numpy")
    srv = S3Server(layer, access_key="ok", secret_key="os")
    srv.start()
    yield srv
    srv.stop()


def test_request_id_correlates_http_and_storage_spans(served):
    c = S3Client(served.endpoint, "ok", "os")
    with served.trace_hub.subscribe() as sub:
        c.make_bucket("corrbkt")
        c.put_object("corrbkt", "obj", b"z" * 20000)
        spans = list(sub.drain(400, timeout=2.0))
    https = [s for s in spans if s.get("type") == "http"
             and s["funcName"] == "PutObject"]
    assert https
    rid = https[0]["requestID"]
    assert rid
    # every layer the PUT crossed shares the frontend's request ID —
    # including drive writes running in fan-out pool threads
    storage = [s for s in spans if s.get("type") == "storage"
               and s.get("requestID") == rid]
    assert storage, "no storage span carries the request ID"
    assert any(s["storage"]["volume"] == "corrbkt" for s in storage)
    tpu = [s for s in spans if s.get("type") == "tpu"
           and s.get("requestID") == rid]
    assert tpu, "no tpu (erasure-kernel) span carries the request ID"
    enc = tpu[0]
    assert enc["tpu"]["k"] + enc["tpu"]["m"] == 4
    assert enc["callStats"]["inputBytes"] >= 20000


def test_admin_trace_type_filter(served):
    c = S3Client(served.endpoint, "ok", "os")
    c.make_bucket("filtbkt")
    got = {}

    def consume(name, qs):
        r = c.request("GET", "/minio-tpu/admin/v1/trace", qs)
        got[name] = [json.loads(x)
                     for x in r.body.decode().splitlines() if x]

    threads = [
        threading.Thread(target=consume,
                         args=("http", "timeout=3&max-items=2")),
        threading.Thread(target=consume, args=(
            "deep", "timeout=3&max-items=5&type=storage,internode,tpu")),
    ]
    for t in threads:
        t.start()
    for _ in range(100):
        if served.trace_hub.num_subscribers >= 2:
            break
        time.sleep(0.02)
    c.put_object("filtbkt", "o1", b"traced" * 1000)
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    # default stream: http only (pre-deep-tracing contract unchanged)
    assert got["http"]
    assert all(i.get("type", "http") == "http" for i in got["http"])
    # typed stream: subsystem spans only, no http records
    assert got["deep"]
    kinds = {i["type"] for i in got["deep"]}
    assert kinds <= {"storage", "internode", "tpu"}
    assert "storage" in kinds


def test_http_only_stream_builds_no_deep_spans(served, monkeypatch):
    """The default (http-only) admin trace stream must not activate
    subsystem-span construction: it registers an opt-out, so the
    deep-span predicate stays False while it runs — pre-PR consumers
    keep pre-PR costs, not just pre-PR record shapes."""
    calls = {"span": 0}
    real = trace.make_span
    monkeypatch.setattr(
        trace, "make_span",
        lambda *a, **k: (calls.__setitem__("span", calls["span"] + 1),
                         real(*a, **k))[1])
    c = S3Client(served.endpoint, "ok", "os")
    c.make_bucket("hobkt")
    got = {}

    def consume():
        r = c.request("GET", "/minio-tpu/admin/v1/trace",
                      "timeout=3&max-items=1")
        got["lines"] = [json.loads(x)
                        for x in r.body.decode().splitlines() if x]

    t = threading.Thread(target=consume)
    t.start()
    for _ in range(100):
        if served.trace_hub.num_subscribers > 0:
            break
        time.sleep(0.02)
    assert not trace.active(), \
        "an http-only consumer must not arm deep spans"
    c.put_object("hobkt", "o1", b"h" * 4096)
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["lines"] and got["lines"][0]["type"] == "http"
    assert calls["span"] == 0, "subsystem span built for http-only"


def test_broken_subscriber_filter_never_fails_publish(tmp_path):
    """publish() now runs inside storage data-path finallys: a raising
    subscriber filter must be dropped, never propagate to the drive op."""
    def bad_filter(item):
        raise RuntimeError("broken consumer")

    with trace.HTTP_TRACE.subscribe(bad_filter), \
            trace.HTTP_TRACE.subscribe() as good:
        d = tmp_path / "d0"
        d.mkdir()
        x = XLStorage(str(d))
        x.make_vol("vol")
        x.write_all("vol", "obj", b"ok")        # must not raise
        assert x.read_all("vol", "obj") == b"ok"
        spans = list(good.drain(10, timeout=1.0))
    assert any(s["funcName"] == "storage.write_all" for s in spans)


def test_unknown_trace_type_is_rejected(served):
    from minio_tpu.s3.client import S3ClientError
    import urllib.error
    c = S3Client(served.endpoint, "ok", "os")
    with pytest.raises((S3ClientError, urllib.error.HTTPError)):
        c.request("GET", "/minio-tpu/admin/v1/trace",
                  "timeout=1&type=storge")


def test_top_endpoint_reports_apis_and_drives(served):
    c = S3Client(served.endpoint, "ok", "os")
    c.make_bucket("topbkt")
    for i in range(4):
        c.put_object("topbkt", f"o{i}", b"t" * 2048)
        c.get_object("topbkt", f"o{i}")
    # the handler records its API window after the response is flushed
    doc = {}
    for _ in range(50):
        r = c.request("GET", "/minio-tpu/admin/v1/top", "")
        doc = json.loads(r.body)
        if any(a["name"] == "PutObject" for a in doc["apis"]):
            break
        time.sleep(0.05)
    apis = {a["name"]: a for a in doc["apis"]}
    assert apis["PutObject"]["count"] >= 4
    assert apis["PutObject"]["avg_ns"] > 0
    assert doc["drives"], "drive latency rows missing"
    d0 = doc["drives"][0]
    assert d0["count"] > 0 and d0["p50_ns"] >= 0
    assert "slow" in d0 and "ops" in d0
    assert doc["knobs"]["slow_latency_multiple"] == 4.0


def test_scrape_has_lastminute_and_tpu_families(served):
    c = S3Client(served.endpoint, "ok", "os")
    c.make_bucket("scrbkt")
    # above the inline threshold: shard files land via write_data_commit
    c.put_object("scrbkt", "obj", b"s" * (1 << 20))
    c.get_object("scrbkt", "obj")
    import http.client
    host, port = served.endpoint.replace("http://", "").split(":")
    text = ""
    for _ in range(40):
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.request("GET", "/minio-tpu/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        if 'mt_s3_api_last_minute_requests{api="PutObject"}' in text:
            break
        time.sleep(0.05)
    m = re.search(r'mt_node_disk_latency_ops\{[^}]*op="'
                  r'write_data_commit"\} (\d+)', text)
    assert m and int(m.group(1)) > 0
    assert re.search(r"mt_tpu_ops_total\{[^}]*\} [1-9]", text)
    assert re.search(r"mt_tpu_bytes_total\{[^}]*\} [1-9]", text)
    assert re.search(r'mt_s3_api_last_minute_requests\{api="PutObject"\}'
                     r" [1-9]", text)
    assert "mt_node_disk_slow{" in text
    assert "mt_node_disk_latency_p50_ns{" in text


# -- cluster-wide correlation (2 nodes over real internode RPC) -------------

def test_peer_spans_carry_frontend_request_id(tmp_path):
    """A PUT served by node0 fans shard writes to node1 over RPC; the
    spans node1 emits (internode server side + its local drive ops)
    must carry node0's frontend request ID, forwarded in the
    X-Request-ID header — contextvars do not cross processes/threads,
    so only the wire can have carried it."""
    from minio_tpu.cluster import NodeSpec, start_cluster
    specs = []
    for n in range(2):
        dirs = []
        for d in range(2):
            p = tmp_path / f"node{n}-drive{d}"
            p.mkdir()
            dirs.append(str(p))
        specs.append(NodeSpec(f"node{n}", dirs))
    nodes = start_cluster(specs, "obs-secret", set_drive_count=4,
                          parity=1, block_size=16 * 1024,
                          backend="numpy")
    srv = S3Server(nodes[0].layer, access_key="ck", secret_key="cs")
    srv.start()
    try:
        c = S3Client(srv.endpoint, "ck", "cs")
        with trace.HTTP_TRACE.subscribe() as sub:
            c.make_bucket("xbkt")
            c.put_object("xbkt", "xobj", b"q" * 40000)
            c.get_object("xbkt", "xobj")
            spans = list(sub.drain(2000, timeout=3.0))
        https = [s for s in spans if s.get("type") == "http"
                 and s["funcName"] == "PutObject"]
        assert https
        rid = https[0]["requestID"]
        assert rid
        node1_roots = tuple(specs[1].drive_dirs)
        # node1's drive-local spans (emitted inside its RPC handler
        # threads) carry node0's request ID
        peer_disk = [
            s for s in spans if s.get("type") == "storage"
            and not s.get("storage", {}).get("remote")
            and s.get("storage", {}).get("drive", "")
            .startswith(node1_roots)]
        assert peer_disk, "no drive-local span from the peer node"
        assert any(s.get("requestID") == rid for s in peer_disk)
        # and the internode client+server spans correlate too
        internode = [s for s in spans if s.get("type") == "internode"
                     and s.get("requestID") == rid]
        sides = {s["internode"]["side"] for s in internode}
        assert {"client", "server"} <= sides
    finally:
        srv.stop()
        for node in nodes:
            node.stop()

def test_cross_node_tree_assembles_idle_from_rings(tmp_path):
    """ISSUE 17 e2e: a PUT served by node0 fans shards to node1 over
    internode RPC with ZERO trace subscribers — yet the causal rings
    alone reconstruct the full cross-node tree: node1's drive ops knit
    under the internode client span via the X-Span-Parent header, the
    quorum gating row rides the quorum.write span, and nothing in the
    peer subtree is an orphan."""
    from minio_tpu.cluster import NodeSpec, start_cluster
    from minio_tpu.obs import tracetree
    specs = []
    for n in range(2):
        dirs = []
        for d in range(2):
            p = tmp_path / f"node{n}-drive{d}"
            p.mkdir()
            dirs.append(str(p))
        specs.append(NodeSpec(f"node{n}", dirs))
    nodes = start_cluster(specs, "obs-secret", set_drive_count=4,
                          parity=1, block_size=16 * 1024,
                          backend="numpy")
    srv = S3Server(nodes[0].layer, access_key="ck", secret_key="cs")
    srv.start()
    try:
        assert not trace.active()
        c = S3Client(srv.endpoint, "ck", "cs")
        c.make_bucket("treebkt")
        c.put_object("treebkt", "tobj", b"q" * 200_000)
        # the handler stamps its completion record after flushing
        rid = ""
        for _ in range(50):
            recs = [r for r in srv.flightrec.query(limit=50)
                    if r.get("api") == "PutObject"]
            if recs:
                rid = recs[-1]["requestID"]
                break
            time.sleep(0.05)
        assert rid, "PutObject never landed in the flight recorder"
        trees = tracetree.assemble(tracetree.local_spans(rid=rid))
        assert len(trees) == 1
        root = trees[0]
        assert root["spanID"] == rid and root["type"] == "http"
        assert not root.get("partial")
        # flatten with parent links intact
        flat = []

        def walk(node):
            flat.append(node)
            for ch in node.get("children", ()):
                walk(ch)

        walk(root)
        names = [s["name"] for s in flat]
        # the quorum critical-path span carries its gating row even
        # though nobody subscribed during the request
        gated = [s for s in flat if s["name"] == "quorum.write"]
        assert gated and all("gating" in s for s in gated), names
        g = gated[0]["gating"]
        assert g["k"] >= 1 and g["wallNs"] >= g["kthNs"] >= 0
        # internode client spans made it into the tree...
        inode = [s for s in flat if s["type"] == "internode"]
        assert inode, names
        # ...and node1's drive-local ops (labels under its drive
        # roots) rode the wire context: present AND knitted — their
        # parentID resolved to a live span, never the orphan rewire
        node1_roots = tuple(specs[1].drive_dirs)
        peer_disk = [s for s in flat if s["type"] == "storage"
                     and s.get("label", "").startswith(node1_roots)]
        assert peer_disk, "no peer drive span in the assembled tree"
        assert not any(s.get("orphan") for s in peer_disk), peer_disk
        # every peer drive op's parent chain reaches the http root
        by_sid = {s["spanID"]: s for s in flat}
        parents = {}
        for s in flat:
            for ch in s.get("children", ()):
                parents[ch["spanID"]] = s["spanID"]
        for s in peer_disk:
            sid, hops = s["spanID"], 0
            while sid != rid and hops < 64:
                sid = parents.get(sid, rid)
                hops += 1
            assert sid == rid
        assert all(s["spanID"] in by_sid for s in peer_disk)
    finally:
        srv.stop()
        for node in nodes:
            node.stop()
