"""In-process stub etcd server: the v3 grpc-gateway JSON KV surface.

Implements /v3/kv/put, /v3/kv/range (point + range_end prefix), and
/v3/kv/deleterange with base64 keys/values — byte-compatible with what
a real etcd answers on those routes, so minio_tpu.utils.etcd is tested
against the actual wire shapes (zero-egress analog of a real cluster,
like the OIDC/LDAP stubs)."""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class StubEtcd:
    def __init__(self):
        self.kv: dict[bytes, bytes] = {}
        self._mu = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                key = base64.b64decode(body.get("key", ""))
                range_end = base64.b64decode(body["range_end"]) \
                    if "range_end" in body else None
                out: dict = {}
                with stub._mu:
                    if self.path == "/v3/kv/put":
                        stub.kv[key] = base64.b64decode(
                            body.get("value", ""))
                    elif self.path == "/v3/kv/range":
                        kvs = []
                        for k in sorted(stub.kv):
                            if (range_end is None and k == key) or \
                                    (range_end is not None
                                     and key <= k < range_end):
                                kvs.append({
                                    "key":
                                        base64.b64encode(k).decode(),
                                    "value": base64.b64encode(
                                        stub.kv[k]).decode()})
                        out = {"kvs": kvs, "count": str(len(kvs))}
                    elif self.path == "/v3/kv/txn":
                        # the create-revision-guard transaction shape
                        # put_if_absent sends (compare CREATE == 0)
                        cmp = (body.get("compare") or [{}])[0]
                        ckey = base64.b64decode(cmp.get("key", ""))
                        absent = ckey not in stub.kv
                        if absent:
                            for req in body.get("success") or []:
                                rp = req.get("request_put") or {}
                                stub.kv[base64.b64decode(rp["key"])] = \
                                    base64.b64decode(
                                        rp.get("value", ""))
                        out = {"succeeded": absent}
                    elif self.path == "/v3/kv/deleterange":
                        if range_end is None:
                            deleted = 1 if stub.kv.pop(key, None) \
                                is not None else 0
                        else:
                            dead = [k for k in stub.kv
                                    if key <= k < range_end]
                            for k in dead:
                                del stub.kv[k]
                            deleted = len(dead)
                        out = {"deleted": str(deleted)}
                    else:
                        self.send_response(404)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                blob = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self) -> str:
        self._thread.start()
        host, port = self._srv.server_address
        return f"http://{host}:{port}"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
