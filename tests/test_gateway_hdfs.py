"""HDFS gateway over the WebHDFS wire — namenode+datanode stub with
the real two-step redirect (tests/hdfs_stub.py).

Covers the gateway surface the azure/gcs suites established: bucket
lifecycle, object CRUD with ranged reads, one-level and recursive
listings with pagination, multipart staged under the sys tmp dir and
assembled via CREATE+APPEND, plus the wire details (redirect dance
actually runs, auth parameter required, HDFS's no-metadata semantics).
"""

import os

import pytest

from minio_tpu import gateway as gw
from minio_tpu.gateway.hdfs import (HDFSError, HDFSObjects,
                                    WebHDFSClient)
from minio_tpu.objectlayer.interface import (BucketExists,
                                             BucketNotEmpty,
                                             BucketNotFound, InvalidPart,
                                             ObjectNotFound)

from .hdfs_stub import HDFSStubServer


@pytest.fixture(scope="module")
def stub():
    srv = HDFSStubServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def layer(stub):
    return HDFSObjects(WebHDFSClient(stub.endpoint), root="/minio")


def test_redirect_dance_is_real(stub, layer):
    layer.make_bucket("redir")
    before = stub.redirects
    layer.put_object("redir", "f.bin", b"x" * 100)
    _, data = layer.get_object("redir", "f.bin")
    assert data == b"x" * 100
    assert stub.redirects >= before + 2     # CREATE + OPEN both hopped


def test_missing_user_param_is_401(stub):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", stub.port, timeout=5)
    conn.request("GET", "/webhdfs/v1/minio?op=LISTSTATUS")
    resp = conn.getresponse()
    resp.read()
    conn.close()
    assert resp.status == 401


def test_bucket_lifecycle(layer):
    layer.make_bucket("hb")
    assert layer.get_bucket_info("hb").name == "hb"
    with pytest.raises(BucketExists):
        layer.make_bucket("hb")
    assert any(b.name == "hb" for b in layer.list_buckets())
    layer.put_object("hb", "x", b"1")
    with pytest.raises(BucketNotEmpty):
        layer.delete_bucket("hb")
    layer.delete_object("hb", "x")
    layer.delete_bucket("hb")
    with pytest.raises(BucketNotFound):
        layer.get_bucket_info("hb")


def test_object_crud_and_ranges(layer):
    layer.make_bucket("hobj")
    body = os.urandom(64 * 1024)
    info = layer.put_object("hobj", "dir/deep/obj.bin", body)
    assert info.size == len(body) and info.etag
    # HDFS carries no metadata: octet-stream, no x-amz-meta
    assert info.content_type == "application/octet-stream"
    _, data = layer.get_object("hobj", "dir/deep/obj.bin")
    assert data == body
    _, part = layer.get_object("hobj", "dir/deep/obj.bin",
                               offset=1000, length=50)
    assert part == body[1000:1050]
    layer.delete_object("hobj", "dir/deep/obj.bin")
    with pytest.raises(ObjectNotFound):
        layer.get_object_info("hobj", "dir/deep/obj.bin")
    with pytest.raises(BucketNotFound):
        layer.get_object_info("nosuchbkt", "x")


def test_listing_delimiter_recursive_pagination(layer):
    layer.make_bucket("hls")
    for k in ("a/1", "a/2", "b/c/3", "top"):
        layer.put_object("hls", k, b"x")
    one = layer.list_objects("hls", delimiter="/")
    assert [o.name for o in one.objects] == ["top"]
    assert one.prefixes == ["a/", "b/"]
    sub = layer.list_objects("hls", prefix="a/", delimiter="/")
    assert [o.name for o in sub.objects] == ["a/1", "a/2"]
    rec = layer.list_objects("hls")
    assert [o.name for o in rec.objects] == ["a/1", "a/2", "b/c/3",
                                             "top"]
    page1 = layer.list_objects("hls", max_keys=2)
    assert [o.name for o in page1.objects] == ["a/1", "a/2"]
    assert page1.is_truncated
    page2 = layer.list_objects("hls", marker=page1.next_marker)
    assert [o.name for o in page2.objects] == ["b/c/3", "top"]


def test_multipart_create_append_assembly(layer, stub):
    layer.make_bucket("hmp")
    uid = layer.new_multipart_upload("hmp", "big")
    e1 = layer.put_object_part("hmp", "big", uid, 1, b"a" * 1000)
    e2 = layer.put_object_part("hmp", "big", uid, 2, b"b" * 500)
    assert [(n, s) for n, _, s in
            layer.list_object_parts("hmp", "big", uid)] == \
        [(1, 1000), (2, 500)]
    assert ("big", uid) in layer.list_multipart_uploads("hmp")
    with pytest.raises(InvalidPart):
        layer.complete_multipart_upload("hmp", "big", uid,
                                        [(1, e1), (9, "zz")])
    oi = layer.complete_multipart_upload("hmp", "big", uid,
                                         [(1, e1), (2, e2)])
    assert oi.size == 1500
    _, data = layer.get_object("hmp", "big")
    assert data == b"a" * 1000 + b"b" * 500
    # tmp dir cleaned; sys dir never lists as a bucket
    assert layer.list_multipart_uploads("hmp") == []
    assert all(b.name != ".minio-tpu.sys" for b in layer.list_buckets())


def test_multipart_abort(layer):
    layer.make_bucket("hab")
    uid = layer.new_multipart_upload("hab", "gone")
    layer.put_object_part("hab", "gone", uid, 1, b"zz")
    layer.abort_multipart_upload("hab", "gone", uid)
    with pytest.raises(ObjectNotFound):
        layer.complete_multipart_upload("hab", "gone", uid, [(1, "e")])
    with pytest.raises(ObjectNotFound):
        layer.get_object_info("hab", "gone")


def test_copy_object(layer):
    layer.make_bucket("hcp")
    layer.put_object("hcp", "src", b"copy me")
    layer.copy_object("hcp", "src", "hcp", "dst/copy")
    _, data = layer.get_object("hcp", "dst/copy")
    assert data == b"copy me"


def test_registered_production_gateway(stub, monkeypatch):
    monkeypatch.setenv("HDFS_NAMENODE_URL", stub.endpoint)
    monkeypatch.setenv("HDFS_ROOT_DIR", "/gwroot")
    g = gw.lookup("hdfs")()
    assert g.production() and g.name() == "hdfs"
    lay = g.new_gateway_layer()
    lay.make_bucket("envb")
    lay.put_object("envb", "k", b"v")
    assert lay.get_object("envb", "k")[1] == b"v"


def test_full_s3_frontend_over_hdfs_gateway(stub):
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    layer = HDFSObjects(WebHDFSClient(stub.endpoint), root="/s3gw")
    srv = S3Server(layer, access_key="hk", secret_key="hs")
    srv.start()
    try:
        cl = S3Client(srv.endpoint, "hk", "hs")
        cl.make_bucket("s3hdfs")
        body = os.urandom(100_000)
        cl.put_object("s3hdfs", "deep/obj x.bin", body)
        r = cl.get_object("s3hdfs", "deep/obj x.bin")
        assert r.status == 200 and r.body == body
        lst = cl.request("GET", "/s3hdfs", "list-type=2")
        assert b"deep/obj x.bin" in lst.body
    finally:
        srv.stop()


def test_namenode_down_fails_loudly():
    layer = HDFSObjects.__new__(HDFSObjects)
    layer.client = WebHDFSClient("http://127.0.0.1:1", timeout=2)
    layer.root = "/minio"
    with pytest.raises(OSError):
        layer.list_buckets()


def test_hdfs_error_shape(stub):
    c = WebHDFSClient(stub.endpoint)
    with pytest.raises(HDFSError) as ei:
        c.status("/no/such/path")
    assert ei.value.status == 404
    assert "FileNotFoundException" in ei.value.exception


def test_delete_prunes_empty_parent_dirs(layer):
    layer.make_bucket("hprune")
    layer.put_object("hprune", "deep/a/b/only.bin", b"x")
    layer.put_object("hprune", "deep/keep.bin", b"y")
    layer.delete_object("hprune", "deep/a/b/only.bin")
    lst = layer.list_objects("hprune", delimiter="/")
    # 'deep/' survives (keep.bin inside); 'deep/a/' pruned entirely
    assert lst.prefixes == ["deep/"]
    sub = layer.list_objects("hprune", prefix="deep/", delimiter="/")
    assert sub.prefixes == []
    assert [o.name for o in sub.objects] == ["deep/keep.bin"]


def test_complete_multipart_is_atomic_under_crash(layer):
    """Crash mid-complete: the assembly happens under the upload's
    staging dir and is RENAMEd into place, so the destination is never
    a truncated object that looks complete (ADVICE round 5)."""
    layer.make_bucket("hcr")
    uid = layer.new_multipart_upload("hcr", "obj")
    e1 = layer.put_object_part("hcr", "obj", uid, 1, b"a" * 1000)
    e2 = layer.put_object_part("hcr", "obj", uid, 2, b"b" * 500)

    orig_append = layer.client.append

    def crash_append(path, body):
        raise HDFSError(500, "NodeDied", "simulated crash mid-complete")

    layer.client.append = crash_append
    try:
        with pytest.raises(HDFSError):
            layer.complete_multipart_upload("hcr", "obj", uid,
                                            [(1, e1), (2, e2)])
    finally:
        layer.client.append = orig_append
    # the crash left NO destination object (old behavior: a truncated
    # 1000-byte "obj" that looked complete)
    with pytest.raises(ObjectNotFound):
        layer.get_object_info("hcr", "obj")
    # the upload is still intact: retrying the complete succeeds
    oi = layer.complete_multipart_upload("hcr", "obj", uid,
                                         [(1, e1), (2, e2)])
    assert oi.size == 1500
    _, data = layer.get_object("hcr", "obj")
    assert data == b"a" * 1000 + b"b" * 500


def test_complete_multipart_replaces_existing_object(layer):
    """Promote-over-existing path: HDFS rename refuses to clobber, so
    the complete clears the old object and promotes again."""
    layer.make_bucket("hrp")
    layer.put_object("hrp", "obj", b"old-contents")
    uid = layer.new_multipart_upload("hrp", "obj")
    e1 = layer.put_object_part("hrp", "obj", uid, 1, b"new" * 100)
    oi = layer.complete_multipart_upload("hrp", "obj", uid, [(1, e1)])
    assert oi.size == 300
    _, data = layer.get_object("hrp", "obj")
    assert data == b"new" * 100
