"""Resilience primitives — circuit breaker, retry policy/budget, and
their integration into the RPC client (the unit tier under the chaos
tests in test_chaos_network.py).

Everything here is deterministic: breakers run on injected fake clocks,
retry policies on seeded RNGs with recording sleeps — no wall-clock
races (the NaughtyDisk discipline applied to the wire layer).
"""

import random

import pytest

from minio_tpu.parallel.rpc import (CircuitBreaker, RPCClient, RPCError,
                                    RPCServer)
from minio_tpu.utils.retry import RetryBudget, RetryPolicy


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- CircuitBreaker ---------------------------------------------------------

def test_breaker_opens_after_consecutive_failures():
    clk = FakeClock()
    br = CircuitBreaker(fail_max=3, cooldown_s=5.0, clock=clk)
    assert br.state == CircuitBreaker.CLOSED
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == CircuitBreaker.CLOSED    # below threshold
    assert br.allow()
    br.record_failure()                          # third consecutive
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()                        # fail fast while open
    assert not br.ready()


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(fail_max=2, cooldown_s=5.0, clock=FakeClock())
    br.record_failure()
    br.record_success()                          # streak broken
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED     # 1 consecutive, not 2


def test_breaker_half_open_single_probe():
    clk = FakeClock()
    br = CircuitBreaker(fail_max=1, cooldown_s=5.0, clock=clk)
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    clk.advance(5.0)
    assert br.ready()
    assert br.allow()                # first caller becomes the probe
    assert br.state == CircuitBreaker.HALF_OPEN
    assert not br.allow()            # everyone else still fails fast
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()


def test_breaker_failed_probe_reopens_fresh_cooldown():
    clk = FakeClock()
    br = CircuitBreaker(fail_max=1, cooldown_s=5.0, clock=clk)
    br.record_failure()
    clk.advance(5.0)
    assert br.allow()                # probe admitted
    br.record_failure()              # probe failed
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()            # cooldown restarted
    clk.advance(4.9)
    assert not br.allow()
    clk.advance(0.2)
    assert br.allow()                # next probe window


# -- RetryPolicy / RetryBudget ----------------------------------------------

def test_retry_backoff_is_jittered_exponential_and_capped():
    rp = RetryPolicy(attempts=10, base_s=0.1, cap_s=0.4,
                     rng=random.Random(7))
    for retry_nr, ceiling in [(0, 0.1), (1, 0.2), (2, 0.4), (5, 0.4)]:
        samples = [rp.backoff_s(retry_nr) for _ in range(50)]
        assert all(0.0 <= s <= ceiling for s in samples)
    # jitter: not constant
    assert len({round(rp.backoff_s(3), 9) for _ in range(10)}) > 1


def test_retry_idempotent_only_and_attempt_cap():
    rp = RetryPolicy(attempts=3)
    assert not rp.may_retry(0, idempotent=False)   # mutations never
    assert rp.may_retry(0, idempotent=True)
    assert rp.may_retry(1, idempotent=True)
    assert not rp.may_retry(2, idempotent=True)    # 3 attempts total


def test_retry_budget_caps_retry_storms():
    budget = RetryBudget(capacity=2.0, refund=0.5)
    rp = RetryPolicy(attempts=10, budget=budget)
    assert rp.may_retry(0, True)                   # spends 1
    assert rp.may_retry(0, True)                   # spends 1 -> empty
    assert not rp.may_retry(0, True)               # bucket dry: shed
    rp.on_success()
    rp.on_success()                                # refunds 2 * 0.5
    assert rp.may_retry(0, True)


def test_retry_budget_caps_at_capacity():
    budget = RetryBudget(capacity=1.0, refund=0.5)
    for _ in range(10):
        budget.credit()
    assert budget.tokens == 1.0


# -- RPCClient integration --------------------------------------------------

@pytest.fixture
def rpc_server():
    srv = RPCServer("testsecret")
    srv.start()
    yield srv
    try:
        srv.stop()
    except Exception:  # noqa: BLE001 — some tests stop it themselves
        pass


def _client(endpoint, fail_max=3, cooldown_s=60.0, clock=None,
            attempts=1, sleeps=None):
    return RPCClient(
        endpoint, "testsecret",
        breaker=CircuitBreaker(fail_max=fail_max, cooldown_s=cooldown_s,
                               clock=clock or FakeClock()),
        retry=RetryPolicy(attempts=attempts, base_s=0.001,
                          rng=random.Random(1),
                          sleep=(sleeps.append if sleeps is not None
                                 else (lambda s: None))))


def test_rpc_breaker_opens_on_dead_peer_and_fails_fast(rpc_server):
    port = rpc_server.port
    rpc_server.stop()
    clk = FakeClock()
    c = _client(f"http://127.0.0.1:{port}", fail_max=3, clock=clk)
    for _ in range(3):
        with pytest.raises(RPCError) as ei:
            c.call("sys", "ping")
        assert ei.value.error_type == "ConnectionError"
    assert c.breaker.state == CircuitBreaker.OPEN
    assert not c.is_online()
    # while open: PeerOffline without touching the socket
    with pytest.raises(RPCError) as ei:
        c.call("sys", "ping")
    assert ei.value.error_type == "PeerOffline"


def test_rpc_half_open_probe_readmits_restarted_peer(rpc_server):
    port = rpc_server.port
    rpc_server.stop()
    clk = FakeClock()
    c = _client(f"http://127.0.0.1:{port}", fail_max=1, cooldown_s=5.0,
                clock=clk)
    with pytest.raises(RPCError):
        c.call("sys", "ping")
    assert c.breaker.state == CircuitBreaker.OPEN
    # peer comes back on the SAME port; cooldown elapses -> next call
    # doubles as the half-open probe and closes the breaker
    srv2 = RPCServer("testsecret", port=port)
    srv2.start()
    try:
        clk.advance(5.0)
        assert c.is_online()
        assert c.call("sys", "ping") == "pong"
        assert c.breaker.state == CircuitBreaker.CLOSED
    finally:
        srv2.stop()


def test_rpc_retries_idempotent_with_recorded_backoff():
    sleeps = []
    c = _client("http://127.0.0.1:1", fail_max=100, attempts=3,
                sleeps=sleeps)
    with pytest.raises(RPCError):
        c.call("sys", "ping", _idempotent=True)
    assert len(sleeps) == 2                       # two retries
    assert all(s >= 0.0 for s in sleeps)


def test_rpc_never_retries_mutations():
    sleeps = []
    c = _client("http://127.0.0.1:1", fail_max=100, attempts=3,
                sleeps=sleeps)
    with pytest.raises(RPCError):
        c.call("sys", "ping")                     # not idempotent
    assert sleeps == []


def test_rpc_app_errors_do_not_trip_breaker(rpc_server):
    rpc_server.register("t", {"boom": lambda: 1 / 0})
    c = _client(rpc_server.endpoint, fail_max=1)
    for _ in range(3):
        with pytest.raises(RPCError) as ei:
            c.call("t", "boom")
        assert ei.value.error_type == "ZeroDivisionError"
    # the peer answered every time: transport is healthy
    assert c.breaker.state == CircuitBreaker.CLOSED


def test_rpc_stale_pooled_connection_replay(rpc_server):
    """A peer restart between calls leaves stale pooled connections;
    the next call must replay transparently on a fresh one."""
    port = rpc_server.port
    c = _client(f"http://127.0.0.1:{port}", fail_max=3)
    assert c.call("sys", "ping") == "pong"        # pools the connection
    rpc_server.stop()
    srv2 = RPCServer("testsecret", port=port)
    srv2.start()
    try:
        # idempotent: replayable whether the stale connection dies in
        # the send phase or the response phase (the race is real — a
        # non-idempotent call may legitimately fail here)
        assert c.call("sys", "ping", _idempotent=True) == "pong"
        assert c.breaker.state == CircuitBreaker.CLOSED
    finally:
        srv2.stop()
