"""Browser SPA serving tests (role of the reference's React app,
browser/app/js served via cmd/web-router.go go-bindata assets).

The app itself is exercised end to end by a real-browser smoke drive
during development; these tests pin the serving contract: the page is
served at /minio-tpu/browser, unauthenticated browser GETs of / are
redirected to it, S3 clients are NOT redirected, and every RPC/endpoint
the page's JavaScript calls exists on the backend.
"""

import json
import re
import urllib.request

import pytest

from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.web import BROWSER_PATH
from minio_tpu.storage.xl_storage import XLStorage

UA_BROWSER = ("Mozilla/5.0 (X11; Linux x86_64) AppleWebKit/537.36 "
              "(KHTML, like Gecko) Chrome/126.0 Safari/537.36")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("uidrives")
    disks = []
    for i in range(4):
        d = tmp / f"disk{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=128 * 1024,
                           backend="numpy")
    srv = S3Server(layer, access_key="uikey", secret_key="uisecret")
    srv.start()
    yield srv
    srv.stop()


def _get(server, path, headers=None, follow=True):
    class NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    req = urllib.request.Request(server.endpoint + path,
                                 headers=headers or {})
    opener = urllib.request.build_opener() if follow else \
        urllib.request.build_opener(NoRedirect)
    try:
        return opener.open(req, timeout=10)
    except urllib.error.HTTPError as e:
        return e


def test_spa_served(server):
    r = _get(server, BROWSER_PATH)
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/html")
    body = r.read().decode()
    # the page is self-contained: login form, RPC client, upload wiring
    for marker in ["minio-tpu browser", "/minio-tpu/webrpc",
                   '"web." + method', "/minio-tpu/upload/",
                   "PresignedGet"]:
        assert marker in body, marker
    # no external assets — the zero-egress single-file contract
    assert not re.search(r'(src|href)\s*=\s*"https?://', body)


def test_browser_redirect_from_root(server):
    r = _get(server, "/", headers={"User-Agent": UA_BROWSER}, follow=False)
    assert r.status == 303
    assert r.headers["Location"] == BROWSER_PATH
    # following the redirect lands on the app
    r = _get(server, "/", headers={"User-Agent": UA_BROWSER})
    assert r.status == 200 and b"minio-tpu browser" in r.read()


def test_s3_clients_not_redirected(server):
    # non-browser UA: anonymous ListBuckets XML error, not a redirect
    r = _get(server, "/", headers={"User-Agent": "aws-cli/2.0"},
             follow=False)
    assert r.status != 303
    # browser UA but SIGNED request: S3 semantics preserved
    from minio_tpu.s3.client import S3Client
    c = S3Client(server.endpoint, "uikey", "uisecret")
    resp = c.request("GET", "/", headers={"User-Agent": UA_BROWSER})
    assert resp.status == 200
    assert b"ListAllMyBucketsResult" in resp.body


def test_every_rpc_the_page_calls_exists(server):
    page = _get(server, BROWSER_PATH).read().decode()
    called = set(re.findall(r'rpc\("([A-Za-z]+)"', page))
    assert called, "no RPC calls found in page"
    from minio_tpu.s3.web import WebRPC
    backend = {m[len("rpc_"):] for m in dir(WebRPC)
               if m.startswith("rpc_")}
    missing = called - backend
    assert not missing, f"page calls missing RPCs: {missing}"


def test_ui_flow_over_http(server):
    """The exact request sequence the page's JS issues: login ->
    make bucket -> upload -> list -> presigned share -> download."""
    def rpc(method, params=None, token=""):
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": f"web.{method}",
                           "params": params or {}}).encode()
        req = urllib.request.Request(
            f"{server.endpoint}/minio-tpu/webrpc", data=body,
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {token}"}
                        if token else {})})
        with urllib.request.urlopen(req, timeout=10) as resp:
            doc = json.loads(resp.read())
        assert "error" not in doc, doc
        return doc["result"]

    tok = rpc("Login", {"username": "uikey", "password": "uisecret"})["token"]
    rpc("MakeBucket", {"bucketName": "uibucket"}, tok)
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/upload/uibucket/docs/hello.txt",
        data=b"hello from the browser", method="PUT",
        headers={"Authorization": f"Bearer {tok}",
                 "Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert json.loads(resp.read())["ok"] is True
    objs = rpc("ListObjects", {"bucketName": "uibucket", "prefix": ""},
               tok)["objects"]
    assert any(o["name"] == "docs/" for o in objs)
    share = rpc("PresignedGet", {"bucketName": "uibucket",
                                 "objectName": "docs/hello.txt",
                                 "host": f"127.0.0.1:{server.port}"}, tok)
    with urllib.request.urlopen(share["url"], timeout=10) as resp:
        assert resp.read() == b"hello from the browser"
    dl = rpc("CreateURLToken", {}, tok)["token"]
    with urllib.request.urlopen(
            f"{server.endpoint}/minio-tpu/download/uibucket/docs/hello.txt"
            f"?token={dl}", timeout=10) as resp:
        assert resp.read() == b"hello from the browser"


def _rpc(server, method, params=None, token=""):
    body = json.dumps({"jsonrpc": "2.0", "id": 1,
                       "method": f"web.{method}",
                       "params": params or {}}).encode()
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/webrpc", data=body,
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"}
                    if token else {})})
    with urllib.request.urlopen(req, timeout=10) as resp:
        doc = json.loads(resp.read())
    assert "error" not in doc, doc
    return doc["result"]


def test_new_ui_flows_present(server):
    """r4 breadth: the page carries policy management, share expiry,
    multi-select delete, and upload progress wiring."""
    page = _get(server, BROWSER_PATH).read().decode()
    for marker in ["polselect", "SetBucketPolicy", "GetBucketPolicy",
                   "delselected", "selectedObjects", "parseExpiry",
                   "upload.onprogress", "progwrap"]:
        assert marker in page, marker


def test_policy_management_flow(server):
    """Set readonly on a prefix via the web RPC, verify it round-trips,
    is listed, and actually grants ANONYMOUS reads — then revoke."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "polbkt"}, tok)
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/upload/polbkt/pub/doc.txt",
        data=b"public document", method="PUT",
        headers={"Authorization": f"Bearer {tok}",
                 "Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert json.loads(resp.read())["ok"] is True

    # anonymous read denied before a policy exists
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{server.endpoint}/polbkt/pub/doc.txt", timeout=10)
    assert ei.value.status == 403

    _rpc(server, "SetBucketPolicy",
         {"bucketName": "polbkt", "prefix": "pub/",
          "policy": "readonly"}, tok)
    got = _rpc(server, "GetBucketPolicy",
               {"bucketName": "polbkt", "prefix": "pub/"}, tok)
    assert got["policy"] == "readonly"
    lst = _rpc(server, "ListAllBucketPolicies",
               {"bucketName": "polbkt"}, tok)
    assert {"bucket": "polbkt", "prefix": "pub/",
            "policy": "readonly"} in lst["policies"]

    # the canned policy is ENFORCED: anonymous read now succeeds
    with urllib.request.urlopen(
            f"{server.endpoint}/polbkt/pub/doc.txt", timeout=10) as r:
        assert r.read() == b"public document"

    # revoke -> anonymous denied again
    _rpc(server, "SetBucketPolicy",
         {"bucketName": "polbkt", "prefix": "pub/",
          "policy": "none"}, tok)
    assert _rpc(server, "GetBucketPolicy",
                {"bucketName": "polbkt", "prefix": "pub/"},
                tok)["policy"] == "none"
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{server.endpoint}/polbkt/pub/doc.txt", timeout=10)
    assert ei.value.status == 403


def test_invalid_policy_kind_rejected(server):
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "polbad"}, tok)
    body = json.dumps({"jsonrpc": "2.0", "id": 1,
                       "method": "web.SetBucketPolicy",
                       "params": {"bucketName": "polbad",
                                  "policy": "everything"}}).encode()
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/webrpc", data=body,
        headers={"Content-Type": "application/json",
                 "Authorization": f"Bearer {tok}"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        doc = json.loads(resp.read())
    assert "error" in doc and "invalid policy kind" in \
        doc["error"]["message"]


def test_multi_object_delete_flow(server):
    """The Delete-selected UI path: one RemoveObject RPC with many
    keys removes exactly those keys."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "multidel"}, tok)
    for name in ("a.txt", "b.txt", "keep.txt"):
        req = urllib.request.Request(
            f"{server.endpoint}/minio-tpu/upload/multidel/{name}",
            data=b"x", method="PUT",
            headers={"Authorization": f"Bearer {tok}"})
        urllib.request.urlopen(req, timeout=10).read()
    res = _rpc(server, "RemoveObject",
               {"bucketName": "multidel",
                "objects": ["a.txt", "b.txt"]}, tok)
    assert sorted(res["removed"]) == ["a.txt", "b.txt"]
    objs = _rpc(server, "ListObjects",
                {"bucketName": "multidel", "prefix": ""}, tok)["objects"]
    assert [o["name"] for o in objs] == ["keep.txt"]


def test_policy_prefix_editor_flow(server):
    """The Policies… panel flow the SPA drives: add policies on TWO
    different prefixes, list them all, remove one, re-list (r4 verdict
    #9, browser/app/js/bucket PolicyInput role)."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "poledit"}, tok)
    _rpc(server, "SetBucketPolicy",
         {"bucketName": "poledit", "prefix": "pub/",
          "policy": "readonly"}, tok)
    _rpc(server, "SetBucketPolicy",
         {"bucketName": "poledit", "prefix": "drop/",
          "policy": "writeonly"}, tok)
    pols = _rpc(server, "ListAllBucketPolicies",
                {"bucketName": "poledit"}, tok)["policies"]
    assert {(p["prefix"], p["policy"]) for p in pols} == \
        {("pub/", "readonly"), ("drop/", "writeonly")}
    # the remove button sends policy: "none"
    _rpc(server, "SetBucketPolicy",
         {"bucketName": "poledit", "prefix": "pub/",
          "policy": "none"}, tok)
    pols = _rpc(server, "ListAllBucketPolicies",
                {"bucketName": "poledit"}, tok)["policies"]
    assert {(p["prefix"], p["policy"]) for p in pols} == \
        {("drop/", "writeonly")}
    # page wiring present
    page = _get(server, BROWSER_PATH).read().decode()
    for marker in ["poledit", "polpanel", "openPolicyPanel",
                   "addPrefixPolicy", "ListAllBucketPolicies",
                   "polrows", "poladdbtn"]:
        assert marker in page, marker


def test_object_preview_flow(server):
    """The Preview panel flow: HEAD probes type/size, text objects
    fetch a ranged body, images ride <img src>; exact request sequence
    the page's preview() issues (browser/app/js/objects preview)."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "prevb"}, tok)
    # upload a text object through the raw upload route the SPA uses
    body = b"line one\nline two\n" * 200
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/upload/prevb/notes.txt",
        data=body, method="PUT",
        headers={"Authorization": f"Bearer {tok}",
                 "Content-Type": "text/plain"})
    urllib.request.urlopen(req, timeout=10).read()
    url_tok = _rpc(server, "CreateURLToken", {}, tok)["token"]
    dl = f"/minio-tpu/download/prevb/notes.txt?token={url_tok}"
    # HEAD: content type + size, no body
    req = urllib.request.Request(server.endpoint + dl, method="HEAD")
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert int(resp.headers["Content-Length"]) == len(body)
        assert resp.read() == b""
    # ranged GET: first bytes only, 206 + Content-Range
    req = urllib.request.Request(server.endpoint + dl,
                                 headers={"Range": "bytes=0-99"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 206
        assert resp.headers["Content-Range"] == \
            f"bytes 0-99/{len(body)}"
        assert resp.read() == body[:100]
    # full GET still plain 200
    with urllib.request.urlopen(server.endpoint + dl,
                                timeout=10) as resp:
        assert resp.status == 200 and resp.read() == body
    # page wiring present
    page = _get(server, BROWSER_PATH).read().decode()
    for marker in ["preview(", "prevtext", "previmg", "PREVIEW_MAX",
                   "prevclose", "Preview"]:
        assert marker in page, marker


def test_download_head_error_has_no_body(server):
    """RFC 9110: HEAD responses carry no body even on errors — a JSON
    body would desync the keep-alive connection (review r5)."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    url_tok = _rpc(server, "CreateURLToken", {}, tok)["token"]
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/download/prevb/ghost.bin"
        f"?token={url_tok}", method="HEAD")
    try:
        resp = urllib.request.urlopen(req, timeout=10)
    except urllib.error.HTTPError as e:
        resp = e
    assert resp.status == 404
    assert resp.read() == b""
    assert resp.headers["Content-Length"] == "0"


def test_download_unsatisfiable_range_gets_416(server):
    """An unsatisfiable Range must answer 416 + 'Content-Range:
    bytes */total', not a silent 200 with the whole object (ADVICE
    round 5)."""
    tok = _rpc(server, "Login", {"username": "uikey",
                                 "password": "uisecret"})["token"]
    _rpc(server, "MakeBucket", {"bucketName": "rngb"}, tok)
    body = b"0123456789" * 10
    req = urllib.request.Request(
        f"{server.endpoint}/minio-tpu/upload/rngb/r.bin",
        data=body, method="PUT",
        headers={"Authorization": f"Bearer {tok}"})
    urllib.request.urlopen(req, timeout=10).read()
    url_tok = _rpc(server, "CreateURLToken", {}, tok)["token"]
    dl = f"/minio-tpu/download/rngb/r.bin?token={url_tok}"
    for spec in [f"bytes={len(body)}-", "bytes=500-600"]:
        req = urllib.request.Request(server.endpoint + dl,
                                     headers={"Range": spec})
        try:
            resp = urllib.request.urlopen(req, timeout=10)
            raise AssertionError(
                f"{spec}: got {resp.status}, wanted 416")
        except urllib.error.HTTPError as e:
            assert e.code == 416, spec
            assert e.headers["Content-Range"] == f"bytes */{len(body)}"
            assert e.read() == b""
    # a syntactically INVALID range (last < first) is IGNORED, not
    # 416'd (RFC 9110 §14.1.1): full object, 200
    req = urllib.request.Request(server.endpoint + dl,
                                 headers={"Range": "bytes=9-2"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
        assert resp.read() == body
    # a satisfiable range still works
    req = urllib.request.Request(server.endpoint + dl,
                                 headers={"Range": "bytes=0-9"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 206
        assert resp.read() == body[:10]
