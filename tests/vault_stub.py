"""In-process Vault stub — transit engine with real context-bound
sealing (same cipher discipline as tests/kes_stub.py) plus both auth
modes the reference's vault.go uses: static token (X-Vault-Token) and
AppRole login minting a client token.  Ciphertexts carry the
``vault:v1:`` prefix like the real transit engine.
"""

from __future__ import annotations

import base64
import http.server
import json
import os
import secrets
import threading

from .kes_stub import _seal, _unseal

ROOT_TOKEN = "s.stub-root-token"
ROLE_ID = "stub-role-id"
SECRET_ID = "stub-secret-id"


class VaultStubServer:
    def __init__(self):
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, status: int, doc: dict | None = None):
                body = json.dumps(doc or {}).encode() \
                    if doc is not None else b""
                self.send_response(status)
                if body:
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                doc = json.loads(self.rfile.read(length) or b"{}")
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v1", "auth", "approle", "login"]:
                    if (doc.get("role_id") == ROLE_ID
                            and doc.get("secret_id") == SECRET_ID):
                        tok = "s." + secrets.token_hex(12)
                        stub.tokens.add(tok)
                        return self._reply(
                            200, {"auth": {"client_token": tok}})
                    return self._reply(
                        400, {"errors": ["invalid role or secret id"]})
                tok = self.headers.get("X-Vault-Token", "")
                if tok != ROOT_TOKEN and tok not in stub.tokens:
                    return self._reply(403,
                                       {"errors": ["permission denied"]})
                if len(parts) == 4 and parts[:3] == \
                        ["v1", "transit", "keys"]:
                    stub.keys.setdefault(parts[3], os.urandom(32))
                    return self._reply(204)
                if len(parts) == 5 and parts[1] == "transit" and \
                        parts[2] == "datakey" and parts[3] == "plaintext":
                    name = parts[4]
                    if name not in stub.keys:
                        return self._reply(
                            400, {"errors": ["unknown key"]})
                    ctx = base64.b64decode(doc.get("context", ""))
                    plain = os.urandom(32)
                    sealed = _seal(stub.keys[name], ctx, plain)
                    return self._reply(200, {"data": {
                        "plaintext": base64.b64encode(plain).decode(),
                        "ciphertext": "vault:v1:"
                        + base64.b64encode(sealed).decode()}})
                if len(parts) == 4 and parts[:3] == \
                        ["v1", "transit", "decrypt"]:
                    name = parts[3]
                    if name not in stub.keys:
                        return self._reply(
                            400, {"errors": ["unknown key"]})
                    ct = doc.get("ciphertext", "")
                    if not ct.startswith("vault:v1:"):
                        return self._reply(
                            400, {"errors": ["bad ciphertext prefix"]})
                    ctx = base64.b64decode(doc.get("context", ""))
                    try:
                        plain = _unseal(
                            stub.keys[name], ctx,
                            base64.b64decode(ct[len("vault:v1:"):]))
                    except ValueError as e:
                        return self._reply(400, {"errors": [str(e)]})
                    return self._reply(200, {"data": {
                        "plaintext":
                            base64.b64encode(plain).decode()}})
                return self._reply(404, {"errors": ["unknown route"]})

        self._http = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.port = self._http.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        self.keys: dict[str, bytes] = {}
        self.tokens: set[str] = set()
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True)

    def start(self) -> "VaultStubServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
