"""Sets + server-pools topology tests (mirrors the multi-set tier of the
reference suite: prepareErasureSets32, cmd/erasure-sets_test.go)."""

import pytest

from minio_tpu.objectlayer.interface import ObjectNotFound, PutObjectOptions
from minio_tpu.objectlayer.pools import ErasureServerPools
from minio_tpu.objectlayer.sets import ErasureSets
from minio_tpu.storage.xl_storage import XLStorage

BS = 64 * 1024


def make_sets(tmp_path, tag, set_count=2, drives=4, parity=2) -> ErasureSets:
    dirs = []
    for i in range(set_count * drives):
        d = tmp_path / f"{tag}-disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    return ErasureSets.from_dirs(
        dirs, set_count, drives, parity=parity, block_size=BS,
        backend="numpy")


@pytest.fixture
def sets(tmp_path):
    s = make_sets(tmp_path, "a")
    s.make_bucket("bkt")
    return s


def test_distribution_is_deterministic_and_spread(sets):
    idx = {name: sets.get_hashed_set_index(name)
           for name in (f"obj-{i}" for i in range(64))}
    # deterministic
    for name, i in idx.items():
        assert sets.get_hashed_set_index(name) == i
    # both sets get used
    assert set(idx.values()) == {0, 1}


def test_sets_roundtrip_and_listing(sets):
    names = [f"dir/obj-{i}" for i in range(10)]
    for n in names:
        sets.put_object("bkt", n, n.encode())
    for n in names:
        _, got = sets.get_object("bkt", n)
        assert got == n.encode()
    out = sets.list_objects("bkt", prefix="dir/")
    assert [o.name for o in out.objects] == sorted(names)
    # objects actually live on different sets
    on0 = sum(1 for n in names if sets.get_hashed_set_index(n) == 0)
    assert 0 < on0 < len(names)
    sets.delete_object("bkt", names[0])
    with pytest.raises(ObjectNotFound):
        sets.get_object("bkt", names[0])


def test_sets_multipart_routing(sets):
    uid = sets.new_multipart_upload("bkt", "mp-obj")
    e1 = sets.put_object_part("bkt", "mp-obj", uid, 1, b"x" * 1000)
    oi = sets.complete_multipart_upload("bkt", "mp-obj", uid, [(1, e1.etag)])
    assert oi.size == 1000
    _, got = sets.get_object("bkt", "mp-obj")
    assert got == b"x" * 1000


def test_sets_format_persistence(tmp_path):
    s1 = make_sets(tmp_path, "p")
    dep = s1.deployment_id
    s1.make_bucket("bkt")
    s1.put_object("bkt", "persistent", b"data")
    # reopen from the same dirs: same deployment id, same routing
    s2 = make_sets(tmp_path, "p")
    assert s2.deployment_id == dep
    _, got = s2.get_object("bkt", "persistent")
    assert got == b"data"


def test_heal_bucket_across_sets(sets):
    # drop the bucket from set 1 only
    sets.sets[1].delete_bucket("bkt", force=True)
    assert sets.heal_bucket("bkt") == 1
    sets.sets[1].get_bucket_info("bkt")


def test_pools_placement_and_read(tmp_path):
    p0 = make_sets(tmp_path, "pool0", set_count=1)
    p1 = make_sets(tmp_path, "pool1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    pools.put_object("bkt", "obj1", b"contents-1")
    _, got = pools.get_object("bkt", "obj1")
    assert got == b"contents-1"
    # overwrite goes to the pool that already has it
    pools.put_object("bkt", "obj1", b"contents-2")
    count = sum(1 for p in (p0, p1)
                if _has_object(p, "bkt", "obj1"))
    assert count == 1
    _, got = pools.get_object("bkt", "obj1")
    assert got == b"contents-2"
    pools.delete_object("bkt", "obj1")
    with pytest.raises(ObjectNotFound):
        pools.get_object("bkt", "obj1")


def _has_object(p, bucket, name):
    try:
        p.get_object_info(bucket, name)
        return True
    except Exception:  # noqa: BLE001
        return False


def test_pools_merge_listing(tmp_path):
    p0 = make_sets(tmp_path, "m0", set_count=1)
    p1 = make_sets(tmp_path, "m1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    # place objects directly on different pools (simulating history)
    p0.put_object("bkt", "a", b"1")
    p1.put_object("bkt", "b", b"2")
    out = pools.list_objects("bkt")
    assert [o.name for o in out.objects] == ["a", "b"]


def test_pools_multipart(tmp_path):
    p0 = make_sets(tmp_path, "q0", set_count=1)
    p1 = make_sets(tmp_path, "q1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    uid = pools.new_multipart_upload("bkt", "big")
    e1 = pools.put_object_part("bkt", "big", uid, 1, b"part-one")
    oi = pools.complete_multipart_upload("bkt", "big", uid, [(1, e1.etag)])
    assert oi.size == 8
    _, got = pools.get_object("bkt", "big")
    assert got == b"part-one"
