"""Sets + server-pools topology tests (mirrors the multi-set tier of the
reference suite: prepareErasureSets32, cmd/erasure-sets_test.go)."""

import pytest

from minio_tpu.objectlayer.interface import ObjectNotFound, PutObjectOptions
from minio_tpu.objectlayer.pools import ErasureServerPools
from minio_tpu.objectlayer.sets import ErasureSets
from minio_tpu.storage.xl_storage import XLStorage

BS = 64 * 1024


def make_sets(tmp_path, tag, set_count=2, drives=4, parity=2) -> ErasureSets:
    dirs = []
    for i in range(set_count * drives):
        d = tmp_path / f"{tag}-disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    return ErasureSets.from_dirs(
        dirs, set_count, drives, parity=parity, block_size=BS,
        backend="numpy")


@pytest.fixture
def sets(tmp_path):
    s = make_sets(tmp_path, "a")
    s.make_bucket("bkt")
    return s


def test_distribution_is_deterministic_and_spread(sets):
    idx = {name: sets.get_hashed_set_index(name)
           for name in (f"obj-{i}" for i in range(64))}
    # deterministic
    for name, i in idx.items():
        assert sets.get_hashed_set_index(name) == i
    # both sets get used
    assert set(idx.values()) == {0, 1}


def test_sets_roundtrip_and_listing(sets):
    names = [f"dir/obj-{i}" for i in range(10)]
    for n in names:
        sets.put_object("bkt", n, n.encode())
    for n in names:
        _, got = sets.get_object("bkt", n)
        assert got == n.encode()
    out = sets.list_objects("bkt", prefix="dir/")
    assert [o.name for o in out.objects] == sorted(names)
    # objects actually live on different sets
    on0 = sum(1 for n in names if sets.get_hashed_set_index(n) == 0)
    assert 0 < on0 < len(names)
    sets.delete_object("bkt", names[0])
    with pytest.raises(ObjectNotFound):
        sets.get_object("bkt", names[0])


def test_sets_multipart_routing(sets):
    uid = sets.new_multipart_upload("bkt", "mp-obj")
    e1 = sets.put_object_part("bkt", "mp-obj", uid, 1, b"x" * 1000)
    oi = sets.complete_multipart_upload("bkt", "mp-obj", uid, [(1, e1.etag)])
    assert oi.size == 1000
    _, got = sets.get_object("bkt", "mp-obj")
    assert got == b"x" * 1000


def test_sets_format_persistence(tmp_path):
    s1 = make_sets(tmp_path, "p")
    dep = s1.deployment_id
    s1.make_bucket("bkt")
    s1.put_object("bkt", "persistent", b"data")
    # reopen from the same dirs: same deployment id, same routing
    s2 = make_sets(tmp_path, "p")
    assert s2.deployment_id == dep
    _, got = s2.get_object("bkt", "persistent")
    assert got == b"data"


def test_heal_bucket_across_sets(sets):
    # drop the bucket from set 1 only
    sets.sets[1].delete_bucket("bkt", force=True)
    assert sets.heal_bucket("bkt") == 1
    sets.sets[1].get_bucket_info("bkt")


def test_pools_placement_and_read(tmp_path):
    p0 = make_sets(tmp_path, "pool0", set_count=1)
    p1 = make_sets(tmp_path, "pool1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    pools.put_object("bkt", "obj1", b"contents-1")
    _, got = pools.get_object("bkt", "obj1")
    assert got == b"contents-1"
    # overwrite goes to the pool that already has it
    pools.put_object("bkt", "obj1", b"contents-2")
    count = sum(1 for p in (p0, p1)
                if _has_object(p, "bkt", "obj1"))
    assert count == 1
    _, got = pools.get_object("bkt", "obj1")
    assert got == b"contents-2"
    pools.delete_object("bkt", "obj1")
    with pytest.raises(ObjectNotFound):
        pools.get_object("bkt", "obj1")


def _has_object(p, bucket, name):
    try:
        p.get_object_info(bucket, name)
        return True
    except Exception:  # noqa: BLE001
        return False


def test_pools_merge_listing(tmp_path):
    p0 = make_sets(tmp_path, "m0", set_count=1)
    p1 = make_sets(tmp_path, "m1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    # place objects directly on different pools (simulating history)
    p0.put_object("bkt", "a", b"1")
    p1.put_object("bkt", "b", b"2")
    out = pools.list_objects("bkt")
    assert [o.name for o in out.objects] == ["a", "b"]


def test_pools_multipart(tmp_path):
    p0 = make_sets(tmp_path, "q0", set_count=1)
    p1 = make_sets(tmp_path, "q1", set_count=1)
    pools = ErasureServerPools([p0, p1])
    pools.make_bucket("bkt")
    uid = pools.new_multipart_upload("bkt", "big")
    e1 = pools.put_object_part("bkt", "big", uid, 1, b"part-one")
    oi = pools.complete_multipart_upload("bkt", "big", uid, [(1, e1.etag)])
    assert oi.size == 8
    _, got = pools.get_object("bkt", "big")
    assert got == b"part-one"


# -- elastic topology (ISSUE 16): manifest, router, rebalance, decommission -

def _pools2(tmp_path, tag="e", secret=""):
    p0 = make_sets(tmp_path, f"{tag}0", set_count=1)
    p1 = make_sets(tmp_path, f"{tag}1", set_count=1)
    pools = ErasureServerPools([p0, p1], secret=secret)
    pools.make_bucket("bkt")
    return pools


def _names_on(pool, bucket="bkt"):
    return sorted(o.name for o in pool.list_object_versions(bucket))


def test_attach_pool_persists_manifest_and_survives_restart(tmp_path):
    from minio_tpu.objectlayer.pools import STATUS_DRAINING
    p0 = make_sets(tmp_path, "r0", set_count=1)
    pools = ErasureServerPools([p0], secret="topo-secret")
    pools.make_bucket("bkt")
    pools.put_object("bkt", "pre", b"before-expansion")
    dirs = []
    for i in range(4):
        d = tmp_path / f"r1-disk{i}"
        d.mkdir()
        dirs.append(str(d))
    idx = pools.attach_pool(dirs, 1, 4, parity=2, block_size=BS,
                            backend="numpy")
    assert idx == 1
    # duplicate attach refused (same deployment id)
    with pytest.raises(ValueError):
        pools.attach_pool(dirs, 1, 4, parity=2, block_size=BS,
                          backend="numpy")
    # the attached pool already has every existing bucket
    pools.pools[1].get_bucket_info("bkt")
    pools.start_decommission(1)
    # "restart": a fresh layer over pool 0's dirs adopts the manifest,
    # re-attaches pool 1 from its recorded dirs, re-applies draining
    p0b = make_sets(tmp_path, "r0", set_count=1)
    reborn = ErasureServerPools([p0b], secret="topo-secret")
    assert reborn.load_manifest()
    assert len(reborn.pools) == 2
    assert reborn.specs[1].status == STATUS_DRAINING
    assert reborn.specs[1].pool_id == pools.specs[1].pool_id
    _, got = reborn.get_object("bkt", "pre")
    assert got == b"before-expansion"


def test_router_skips_draining_pool_and_delete_reaches_all(tmp_path):
    from minio_tpu.objectlayer.interface import ObjectOptions
    pools = _pools2(tmp_path, "d")
    pools.start_decommission(1)
    # new writes never land on the draining pool
    for i in range(12):
        pools.put_object("bkt", f"fresh-{i}", b"x")
    assert _names_on(pools.pools[1]) == []
    pools.abort_decommission(1)
    # a name living on BOTH pools (mid-move shape) is deleted from all
    pools.pools[0].put_object("bkt", "both", b"v0")
    pools.pools[1].put_object("bkt", "both", b"v1")
    pools.delete_object("bkt", "both", ObjectOptions())
    assert "both" not in _names_on(pools.pools[0])
    assert "both" not in _names_on(pools.pools[1])


def test_decommission_guards(tmp_path):
    pools = _pools2(tmp_path, "g")
    with pytest.raises(ValueError):       # pool 0 = system volume
        pools.start_decommission(0)
    with pytest.raises(ValueError):       # unknown pool
        pools.start_decommission(7)
    with pytest.raises(ValueError):       # not draining
        pools.abort_decommission(1)
    pools.pools[1].put_object("bkt", "resident", b"x")
    pools.start_decommission(1)
    with pytest.raises(ValueError):       # last active pool
        pools.start_decommission(0)
    with pytest.raises(ValueError):       # not empty yet
        pools.finish_decommission(1)
    assert pools.decommission_pending(1) == (1, 0)


def test_multipart_pinned_to_starting_pool(tmp_path):
    pools = _pools2(tmp_path, "mp")
    uid = pools.new_multipart_upload("bkt", "pinned")
    home = pools._upload_pool("bkt", "pinned", uid)
    other = 1 if home is pools.pools[0] else 0
    # draining the OTHER pool must not disturb the pinned upload; and
    # even a drain of the HOME pool keeps in-flight uploads working
    if other != 0:
        pools.start_decommission(other)
    e1 = pools.put_object_part("bkt", "pinned", uid, 1, b"p" * 512)
    oi = pools.complete_multipart_upload("bkt", "pinned", uid,
                                         [(1, e1.etag)])
    assert oi.size == 512
    assert "pinned" in _names_on(home)
    # the pin record is dropped on complete
    from minio_tpu.storage.xl_storage import SYS_DIR
    res, _ = pools.pools[0]._fanout(
        lambda d: d.read_all(SYS_DIR, f"pools/uploads/{uid}.json"))
    assert all(b is None for b in res)


def test_move_version_preserves_identity_bit_identical(tmp_path):
    from minio_tpu.background.rebalance import move_version
    from minio_tpu.objectlayer.interface import ObjectOptions
    pools = _pools2(tmp_path, "mv")
    src, dst = pools.pools[1], pools.pools[0]
    # versioned object + a delete marker on top + a multipart object
    v1 = src.put_object("bkt", "ver", b"A" * 100,
                        PutObjectOptions(versioned=True,
                                         user_defined={"x-amz-meta-k":
                                                       "v"}))
    src.delete_object("bkt", "ver", ObjectOptions(versioned=True))
    uid = src.new_multipart_upload("bkt", "multi")
    e1 = src.put_object_part("bkt", "multi", uid, 1, b"B" * 1000)
    moi = src.complete_multipart_upload("bkt", "multi", uid,
                                        [(1, e1.etag)])
    before = {(o.name, o.version_id, o.etag, o.mod_time,
               o.delete_marker, o.size)
              for o in pools.list_object_versions("bkt")}
    for oi in list(src.list_object_versions("bkt")):
        move_version(pools, 1, 0, "bkt", oi)
    # source fully emptied; identities carried bit-identically
    assert _names_on(src) == []
    after = {(o.name, o.version_id, o.etag, o.mod_time,
              o.delete_marker, o.size)
             for o in pools.list_object_versions("bkt")}
    assert after == before
    got = dst.get_object_info("bkt", "ver",
                              ObjectOptions(version_id=v1.version_id))
    assert got.user_defined.get("x-amz-meta-k") == "v"
    assert got.etag == v1.etag
    mgot = dst.get_object_info("bkt", "multi")
    assert mgot.etag == moi.etag and "-" in mgot.etag
    assert mgot.parts == moi.parts
    _, body = dst.get_object("bkt", "multi")
    assert body == b"B" * 1000
    # idempotency: a repeated move (crash between copy and source
    # delete) is a no-op skip, not a duplicate
    dst2 = pools.pools[1]
    assert _names_on(dst2) == []


def test_rebalance_journal_crash_resume_no_lost_or_dup_versions(tmp_path):
    """The crash-resume pin: kill the rebalancer mid-drain (after the
    journal committed a partial cursor), resume with a FRESH
    rebalancer — the drain completes with zero lost and zero
    duplicated versions and the pool retires."""
    from minio_tpu.background import rebalance as rb_mod
    pools = _pools2(tmp_path, "cr")
    bodies = {}
    for i in range(6):
        name = f"obj-{i}"
        bodies[name] = f"payload-{i}".encode() * 20
        pools.pools[1].put_object("bkt", name, bodies[name])
    pools.start_decommission(1)
    rb1 = rb_mod.Rebalancer(pools, interval_s=3600.0)
    moves = {"n": 0}
    real_move = rb_mod.move_version

    def dying_move(*a, **kw):
        if moves["n"] >= 3:
            raise RuntimeError("simulated crash mid-drain")
        moves["n"] += 1
        return real_move(*a, **kw)

    rb_mod.move_version = dying_move
    try:
        with pytest.raises(RuntimeError):
            rb1.rebalance_pool(1)
    finally:
        rb_mod.move_version = real_move
    # the journal recorded partial progress
    j = rb1.load_journal()
    assert j is not None and j["state"] == "running"
    assert j["cursor"] or j["doneBuckets"]
    # "restart": a fresh rebalancer resumes from the journal
    rb2 = rb_mod.Rebalancer(pools, interval_s=3600.0)
    assert rb2.run_once()
    # pool retired; every object exactly once, bytes intact
    assert len(pools.pools) == 1
    assert _names_on(pools.pools[0]) == sorted(bodies)
    for name, body in bodies.items():
        _, got = pools.get_object("bkt", name)
        assert got == body
    assert rb2.load_journal()["state"] == "done"
    # no version appears twice
    vers = [(o.name, o.version_id)
            for o in pools.list_object_versions("bkt")]
    assert len(vers) == len(set(vers))


# -- admin surface conformance (ISSUE 16): topology routes, remote-target
# removal, per-pool usage exposition ----------------------------------------


def test_admin_topology_routes_and_pool_usage_scrape(tmp_path, monkeypatch):
    """One live server over a pools layer: every topology admin route,
    remote-target set/list/remove round-trip, crawler per-pool usage,
    and the ``mt_pool_usage_*{pool=...}`` / ``mt_rebalance_*`` metric
    families on a real 2-pool scrape."""
    import json

    from minio_tpu.admin.client import AdminClient, AdminError
    from minio_tpu.background.crawler import Crawler, load_usage
    from minio_tpu.background.rebalance import Rebalancer
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server

    monkeypatch.setenv("MT_REBALANCE_ENABLE", "on")
    pools = ErasureServerPools([make_sets(tmp_path, "adm0", set_count=1)])
    srv = S3Server(pools, access_key="admin", secret_key="adminpw",
                   host="127.0.0.1", port=0)
    srv.iam.load()
    rb = Rebalancer(pools, interval_s=3600.0)
    crawler = Crawler(pools, bucket_meta=srv.bucket_meta,
                      interval_s=3600.0)
    srv.crawler = crawler
    srv.attach_background(rb, crawler)
    assert rb.enabled, "MT_REBALANCE_ENABLE=on must enable via kvconfig"
    srv.start()
    try:
        s3 = S3Client(srv.endpoint, "admin", "adminpw")
        adm = AdminClient(srv.endpoint, "admin", "adminpw")
        s3.make_bucket("bkt")
        for i in range(6):
            s3.put_object("bkt", f"obj-{i}", bytes([i]) * 100)

        st = adm.pool_status()
        assert len(st["pools"]) == 1
        assert st["pools"][0]["status"] == "active"

        ndirs = []
        for i in range(4):
            d = tmp_path / f"adm1-disk{i}"
            d.mkdir()
            ndirs.append(str(d))
        r = adm.pool_add(ndirs, 1, 4, backend="numpy", parity=2,
                         block_size=BS)
        assert r["pool"] == 1
        assert len(adm.pool_status()["pools"]) == 2

        # crawler cycle feeds per-pool usage into status + scrape
        crawler.run_cycle()
        info = load_usage(pools)
        assert info.pools_usage
        assert sum(u["objects"] for u in info.pools_usage.values()) == 6
        st = adm.pool_status()
        assert any("usedBytes" in row for row in st["pools"])

        doc = s3.request("GET", "/minio-tpu/metrics", "", b"",
                         expect=(200,)).body.decode()
        assert 'mt_pool_usage_bytes{' in doc
        assert 'mt_pool_usage_objects{' in doc
        # the pool label is the stable pool_id (survives index shifts
        # after a decommission) — one series per attached pool
        for sp in pools.specs:
            assert f'pool="{sp.pool_id}"' in doc
        assert "mt_rebalance_moved_objects_total" in doc

        # storageinfo carries the pools section (satellite 4 pin)
        raw = s3.request("GET", "/minio-tpu/admin/v1/storageinfo", "",
                         b"", expect=(200,))
        si = json.loads(raw.body)
        assert len(si.get("pools", [])) == 2

        # decommission lifecycle over the wire: drain, abort, guard
        assert adm.pool_decommission("1")["status"] == "draining"
        assert adm.pool_status()["pools"][1]["status"] == "draining"
        assert adm.pool_decommission_abort("1")["status"] == "active"
        with pytest.raises(AdminError) as ei:
            adm.pool_decommission("0")  # carries the system volume
        assert ei.value.status == 400

        rs = adm.rebalance_status()
        assert rs["enabled"] is True and "stats" in rs

        raw = s3.request("GET", "/minio-tpu/admin/v1/background-status",
                         "", b"", expect=(200,))
        assert json.loads(raw.body)["rebalance"] is not None

        # remote-target removal round-trip (admin-parity row 49)
        adm.set_remote_target("bkt", {
            "arn": "arn:x", "endpoint": "127.0.0.1:1",
            "target_bucket": "tb"})
        assert "bkt" in adm.list_remote_targets()
        adm.remove_remote_target("bkt")
        assert "bkt" not in adm.list_remote_targets()
        with pytest.raises(AdminError) as ei:
            adm.remove_remote_target("no-such-bucket")
        assert ei.value.status == 404

        # live config write dispatches to the running rebalancer
        adm.set_config_kv("rebalance", "max_workers", "3")
        assert rb.max_workers == 3
    finally:
        srv.stop()
