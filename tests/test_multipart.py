"""Multipart upload tests — object layer + S3 API
(mirrors cmd/erasure-multipart.go behavior and the reference's
object-handlers multipart suites)."""

import numpy as np
import pytest

from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.objectlayer.interface import (InvalidPart, InvalidPartOrder,
                                             InvalidUploadID,
                                             PutObjectOptions)
from minio_tpu.s3.client import S3Client, S3ClientError
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.xl_storage import XLStorage

BS = 128 * 1024


def make_layer(tmp_path, n=4, parity=2):
    disks = []
    for i in range(n):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=parity, block_size=BS,
                          backend="numpy", enforce_min_part_size=False)


@pytest.fixture
def er(tmp_path):
    layer = make_layer(tmp_path)
    layer.make_bucket("bkt")
    return layer


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_multipart_roundtrip(er):
    uid = er.new_multipart_upload("bkt", "big.bin")
    p1 = _data(BS + 100, 1)
    p2 = _data(2 * BS, 2)
    p3 = _data(777, 3)
    e1 = er.put_object_part("bkt", "big.bin", uid, 1, p1)
    e2 = er.put_object_part("bkt", "big.bin", uid, 2, p2)
    e3 = er.put_object_part("bkt", "big.bin", uid, 3, p3)
    parts = er.list_object_parts("bkt", "big.bin", uid)
    assert [p.part_number for p in parts] == [1, 2, 3]
    oi = er.complete_multipart_upload(
        "bkt", "big.bin", uid, [(1, e1.etag), (2, e2.etag), (3, e3.etag)])
    assert oi.etag.endswith("-3")
    assert oi.size == len(p1) + len(p2) + len(p3)
    _, got = er.get_object("bkt", "big.bin")
    assert got == p1 + p2 + p3
    # upload dir cleaned up
    with pytest.raises(InvalidUploadID):
        er.list_object_parts("bkt", "big.bin", uid)


def test_multipart_part_overwrite(er):
    uid = er.new_multipart_upload("bkt", "obj")
    er.put_object_part("bkt", "obj", uid, 1, b"old-part-content")
    e1b = er.put_object_part("bkt", "obj", uid, 1, b"new")
    oi = er.complete_multipart_upload("bkt", "obj", uid, [(1, e1b.etag)])
    _, got = er.get_object("bkt", "obj")
    assert got == b"new"
    assert oi.size == 3


def test_multipart_bad_etag_and_order(er):
    uid = er.new_multipart_upload("bkt", "obj")
    e1 = er.put_object_part("bkt", "obj", uid, 1, b"a" * 100)
    e2 = er.put_object_part("bkt", "obj", uid, 2, b"b" * 100)
    with pytest.raises(InvalidPart):
        er.complete_multipart_upload("bkt", "obj", uid, [(1, "deadbeef" * 4)])
    with pytest.raises(InvalidPartOrder):
        er.complete_multipart_upload("bkt", "obj", uid,
                                     [(2, e2.etag), (1, e1.etag)])
    with pytest.raises(InvalidPart):
        er.put_object_part("bkt", "obj", uid, 0, b"x")


def test_multipart_abort(er):
    uid = er.new_multipart_upload("bkt", "obj")
    er.put_object_part("bkt", "obj", uid, 1, b"data")
    assert len(er.list_multipart_uploads("bkt")) == 1
    er.abort_multipart_upload("bkt", "obj", uid)
    assert er.list_multipart_uploads("bkt") == []
    with pytest.raises(InvalidUploadID):
        er.put_object_part("bkt", "obj", uid, 2, b"more")


def test_unknown_upload_id(er):
    with pytest.raises(InvalidUploadID):
        er.put_object_part("bkt", "obj", "nope", 1, b"x")
    with pytest.raises(InvalidUploadID):
        er.complete_multipart_upload("bkt", "obj", "nope", [])


def test_multipart_metadata_preserved(er):
    uid = er.new_multipart_upload(
        "bkt", "obj", PutObjectOptions(
            user_defined={"content-type": "text/x-part",
                          "x-amz-meta-tag": "v"}))
    e1 = er.put_object_part("bkt", "obj", uid, 1, b"payload")
    er.complete_multipart_upload("bkt", "obj", uid, [(1, e1.etag)])
    oi = er.get_object_info("bkt", "obj")
    assert oi.content_type == "text/x-part"
    assert oi.user_defined.get("x-amz-meta-tag") == "v"


def test_multipart_over_http(tmp_path):
    layer = make_layer(tmp_path, n=4, parity=2)
    srv = S3Server(layer, access_key="k", secret_key="s")
    srv.start()
    try:
        c = S3Client(srv.endpoint, "k", "s")
        c.make_bucket("mpb")
        # initiate
        r = c.request("POST", "/mpb/file.bin", "uploads")
        uid = r.xml().findtext(
            "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId")
        assert uid
        data1, data2 = _data(BS, 7), _data(100, 8)
        r1 = c.request("PUT", "/mpb/file.bin",
                       f"partNumber=1&uploadId={uid}", data1)
        r2 = c.request("PUT", "/mpb/file.bin",
                       f"partNumber=2&uploadId={uid}", data2)
        body = (
            '<CompleteMultipartUpload>'
            f'<Part><PartNumber>1</PartNumber><ETag>{r1.headers["ETag"]}'
            '</ETag></Part>'
            f'<Part><PartNumber>2</PartNumber><ETag>{r2.headers["ETag"]}'
            '</ETag></Part>'
            '</CompleteMultipartUpload>').encode()
        r = c.request("POST", "/mpb/file.bin", f"uploadId={uid}", body)
        assert b"CompleteMultipartUploadResult" in r.body
        g = c.get_object("mpb", "file.bin")
        assert g.body == data1 + data2
        assert g.headers["ETag"].strip('"').endswith("-2")
    finally:
        srv.stop()
