"""Sanitizer tier for the native libraries (buildscripts/race.sh role).

All four C/C++ libraries (native/gf8.cc, native/snappy.cc,
native/jsonscan.cc, hashing/native/highwayhash.c) are rebuilt with
``-fsanitize=address,undefined`` into a scratch build dir
(MT_NATIVE_BUILD_DIR) and exercised — through their normal Python
bindings, under concurrent load — in a subprocess running with libasan
preloaded.  Any ASan/UBSan report fails the run.

A canary proves the harness has teeth: a deliberately buggy library
built and driven the same way MUST be caught.
"""

import os
import subprocess
import sys
import textwrap

import pytest

# slow: full ASan/UBSan rebuilds of every native library — runs in the
# full tier, not the tier-1 `-m 'not slow'` budget (VERDICT weak #5)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _libasan() -> str | None:
    try:
        out = subprocess.run(["g++", "-print-file-name=libasan.so"],
                             capture_output=True, text=True, timeout=30)
        path = out.stdout.strip()
        return path if path and os.path.exists(path) else None
    except (OSError, subprocess.TimeoutExpired):
        return None


asan = pytest.mark.skipif(_libasan() is None,
                          reason="libasan not available")


def _run_sanitized(code: str, tmp_path) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": _libasan(),
        "MT_NATIVE_BUILD_DIR": str(tmp_path / "san-build"),
        "MT_NATIVE_CFLAGS":
            "-fsanitize=address,undefined -fno-sanitize-recover=all -g",
        # python itself leaks by design at exit; halt_on_error keeps
        # real findings fatal
        "ASAN_OPTIONS": "detect_leaks=0:halt_on_error=1:abort_on_error=1",
        "UBSAN_OPTIONS": "halt_on_error=1:abort_on_error=1",
        "JAX_PLATFORMS": "cpu",
    })
    return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=600)


WORKLOAD = textwrap.dedent("""
    import os, threading
    import numpy as np

    errors = []

    def gf8_work():
        from minio_tpu.ops import gf8_native, gf8
        assert gf8_native.available(), "gf8 sanitized build failed"
        M = np.asarray(gf8.rs_matrix(8, 12))[8:]
        rng = np.random.default_rng(0)
        for n in (1, 31, 64, 4096, 87382):      # incl. GFNI tail sizes
            B = rng.integers(0, 256, (8, n), dtype=np.uint8)
            out = np.empty((4, n), dtype=np.uint8)
            gf8_native.matmul_into(M, B, out)
            exp = gf8_native.matmul(M, B)
            assert np.array_equal(out, exp)

    def snappy_work():
        from minio_tpu import compress
        if not compress.native_available():
            return
        for size in (0, 1, 100, 70000):
            blob = os.urandom(size // 2) * 2
            assert compress.decompress_block(
                compress.compress_block(blob)) == blob
            assert compress.decompress_stream(
                compress.compress_stream(blob)) == blob

    def hh_work():
        from minio_tpu.hashing import highwayhash as hh
        for size in (0, 1, 31, 32, 33, 1024, 87382):
            hh.hh256(os.urandom(size))

    def jsonscan_work():
        from minio_tpu.s3select import records
        data = b'\\n'.join(
            b'{"k":"v%d","n":%d}' % (i, i) for i in range(200)) + b'\\n'
        records.ndjson_prefilter(data, "k", "=", "v7")
        records.ndjson_prefilter(data, "n", ">", 100)

    def run(fn):
        try:
            for _ in range(5):
                fn()
        except Exception as e:      # noqa: BLE001
            errors.append(f"{fn.__name__}: {e!r}")

    threads = [threading.Thread(target=run, args=(f,))
               for f in (gf8_work, snappy_work, hh_work, jsonscan_work)
               for _ in range(3)]
    for t in threads: t.start()
    for t in threads: t.join()
    assert not errors, errors
    print("SANITIZED-WORKLOAD-OK")
""")


@asan
def test_native_libs_clean_under_asan_ubsan(tmp_path):
    res = _run_sanitized(WORKLOAD, tmp_path)
    assert "SANITIZED-WORKLOAD-OK" in res.stdout, \
        f"stdout={res.stdout[-2000:]}\nstderr={res.stderr[-4000:]}"
    for marker in ("AddressSanitizer", "runtime error:",
                   "SUMMARY: UndefinedBehaviorSanitizer"):
        assert marker not in res.stderr, res.stderr[-4000:]
    assert res.returncode == 0


CANARY_SRC = textwrap.dedent("""
    #include <cstring>
    extern "C" int mt_canary(const unsigned char* src, int n) {
        unsigned char buf[8];
        std::memcpy(buf, src, n);     // n > 8 overflows the stack buf
        return buf[0];
    }
""")

CANARY_DRIVER = textwrap.dedent("""
    import ctypes, os
    from minio_tpu.utils import nativelib
    src = os.environ["CANARY_SRC"]
    so = os.path.join(os.environ["MT_NATIVE_BUILD_DIR"], "libcanary.so")
    lib = nativelib.load(src, so)
    assert lib is not None, "canary build failed"
    lib.mt_canary(b"x" * 64, 64)      # overflow -> ASan must abort
    print("CANARY-SURVIVED")          # must never print
""")


@asan
def test_harness_catches_injected_overflow(tmp_path):
    """The tier is only evidence if it FAILS on a real bug."""
    src = tmp_path / "canary.cc"
    src.write_text(CANARY_SRC)
    env_extra = {"CANARY_SRC": str(src)}
    code = CANARY_DRIVER
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": _libasan(),
        "MT_NATIVE_BUILD_DIR": str(tmp_path / "san-build"),
        "MT_NATIVE_CFLAGS":
            "-fsanitize=address,undefined -fno-sanitize-recover=all -g",
        "ASAN_OPTIONS": "detect_leaks=0:halt_on_error=1:abort_on_error=1",
        **env_extra,
    })
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=300)
    assert res.returncode != 0, "injected overflow was NOT caught"
    assert "CANARY-SURVIVED" not in res.stdout
    assert "AddressSanitizer" in res.stderr, res.stderr[-2000:]
