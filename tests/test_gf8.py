"""GF(2^8) field / matrix / shard-math unit tests.

Mirrors the codec-level tier of the reference test strategy (SURVEY.md §4;
cmd/erasure_test.go, cmd/erasure-coding.go shard math).
"""

import numpy as np
import pytest

from minio_tpu.ops import gf8


def test_exp_log_tables():
    # generator walk: exp[0]=1, exp[1]=2, exp[8]=0x1d (x^8 reduced by 0x11d)
    assert gf8.GF_EXP[0] == 1
    assert gf8.GF_EXP[1] == 2
    assert gf8.GF_EXP[8] == 0x1D
    assert gf8.GF_LOG[1] == 0
    assert gf8.GF_LOG[2] == 1
    # log/exp inverses
    for a in range(1, 256):
        assert gf8.GF_EXP[gf8.GF_LOG[a]] == a


def test_mul_table_properties():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, 1000).astype(np.uint8)
    b = rng.integers(0, 256, 1000).astype(np.uint8)
    c = rng.integers(0, 256, 1000).astype(np.uint8)
    # commutative, zero, one
    assert np.array_equal(gf8.gf_mul(a, b), gf8.gf_mul(b, a))
    assert np.all(gf8.gf_mul(a, 0) == 0)
    assert np.array_equal(gf8.gf_mul(a, 1), a)
    # distributive over XOR
    assert np.array_equal(
        gf8.gf_mul(a, b ^ c), gf8.gf_mul(a, b) ^ gf8.gf_mul(a, c))
    # known value in this field: 0x80 * 2 = 0x11d & 0xff ^ 0x100 -> 0x1d
    assert gf8.gf_mul(0x80, 2) == 0x1D


def test_inverse_table():
    for a in range(1, 256):
        assert gf8.gf_mul(a, gf8.GF_INV[a]) == 1


def test_matrix_systematic():
    for k, m in [(2, 2), (4, 2), (8, 4), (12, 4), (16, 4), (5, 5)]:
        M = gf8.rs_matrix(k, k + m)
        assert M.shape == (k + m, k)
        assert np.array_equal(M[:k], np.eye(k, dtype=np.uint8))
        # any k rows must be invertible (MDS property of Vandermonde-derived)
        rng = np.random.default_rng(k * 31 + m)
        for _ in range(5):
            rows = sorted(rng.choice(k + m, size=k, replace=False))
            gf8.gf_mat_inv(M[rows])  # must not raise


def test_cauchy_mds():
    for k, m in [(4, 4), (12, 4)]:
        M = gf8.cauchy_matrix(k, k + m)
        assert np.array_equal(M[:k], np.eye(k, dtype=np.uint8))
        rng = np.random.default_rng(1)
        for _ in range(5):
            rows = sorted(rng.choice(k + m, size=k, replace=False))
            gf8.gf_mat_inv(M[rows])


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(2)
    for n in (1, 2, 5, 16):
        while True:
            M = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                Mi = gf8.gf_mat_inv(M)
                break
            except ValueError:
                continue
        assert np.array_equal(gf8.gf_matmul(M, Mi), np.eye(n, dtype=np.uint8))


def test_singular_raises():
    M = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        gf8.gf_mat_inv(M)


def test_gf2_expand_matches_gf_mul():
    rng = np.random.default_rng(3)
    M = rng.integers(0, 256, (4, 12)).astype(np.uint8)
    d = rng.integers(0, 256, (12, 33)).astype(np.uint8)
    want = gf8.gf_matmul(M, d)
    # bit-domain: expand, unpack, binary matmul mod 2, pack
    M2 = gf8.gf2_expand(M)
    bits = ((d[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(96, 33)
    out_bits = (M2.astype(np.int32) @ bits.astype(np.int32)) & 1
    out = np.zeros((4, 33), dtype=np.uint8)
    for b in range(8):
        out |= (out_bits.reshape(4, 8, 33)[:, b] << b).astype(np.uint8)
    assert np.array_equal(out, want)


# -- shard math: bit-identical with cmd/erasure-coding.go:115-143 ----------

def test_shard_size():
    assert gf8.shard_size(10 * 1024 * 1024, 10) == 1024 * 1024
    assert gf8.shard_size(1, 10) == 1
    assert gf8.shard_size(10, 3) == 4


@pytest.mark.parametrize("k,bs,total,want", [
    # mirrors ShardFileSize: numShards*ShardSize + ceil(lastBlock/k)
    (10, 10 * 1024 * 1024, 0, 0),
    (10, 10 * 1024 * 1024, -1, -1),
    (10, 10 * 1024 * 1024, 10 * 1024 * 1024, 1024 * 1024),
    (10, 10 * 1024 * 1024, 10 * 1024 * 1024 + 1, 1024 * 1024 + 1),
    (4, 1024, 4096 + 100, 4 * 256 + 25),
])
def test_shard_file_size(k, bs, total, want):
    assert gf8.shard_file_size(bs, k, total) == want


def test_shard_file_offset_clamps():
    bs, k, total = 1024, 4, 10000
    sfs = gf8.shard_file_size(bs, k, total)
    assert gf8.shard_file_offset(bs, k, 0, total, total) == sfs
    # mid-range read covers only the blocks it touches
    off = gf8.shard_file_offset(bs, k, 0, 1, total)
    assert off == gf8.shard_size(bs, k)


def test_split_padding():
    data = bytes(range(10))
    shards = gf8.split(data, 3)
    assert shards.shape == (3, 4)
    assert bytes(shards[0]) == b"\x00\x01\x02\x03"
    assert bytes(shards[2]) == b"\x08\x09\x00\x00"  # zero-padded tail
    with pytest.raises(ValueError):
        gf8.split(b"", 3)


def test_ceil_frac_negatives():
    # bit-identical with cmd/utils.go:613 (truncate toward zero, bump only
    # positive inexact quotients, zero denominator -> 0)
    assert gf8.ceil_frac(7, 2) == 4
    assert gf8.ceil_frac(-7, 2) == -3
    assert gf8.ceil_frac(7, -2) == -3
    assert gf8.ceil_frac(-7, -2) == 4
    assert gf8.ceil_frac(0, 5) == 0
    assert gf8.ceil_frac(10, 0) == 0
    assert gf8.ceil_frac(6, 2) == 3
