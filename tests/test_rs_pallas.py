"""Conformance tests for the fused Pallas RS kernel (ops/rs_pallas.py).

Runs the kernel in the pallas interpreter on CPU; bit-identical
agreement with the host reference codec (gf8_ref) and the XLA
formulation (rs_kernels) is the contract — the TPU path must produce
the same shards the drives already hold (cmd/erasure-coding.go:56).
"""

import numpy as np
import pytest

from minio_tpu.ops import gf8, rs_kernels, rs_pallas


def _rand(shape, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=shape, dtype=np.uint8)


def test_bitmajor_expansion_equivalent():
    """Bit-major permuted matrix computes the same GF product."""
    M = np.asarray(gf8.rs_matrix(4, 6))[4:]          # (2, 4) parity rows
    E = gf8.gf2_expand(M)                            # shard-major
    Ebm = rs_pallas.expand_bitmajor(M)               # bit-major
    data = _rand((4, 16))
    # shard-major product
    bits = ((data[:, None, :] >> np.arange(8)[None, :, None]) & 1)
    bits_sm = bits.reshape(32, 16)
    out_sm = (E.astype(np.int32) @ bits_sm) & 1
    # bit-major product, rows b*k+j
    bits_bm = np.concatenate([(data >> b) & 1 for b in range(8)], axis=0)
    out_bm = (Ebm.astype(np.int32) @ bits_bm) & 1
    # repack both and compare
    sm = sum(out_sm.reshape(2, 8, 16)[:, b] << b for b in range(8))
    bm = sum(out_bm.reshape(8, 2, 16)[b] << b for b in range(8))
    np.testing.assert_array_equal(sm, bm)


@pytest.mark.parametrize("k,m", [(4, 2), (12, 4)])
def test_encode_matches_reference(k, m):
    data = _rand((3, k, 300), seed=k)
    M = np.asarray(gf8.rs_matrix(k, k + m))
    got = np.asarray(rs_pallas.apply_matrix(M[k:], data, interpret=True))
    want = np.stack([gf8.gf_matmul(M[k:], d) for d in data])
    np.testing.assert_array_equal(got, want)


def test_matches_xla_formulation():
    k, m = 12, 4
    data = _rand((2, k, 1000), seed=7)
    M = np.asarray(gf8.rs_matrix(k, k + m))
    got = np.asarray(rs_pallas.apply_matrix(M[k:], data, interpret=True))
    want = rs_kernels.apply_matrix(np.asarray(M[k:]), data)
    np.testing.assert_array_equal(got, np.asarray(want))


def test_decode_roundtrip():
    k, m = 12, 4
    M = np.asarray(gf8.rs_matrix(k, k + m))
    data = _rand((2, k, 200), seed=3)
    parity = np.asarray(rs_pallas.apply_matrix(M[k:], data, interpret=True))
    # lose shards 0 and 1; reconstruct from 2..13
    present = list(range(2, k + 2))
    rows = rs_kernels.decode_rows(M, k, present, [0, 1])
    full = np.concatenate([data, parity], axis=1)
    survivors = full[:, present, :]
    rebuilt = np.asarray(
        rs_pallas.apply_matrix(rows, survivors, interpret=True))
    np.testing.assert_array_equal(rebuilt, full[:, :2, :])


def test_rs_kernels_dispatcher_pallas_branch(monkeypatch):
    """The production dispatcher (rs_kernels.apply_matrix) must produce
    identical results when routed through the pallas kernel — this is
    the default TPU path but the CPU suite otherwise never runs it."""
    monkeypatch.setenv("MT_RS_PALLAS", "1")
    k, m = 12, 4
    M = np.asarray(gf8.rs_matrix(k, k + m))
    for B, n in [(1, 300), (2, 128), (70, 1000)]:   # chunking + padding
        data = _rand((B, k, n), seed=B)
        got = rs_kernels.apply_matrix(np.asarray(M[k:]), data)
        monkeypatch.setenv("MT_RS_PALLAS", "0")
        want = rs_kernels.apply_matrix(np.asarray(M[k:]), data)
        monkeypatch.setenv("MT_RS_PALLAS", "1")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # 2-D squeeze contract
    data2 = _rand((k, 257), seed=9)
    got2 = rs_kernels.apply_matrix(np.asarray(M[k:]), data2)
    want2 = gf8.gf_matmul(M[k:], data2)
    assert got2.shape == (m, 257)
    np.testing.assert_array_equal(np.asarray(got2), want2)


def test_lane_padding_roundtrip():
    """n not a multiple of the kernel tile is padded and cropped."""
    k, m = 4, 2
    M = np.asarray(gf8.rs_matrix(k, k + m))
    for n in (1, 127, 128, 129, 4097):
        data = _rand((1, k, n), seed=n)
        got = np.asarray(
            rs_pallas.apply_matrix(M[k:], data, interpret=True))
        want = gf8.gf_matmul(M[k:], data[0])
        np.testing.assert_array_equal(got[0], want)
