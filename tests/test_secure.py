"""Secrets-at-rest + external-policy tier (ISSUE 13 tentpoles b + c):
the ctypes-libcrypto AES-GCM backend against NIST vectors, the sealed
config/IAM persistence format (ciphertext on every drive, plaintext
migration, credentials-rotation re-seal), and the OPA-shaped webhook
authorizer end to end through live ``is_allowed`` calls.
"""

import glob
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from minio_tpu.crypto import dare
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.s3.client import S3Client, S3ClientError
from minio_tpu.s3.server import S3Server
from minio_tpu.secure import configcrypt
from minio_tpu.storage.xl_storage import SYS_DIR, XLStorage


def _layer(tmp_path, n=4, sub="drv"):
    disks = []
    for i in range(n):
        d = tmp_path / f"{sub}{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=2, block_size=64 * 1024,
                          backend="numpy")


# -- libcrypto backend ------------------------------------------------------

def test_backend_present_on_this_image():
    """The whole point of the libcrypto ladder: the bare image (no
    cryptography wheel) still gets a working AES-GCM engine — this
    repo's CI MUST run the crypto tiers, not skip them."""
    assert dare.backend_available(), dare.BACKEND
    assert dare.BACKEND in ("cryptography", "libcrypto")


def test_libcrypto_matches_nist_gcm_vector():
    """AES-256-GCM NIST test case (key/IV/PT/AAD with known CT+tag):
    the ctypes EVP binding must produce bit-identical output to the
    published vector — not merely round-trip with itself."""
    from minio_tpu.crypto import libcrypto
    if not libcrypto.available():
        pytest.skip(f"libcrypto unavailable: "
                    f"{libcrypto.unavailable_reason()}")
    key = bytes.fromhex("feffe9928665731c6d6a8f9467308308"
                        "feffe9928665731c6d6a8f9467308308")
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    pt = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d"
        "8a318a721c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657"
        "ba637b39")
    aad = bytes.fromhex("feedfacedeadbeeffeedfacedeadbeefabaddad2")
    ct = bytes.fromhex(
        "522dc1f099567d07f47f37a32a84427d643a8cdcbfe5c0c97598a2bd"
        "2555d1aa8cb08e48590dbb3da7b08b1056828838c5f61e6393ba7a0a"
        "bcc9f662")
    tag = bytes.fromhex("76fc6ece0f4e1768cddf8853bb2d551b")
    aead = libcrypto.AESGCM(key)
    assert aead.encrypt(iv, pt, aad) == ct + tag
    assert aead.decrypt(iv, ct + tag, aad) == pt
    with pytest.raises(libcrypto.InvalidTag):
        aead.decrypt(iv, ct + bytes(16), aad)
    with pytest.raises(libcrypto.InvalidTag):
        aead.decrypt(iv, ct + tag, b"wrong-aad")


# -- configcrypt format -----------------------------------------------------

def test_configcrypt_roundtrip_and_wrong_secret():
    blob = configcrypt.encrypt_data("topsecret", b'{"a": 1}')
    assert configcrypt.is_encrypted(blob)
    assert b'"a"' not in blob
    assert configcrypt.decrypt_data("topsecret", blob) == b'{"a": 1}'
    with pytest.raises(configcrypt.DecryptError):
        configcrypt.decrypt_data("wrong", blob)


def test_configcrypt_maybe_decrypt_migration_paths():
    sealed = configcrypt.encrypt_data("new", b"doc")
    # current secret: no re-seal needed
    assert configcrypt.maybe_decrypt("new", sealed) == (b"doc", False)
    # retired secret opens it and flags the re-seal
    old_sealed = configcrypt.encrypt_data("old", b"doc")
    assert configcrypt.maybe_decrypt("new", old_sealed,
                                     ("old",)) == (b"doc", True)
    # plaintext parses and flags migration (backend present here)
    assert configcrypt.maybe_decrypt("new", b"doc") == (b"doc", True)
    with pytest.raises(configcrypt.DecryptError):
        configcrypt.maybe_decrypt("new", old_sealed, ("alsowrong",))


# -- at-rest e2e ------------------------------------------------------------

PLAINTEXT_MARKERS = (b'"users"', b'"policies"', b'"groups"', b'"ak"',
                     b'"dynamic"', b'requests_max')


def _sys_blobs(tmp_path, name):
    out = {}
    for f in glob.glob(str(tmp_path / "*" / SYS_DIR / "config" / name)):
        with open(f, "rb") as fh:
            out[f] = fh.read()
    return out


def test_iam_and_config_are_ciphertext_on_every_drive(tmp_path):
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="rootk",
                   secret_key="root-secret-key")
    srv.iam.add_user("carol", "carol-secret-12", policies=["readwrite"])
    srv.config.set("api", "requests_max", "77")
    iam_blobs = _sys_blobs(tmp_path, "iam.json")
    cfg_blobs = _sys_blobs(tmp_path, "config.json")
    assert len(iam_blobs) == 4 and len(cfg_blobs) == 4
    for blob in {**iam_blobs, **cfg_blobs}.values():
        assert blob.startswith(configcrypt.MAGIC)
        assert b"carol-secret-12" not in blob
        assert b"root-secret-key" not in blob
        for marker in PLAINTEXT_MARKERS:
            assert marker not in blob
    # a fresh server over the same drives + creds reads it all back
    srv2 = S3Server(layer, access_key="rootk",
                    secret_key="root-secret-key")
    srv2.iam.load()
    assert srv2.iam.lookup_secret("carol") == "carol-secret-12"
    assert srv2.config.get("api", "requests_max") == "77"


def test_plaintext_state_migrates_to_ciphertext_on_load(tmp_path):
    """A pre-ISSUE-13 deployment left plaintext JSON on the drives:
    it must still load, and the very load re-seals it in place."""
    layer = _layer(tmp_path)
    plain_iam = json.dumps({
        "users": {"dave": {"ak": "dave", "sk": "dave-secret-123",
                           "status": "enabled",
                           "policies": ["readwrite"], "groups": [],
                           "parent": "", "exp": 0, "spol": ""}},
        "policies": {}, "groups": {}, "ldap_policies": {}}).encode()
    plain_cfg = json.dumps({"api": {"requests_max": "33"}}).encode()
    layer._fanout(lambda d: d.write_all(SYS_DIR, "config/iam.json",
                                        plain_iam))
    layer._fanout(lambda d: d.write_all(SYS_DIR, "config/config.json",
                                        plain_cfg))
    srv = S3Server(layer, access_key="rootk", secret_key="migr-secret")
    srv.iam.load()
    assert srv.iam.lookup_secret("dave") == "dave-secret-123"
    assert srv.config.get("api", "requests_max") == "33"
    for blob in {**_sys_blobs(tmp_path, "iam.json"),
                 **_sys_blobs(tmp_path, "config.json")}.values():
        assert blob.startswith(configcrypt.MAGIC)
        assert b"dave-secret-123" not in blob


def test_credentials_rotation_reseals_in_place(tmp_path, monkeypatch):
    """Boot with rotated admin credentials + MT_ADMIN_SECRET_OLD: the
    state sealed under the retired secret loads AND lands back on disk
    sealed under the NEW one (the old secret can no longer open it)."""
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="rootk", secret_key="old-secret")
    srv.iam.add_user("erin", "erin-secret-123")
    srv.config.set("api", "requests_max", "55")
    monkeypatch.setenv("MT_ADMIN_SECRET_OLD", "old-secret")
    srv2 = S3Server(layer, access_key="rootk", secret_key="new-secret")
    srv2.iam.load()
    assert srv2.iam.lookup_secret("erin") == "erin-secret-123"
    assert srv2.config.get("api", "requests_max") == "55"
    monkeypatch.delenv("MT_ADMIN_SECRET_OLD")
    for blob in {**_sys_blobs(tmp_path, "iam.json"),
                 **_sys_blobs(tmp_path, "config.json")}.values():
        assert configcrypt.decrypt_data("new-secret", blob)
        with pytest.raises(configcrypt.DecryptError):
            configcrypt.decrypt_data("old-secret", blob)
    # and WITHOUT the old secret in the env, a third boot under the
    # new creds just works (state is current-generation now)
    srv3 = S3Server(layer, access_key="rootk", secret_key="new-secret")
    srv3.iam.load()
    assert srv3.iam.lookup_secret("erin") == "erin-secret-123"


def test_unreadable_sealed_state_degrades_to_defaults(tmp_path):
    """State sealed under UNKNOWN credentials must not crash boot —
    the replica is skipped (same contract as a corrupt JSON blob)."""
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="rootk", secret_key="secret-a")
    srv.iam.add_user("frank", "frank-secret-12")
    srv2 = S3Server(layer, access_key="rootk", secret_key="secret-b")
    srv2.iam.load()
    assert srv2.iam.lookup_secret("frank") is None      # can't open
    assert srv2.config.get("api", "requests_max") == "0"  # defaults


# -- OPA webhook ------------------------------------------------------------

class _OpaStub(BaseHTTPRequestHandler):
    """Programmable OPA: allow only s3:GetObject; /slow sleeps past
    the client deadline; /garbage answers non-JSON."""
    seen: list = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        doc = json.loads(self.rfile.read(n))
        type(self).seen.append((self.path, doc["input"],
                                self.headers.get("Authorization", "")))
        if self.path.endswith("/slow"):
            time.sleep(1.0)
        if self.path.endswith("/garbage"):
            body = b"<not-json>"
        else:
            body = json.dumps(
                {"result": doc["input"]["action"] == "s3:GetObject"}
            ).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture
def opa_stub():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _OpaStub)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="mt-test-opa-stub")
    t.start()
    _OpaStub.seen = []
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


@pytest.fixture
def opa_cluster(tmp_path):
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="rootk", secret_key="opa-secret")
    srv.start()
    srv.iam.add_user("alice", "alice-secret-12",
                     policies=["readwrite"])
    root = S3Client(srv.endpoint, "rootk", "opa-secret")
    root.make_bucket("opabkt")
    root.put_object("opabkt", "k", b"data")
    alice = S3Client(srv.endpoint, "alice", "alice-secret-12")
    from minio_tpu.admin.client import AdminClient
    admin = AdminClient(srv.endpoint, "rootk", "opa-secret")
    yield srv, root, alice, admin
    srv.stop()


def test_opa_allow_deny_live_reload_and_admin_bypass(opa_cluster,
                                                     opa_stub):
    srv, root, alice, admin = opa_cluster
    # before OPA: local policy grants alice readwrite
    alice.put_object("opabkt", "pre", b"x")
    # arm via admin SetConfigKV — live, no restart
    admin.set_config_kv("policy_opa", "auth_token", "opatok")
    admin.set_config_kv("policy_opa", "url",
                        f"{opa_stub}/v1/data/s3/allow")
    assert srv.iam.authorizer is not None
    assert alice.get_object("opabkt", "k").body == b"data"  # allowed
    with pytest.raises(S3ClientError) as ei:
        alice.put_object("opabkt", "denied", b"y")          # denied
    assert ei.value.code == "AccessDenied"
    # the webhook saw the PolicyArgs shape + the bearer token
    path, args, auth = _OpaStub.seen[-1]
    assert auth == "Bearer opatok"
    assert args["account"] == "alice"
    assert args["action"] == "s3:PutObject"
    assert args["bucket"] == "opabkt"
    # root bypasses the webhook entirely
    calls = len(_OpaStub.seen)
    root.put_object("opabkt", "adm", b"z")
    assert len(_OpaStub.seen) == calls
    # disarm: local evaluation returns
    admin.set_config_kv("policy_opa", "url", "")
    assert srv.iam.authorizer is None
    alice.put_object("opabkt", "post", b"w")


def test_opa_fail_closed_on_timeout_and_dead_endpoint(opa_cluster,
                                                      opa_stub):
    srv, root, alice, admin = opa_cluster
    admin.set_config_kv("policy_opa", "timeout", "200ms")
    admin.set_config_kv("policy_opa", "retry_attempts", "1")
    # timeout: the stub sleeps past the deadline -> DENY, bounded
    admin.set_config_kv("policy_opa", "url", f"{opa_stub}/slow")
    t0 = time.monotonic()
    with pytest.raises(S3ClientError):
        alice.get_object("opabkt", "k")
    assert time.monotonic() - t0 < 5.0
    # dead endpoint -> DENY
    admin.set_config_kv("policy_opa", "url", "http://127.0.0.1:1/x")
    with pytest.raises(S3ClientError):
        alice.get_object("opabkt", "k")
    # garbage reply -> DENY (fail-closed, not a crash)
    admin.set_config_kv("policy_opa", "url", f"{opa_stub}/garbage")
    with pytest.raises(S3ClientError):
        alice.get_object("opabkt", "k")
    # root is untouched by all of it
    root.put_object("opabkt", "still-admin", b"!")
    # unknown credentials are denied LOCALLY (authN never delegates)
    calls = len([s for s in _OpaStub.seen])
    assert srv.iam.is_allowed("ghost", "s3:GetObject",
                              "opabkt/k") is False


def test_opa_from_config_unit():
    from minio_tpu.secure.opa import OpaWebhook
    from minio_tpu.utils.kvconfig import Config
    assert OpaWebhook.from_config(Config()) is None  # url empty
    cfg = Config()
    cfg._dynamic = {"policy_opa": {"url": "http://x/",
                                   "timeout": "700ms",
                                   "retry_attempts": "3"}}
    hook = OpaWebhook.from_config(cfg)
    assert hook.timeout_s == pytest.approx(0.7)
    assert hook.retry.attempts == 3


def test_opa_does_not_lift_sts_session_policy(opa_cluster, opa_stub):
    """An STS session policy is a HARD bound the caller scoped the
    credential down to at mint time — the webhook can narrow within
    it but never widen past it (the same intersection the
    bucket-policy-Allow path enforces)."""
    srv, root, alice, admin = opa_cluster
    session_policy = json.dumps({
        "Version": "2012-10-17",
        "Statement": [{"Effect": "Allow",
                       "Action": ["s3:GetObject"],
                       "Resource": ["arn:aws:s3:::opabkt/*"]}]})
    creds = srv.iam.assume_role("rootk", 900, session_policy)
    # the stub allows GetObject AND would allow nothing else; but even
    # an allow-everything webhook must not lift the session bound, so
    # point it at an allow-all decision for the PUT probe
    admin.set_config_kv("policy_opa", "url",
                        f"{opa_stub}/v1/data/s3/allow")
    assert srv.iam.is_allowed(creds.access_key, "s3:GetObject",
                              "opabkt/k") is True
    calls = len(_OpaStub.seen)
    # session policy denies PutObject LOCALLY — the webhook is not
    # even consulted for a request outside the credential's bound
    assert srv.iam.is_allowed(creds.access_key, "s3:PutObject",
                              "opabkt/x") is False
    assert len(_OpaStub.seen) == calls
    admin.set_config_kv("policy_opa", "url", "")


def test_opa_bad_aux_knob_keeps_webhook_armed():
    """A typo in an auxiliary knob must not silently DISARM the
    authorizer (that would be fail-open): the webhook stays armed with
    the bad knob's default."""
    from minio_tpu.secure.opa import OpaWebhook
    from minio_tpu.utils.kvconfig import Config
    cfg = Config()
    cfg._dynamic = {"policy_opa": {"url": "http://opa.example/",
                                   "retry_attempts": "two",
                                   "timeout": "garbage"}}
    hook = OpaWebhook.from_config(cfg)
    assert hook is not None
    assert hook.retry.attempts == 2
    assert hook.timeout_s == pytest.approx(2.0)
