"""Cluster self-measurement (tentpole of the observability PR):
speedtest probes + admin routes, sampling profiler thread coverage,
heal-sweep stop latency, background-status, and the background-plane
trace types' idle contract.

Reference tier: cmd/admin-handlers.go SpeedtestHandler /
DriveSpeedtestHandler + cmd/speedtest.go autotune, cmd/utils.go:286
getProfileData, madmin BgHealState.
"""

import json
import os
import threading
import time

import pytest

from minio_tpu.background.crawler import Crawler, scan_usage
from minio_tpu.background.heal import BackgroundHealer
from minio_tpu.obs import selftest, trace
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.s3.client import S3Client
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.xl_storage import XLStorage


def _mk_layer(tmp_path, n=4, parity=2):
    disks = []
    for i in range(n):
        d = tmp_path / f"d{i}"
        d.mkdir(exist_ok=True)
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=parity, block_size=64 * 1024,
                          backend="numpy")


# -- probes ------------------------------------------------------------------

def test_drive_speedtest_measures_and_cleans_up(tmp_path):
    layer = _mk_layer(tmp_path)
    paths = selftest.local_drive_paths(layer)
    assert len(paths) == 4
    rows = selftest.drive_speedtest(paths, file_size=1 << 20)
    assert len(rows) == 4
    for r in rows:
        assert r["writeGiBps"] > 0 and r["readGiBps"] > 0
        assert r["bytes"] == 1 << 20
    # the probe file is gone from every drive
    for root in paths:
        st = os.path.join(root, ".mt.sys", "speedtest")
        assert not os.path.exists(st) or not os.listdir(st)


def test_object_speedtest_autotunes_and_removes_probe_bucket(tmp_path):
    layer = _mk_layer(tmp_path)
    r = selftest.object_speedtest(layer, size=16384, duration_s=0.15)
    assert r["autotuned"] is True
    assert r["concurrency"] >= 1
    assert r["putOps"] >= 1 and r["getOps"] >= 1
    assert r["putGiBps"] > 0 and r["getGiBps"] > 0
    # probe bucket + objects fully cleaned up
    assert not [b for b in layer.list_buckets()
                if b.name.startswith("mt-speedtest-")]


def test_object_speedtest_fixed_concurrency_runs_one_round(tmp_path):
    layer = _mk_layer(tmp_path)
    r = selftest.object_speedtest(layer, size=8192, duration_s=0.1,
                                  concurrency=2)
    assert r["concurrency"] == 2 and r["autotuned"] is False


def test_tpu_codec_speedtest_reports_both_directions():
    r = selftest.tpu_codec_speedtest(size=1 << 20, k=4, m=2,
                                     block_size=256 * 1024,
                                     backend="numpy")
    assert r["encodeGiBps"] > 0 and r["decodeGiBps"] > 0
    assert (r["k"], r["m"], r["backend"]) == (4, 2, "numpy")


def test_bench_record_shape_matches_bench_json():
    rec = selftest.bench_record("probe_metric_GiBps", 1.5,
                                {"encode_GiBps": 1.5})
    # the BENCH_*.json contract: bench.py emits exactly these keys
    assert set(rec) == {"metric", "value", "unit", "detail"}
    assert rec["unit"] == "GiB/s"


# -- sampling profiler (satellite) ------------------------------------------

def test_sampling_profiler_sees_other_threads():
    """cProfile only hooks the enabling thread; the sampler must catch
    a busy WORKER thread by walking sys._current_frames()."""
    from minio_tpu.obs import profiling

    stop = threading.Event()

    def busy_worker_fn():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy_worker_fn, name="busy-worker",
                         daemon=True)
    profiling.start("cpu")
    t.start()
    try:
        time.sleep(0.25)
    finally:
        stop.set()
        t.join()
    dumps = profiling.stop_dumps()
    assert "profile-cpu.txt" in dumps          # pstats path kept
    sampled = dumps["profile-cpu-sampled.txt"].decode()
    assert "busy_worker_fn" in sampled, \
        "sampler never saw the worker thread's stack"
    # collapsed-stack lines: "frame;frame;... count"
    body = [ln for ln in sampled.splitlines()
            if ln and not ln.startswith("#")]
    assert body and all(ln.rsplit(" ", 1)[1].isdigit() for ln in body)


# -- heal sweep stop latency (satellite) ------------------------------------

def test_heal_sweep_stop_bails_mid_walk(tmp_path, monkeypatch):
    layer = _mk_layer(tmp_path)
    layer.make_bucket("healbkt")
    for i in range(40):
        layer.put_object("healbkt", f"o{i:03d}", b"x" * 128)
    healer = BackgroundHealer(layer)

    real_heal = layer.heal_object

    def slow_heal(*a, **k):
        time.sleep(0.05)
        return real_heal(*a, **k)

    monkeypatch.setattr(layer, "heal_object", slow_heal)
    done = threading.Event()

    def run():
        healer.sweep()
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # let a few objects heal, then stop: the sweep must bail within
    # ~one object's heal time, not walk all 40 (2+ seconds)
    deadline = time.monotonic() + 5.0
    while healer.stats.objects_scanned < 2 and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert healer.stats.objects_scanned >= 2, "sweep never started"
    t0 = time.monotonic()
    healer._stop.set()
    assert done.wait(timeout=1.0), "sweep ignored stop mid-walk"
    assert time.monotonic() - t0 < 0.5
    # partial-cycle stats kept, cycle not counted as completed
    assert 0 < healer.stats.objects_scanned < 40
    assert healer.stats.cycles == 0
    # the aborted cycle must not leak an eternal active flag or
    # record lying last-cycle rates
    assert healer.progress.active is False
    assert healer.progress.last == {}
    assert healer.progress.cycles == 0


# -- background-plane spans: idle contract + types --------------------------

def test_background_spans_follow_idle_contract(tmp_path, monkeypatch):
    assert not trace.active(), "leaked subscriber/ring from another test"
    layer = _mk_layer(tmp_path)
    layer.make_bucket("bgbkt")
    for i in range(3):
        layer.put_object("bgbkt", f"o{i}", b"y" * 256)
    calls = {"make": 0}
    real_make = trace.make_span
    monkeypatch.setattr(
        trace, "make_span",
        lambda *a, **k: (calls.__setitem__("make", calls["make"] + 1),
                         real_make(*a, **k))[1])
    healer = BackgroundHealer(layer)
    healer.sweep()
    scan_usage(layer, apply_lifecycle=False)
    assert calls["make"] == 0, \
        "background spans built with zero subscribers"
    with trace.HTTP_TRACE.subscribe() as sub:
        healer.sweep()
        scan_usage(layer, apply_lifecycle=False)
        spans = list(sub.drain(500, timeout=1.0))
    kinds = {s["type"] for s in spans}
    assert "healing" in kinds and "scanner" in kinds
    heal_spans = [s for s in spans if s["type"] == "healing"]
    assert all(s["funcName"] == "healing.sweep" for s in heal_spans)
    assert any(s["healing"]["bucket"] == "bgbkt" for s in heal_spans)
    scans = [s for s in spans if s["type"] == "scanner"]
    assert any(s["scanner"]["bucket"] == "bgbkt"
               and s["scanner"]["objects"] == 3 for s in scans)


def test_replication_spans_follow_idle_contract(tmp_path, monkeypatch):
    from minio_tpu.background.replication import ReplicationSys
    from minio_tpu.objectlayer.bucket_meta import BucketMetadataSys
    assert not trace.active()
    layer = _mk_layer(tmp_path)
    rs = ReplicationSys(layer, BucketMetadataSys(layer), workers=1)
    calls = {"make": 0}
    real_make = trace.make_span
    monkeypatch.setattr(
        trace, "make_span",
        lambda *a, **k: (calls.__setitem__("make", calls["make"] + 1),
                         real_make(*a, **k))[1])
    rs.start()
    try:
        rs._q.put(("rbkt", "robj", "", False))   # no target: no-op task
        rs.drain(timeout=2.0)
        assert calls["make"] == 0
        with trace.HTTP_TRACE.subscribe() as sub:
            rs._q.put(("rbkt", "robj2", "", False))
            spans = list(sub.drain(5, timeout=2.0))
        repl = [s for s in spans if s["type"] == "replication"]
        assert repl and repl[0]["replication"]["object"] == "robj2"
    finally:
        rs.stop()


def test_new_trace_types_accepted_by_filter():
    from minio_tpu.admin.handlers import _trace_type_filter
    flt, want = _trace_type_filter(
        {"type": "scanner,healing,replication"})
    assert want == {"scanner", "healing", "replication"}
    assert flt({"type": "healing"}) and not flt({"type": "http"})
    assert set(trace.TRACE_TYPES) >= want


# -- served admin surface ----------------------------------------------------

@pytest.fixture
def served(tmp_path):
    layer = _mk_layer(tmp_path)
    srv = S3Server(layer, access_key="stk", secret_key="sts")
    srv.healer = BackgroundHealer(layer)
    srv.crawler = Crawler(layer)
    srv.start()
    yield srv
    srv.stop()


def _lines(body: bytes) -> list:
    return [json.loads(x) for x in body.decode().splitlines() if x]


def test_admin_speedtest_tpu_streams_bench_record(served):
    c = S3Client(served.endpoint, "stk", "sts")
    r = c.request("POST", "/minio-tpu/admin/v1/speedtest-tpu",
                  "size=262144&blocksize=65536&k=4&m=2")
    lines = _lines(r.body)
    assert len(lines) == 2                      # local node + final
    node = lines[0]
    assert node["node"] == served.node_name
    assert node["encodeGiBps"] > 0 and node["decodeGiBps"] > 0
    final = lines[-1]
    assert set(final) == {"metric", "value", "unit", "detail"}
    assert final["metric"] == "tpu_codec_encode_decode_GiBps_4+2"
    assert final["unit"] == "GiB/s" and final["value"] > 0
    assert final["detail"]["encode_GiBps"] > 0
    assert final["detail"]["decode_GiBps"] > 0


def test_admin_speedtest_drive_reports_every_drive(served):
    c = S3Client(served.endpoint, "stk", "sts")
    r = c.request("POST", "/minio-tpu/admin/v1/speedtest-drive",
                  "size=131072")
    lines = _lines(r.body)
    assert len(lines[0]["drives"]) == 4
    assert all(d["writeGiBps"] > 0 for d in lines[0]["drives"])
    final = lines[-1]
    assert final["metric"] == "drive_seq_write_GiBps"
    assert final["detail"]["driveCount"] == 4


def test_admin_object_speedtest_single_node(served):
    c = S3Client(served.endpoint, "stk", "sts")
    r = c.request("POST", "/minio-tpu/admin/v1/speedtest",
                  "size=16384&duration=0.1&concurrency=2")
    lines = _lines(r.body)
    node = lines[0]
    assert node["putGiBps"] > 0 and node["getGiBps"] > 0
    final = lines[-1]
    assert final["detail"]["putGiBps"] == pytest.approx(
        node["putGiBps"], rel=1e-6)
    assert final["detail"]["concurrency"] == 2


def test_admin_trace_streams_healing_type(served):
    """`?type=healing` on the admin trace route delivers the heal
    sweep's spans — the background planes ride the same type-filter
    machinery as the PR-2 subsystem types."""
    served.layer.make_bucket("htrbkt")
    served.layer.put_object("htrbkt", "o1", b"h" * 256)
    c = S3Client(served.endpoint, "stk", "sts")
    got = {}

    def consume():
        r = c.request("GET", "/minio-tpu/admin/v1/trace",
                      "timeout=5&max-items=1&type=healing")
        got["lines"] = [json.loads(x)
                        for x in r.body.decode().splitlines() if x]

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.monotonic() + 3
    while served.trace_hub.num_subscribers < 1 and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    served.healer.sweep()
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["lines"], "no healing span reached the typed stream"
    span = got["lines"][0]
    assert span["type"] == "healing"
    assert span["healing"]["bucket"] == "htrbkt"


def test_background_status_route(served):
    served.layer.make_bucket("bgsbkt")
    served.layer.put_object("bgsbkt", "o1", b"z" * 512)
    served.healer.sweep()
    served.crawler.run_cycle()
    c = S3Client(served.endpoint, "stk", "sts")
    doc = json.loads(c.request(
        "GET", "/minio-tpu/admin/v1/background-status", "").body)
    assert doc["node"] == served.node_name
    heal = doc["healing"]
    assert heal["stats"]["objectsScanned"] >= 1
    assert heal["progress"]["cycles"] == 1
    last = heal["progress"]["lastCycle"]
    assert last["objects"] >= 1 and last["objectsPerSecond"] > 0
    scan = doc["scanner"]
    assert scan["cycles"] == 1
    assert scan["progress"]["lastCycle"]["objects"] >= 1
    assert doc["replication"] is None           # not enabled here


def test_scrape_exports_background_rate_gauges(served):
    served.layer.make_bucket("ratebkt")
    served.layer.put_object("ratebkt", "o1", b"r" * 2048)
    served.healer.sweep()
    served.crawler.run_cycle()
    from minio_tpu.admin import metrics
    text = metrics.render(served.layer, healer=served.healer,
                          crawler=served.crawler)
    assert "mt_heal_objects_per_second " in text
    assert "mt_scanner_objects_per_second " in text
    assert "mt_scanner_cycles_total 1" in text
    assert "mt_heal_cycle_active 0" in text


def test_replication_and_bandwidth_gauges_exported(tmp_path):
    from minio_tpu.admin import metrics
    from minio_tpu.background.replication import ReplicationSys
    from minio_tpu.objectlayer.bucket_meta import BucketMetadataSys
    layer = _mk_layer(tmp_path)
    rs = ReplicationSys(layer, BucketMetadataSys(layer))
    rs.stats.queued = 5
    rs.stats.replicated = 3
    rs.stats.replica_bytes = 4096
    rs.monitor.set_limit("bwbkt", 1 << 20)
    rs.monitor.throttle("bwbkt", 100)
    text = metrics.render(layer, replication=rs)
    assert "mt_replication_queued_total 5" in text
    assert "mt_replication_objects_total 3" in text
    assert "mt_replication_bytes_total 4096" in text
    assert ('mt_bucket_bandwidth_limit_bytes_per_second'
            '{bucket="bwbkt"} 1048576') in text
    assert ('mt_bucket_bandwidth_moved_bytes_total'
            '{bucket="bwbkt"} 100') in text
