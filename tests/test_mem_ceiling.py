"""Tier-1 bounded-memory regression fence (the tentpole's acceptance
gate): Select over a multi-hundred-MiB-class synthetic object and a
100k-key listing both run under ``tracemalloc`` with peak traced
allocation bounded by a small multiple of the block size — if a
whole-buffer path ever creeps back into the scanner or the metacache,
this fails loudly.

The objects are synthesized as chunk generators (never materialized),
so the fence measures the SCANNER's footprint, not the harness's."""

import tracemalloc

from minio_tpu.objectlayer.interface import ObjectInfo
from minio_tpu.objectlayer.metacache import MetacacheManager, paginate
from minio_tpu.s3select import records, run_select_stream
from minio_tpu.storage.xl_storage import XLStorage

BLOCK = 1 << 20


def _select_req(expr: str, input_xml: str) -> bytes:
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        '<SelectObjectContentRequest '
        'xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        f"<Expression>{expr}</Expression>"
        "<ExpressionType>SQL</ExpressionType>"
        f"<InputSerialization>{input_xml}</InputSerialization>"
        "<OutputSerialization><CSV/></OutputSerialization>"
        "</SelectObjectContentRequest>").encode()


def _traced_peak(fn) -> int:
    tracemalloc.start()
    try:
        fn()
        _, peak = tracemalloc.get_traced_memory()
        return peak
    finally:
        tracemalloc.stop()


def test_select_ndjson_quarter_gib_is_o_block():
    """~256 MiB NDJSON Select (the native prefilter's target shape;
    sized down when the C scanner can't build) — peak traced memory
    stays under a small multiple of the scanner block."""
    native = records._scan_lib() is not None
    total = (256 << 20) if native else (8 << 20)
    line = b'{"user":"u%d","score":%d,"tag":"abcdefgh"}\n'
    piece = b"".join(line % (i, i % 1000) for i in range(20000))
    npieces = total // len(piece) + 1

    def chunks():
        for _ in range(npieces):
            yield piece

    payload = _select_req(
        "SELECT s.user FROM S3Object s WHERE s.score = 999",
        "<JSON><Type>LINES</Type></JSON>")
    got = {"frames": 0, "bytes": 0}

    def scan():
        for f in run_select_stream(payload, chunks(),
                                   block_bytes=BLOCK):
            got["frames"] += 1
            got["bytes"] += len(f)

    peak = _traced_peak(scan)
    assert got["frames"] >= 3 and got["bytes"] > 0
    assert peak < 24 * BLOCK, \
        f"select scanner peak {peak >> 20} MiB — whole-buffer path back?"


def test_select_csv_multi_mib_is_o_block():
    """CSV rides the pure-Python record loop — smaller corpus, same
    O(block) contract."""
    row = b"user%d,%d,paris\n"
    piece = b"".join(row % (i, i % 100) for i in range(20000))
    npieces = (8 << 20) // len(piece) + 1

    def chunks():
        yield b"name,age,city\n"
        for _ in range(npieces):
            yield piece

    payload = _select_req(
        "SELECT name FROM S3Object WHERE age = 99",
        "<CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV>")

    def scan():
        for _ in run_select_stream(payload, chunks(),
                                   block_bytes=BLOCK):
            pass

    peak = _traced_peak(scan)
    assert peak < 24 * BLOCK, \
        f"CSV scanner peak {peak >> 20} MiB — whole-buffer path back?"


def test_listing_100k_keys_is_o_block(tmp_path):
    """A 100k-entry walk streams into persisted metacache blocks and a
    million-object-class listing pages load one block each — peak
    traced memory bounded by a small multiple of one block's entries,
    never the namespace."""
    d = tmp_path / "mcdisk"
    d.mkdir()
    disk = XLStorage(str(d))
    disk.make_vol(".minio-tpu.sys")
    # ttl pinned high: the 100k build under tracemalloc can take longer
    # than DEFAULT_TTL on a loaded machine, and an expired manifest
    # makes the cold-manager check below legitimately re-walk
    mgr = MetacacheManager(disks=[disk], sys_volume=".minio-tpu.sys",
                           block_entries=1000, cache_blocks=4, ttl=300.0)
    n = 100_000

    def loader():
        for i in range(n):
            yield ObjectInfo(bucket="big", name=f"pfx/obj-{i:07d}",
                             size=4096, etag="e" * 32, mod_time=1,
                             user_defined={"content-type": "x/y"})

    state: dict = {}

    def build_and_page():
        snap = mgr.list_path_stream("big", "", loader)
        state["snap"] = snap
        # page from the middle: the bisect must land on one block, not
        # stream the namespace
        page = paginate(snap.iter_from("pfx/obj-0050000"), "",
                        "pfx/obj-0050000", "", 1000)
        state["page"] = page

    peak = _traced_peak(build_and_page)
    snap, page = state["snap"], state["page"]
    assert len(snap.block_keys) == 100
    assert [o.name for o in page.objects][:2] == \
        ["pfx/obj-0050001", "pfx/obj-0050002"]
    assert page.is_truncated
    # in-memory LRU held, not the namespace
    assert len(snap._blocks) <= mgr.cache_blocks
    # ~1000-entry blocks at ~settings bytes each; 100k materialized
    # ObjectInfos would be tens of MiB — fence well under that
    assert peak < 16 << 20, \
        f"listing peak {peak >> 20} MiB — namespace materialized?"

    # a cold manager over the same drive serves from persisted blocks
    mgr2 = MetacacheManager(disks=[disk], sys_volume=".minio-tpu.sys",
                            block_entries=1000, cache_blocks=4, ttl=300.0)
    snap2 = mgr2.list_path_stream(
        "big", "", lambda: (_ for _ in ()).throw(
            AssertionError("cold lookup must not re-walk")))
    page2 = paginate(snap2.iter_from("pfx/obj-0099000"), "",
                     "pfx/obj-0099000", "", 500)
    assert len(page2.objects) == 500
    assert mgr2.hits == 1 and mgr2.misses == 0
