"""TLS transport tier (ISSUE 13 tentpole a): the auto-reloading cert
manager, both encrypted listeners (S3 front + internode mTLS), both
scheme-aware client stacks, SNI, live cert rotation, the SSE-C-over-
plaintext gate, and the scrape families.

Every test minting certs rides the session-shared PKI fixture
(tests/_pki.py — skips with a named reason when the image has no
openssl binary); tests that ROTATE material mint their own throwaway
PKI so the shared one stays pristine.
"""

import os
import socket
import ssl
import time

import pytest

from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.parallel.rpc import (Iovecs, RPCClient, RPCError,
                                    RPCServer)
from minio_tpu.s3.client import S3Client
from minio_tpu.s3.server import S3Server
from minio_tpu.secure import certs as secure_certs
from minio_tpu.secure import pki as secure_pki
from minio_tpu.secure import transport as secure_transport
from minio_tpu.storage.xl_storage import XLStorage
from tests._pki import cluster_pki

pytestmark = pytest.mark.skipif(
    not secure_pki.available(),
    reason=f"{secure_pki.OPENSSL} not present: cannot mint the test PKI")


def _layer(tmp_path, n=4):
    disks = []
    for i in range(n):
        d = tmp_path / f"d{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=2, block_size=64 * 1024,
                          backend="numpy")


@pytest.fixture
def pki(tmp_path_factory):
    return cluster_pki(tmp_path_factory)


@pytest.fixture
def tls_s3(tmp_path, pki):
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="tlskey", secret_key="tlssecret",
                   tls=pki.cert_manager())
    srv.start()
    yield srv, pki
    srv.stop()
    secure_transport.configure(None)


# -- cert manager units -----------------------------------------------------


def test_manager_requires_material(tmp_path):
    with pytest.raises(secure_certs.TLSConfigError):
        secure_certs.CertManager((str(tmp_path / "no.crt"),
                                  str(tmp_path / "no.key")))


def test_manager_reload_on_mtime(pki):
    mgr = pki.cert_manager(check_interval_s=0.0)
    ctx0 = mgr.server_context("s3")
    assert mgr.server_context("s3") is ctx0      # cached while unchanged
    # touch the cert: the next lookup rebuilds (rotation re-keys the
    # NEXT connection; nothing rebinds)
    os.utime(pki.s3_cert, (time.time(), time.time() + 1))
    assert mgr.maybe_reload() is True
    assert mgr.reloads == 1
    assert mgr.server_context("s3") is not ctx0
    # throttle: with a long interval the stat is skipped entirely
    mgr.check_interval_s = 3600.0
    os.utime(pki.s3_cert, (time.time(), time.time() + 2))
    assert mgr.maybe_reload() is False


def test_manager_expiry_gauges(pki):
    mgr = pki.cert_manager()
    exp = mgr.cert_expiries()
    assert set(exp) == {"s3", "internode"}
    # minted for ~2 days; the gauge renders seconds-to-expiry
    for v in exp.values():
        assert v > time.time() + 3600
    lines = secure_certs.render_metrics()
    assert any(l.startswith("# TYPE mt_tls_cert_expiry_seconds gauge")
               for l in lines)
    assert any('cert="s3"' in l for l in lines)


def test_idle_contract_no_managers_no_families(monkeypatch):
    import weakref
    monkeypatch.setattr(secure_certs, "_MANAGERS", weakref.WeakSet())
    assert secure_certs.render_metrics() == []


def test_from_dir_layout(tmp_path, pki):
    certs_dir = pki.write_certs_dir(str(tmp_path / "certs"))
    mgr = secure_certs.CertManager.from_dir(certs_dir)
    assert mgr.ca_file and mgr.ca_file.endswith("ca.crt")
    assert set(mgr.cert_expiries()) == {"s3", "internode"}
    # the kvconfig boot path agrees with the layout
    from minio_tpu.utils.kvconfig import Config
    cfg = Config()
    monkey = {"MT_TLS_ENABLE": "on", "MT_TLS_CERTS_DIR": certs_dir}
    old = {k: os.environ.get(k) for k in monkey}
    os.environ.update(monkey)
    try:
        m2 = secure_certs.CertManager.from_config(cfg)
        assert m2 is not None and m2.ca_file == mgr.ca_file
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)


# -- S3 front over TLS ------------------------------------------------------


def test_s3_roundtrip_and_admin_over_tls(tls_s3):
    srv, pki = tls_s3
    assert srv.endpoint.startswith("https://")
    c = S3Client(srv.endpoint, "tlskey", "tlssecret",
                 ca_file=pki.ca_cert)
    c.make_bucket("tlsbkt")
    body = os.urandom(300_000)
    c.put_object("tlsbkt", "obj", body)
    assert c.get_object("tlsbkt", "obj").body == body
    objs, _ = c.list_objects("tlsbkt")
    assert [o["key"] for o in objs] == ["obj"]
    # admin SDK over the same encrypted front
    from minio_tpu.admin.client import AdminClient
    admin = AdminClient(srv.endpoint, "tlskey", "tlssecret",
                        ca_file=pki.ca_cert)
    assert admin.server_info()["region"] == srv.region
    # a CA-less client resolves the pin via the process registry
    # (configured by the TLS-armed server)
    c2 = S3Client(srv.endpoint, "tlskey", "tlssecret")
    assert c2.get_object("tlsbkt", "obj").body == body


def test_wrong_ca_rejected(tls_s3, tmp_path):
    srv, _ = tls_s3
    other_ca, _ = secure_pki.mint_ca(str(tmp_path / "otherca"),
                                     cn="imposter CA")
    c = S3Client(srv.endpoint, "tlskey", "tlssecret", ca_file=other_ca)
    with pytest.raises(ssl.SSLError):
        c.list_buckets()


def test_handshake_counters_tick(tls_s3):
    from minio_tpu.admin.metrics import GLOBAL
    srv, pki = tls_s3

    def shakes(fam):
        return sum(v for k, v in GLOBAL.snapshot().items()
                   if k[0] == fam and ("plane", "s3") in k[1])
    ok0, bad0 = shakes("mt_tls_handshake_total"), \
        shakes("mt_tls_handshake_failed_total")
    S3Client(srv.endpoint, "tlskey", "tlssecret",
             ca_file=pki.ca_cert).list_buckets()
    assert shakes("mt_tls_handshake_total") > ok0
    # a PLAINTEXT client on the TLS port fails the handshake — counted,
    # quieted, and fatal only to that connection
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
    try:
        s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        try:
            s.recv(64)          # server drops the connection
        except OSError:
            pass
    finally:
        s.close()
    deadline = time.monotonic() + 5
    while shakes("mt_tls_handshake_failed_total") <= bad0:
        assert time.monotonic() < deadline, "failed handshake not counted"
        time.sleep(0.05)
    # and the server still serves fine afterwards
    S3Client(srv.endpoint, "tlskey", "tlssecret",
             ca_file=pki.ca_cert).list_buckets()


def test_sni_serves_hostname_pair(tmp_path):
    """A connection naming a configured SNI hostname handshakes with
    that pair; others get the default."""
    p = secure_pki.mint_cluster_pki(str(tmp_path / "pki"))
    alt_crt, alt_key = secure_pki.mint_leaf(
        str(tmp_path / "pki"), p.ca_cert, p.ca_key, "alt.example",
        san="DNS:alt.example")
    mgr = secure_certs.CertManager(
        (p.s3_cert, p.s3_key), ca_file=p.ca_cert,
        sni={"alt.example": (alt_crt, alt_key)})
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="k", secret_key="sni-secret",
                   tls=mgr)
    srv.start()
    try:
        ctx = ssl.create_default_context(cafile=p.ca_cert)

        def peer_cn(server_hostname):
            raw = socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=5)
            with ctx.wrap_socket(raw,
                                 server_hostname=server_hostname) as s:
                subj = dict(x[0] for x in s.getpeercert()["subject"])
                return subj["commonName"]

        assert peer_cn("alt.example") == "alt.example"
        assert peer_cn("localhost") == "s3"
    finally:
        srv.stop()
        secure_transport.configure(None)


def test_live_cert_rotation_rekeys_next_connection(tmp_path):
    """Overwrite the PEM files in place (what a cert-renewal cron
    does): the manager's mtime watcher re-keys the NEXT connection
    with no restart — the serial number visibly changes."""
    pdir = str(tmp_path / "pki")
    p = secure_pki.mint_cluster_pki(pdir)
    mgr = p.cert_manager(check_interval_s=0.0)
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="k", secret_key="rot-secret",
                   tls=mgr)
    srv.start()
    try:
        ctx = ssl.create_default_context(cafile=p.ca_cert)

        def serial():
            raw = socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=5)
            with ctx.wrap_socket(raw,
                                 server_hostname="localhost") as s:
                return s.getpeercert()["serialNumber"]

        s0 = serial()
        # renewal: a FRESH leaf lands on the same paths
        secure_pki.mint_leaf(pdir, p.ca_cert, p.ca_key, "s3")
        # ensure the mtime moves even on coarse filesystem clocks
        os.utime(p.s3_cert, (time.time(), time.time() + 5))
        s1 = serial()
        assert s1 != s0
        assert mgr.reloads >= 1
    finally:
        srv.stop()
        secure_transport.configure(None)


def test_ssec_over_plaintext_rejected(tmp_path):
    """The AWS InsecureSSECustomerRequest gate: SSE-C headers on a
    plaintext connection are 400 before auth (the e2e SSE-C tiers in
    test_sse.py run over TLS and prove the positive path)."""
    import base64
    import hashlib

    from minio_tpu.s3.client import S3ClientError
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="k", secret_key="plain-secret")
    srv.start()
    try:
        key = b"2" * 32
        c = S3Client(srv.endpoint, "k", "plain-secret")
        c.make_bucket("gate")
        with pytest.raises(S3ClientError) as ei:
            c.request(
                "PUT", "/gate/o", body=b"x",
                headers={
                    "x-amz-server-side-encryption-customer-algorithm":
                        "AES256",
                    "x-amz-server-side-encryption-customer-key":
                        base64.b64encode(key).decode(),
                    "x-amz-server-side-encryption-customer-key-md5":
                        base64.b64encode(
                            hashlib.md5(key).digest()).decode()})
        assert ei.value.status == 400
        assert ei.value.code == "InvalidRequest"
        assert "secure connection" in str(ei.value)
    finally:
        srv.stop()


# -- internode mTLS ---------------------------------------------------------


@pytest.fixture
def tls_rpc(pki):
    mgr = pki.cert_manager()
    srv = RPCServer("rpc-tls-secret", tls=mgr)
    srv.register("t", {"echo": lambda x: x})
    srv.register_raw("rev", lambda params, data: bytes(data)[::-1])
    srv.start()
    secure_transport.configure(mgr)
    yield srv, pki
    srv.stop()
    secure_transport.configure(None)


def test_rpc_mtls_roundtrip(tls_rpc):
    srv, _ = tls_rpc
    assert srv.endpoint.startswith("https://")
    c = RPCClient(srv.endpoint, "rpc-tls-secret")
    assert c.call("t", "echo", x={"n": 1}) == {"n": 1}
    assert c.raw_call("rev", {}, b"abcdef") == b"fedcba"
    # PR-8 iovec sidecar bodies cross the encrypted channel unchanged
    assert c.raw_call("rev", {},
                      Iovecs([b"abc", memoryview(b"def")])) == b"fedcba"
    # keep-alive reuse over TLS (pooled connection serves the replay)
    assert c.call("t", "echo", x=2) == 2


def test_rpc_requires_client_cert(tls_rpc):
    """mTLS: a client WITHOUT the CA-signed internode identity is cut
    at the handshake — it never reaches the HMAC token check."""
    import http.client
    srv, pki = tls_rpc
    ctx = ssl.create_default_context(cafile=pki.ca_cert)  # no identity
    conn = http.client.HTTPSConnection("127.0.0.1", srv.port,
                                       timeout=5, context=ctx)
    with pytest.raises((ssl.SSLError, ConnectionError, OSError)):
        conn.request("POST", "/rpc/sys/ping", body=b"")
        conn.getresponse()


def test_rpc_bad_token_still_403_over_tls(tls_rpc):
    """The HMAC bearer token stays load-bearing INSIDE the encrypted
    channel: a valid mTLS identity with a bad token is refused at the
    application layer."""
    srv, _ = tls_rpc
    c = RPCClient(srv.endpoint, "the-wrong-secret")
    with pytest.raises(RPCError) as ei:
        c.call("t", "echo", x=1)
    assert ei.value.error_type == "AuthError"


def test_remote_storage_framed_streaming_over_tls(tmp_path, pki,
                                                  monkeypatch):
    """The PR-6 framed streaming mode rides the encrypted channel
    byte-for-byte: a streamed create lands chunk-by-chunk on the
    remote drive and reads back identical (streamed response leg
    included)."""
    from minio_tpu.parallel.rpc import STREAM
    from minio_tpu.storage.remote import (RemoteStorage,
                                          register_storage_service)
    monkeypatch.setattr(STREAM, "enable", True)
    monkeypatch.setattr(STREAM, "chunk_bytes", 1024)
    monkeypatch.setattr(STREAM, "_loaded", True)
    mgr = pki.cert_manager()
    d = tmp_path / "remote"
    d.mkdir()
    drive = XLStorage(str(d))
    srv = RPCServer("stream-tls", tls=mgr)
    register_storage_service(srv, {"r0": drive})
    srv.start()
    secure_transport.configure(mgr)
    try:
        r = RemoteStorage(RPCClient(srv.endpoint, "stream-tls"), "r0")
        r.make_vol("vol1")
        blob = os.urandom(64 * 1024 + 123)   # dozens of 1 KiB frames
        r.create_file("vol1", "shard", blob)
        got = r.read_all("vol1", "shard")
        assert got == blob
        assert drive.read_all("vol1", "shard") == blob
    finally:
        srv.stop()
        secure_transport.configure(None)


def test_corrupt_cert_rotation_costs_one_connection_not_the_listener(
        tmp_path):
    """A non-atomic cert renewal (half-written PEM on disk when the
    mtime watcher fires) must drop the affected connection(s) ONLY:
    socketserver's accept loop survives, and once the good file lands
    the very next connection serves again — no restart."""
    pdir = str(tmp_path / "pki")
    p = secure_pki.mint_cluster_pki(pdir)
    mgr = p.cert_manager(check_interval_s=0.0)
    layer = _layer(tmp_path)
    srv = S3Server(layer, access_key="k", secret_key="corrupt-secret",
                   tls=mgr)
    srv.start()
    try:
        c = S3Client(srv.endpoint, "k", "corrupt-secret",
                     ca_file=p.ca_cert)
        c.list_buckets()
        good = open(p.s3_cert, "rb").read()
        with open(p.s3_cert, "wb") as f:     # rotation caught mid-write
            f.write(b"-----BEGIN GARBAGE-----\n")
        os.utime(p.s3_cert, (time.time(), time.time() + 5))
        with pytest.raises((ssl.SSLError, OSError)):
            S3Client(srv.endpoint, "k", "corrupt-secret",
                     ca_file=p.ca_cert).list_buckets()
        # the renewal completes: the good bytes land, and the SAME
        # listener serves the next connection
        with open(p.s3_cert, "wb") as f:
            f.write(good)
        os.utime(p.s3_cert, (time.time(), time.time() + 10))
        S3Client(srv.endpoint, "k", "corrupt-secret",
                 ca_file=p.ca_cert).list_buckets()
    finally:
        srv.stop()
        secure_transport.configure(None)
