"""ThreadSanitizer tier for the native libraries — the thread-race half
of the buildscripts/race.sh role (the ASan/UBSan half lives in
tests/test_sanitizers.py).

The GIL-released C paths (native/gf8.cc matmuls, the framed
highwayhash verify/fill, snappy, jsonscan) run concurrently in
production: every drive fan-out and every GET verify can execute them
from multiple threads at once.  This tier rebuilds them with
``-fsanitize=thread`` into a scratch dir and drives them from many
Python threads under a preloaded libtsan; any ThreadSanitizer report
fails the run.

Same canary discipline as the ASan tier: a deliberately racy library
driven the same way MUST be caught, or the tier is not evidence.
"""

import os
import subprocess
import sys
import textwrap

import pytest

# slow: TSan rebuilds + multi-minute race-hunting subprocesses — runs
# in the full tier, not the tier-1 `-m 'not slow'` budget
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _libtsan() -> str | None:
    try:
        out = subprocess.run(["g++", "-print-file-name=libtsan.so"],
                             capture_output=True, text=True, timeout=30)
        path = os.path.realpath(out.stdout.strip())
        return path if path and os.path.exists(path) else None
    except (OSError, subprocess.TimeoutExpired):
        return None


tsan = pytest.mark.skipif(_libtsan() is None,
                          reason="libtsan not available")


def _run_tsan(code: str, tmp_path, extra_env=None
              ) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": _libtsan(),
        "MT_NATIVE_BUILD_DIR": str(tmp_path / "tsan-build"),
        "MT_NATIVE_CFLAGS": "-fsanitize=thread -g",
        # report_bugs stays on; exitcode marks any report even without
        # halting mid-workload
        "TSAN_OPTIONS": "halt_on_error=0:exitcode=66",
        "JAX_PLATFORMS": "cpu",
        **(extra_env or {}),
    })
    return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=600)


WORKLOAD = textwrap.dedent("""
    import os, threading
    import numpy as np

    errors = []

    def gf8_work():
        from minio_tpu.ops import gf8_native, gf8
        assert gf8_native.available(), "gf8 tsan build failed"
        M = np.asarray(gf8.rs_matrix(8, 12))[8:]
        rng = np.random.default_rng(0)
        for _ in range(10):
            B = rng.integers(0, 256, (8, 87382), dtype=np.uint8)
            out = np.empty((4, 87382), dtype=np.uint8)
            gf8_native.matmul_into(M, B, out)

    def hh_work():
        # the framed fill + verify pair the PUT/GET hot paths run
        # concurrently across drive fan-out threads
        from minio_tpu.hashing import bitrot, highwayhash as hh
        for _ in range(10):
            data = os.urandom(300_000)
            framed = np.frombuffer(
                bitrot.streaming_encode(data, 4096),
                dtype=np.uint8).copy()
            assert hh.hh256_verify_framed(framed, 4096) == 0
            framed[:32] = 0
            hh.hh256_fill(framed, 4096)

    def snappy_work():
        from minio_tpu import compress
        if not compress.native_available():
            return
        for _ in range(10):
            blob = os.urandom(30000) * 2
            assert compress.decompress_stream(
                compress.compress_stream(blob)) == blob

    def jsonscan_work():
        from minio_tpu.s3select import records
        data = b'\\n'.join(
            b'{"k":"v%d","n":%d}' % (i, i) for i in range(500)) + b'\\n'
        for _ in range(10):
            records.ndjson_prefilter(data, "k", "=", "v7")

    def run(fn):
        try:
            fn()
        except Exception as e:      # noqa: BLE001
            errors.append(f"{fn.__name__}: {e!r}")

    threads = [threading.Thread(target=run, args=(f,))
               for f in (gf8_work, hh_work, snappy_work, jsonscan_work)
               for _ in range(3)]
    for t in threads: t.start()
    for t in threads: t.join()
    assert not errors, errors
    print("TSAN-WORKLOAD-OK")
""")


@tsan
def test_native_libs_clean_under_tsan(tmp_path):
    res = _run_tsan(WORKLOAD, tmp_path)
    assert "TSAN-WORKLOAD-OK" in res.stdout, \
        f"stdout={res.stdout[-2000:]}\nstderr={res.stderr[-4000:]}"
    assert "WARNING: ThreadSanitizer" not in res.stderr, \
        res.stderr[-4000:]
    assert res.returncode == 0, res.stderr[-2000:]


RACE_CANARY_SRC = textwrap.dedent("""
    static long counter = 0;
    extern "C" long mt_race_canary(int n) {
        for (int i = 0; i < n; i++)
            counter = counter + 1;            // unsynchronized RMW
        return counter;
    }
""")

RACE_CANARY_DRIVER = textwrap.dedent("""
    import os, threading
    from minio_tpu.utils import nativelib
    so = os.path.join(os.environ["MT_NATIVE_BUILD_DIR"], "librace.so")
    lib = nativelib.load(os.environ["CANARY_SRC"], so)
    assert lib is not None, "canary build failed"
    def work():
        for _ in range(200):
            lib.mt_race_canary(5000)
    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts: t.start()
    for t in ts: t.join()
    print("RACE-CANARY-DONE")
""")


@tsan
def test_harness_catches_injected_race(tmp_path):
    """The tier is only evidence if it FAILS on a real race.  -O0 keeps
    the per-iteration load/store pair (at -O2 the loop folds into one
    store per call and the race window shrinks below detectability)."""
    src = tmp_path / "race_canary.cc"
    src.write_text(RACE_CANARY_SRC)
    res = _run_tsan(RACE_CANARY_DRIVER, tmp_path, extra_env={
        "MT_NATIVE_CFLAGS": "-fsanitize=thread -O0 -g",
        "CANARY_SRC": str(src),
    })
    assert "WARNING: ThreadSanitizer: data race" in res.stderr, \
        f"injected race was NOT caught\n{res.stderr[-2000:]}"
    assert res.returncode == 66, res.returncode
