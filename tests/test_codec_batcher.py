"""Cross-request batching codec service (parallel/batcher.py): batched
outputs are pinned bit-identical to the serial reference across ragged
geometry mixes and padding boundaries; concurrent waiters coalesce into
fewer dispatches; callers that die mid-queue cancel cleanly (no leaked
``mt-codec-*`` threads); the ``codec`` kvconfig knobs reload live.
"""

import threading
import time

import numpy as np
import pytest

from minio_tpu.admin.metrics import GLOBAL as METRICS
from minio_tpu.ops.codec import Erasure
from minio_tpu.parallel import batcher

RNG = np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _restore_config():
    """Every test runs against the process-global CONFIG/GLOBAL: pin a
    known state going in and restore the defaults going out so test
    order never matters."""
    cfg = batcher.CONFIG
    saved = (cfg.enable, cfg.window_s, cfg.max_blocks, cfg.queue_depth,
             cfg._loaded)
    cfg.enable = True
    cfg.window_s = 200e-6
    cfg.max_blocks = 256
    cfg.queue_depth = 1024
    cfg._loaded = True
    yield
    (cfg.enable, cfg.window_s, cfg.max_blocks, cfg.queue_depth,
     cfg._loaded) = saved
    assert not batcher.GLOBAL._buckets, "batcher bucket leaked"


def _body(size, seed):
    return RNG.__class__(np.random.PCG64(seed)).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def _serial(codec_args, data):
    """The reference output: the same geometry with batching OFF."""
    cfg = batcher.CONFIG
    prev = cfg.enable
    cfg.enable = False
    try:
        return Erasure(*codec_args).encode_object(data)
    finally:
        cfg.enable = prev


# -- bit-identity -----------------------------------------------------------

@pytest.mark.parametrize("backend", ["numpy", "tpu"])
def test_ragged_geometry_mix_bit_identical(backend):
    """Concurrent encodes across a ragged geometry mix — every
    (k, m, blockSize) lands in its own bucket, all coalescing at once —
    stay bit-identical to the serial per-request reference."""
    geos = [(4, 2, 64 * 1024), (6, 3, 128 * 1024), (8, 4, 32 * 1024),
            (2, 2, 4096)]
    jobs = []
    for gi, geo in enumerate(geos):
        bs = geo[2]
        for size in (1, bs - 1, bs, 3 * bs + 17):
            jobs.append((geo, _body(size, 100 * gi + size % 97)))
    want = [_serial((k, m, bs, backend), data)
            for (k, m, bs), data in jobs]
    batcher.CONFIG.window_s = 0.02          # wide window: force overlap
    got = [None] * len(jobs)
    start = threading.Barrier(len(jobs))

    def run(i):
        (k, m, bs), data = jobs[i]
        start.wait()
        got[i] = Erasure(k, m, bs, backend).encode_object(data)

    ths = [threading.Thread(target=run, args=(i,),
                            name=f"mt-codec-rg{i}")
           for i in range(len(jobs))]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    for i, (w, g) in enumerate(zip(want, got)):
        assert g is not None, jobs[i][0]
        for a, b in zip(w, g):
            assert np.array_equal(a, b), jobs[i][0]


def test_padding_boundaries_bit_identical():
    """1 block, exactly max_batch_blocks, and max+1 (the dispatch-split
    boundary) all produce the serial bytes."""
    batcher.CONFIG.max_blocks = 4
    k, m, bs = 4, 2, 4096
    for nblocks in (1, 4, 5):
        data = _body(nblocks * bs, 40 + nblocks)
        want = _serial((k, m, bs, "tpu"), data)
        got = Erasure(k, m, bs, "tpu").encode_object(data)
        for a, b in zip(want, got):
            assert np.array_equal(a, b), nblocks


@pytest.mark.parametrize("backend", ["numpy", "tpu"])
def test_decode_and_reconstruct_bit_identical(backend):
    """The decode path (survivor solve + batched matmul) and the public
    apply_matrix reconstruct path match the serial reference."""
    k, m, bs = 4, 2, 64 * 1024
    data = _body(2 * bs + 999, 9)
    full = _serial((k, m, bs, backend), data)
    degraded = [s.copy() for s in full]
    degraded[0] = None
    degraded[5] = np.zeros(0, np.uint8)
    cfg = batcher.CONFIG
    cfg.enable = False
    ref = Erasure(k, m, bs, backend).decode_data_and_parity_blocks(
        [None if s is None or len(s) == 0 else s.copy()
         for s in degraded])
    cfg.enable = True
    out = Erasure(k, m, bs, backend).decode_data_and_parity_blocks(
        [None if s is None or len(s) == 0 else s.copy()
         for s in degraded])
    for i in range(k + m):
        assert np.array_equal(out[i], ref[i]), i
        assert np.array_equal(out[i], full[i]), i
    # decode_data_blocks (the GET path's early-outs included)
    lost = [s.copy() for s in full]
    lost[1] = None
    out2 = Erasure(k, m, bs, backend).decode_data_blocks(lost)
    for i in range(k):
        assert np.array_equal(out2[i], full[i]), i


# -- coalescing -------------------------------------------------------------

def test_concurrent_waiters_coalesce_and_count():
    """N concurrent same-geometry encodes fuse into fewer dispatches
    than requests; occupancy/blocks land in the mt_codec_batch_*
    counters."""
    batcher.CONFIG.window_s = 0.05
    k, m, bs = 4, 2, 4096
    body = _body(8 * bs, 3)
    want = _serial((k, m, bs, "tpu"), body)
    c = Erasure(k, m, bs, "tpu")
    n = 8
    res = [None] * n
    start = threading.Barrier(n)

    def run(i):
        start.wait()
        res[i] = c.encode_object(body)

    before = batcher.GLOBAL.snapshot()
    d0 = METRICS.snapshot().get(
        ("mt_codec_batch_dispatches_total", (("op", "encode"),)), 0.0)
    ths = [threading.Thread(target=run, args=(i,),
                            name=f"mt-codec-cw{i}") for i in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    after = batcher.GLOBAL.snapshot()
    for r in res:
        for a, b in zip(want, r):
            assert np.array_equal(a, b)
    served = after["requests"] - before["requests"]
    fused = after["dispatches"] - before["dispatches"]
    assert served == n
    assert fused < served, (fused, served)
    d1 = METRICS.snapshot().get(
        ("mt_codec_batch_dispatches_total", (("op", "encode"),)), 0.0)
    assert d1 - d0 == fused


def test_single_caller_takes_serial_fallback():
    """A window that finds one caller dispatches exactly the caller's
    own stripes (occupancy 1) — the strict serial reference path."""
    before = batcher.GLOBAL.snapshot()
    k, m, bs = 4, 2, 4096
    body = _body(3 * bs, 5)
    want = _serial((k, m, bs, "tpu"), body)
    got = Erasure(k, m, bs, "tpu").encode_object(body)
    for a, b in zip(want, got):
        assert np.array_equal(a, b)
    after = batcher.GLOBAL.snapshot()
    delta_d = after["dispatches"] - before["dispatches"]
    delta_r = after["requests"] - before["requests"]
    assert delta_d == delta_r  # nothing coalesced: every dispatch solo


def test_queue_bound_sheds_to_serial():
    """Arrivals past codec.queue_depth blocks take the serial path
    immediately (bounded queue, correct bytes, counted)."""
    cfg = batcher.CONFIG
    cfg.window_s = 0.05
    cfg.max_blocks = 2
    cfg.queue_depth = 2
    k, m, bs = 4, 2, 4096
    body = _body(bs, 11)                    # one block: B=1 queues
    want = _serial((k, m, bs, "tpu"), body)
    c = Erasure(k, m, bs, "tpu")
    n = 6
    res = [None] * n
    start = threading.Barrier(n)

    def run(i):
        start.wait()
        res[i] = c.encode_object(body)

    before = batcher.GLOBAL.snapshot()
    ths = [threading.Thread(target=run, args=(i,),
                            name=f"mt-codec-sh{i}") for i in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    for r in res:
        for a, b in zip(want, r):
            assert np.array_equal(a, b)
    after = batcher.GLOBAL.snapshot()
    assert after["shed"] >= before["shed"]  # sheds are load-dependent;
    # the hard contract is correctness + the bound, asserted above


# -- cancellation -----------------------------------------------------------

def test_caller_death_mid_queue_cancels_cleanly():
    """A waiter whose caller gives up mid-queue cancels out, computes
    its own result on the serial path, and the combiner never touches
    it; nothing mt-codec-shaped survives."""
    cfg = batcher.CONFIG
    cfg.window_s = 1.5                      # long window: the combiner
    k, m, bs = 5, 2, 10240                  # parks followers behind it
    body = _body(2 * bs, 21)
    want = _serial((k, m, bs, "tpu"), body)
    leader_out = [None]
    leading = threading.Event()

    def lead():
        leading.set()
        leader_out[0] = Erasure(k, m, bs, "tpu").encode_object(body)

    tl = threading.Thread(target=lead, name="mt-codec-lead",
                          daemon=True)
    tl.start()
    assert leading.wait(10)
    time.sleep(0.05)                        # leader is window-waiting
    # the doomed follower: enqueues behind the combiner's open window,
    # then its deadline expires — it must cancel OUT of the queue and
    # serve itself serially, well before the window closes
    caller = Erasure(k, m, bs, "tpu")
    rows = np.asarray(caller.matrix)[k:]
    ssize = caller.shard_size()
    blocks = np.frombuffer(body, np.uint8).reshape(2, k, ssize)
    before = batcher.GLOBAL.snapshot()
    t0 = time.monotonic()
    out = batcher.GLOBAL.apply(caller, "encode", rows, blocks,
                               timeout=0.2)
    waited = time.monotonic() - t0
    after = batcher.GLOBAL.snapshot()
    assert after["cancelled"] >= before["cancelled"] + 1
    assert waited < 1.0, waited             # did not ride out the window
    for j in range(m):
        assert np.array_equal(out[:, j].reshape(-1), want[k + j])
    tl.join(20)
    assert not tl.is_alive()
    for a, b in zip(want, leader_out[0]):
        assert np.array_equal(a, b)
    # the mt-codec-* naming discipline: no batcher-related thread
    # outlives its caller (the batcher itself owns none)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(
            t.name.startswith("mt-codec") for t in threading.enumerate()):
        time.sleep(0.02)
    leftover = [t.name for t in threading.enumerate()
                if t.name.startswith("mt-codec")]
    assert not leftover, leftover


def test_numpy_backend_never_routes_through_batcher():
    """The host path has no dispatch-launch cost to amortize, and its
    GIL-releasing native matmuls already run in parallel across caller
    threads — batching would serialize them for nothing, so the numpy
    backend must bypass the batcher entirely."""
    before = batcher.GLOBAL.snapshot()
    c = Erasure(4, 2, 4096, "numpy")
    body = _body(3 * 4096, 2)
    c.encode_object(body)
    full = c.encode_object(body)
    lost = [s.copy() for s in full]
    lost[0] = None
    c.decode_data_and_parity_blocks(lost)
    assert batcher.GLOBAL.snapshot() == before


def test_mesh_fused_framed_path_rides_batcher_bit_identical():
    """The production mesh PUT path (encode_object_framed_fused:
    fused parity + bitrot digests) coalesces through the batcher's
    tuple-result buckets and stays bit-identical to the unbatched
    fused pipeline."""
    from minio_tpu.ops import rs_mesh
    from minio_tpu.parallel import mesh as pmesh
    prev = pmesh._ACTIVE
    pmesh.set_active_mesh(pmesh.make_mesh(stripe=2))
    cfg = batcher.CONFIG
    try:
        data = _body(3 * 65536 + 17, 31)
        cfg.enable = False
        want = rs_mesh.encode_object_framed_fused(4, 2, 65536, data)
        cfg.enable = True
        s0 = batcher.GLOBAL.snapshot()
        got = rs_mesh.encode_object_framed_fused(4, 2, 65536, data)
        s1 = batcher.GLOBAL.snapshot()
        assert s1["dispatches"] > s0["dispatches"]   # it rode the queue
        assert np.array_equal(want, got)
    finally:
        pmesh.set_active_mesh(prev)


# -- shared geometry registry ----------------------------------------------

def test_sidecar_and_local_share_one_codec_per_geometry():
    from minio_tpu.parallel.codec_service import _codec
    a = _codec(4, 2, 64 * 1024, "numpy")
    b = _codec(4, 2, 64 * 1024, "numpy")
    c = batcher.codec_for(4, 2, 64 * 1024, "numpy")
    assert a is b is c
    assert _codec(4, 2, 32 * 1024, "numpy") is not a


# -- live reload ------------------------------------------------------------

def test_codec_config_env_and_load(monkeypatch):
    monkeypatch.setenv("MT_CODEC_BATCH_WINDOW_US", "5000")
    monkeypatch.setenv("MT_CODEC_MAX_BATCH_BLOCKS", "32")
    monkeypatch.setenv("MT_CODEC_QUEUE_DEPTH", "64")
    monkeypatch.setenv("MT_CODEC_ENABLE", "off")
    cfg = batcher.CodecConfig()
    assert cfg.on() is False
    assert cfg.window_s == pytest.approx(0.005)
    assert cfg.max_blocks == 32
    assert cfg.queue_depth == 64


def test_admin_set_config_kv_reloads_window(tmp_path):
    """PUT config/codec/batch_window_us through the real admin route
    retunes the live process-wide batcher."""
    from minio_tpu.admin.client import AdminClient
    from minio_tpu.objectlayer.erasure_object import ErasureObjects
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl_storage import XLStorage
    disks = []
    for i in range(4):
        d = tmp_path / f"d{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=64 * 1024,
                           backend="numpy")
    srv = S3Server(layer, access_key="ck", secret_key="cs")
    srv.start()
    try:
        adm = AdminClient(srv.endpoint, "ck", "cs")
        adm.set_config_kv("codec", "batch_window_us", "4321")
        assert batcher.CONFIG.window_s == pytest.approx(4321e-6)
        adm.set_config_kv("codec", "enable", "off")
        assert batcher.CONFIG.on() is False
        adm.set_config_kv("codec", "enable", "on")
        assert batcher.CONFIG.on() is True
    finally:
        srv.stop()
        from minio_tpu.storage.writers import close_write_planes
        close_write_planes(layer)
