"""The multi-chip mesh wired INTO the object layer (VERDICT r4 #1).

backend="mesh" routes ErasureObjects' encode/reconstruct/heal matmuls
through parallel/mesh.distributed_* (via ops/rs_mesh) — these tests
prove PUT, degraded GET, and heal actually REACH the sharded kernels
on the virtual 8-device mesh and stay bit-identical with the numpy
oracle topology (cmd/erasure-encode.go:36-70 fan-out semantics).
"""

import os

import numpy as np
import pytest

from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.ops import rs_mesh
from minio_tpu.parallel import mesh as mesh_mod
from minio_tpu.storage.xl_storage import XLStorage

# slow: the full mesh dataplane (pallas interpret mode on a virtual
# 8-device CPU mesh) costs minutes of wall clock — fast-tier mesh
# coverage lives in test_mesh.py
pytestmark = pytest.mark.slow

K, M = 5, 3          # 8 drives: 5 data + 3 parity
BS = 128 * 1024


@pytest.fixture
def meshed(tmp_path):
    prev = mesh_mod._ACTIVE
    mesh_mod.set_active_mesh(mesh_mod.make_mesh(stripe=2))   # 2x4
    disks = []
    for i in range(8):
        d = tmp_path / f"d{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=M, block_size=BS,
                           backend="mesh")
    yield layer
    mesh_mod.set_active_mesh(prev)


@pytest.fixture
def counting(monkeypatch):
    """Count dispatches that reach the sharded mesh kernels — either
    engine (XLA psum path or the pallas+ppermute-ring path)."""
    calls = {"apply": 0, "fused": 0}
    real_apply = mesh_mod.distributed_apply
    real_pallas = rs_mesh._apply_pallas
    real_fused = mesh_mod._fused_encode_hash

    def apply_spy(*a, **kw):
        calls["apply"] += 1
        return real_apply(*a, **kw)

    def pallas_spy(*a, **kw):
        calls["apply"] += 1
        return real_pallas(*a, **kw)

    def fused_spy(*a, **kw):
        calls["fused"] += 1
        return real_fused(*a, **kw)

    real_fused_pallas = rs_mesh._encode_with_bitrot_pallas

    def fused_pallas_spy(*a, **kw):
        calls["fused"] += 1
        return real_fused_pallas(*a, **kw)

    monkeypatch.setattr(mesh_mod, "distributed_apply", apply_spy)
    monkeypatch.setattr(rs_mesh, "_apply_pallas", pallas_spy)
    monkeypatch.setattr(mesh_mod, "_fused_encode_hash", fused_spy)
    monkeypatch.setattr(rs_mesh, "_encode_with_bitrot_pallas",
                        fused_pallas_spy)
    # rs_mesh binds the module, not the function, so the spy is seen
    return calls


def test_put_reaches_fused_mesh_pipeline(meshed, counting):
    meshed.make_bucket("meshb")
    body = os.urandom(3 * BS + 12345)
    meshed.put_object("meshb", "obj", body)
    assert counting["fused"] >= 1, \
        "PUT did not route through the fused sharded encode"
    got = meshed.get_object("meshb", "obj")[1]
    assert bytes(got) == body


def test_degraded_get_reaches_mesh_reconstruct(meshed, counting, tmp_path):
    meshed.make_bucket("meshb")
    body = os.urandom(2 * BS + 999)
    meshed.put_object("meshb", "deg", body)
    # wipe M shard files = max erasures; GET must reconstruct via mesh
    wiped = 0
    for i in range(8):
        droot = tmp_path / f"d{i}" / "meshb" / "deg"
        if droot.exists() and wiped < M:
            import shutil
            shutil.rmtree(droot)
            wiped += 1
    assert wiped == M
    before = counting["apply"]
    got = meshed.get_object("meshb", "deg")[1]
    assert bytes(got) == body
    assert counting["apply"] > before, \
        "degraded GET did not route through the sharded reconstruct"


def test_heal_reaches_mesh_and_restores(meshed, counting, tmp_path):
    meshed.make_bucket("meshb")
    body = os.urandom(2 * BS + 31)
    meshed.put_object("meshb", "heal", body)
    import shutil
    victims = []
    for i in range(8):
        droot = tmp_path / f"d{i}" / "meshb" / "heal"
        if droot.exists() and len(victims) < 2:
            shutil.rmtree(droot)
            victims.append(i)
    assert len(victims) == 2
    before = counting["apply"]
    res = meshed.heal_object("meshb", "heal")
    assert counting["apply"] > before, \
        "heal did not route through the sharded reconstruct"
    for i in victims:
        assert (tmp_path / f"d{i}" / "meshb" / "heal").exists(), res
    # wipe DIFFERENT drives: the healed copies must decode
    for i in range(8):
        if i not in victims:
            droot = tmp_path / f"d{i}" / "meshb" / "heal"
            if droot.exists() and i < 3:
                shutil.rmtree(droot)
    got = meshed.get_object("meshb", "heal")[1]
    assert bytes(got) == body


def test_mesh_matches_numpy_oracle_on_disk(tmp_path):
    """Same object through mesh and numpy topologies -> bit-identical
    shard files (framing + digests + parity)."""
    prev = mesh_mod._ACTIVE
    mesh_mod.set_active_mesh(mesh_mod.make_mesh(stripe=2))
    try:
        rng = np.random.default_rng(7)
        body = bytes(rng.integers(0, 256, 2 * BS + 4321, dtype=np.uint8))
        layers = {}
        for be in ("mesh", "numpy"):
            disks = []
            for i in range(8):
                d = tmp_path / f"{be}{i}"
                d.mkdir()
                disks.append(XLStorage(str(d)))
            lay = ErasureObjects(disks, parity=M, block_size=BS,
                                 backend=be)
            lay.make_bucket("oraclebkt")
            lay.put_object("oraclebkt", "o", body)
            layers[be] = lay
        # compare every shard part file byte-for-byte (distribution is
        # keyed by (bucket,object) so drive order matches across layers)
        import glob
        for i in range(8):
            a = sorted(glob.glob(str(tmp_path / f"mesh{i}" / "oraclebkt" / "o" /
                                     "*" / "part.*")))
            b = sorted(glob.glob(str(tmp_path / f"numpy{i}" / "oraclebkt" / "o" /
                                     "*" / "part.*")))
            assert len(a) == len(b) == 1
            da = open(a[0], "rb").read()
            db = open(b[0], "rb").read()
            assert da == db, f"drive {i} shard file differs"
    finally:
        mesh_mod.set_active_mesh(prev)


def test_single_device_mesh_degenerate(tmp_path):
    """A 1-device mesh is the single-chip case: same code path, still
    correct (the degenerate end of SURVEY §2.3's scaling contract)."""
    import jax
    prev = mesh_mod._ACTIVE
    mesh_mod.set_active_mesh(
        mesh_mod.make_mesh(devices=jax.devices()[:1]))
    try:
        disks = []
        for i in range(4):
            d = tmp_path / f"s{i}"
            d.mkdir()
            disks.append(XLStorage(str(d)))
        lay = ErasureObjects(disks, parity=2, block_size=BS,
                             backend="mesh")
        lay.make_bucket("one")
        body = os.urandom(BS + 77)
        lay.put_object("one", "x", body)
        assert bytes(lay.get_object("one", "x")[1]) == body
    finally:
        mesh_mod.set_active_mesh(prev)


def test_rs_mesh_oracle_grid():
    """encode/reconstruct bit-identicality across geometries incl.
    k not divisible by the shard axis and B not divisible by stripe."""
    from minio_tpu.ops import gf8_ref
    prev = mesh_mod._ACTIVE
    mesh_mod.set_active_mesh(mesh_mod.make_mesh(stripe=2))
    try:
        rng = np.random.default_rng(3)
        for k, m in ((4, 2), (10, 3), (12, 4)):
            blocks = rng.integers(0, 256, (3, k, 257), dtype=np.uint8)
            want = np.stack([gf8_ref.encode_parity(b, m) for b in blocks])
            got = rs_mesh.encode_parity(blocks, m)
            assert np.array_equal(want, got), (k, m)
            # reconstruct dead data + parity (up to m erasures) via
            # the batch API
            full = np.concatenate([blocks, want], axis=1)
            dead = [0, 2, k][:m]
            present = [i for i in range(k + m) if i not in dead][:k]
            reb = rs_mesh.reconstruct_batch(
                full[:, present], present, dead, k, m)
            for j, w in enumerate(dead):
                assert np.array_equal(reb[:, j], full[:, w]), (k, m, w)
    finally:
        mesh_mod.set_active_mesh(prev)


def test_pallas_ring_engine_bit_identical(monkeypatch):
    """The TPU-default mesh engine: per-device fused pallas kernel +
    packed-byte XOR over a ppermute ring (GF(2) addition of packed
    parity IS XOR, so no int32 accumulator crosses ICI).  Forced on
    here (MT_MESH_PALLAS=1, interpret mode on CPU) and asserted
    bit-identical with the numpy oracle across geometries including
    ragged k/B/n."""
    from minio_tpu.ops import gf8_ref
    monkeypatch.setenv("MT_MESH_PALLAS", "1")
    prev = mesh_mod._ACTIVE
    mesh_mod.set_active_mesh(mesh_mod.make_mesh(stripe=2))
    try:
        rng = np.random.default_rng(11)
        for k, m, B, n in ((12, 4, 5, 1024), (10, 3, 2, 257),
                           (4, 2, 1, 640)):
            blocks = rng.integers(0, 256, (B, k, n), dtype=np.uint8)
            want = np.stack([gf8_ref.encode_parity(b, m)
                             for b in blocks])
            got = rs_mesh.encode_parity(blocks, m)
            assert np.array_equal(want, got), (k, m, B, n)
            full = np.concatenate([blocks, want], axis=1)
            dead = [0, 2, k][:m]
            present = [i for i in range(k + m)
                       if i not in dead][:k]
            reb = rs_mesh.reconstruct_batch(full[:, present], present,
                                            dead, k, m)
            for j, w in enumerate(dead):
                assert np.array_equal(reb[:, j], full[:, w]), (k, m, w)
        # fused engine: framed output vs the host oracle, bit for bit
        from minio_tpu.hashing import bitrot
        from minio_tpu.ops.codec import Erasure
        data = bytes(rng.integers(0, 256, BS + 4567, dtype=np.uint8))
        cod = Erasure(4, 2, BS, backend="numpy")
        host = cod.encode_object_framed(data)
        assert bitrot.fill_framed(host, cod.shard_size())
        got = rs_mesh.encode_object_framed_fused(4, 2, BS, data)
        assert np.array_equal(host, got), "fused pallas framed mismatch"
    finally:
        mesh_mod.set_active_mesh(prev)
