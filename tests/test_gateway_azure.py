"""Azure Blob gateway over the wire — stub service with SharedKey
signature verification on every request (tests/azure_stub.py).

Covers the full surface VERDICT r3 asked for: CRUD, multipart via
staged blocks + Put Block List, server-side copy with metadata
preservation, ranged reads, listings with delimiters, plus the
round-2 gateway-test asymmetries (multipart abort semantics,
metadata preservation on copy, ranges through the seam).
"""

import os

import pytest

from minio_tpu import gateway as gw
from minio_tpu.gateway.azure import (AzureBlobClient, AzureError,
                                     AzureObjects)
from minio_tpu.objectlayer.interface import (BucketExists, BucketNotFound,
                                             InvalidPart, ObjectNotFound,
                                             PutObjectOptions)

from .azure_stub import ACCOUNT, KEY_B64, AzureStubServer


@pytest.fixture(scope="module")
def stub():
    srv = AzureStubServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def layer(stub):
    return AzureObjects(AzureBlobClient(stub.endpoint, ACCOUNT, KEY_B64))


def test_bad_key_rejected(stub):
    import base64
    bad = base64.b64encode(b"wrong-key").decode()
    client = AzureBlobClient(stub.endpoint, ACCOUNT, bad)
    with pytest.raises(AzureError) as ei:
        client.create_container("nope")
    assert ei.value.status == 403
    assert ei.value.code == "AuthenticationFailed"


def test_bucket_lifecycle(layer):
    layer.make_bucket("azb")
    assert layer.get_bucket_info("azb").name == "azb"
    with pytest.raises(BucketExists):
        layer.make_bucket("azb")
    assert any(b.name == "azb" for b in layer.list_buckets())
    layer.delete_bucket("azb")
    with pytest.raises(BucketNotFound):
        layer.get_bucket_info("azb")


def test_object_crud_and_ranges(layer):
    layer.make_bucket("azo")
    body = os.urandom(64 * 1024)
    info = layer.put_object(
        "azo", "dir/obj.bin", body,
        PutObjectOptions(user_defined={
            "content-type": "application/x-test",
            "x-amz-meta-color": "mauve"}))
    assert info.size == len(body) and info.etag
    got, data = layer.get_object("azo", "dir/obj.bin")
    assert data == body
    assert got.content_type == "application/x-test"
    assert got.user_defined.get("x-amz-meta-color") == "mauve"
    # ranged read reports the FULL size via Content-Range
    got2, part = layer.get_object("azo", "dir/obj.bin",
                                  offset=100, length=50)
    assert part == body[100:150] and got2.size == len(body)
    head = layer.get_object_info("azo", "dir/obj.bin")
    assert head.size == len(body) and head.mod_time > 0
    layer.delete_object("azo", "dir/obj.bin")
    with pytest.raises(ObjectNotFound):
        layer.get_object_info("azo", "dir/obj.bin")


def test_listing_with_delimiter(layer):
    layer.make_bucket("azl")
    for k in ("a/1", "a/2", "b/1", "top"):
        layer.put_object("azl", k, b"x")
    lst = layer.list_objects("azl", delimiter="/")
    assert [o.name for o in lst.objects] == ["top"]
    assert lst.prefixes == ["a/", "b/"]
    lst2 = layer.list_objects("azl", prefix="a/")
    assert [o.name for o in lst2.objects] == ["a/1", "a/2"]


def test_object_name_needing_percent_encoding(layer):
    # SharedKey signs the percent-encoded wire path; a client signing
    # the raw path 403s on names with spaces/unicode/'#' (the stub
    # recomputes from the raw request line, like real Azure).
    layer.make_bucket("azenc")
    for name in ("dir with space/obj #1.bin", "uni/été.txt"):
        layer.put_object("azenc", name, b"payload-" + name.encode())
        _, data = layer.get_object("azenc", name)
        assert data == b"payload-" + name.encode()
        layer.delete_object("azenc", name)


def test_multipart_block_flow(layer):
    layer.make_bucket("azmp")
    uid = layer.new_multipart_upload(
        "azmp", "big",
        PutObjectOptions(user_defined={"x-amz-meta-job": "42",
                                       "content-type": "video/mp4"}))
    e1 = layer.put_object_part("azmp", "big", uid, 1, b"a" * 1000)
    e2 = layer.put_object_part("azmp", "big", uid, 2, b"b" * 500)
    parts = layer.list_object_parts("azmp", "big", uid)
    assert [(n, s) for n, _, s in parts] == [(1, 1000), (2, 500)]
    # completing with a never-uploaded part is InvalidPart
    with pytest.raises(InvalidPart):
        layer.complete_multipart_upload("azmp", "big", uid,
                                        [(1, e1), (7, "zz")])
    oi = layer.complete_multipart_upload("azmp", "big", uid,
                                         [(1, e1), (2, e2)])
    assert oi.size == 1500
    assert oi.user_defined.get("x-amz-meta-job") == "42"
    # content type survives Put Block List (x-ms-blob-content-type) and
    # the metadata came from the persisted temp blob, not process memory
    assert oi.content_type == "video/mp4"
    _, data = layer.get_object("azmp", "big")
    assert data == b"a" * 1000 + b"b" * 500
    # the metadata stash blob is cleaned up and never listed
    assert all(not o.name.startswith(".minio-tpu.sys/")
               for o in layer.list_objects("azmp").objects)


def test_multipart_meta_survives_new_adapter_instance(stub):
    # The reference persists multipart metadata Azure-side
    # (gateway-azure.go azureMultipartMetadata) so complete can run
    # after a restart or on another node.  Simulate with two adapters.
    a1 = AzureObjects(AzureBlobClient(stub.endpoint, ACCOUNT, KEY_B64))
    a1.make_bucket("azre")
    uid = a1.new_multipart_upload(
        "azre", "obj", PutObjectOptions(user_defined={
            "x-amz-meta-node": "one", "content-type": "text/csv"}))
    e1 = a1.put_object_part("azre", "obj", uid, 1, b"z" * 256)
    a2 = AzureObjects(AzureBlobClient(stub.endpoint, ACCOUNT, KEY_B64))
    oi = a2.complete_multipart_upload("azre", "obj", uid, [(1, e1)])
    assert oi.user_defined.get("x-amz-meta-node") == "one"
    assert oi.content_type == "text/csv"


def test_multipart_abort_then_get_fails(layer):
    layer.make_bucket("azab")
    uid = layer.new_multipart_upload("azab", "gone")
    layer.put_object_part("azab", "gone", uid, 1, b"data")
    layer.abort_multipart_upload("azab", "gone", uid)
    # blob was never committed
    with pytest.raises(ObjectNotFound):
        layer.get_object_info("azab", "gone")


def test_copy_preserves_metadata(layer):
    layer.make_bucket("azc")
    layer.put_object(
        "azc", "src", b"copy me",
        PutObjectOptions(user_defined={"x-amz-meta-tier": "gold"}))
    info = layer.copy_object("azc", "src", "azc", "dst")
    assert info.size == 7
    got, data = layer.get_object("azc", "dst")
    assert data == b"copy me"
    assert got.user_defined.get("x-amz-meta-tier") == "gold"
    # copy with replaced metadata
    layer.copy_object("azc", "src", "azc", "dst2",
                      PutObjectOptions(user_defined={
                          "x-amz-meta-tier": "silver"}))
    got2 = layer.get_object_info("azc", "dst2")
    assert got2.user_defined.get("x-amz-meta-tier") == "silver"


def test_registered_production_gateway(stub, monkeypatch):
    monkeypatch.setenv("AZURE_STORAGE_ENDPOINT", stub.endpoint)
    monkeypatch.setenv("AZURE_STORAGE_ACCOUNT", ACCOUNT)
    monkeypatch.setenv("AZURE_STORAGE_KEY", KEY_B64)
    g = gw.lookup("azure")()
    assert g.name() == "azure" and g.production()
    layer = g.new_gateway_layer()
    layer.make_bucket("azreg")
    layer.put_object("azreg", "k", b"v")
    assert layer.get_object("azreg", "k")[1] == b"v"


def test_full_s3_frontend_over_azure_gateway(stub):
    """S3Server + SigV4 -> AzureObjects -> wire protocol -> stub: the
    deployment shape `minio gateway azure` serves."""
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server

    layer = AzureObjects(AzureBlobClient(stub.endpoint, ACCOUNT,
                                         KEY_B64))
    srv = S3Server(layer, access_key="gk", secret_key="gs")
    srv.start()
    try:
        c = S3Client(srv.endpoint, "gk", "gs")
        c.make_bucket("azfront")
        body = os.urandom(200 * 1024)
        c.put_object("azfront", "x/y.bin", body)
        assert c.get_object("azfront", "x/y.bin").body == body
        assert c.get_object("azfront", "x/y.bin",
                            byte_range=(10, 99)).body == body[10:100]
        objs, prefixes = c.list_objects("azfront", delimiter="/")
        assert prefixes == ["x/"]
    finally:
        srv.stop()


def test_reserved_sys_namespace_rejected_at_object_ops(layer):
    """Object-op entry points refuse keys under .minio-tpu.sys/ — list
    filtering alone only HIDES the multipart metadata stashes; direct
    reads/writes by name must be rejected too (ADVICE round 5)."""
    from minio_tpu.objectlayer.interface import ObjectNameInvalid
    layer.make_bucket("azsys")
    uid = layer.new_multipart_upload("azsys", "real-obj")
    stash = f".minio-tpu.sys/multipart/{uid}/azure.json"
    with pytest.raises(ObjectNameInvalid):
        layer.get_object("azsys", stash)
    with pytest.raises(ObjectNameInvalid):
        layer.get_object_info("azsys", stash)
    with pytest.raises(ObjectNameInvalid):
        layer.put_object("azsys", stash, b"{}")       # corrupt attempt
    with pytest.raises(ObjectNameInvalid):
        layer.delete_object("azsys", stash)
    with pytest.raises(ObjectNameInvalid):
        layer.copy_object("azsys", stash, "azsys", "leak.json")
    with pytest.raises(ObjectNameInvalid):
        layer.copy_object("azsys", "real-obj", "azsys", stash)
    with pytest.raises(ObjectNameInvalid):
        layer.new_multipart_upload("azsys", ".minio-tpu.sys/evil")
    # the stash itself is untouched: the upload still completes
    e1 = layer.put_object_part("azsys", "real-obj", uid, 1, b"z" * 64)
    oi = layer.complete_multipart_upload("azsys", "real-obj", uid,
                                         [(1, e1)])
    assert oi.size == 64
