"""Background services tests: crawler/usage, update tracker, MRF +
sweep healing, async replication with bandwidth caps (reference test
models: cmd/data-usage-cache tests, cmd/global-heal.go behavior,
cmd/bucket-replication.go mustReplicate/replicateObject)."""

import json
import time

import pytest

from minio_tpu.background import (BackgroundHealer, BandwidthMonitor,
                                  Crawler, DataUpdateTracker, MRFQueue,
                                  ReplicationSys, load_usage, scan_usage)
from minio_tpu.background.replication import ReplicationTarget
from minio_tpu.hashing.xxhash import xxh64
from minio_tpu.objectlayer import interface as ol
from minio_tpu.objectlayer.bucket_meta import BucketMetadataSys
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.storage.xl_storage import XLStorage


def _mk_layer(base, n=4):
    disks = []
    for i in range(n):
        d = base / f"d{i}"
        d.mkdir(parents=True, exist_ok=True)
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=2, block_size=1 << 20,
                          backend="numpy")


@pytest.fixture
def er(tmp_path):
    return _mk_layer(tmp_path)


def test_xxh64_vectors():
    # official xxhash test vectors (XSUM_XXH64 of "" and known strings)
    assert xxh64(b"") == 0xEF46DB3751D8E999
    assert xxh64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxh64(b"abc") == 0x44BC2CF5AD770999
    assert xxh64(b"Hello, world!") != xxh64(b"Hello, world ")
    # 32+ byte path
    data = bytes(range(64))
    assert xxh64(data) == xxh64(data)
    assert xxh64(data, seed=1) != xxh64(data)


def test_update_tracker_cycles(er):
    t = DataUpdateTracker(er)
    t.mark("bkt", "obj1")
    assert t.changed_since(t.cycle, "bkt", "obj1")
    assert not t.changed_since(t.cycle, "bkt", "untouched-object")
    c0 = t.cycle
    t.advance()
    # history keeps the old cycle's changes visible
    assert t.changed_since(c0, "bkt", "obj1")
    assert not t.changed_since(c0, "bkt", "untouched-object")
    # too-old cycles conservatively report changed
    assert t.changed_since(-5, "bkt", "anything")
    # persistence round-trip
    t2 = DataUpdateTracker(er)
    assert t2.cycle == t.cycle
    assert t2.changed_since(c0, "bkt", "obj1")


def test_scan_usage_histogram(er):
    er.make_bucket("ubkt")
    er.put_object("ubkt", "small", b"x" * 100)
    er.put_object("ubkt", "mid", b"y" * 2048)
    res = scan_usage(er, apply_lifecycle=False)
    u = res.usage.bucket_usage["ubkt"]
    assert u.objects_count == 2
    assert u.size == 100 + 2048
    assert u.histogram["LESS_THAN_1024_B"] == 1
    assert u.histogram["BETWEEN_1024_B_AND_1_MB"] == 1
    assert res.usage.objects_total_count == 2


def test_crawler_persists_usage_and_expires(er):
    bm = BucketMetadataSys(er)
    er.make_bucket("lcb")
    # backdate the doomed object two days so a 1-day expiry fires
    from minio_tpu.storage.datatypes import now_ns
    old = now_ns() - 2 * 24 * 3600 * 10**9
    er.put_object("lcb", "old/doomed", b"d",
                  ol.PutObjectOptions(mod_time=old))
    er.put_object("lcb", "keep/safe", b"k")
    bm.set_config("lcb", "lifecycle", (
        '<LifecycleConfiguration><Rule><ID>r</ID><Status>Enabled</Status>'
        '<Filter><Prefix>old/</Prefix></Filter>'
        '<Expiration><Days>1</Days></Expiration>'
        '</Rule></LifecycleConfiguration>'))
    c = Crawler(er, bm, interval_s=3600)
    res = c.run_cycle()
    assert ("lcb", "old/doomed", "") in [
        (b, n, v) for b, n, v in res.expired]
    with pytest.raises(ol.ObjectNotFound):
        er.get_object_info("lcb", "old/doomed")
    er.get_object_info("lcb", "keep/safe")  # untouched
    # usage persisted and loadable
    info = load_usage(er)
    assert info is not None
    assert "lcb" in info.bucket_usage


def test_crawler_skips_unchanged_bucket_ilm(er):
    """Second cycle skips ILM for buckets with no tracked change."""
    bm = BucketMetadataSys(er)
    er.make_bucket("skipb")
    bm.set_config("skipb", "lifecycle", (
        '<LifecycleConfiguration><Rule><ID>r</ID><Status>Enabled</Status>'
        '<Filter></Filter><Expiration><Days>1</Days></Expiration>'
        '</Rule></LifecycleConfiguration>'))
    tracker = DataUpdateTracker()
    c = Crawler(er, bm, tracker=tracker)
    c.run_cycle()
    # object lands AFTER the first cycle without being marked in the
    # tracker -> second cycle must NOT expire it (bucket looks unchanged);
    # backdated so the 1-day rule would otherwise fire
    from minio_tpu.storage.datatypes import now_ns
    old = now_ns() - 2 * 24 * 3600 * 10**9
    er.put_object("skipb", "later", b"x", ol.PutObjectOptions(mod_time=old))
    res = c.run_cycle()
    assert res.expired == []
    # once marked, the third cycle expires it
    tracker.mark("skipb", "later")
    res = c.run_cycle()
    assert [(b, n) for b, n, _ in res.expired] == [("skipb", "later")]


def test_mrf_queue_heals_partial_write(er, tmp_path):
    er.make_bucket("mrfb")
    mrf = MRFQueue(er)
    er.mrf = mrf
    try:
        # knock out one drive: write meets quorum (3/4) and queues MRF
        dead = er.disks[3]
        er.disks[3] = None
        er.put_object("mrfb", "partial", b"p" * 4096)
        assert mrf.stats.mrf_queued == 1
        er.disks[3] = dead   # drive comes back; MRF heals onto it
        # start the worker only now: entries queue while stopped, and the
        # heal must not race the drive's return
        mrf.start()
        mrf.drain()
        assert mrf.stats.mrf_healed == 1
        r = er.heal_object("mrfb", "partial", dry_run=True)
        assert r.before_ok == 4  # already fully healed
    finally:
        mrf.stop()


def test_background_sweep_heals(er):
    er.make_bucket("swb")
    er.put_object("swb", "o1", b"1" * 2048)
    er.put_object("swb", "o2", b"2" * 2048)
    # wipe one drive's shard of o1 (simulates bitrot/lost file)
    import os
    import shutil
    d0 = er.disks[0].root if hasattr(er.disks[0], "root") else None
    assert d0 is not None
    for dirpath, _dirs, files in os.walk(os.path.join(d0, "swb")):
        shutil.rmtree(dirpath)
        break
    healer = BackgroundHealer(er, interval_s=3600)
    stats = healer.sweep()
    assert stats.objects_scanned == 2
    assert stats.objects_healed >= 1
    assert stats.cycles == 1
    r = er.heal_object("swb", "o1", dry_run=True)
    assert r.before_ok == 4


def test_bandwidth_monitor_throttles():
    m = BandwidthMonitor()
    m.set_limit("bkt", 1 << 20)          # 1 MiB/s
    m.throttle("bkt", 1 << 20)           # drain the initial burst
    t0 = time.monotonic()
    m.throttle("bkt", 512 << 10)         # 0.5 MiB over -> ~0.5s sleep
    assert time.monotonic() - t0 >= 0.4
    rep = m.report()
    assert rep["bkt"]["limitInBytesPerSecond"] == 1 << 20
    assert rep["bkt"]["totalBytesMoved"] == (1 << 20) + (512 << 10)
    # unlimited bucket never sleeps
    assert m.throttle("other", 10 << 20) == 0.0


def _mk_server(tmp_path, name):
    from minio_tpu.s3.server import S3Server
    layer = _mk_layer(tmp_path / name)
    srv = S3Server(layer, port=0)
    srv.start()
    return srv, layer


def test_replication_end_to_end(tmp_path):
    src_srv, src_layer = _mk_server(tmp_path, "src")
    dst_srv, dst_layer = _mk_server(tmp_path, "dst")
    try:
        src_layer.make_bucket("srcb")
        dst_layer.make_bucket("dstb")
        bm = BucketMetadataSys(src_layer)
        bm.set_config("srcb", "replication", (
            '<ReplicationConfiguration>'
            '<Role>arn:minio:replication::1:dstb</Role>'
            '<Rule><ID>r1</ID><Status>Enabled</Status><Priority>1</Priority>'
            '<DeleteReplication><Status>Enabled</Status></DeleteReplication>'
            '<Destination><Bucket>arn:aws:s3:::dstb</Bucket></Destination>'
            '</Rule></ReplicationConfiguration>'))
        repl = ReplicationSys(src_layer, bm, workers=1)
        repl.set_target("srcb", ReplicationTarget(
            arn="arn:minio:replication::1:dstb",
            endpoint=dst_srv.endpoint, target_bucket="dstb",
            access_key="minioadmin", secret_key="minioadmin"))
        repl.start()
        oi = src_layer.put_object(
            "srcb", "doc.txt", b"replicate me",
            ol.PutObjectOptions(user_defined={
                "x-amz-meta-who": "tester", "content-type": "text/plain"}))
        assert repl.queue("srcb", oi) is True
        repl.drain()
        time.sleep(0.2)
        doi, data = dst_layer.get_object("dstb", "doc.txt")
        assert data == b"replicate me"
        assert doi.user_defined.get("x-amz-meta-who") == "tester"
        soi = src_layer.get_object_info("srcb", "doc.txt")
        assert soi.user_defined.get(
            "x-amz-replication-status") == "COMPLETED"
        assert repl.stats.replicated == 1
        # delete replication (rule opts in)
        doomed = src_layer.get_object_info("srcb", "doc.txt")
        src_layer.delete_object("srcb", "doc.txt")
        assert repl.queue("srcb", doomed, delete=True) is True
        repl.drain()
        time.sleep(0.2)
        with pytest.raises(ol.ObjectNotFound):
            dst_layer.get_object_info("dstb", "doc.txt")
        assert repl.stats.deletes_replicated == 1
        # target registry persisted
        repl2 = ReplicationSys(src_layer, bm)
        assert repl2.get_target("srcb").endpoint == dst_srv.endpoint
        repl.stop()
    finally:
        src_srv.stop()
        dst_srv.stop()


def test_replication_no_rule_no_queue(tmp_path):
    src_srv, src_layer = _mk_server(tmp_path, "nr")
    try:
        src_layer.make_bucket("plain")
        bm = BucketMetadataSys(src_layer)
        repl = ReplicationSys(src_layer, bm)
        oi = src_layer.put_object("plain", "x", b"1")
        assert repl.queue("plain", oi) is False
    finally:
        src_srv.stop()


def test_admin_background_endpoints(tmp_path):
    from minio_tpu.s3.client import S3Client
    srv, layer = _mk_server(tmp_path, "adm")
    try:
        c = S3Client(srv.endpoint, "minioadmin", "minioadmin")
        c.make_bucket("abk")
        c.put_object("abk", "k", b"data")
        # no scan yet -> 404
        r = c.request("GET", "/minio-tpu/admin/v1/datausageinfo",
                      expect=(404,))
        assert r.status == 404
        Crawler(layer, BucketMetadataSys(layer)).run_cycle()
        r = c.request("GET", "/minio-tpu/admin/v1/datausageinfo")
        doc = json.loads(r.body)
        assert doc["bucketsUsageInfo"]["abk"]["objectsCount"] == 1
        # heal-status with wired services
        srv.mrf = MRFQueue(layer)
        srv.healer = BackgroundHealer(layer)
        srv.healer.sweep()
        r = c.request("GET", "/minio-tpu/admin/v1/heal-status")
        doc = json.loads(r.body)
        assert doc["sweep"]["objectsScanned"] == 1
        assert doc["mrf"]["mrfQueued"] == 0
        assert doc["mrf"]["mrfDropped"] == 0
    finally:
        srv.stop()


def test_heal_multipart_object_restores_every_part(tmp_path):
    """Regression (found by the soak matrix): rename_data REPLACES the
    data dir, so the old per-part heal commit left only the LAST part
    on the healed drive — a multipart object classified CORRUPT
    forever.  All parts must stage into one tmp dir with a single
    atomic commit per drive, leaving no tmp staging behind."""
    import glob
    import hashlib
    import os
    import shutil

    from minio_tpu.objectlayer.erasure_object import ErasureObjects
    from minio_tpu.storage.xl_storage import XLStorage
    disks = []
    for i in range(6):
        d = tmp_path / f"hd{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    er = ErasureObjects(disks, parity=2, block_size=256 * 1024,
                        backend="numpy")
    er.make_bucket("mph")
    uid = er.new_multipart_upload("mph", "obj")
    part = os.urandom(5 * 1024 * 1024)
    etags = [(pn, er.put_object_part("mph", "obj", uid, pn, part).etag)
             for pn in (1, 2)]
    er.complete_multipart_upload("mph", "obj", uid, etags)
    shutil.rmtree(tmp_path / "hd0" / "mph" / "obj")
    r = er.heal_object("mph", "obj")
    assert len(r.healed_disks) == 1
    # the healed drive classifies OK again — BOTH parts present
    r2 = er.heal_object("mph", "obj", dry_run=True)
    assert r2.before_ok == 6
    _, got = er.get_object("mph", "obj")
    assert hashlib.md5(bytes(got)).digest() == \
        hashlib.md5(part + part).digest()
    # staging cleaned up everywhere
    leftover = [p for i in range(6) for p in glob.glob(
        str(tmp_path / f"hd{i}" / ".mt.sys" / "tmp" / "*"))
        if os.path.isdir(p)]
    assert not leftover, leftover


def test_mrf_queue_full_counts_drops(er):
    """A full MRF queue must COUNT each dropped entry instead of
    silently losing the signal: the admin heal-status payload carries
    mrfDropped beside mrfQueued/mrfHealed and the scrape exports
    mt_heal_mrf_dropped_total (ISSUE 8 satellite)."""
    mrf = MRFQueue(er, maxsize=2)       # worker never started: entries sit
    mrf.add("mdb", "o1")
    mrf.add("mdb", "o2")
    mrf.add("mdb", "o3")                # queue full: dropped, counted
    mrf.add("mdb", "o4")
    assert mrf.stats.mrf_queued == 2
    assert mrf.stats.mrf_dropped == 2
    d = mrf.stats.to_dict()
    assert d["mrfQueued"] == 2 and d["mrfDropped"] == 2
    from minio_tpu.admin import metrics
    text = metrics.render(mrf=mrf)
    assert "mt_heal_mrf_dropped_total 2" in text
    assert "mt_heal_mrf_queued_total 2" in text


def test_build_server_wires_background_services(tmp_path):
    """A served deployment must run the crawler + heal sweep
    (cmd/server-main.go initDataCrawler/initBackgroundHealing) — and
    their state must surface through metrics and the admin API."""
    import re
    import urllib.request

    from minio_tpu.server_main import build_server

    dirs = [str(tmp_path / f"d{i}") for i in range(4)]
    import os as _os
    _os.environ["MT_CRAWL_INTERVAL_S"] = "3600"   # no mid-test cycles
    try:
        srv = build_server(dirs, address="127.0.0.1:0")
    finally:
        _os.environ.pop("MT_CRAWL_INTERVAL_S", None)
    assert srv.crawler is not None and srv.healer is not None
    assert srv.tracker is not None
    srv.start()
    try:
        from minio_tpu.s3.client import S3Client
        c = S3Client(srv.endpoint, "minioadmin", "minioadmin")
        c.make_bucket("bgbkt")
        c.put_object("bgbkt", "o", b"x" * 2048)
        srv.crawler.run_cycle()               # deterministic scan
        srv.healer.sweep()
        with urllib.request.urlopen(
                f"{srv.endpoint}/minio-tpu/metrics", timeout=10) as r:
            text = r.read().decode()
        assert re.search(
            r'mt_bucket_usage_object_total\{bucket="bgbkt"\} 1', text)
        assert "mt_heal_objects_scanned_total" in text
        m = re.search(r"mt_heal_objects_scanned_total (\d+)", text)
        assert m and int(m.group(1)) >= 1
    finally:
        srv.stop()
