"""Fused host PUT pipeline: framed-in-place encode must be bit-identical
to the copying encode_object + streaming_encode_batch path, and the
ETag policy must follow the reference's strict/no-compat semantics."""

import hashlib
import os

import numpy as np
import pytest

from minio_tpu.hashing import bitrot
from minio_tpu.ops import gf8_native
from minio_tpu.ops.codec import Erasure

pytestmark = pytest.mark.skipif(not gf8_native.available(),
                                reason="native gf8 unavailable")


@pytest.mark.parametrize("size", [
    0, 1, 100, 256 * 1024,                 # sub-block
    1 << 20,                               # exactly one block
    (1 << 20) + 1, 3 * (1 << 20) + 12345,  # tail block
    4 * (1 << 20),                         # full blocks only
])
def test_framed_bit_identical(size):
    k, m = 12, 4
    e = Erasure(data_blocks=k, parity_blocks=m, block_size=1 << 20,
                backend="numpy")
    data = np.random.default_rng(size or 7).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    framed2d = e.encode_object_framed(data)
    assert bitrot.fill_framed(framed2d, e.shard_size())
    shards = e.encode_object(data)
    want = bitrot.streaming_encode_batch(shards, e.shard_size())
    for i in range(k + m):
        assert framed2d[i].tobytes() == bytes(want[i]), f"shard {i}"


def test_framed_matches_small_geometry():
    e = Erasure(data_blocks=2, parity_blocks=2, block_size=256 * 1024,
                backend="numpy")
    data = os.urandom(700 * 1024 + 13)
    framed2d = e.encode_object_framed(data)
    assert bitrot.fill_framed(framed2d, e.shard_size())
    want = bitrot.streaming_encode_batch(
        e.encode_object(data), e.shard_size())
    for i in range(4):
        assert framed2d[i].tobytes() == bytes(want[i])


def test_etag_policy(tmp_path, monkeypatch):
    from minio_tpu.objectlayer.erasure_object import ErasureObjects
    from minio_tpu.objectlayer.interface import PutObjectOptions
    from minio_tpu.storage.errors import StorageError
    from minio_tpu.storage.xl_storage import XLStorage

    disks = []
    for i in range(4):
        d = tmp_path / f"d{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=256 * 1024,
                           backend="numpy")
    layer.make_bucket("etagbkt")
    body = os.urandom(300 * 1024)
    md5 = hashlib.md5(body).hexdigest()

    # strict (default): ETag is the md5
    info = layer.put_object("etagbkt", "strict", body)
    assert info.etag == md5

    # no-compat without Content-MD5: random 32-hex + "-1", md5 skipped
    monkeypatch.setenv("MT_NO_COMPAT", "1")
    info = layer.put_object("etagbkt", "nocompat", body)
    assert info.etag.endswith("-1") and len(info.etag) == 34
    assert info.etag != md5

    # no-compat WITH Content-MD5: verified and used
    info = layer.put_object("etagbkt", "withmd5", body,
                            PutObjectOptions(content_md5=md5))
    assert info.etag == md5
    with pytest.raises(StorageError):
        layer.put_object("etagbkt", "badmd5", body,
                         PutObjectOptions(content_md5="0" * 32))
    monkeypatch.delenv("MT_NO_COMPAT")
    # round trip: the fused framed path must read back
    _, got = layer.get_object("etagbkt", "strict")
    assert got == body
