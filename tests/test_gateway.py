"""Gateway mode tests (cmd/gateway-interface.go, cmd/gateway/{nas,s3}).

The S3 gateway is exercised as the reference tests gateways: a real
upstream (here our own erasure-backed server, in-process) fronted by a
gateway layer serving the full S3 frontend — a loopback double-hop.
"""

import pytest

from minio_tpu import gateway as gw
from minio_tpu.gateway.s3 import S3GatewayLayer
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.objectlayer.interface import (BucketExists, BucketNotFound,
                                             ObjectNotFound)
from minio_tpu.s3.client import S3Client
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.xl_storage import XLStorage


@pytest.fixture(scope="module")
def upstream(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gwupstream")
    disks = []
    for i in range(4):
        d = tmp / f"disk{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=128 * 1024,
                           backend="numpy")
    srv = S3Server(layer, access_key="upkey", secret_key="upsecret")
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture
def s3_layer(upstream):
    return S3GatewayLayer(S3Client(upstream.endpoint, "upkey", "upsecret"))


# -- registry -----------------------------------------------------------------

def test_registry_kinds():
    for kind in ("nas", "s3", "azure", "gcs", "hdfs"):
        assert gw.lookup(kind) is not None
    with pytest.raises(gw.GatewayError, match="unknown gateway"):
        gw.lookup("bogus")


def test_cloud_gateways_need_credentials(monkeypatch):
    """azure/gcs/hdfs are real wire gateways; constructing a layer
    without credentials/endpoint fails loudly with what is needed."""
    for var in ("AZURE_STORAGE_ENDPOINT", "AZURE_STORAGE_ACCOUNT",
                "AZURE_STORAGE_KEY", "GOOGLE_OAUTH_TOKEN",
                "HDFS_NAMENODE_URL"):
        monkeypatch.delenv(var, raising=False)
    with pytest.raises(gw.GatewayNotAvailable, match="HDFS_NAMENODE"):
        gw.lookup("hdfs")().new_gateway_layer()
    with pytest.raises(gw.GatewayNotAvailable, match="AZURE_STORAGE"):
        gw.lookup("azure")().new_gateway_layer()
    with pytest.raises(gw.GatewayNotAvailable, match="GOOGLE_OAUTH"):
        gw.lookup("gcs")().new_gateway_layer()


# -- NAS gateway --------------------------------------------------------------

def test_nas_gateway_round_trip(tmp_path):
    layer = gw.lookup("nas")(str(tmp_path / "mnt")).new_gateway_layer()
    layer.make_bucket("nasb")
    layer.put_object("nasb", "a/b.txt", b"nas data")
    info, data = layer.get_object("nasb", "a/b.txt")
    assert data == b"nas data"
    assert info.size == 8
    lst = layer.list_objects("nasb", delimiter="/")
    assert lst.prefixes == ["a/"]


def test_nas_gateway_served(tmp_path):
    from minio_tpu.server_main import build_gateway_server
    srv = build_gateway_server("nas", str(tmp_path / "mnt"),
                               address="127.0.0.1:0",
                               access_key="gk", secret_key="gs")
    srv.start()
    try:
        c = S3Client(srv.endpoint, "gk", "gs")
        c.make_bucket("served")
        c.put_object("served", "k", b"via gateway http")
        assert c.get_object("served", "k").body == b"via gateway http"
    finally:
        srv.stop()


# -- S3 gateway (loopback) ----------------------------------------------------

def test_s3_gateway_buckets(s3_layer):
    s3_layer.make_bucket("gwb")
    assert any(b.name == "gwb" for b in s3_layer.list_buckets())
    with pytest.raises(BucketExists):
        s3_layer.make_bucket("gwb")
    s3_layer.delete_bucket("gwb")
    with pytest.raises(BucketNotFound):
        s3_layer.get_bucket_info("gwb")


def test_s3_gateway_objects(s3_layer):
    s3_layer.make_bucket("gwo")
    from minio_tpu.objectlayer.interface import PutObjectOptions
    info = s3_layer.put_object(
        "gwo", "x/y", b"payload through two hops",
        PutObjectOptions(user_defined={"content-type": "text/x-test",
                                       "x-amz-meta-color": "teal"}))
    assert info.etag
    got, data = s3_layer.get_object("gwo", "x/y")
    assert data == b"payload through two hops"
    assert got.user_defined.get("x-amz-meta-color") == "teal"
    assert got.content_type == "text/x-test"

    # ranged read reports full object size via Content-Range
    got2, part = s3_layer.get_object("gwo", "x/y", offset=8, length=7)
    assert part == b"through"
    assert got2.size == len(data)

    head = s3_layer.get_object_info("gwo", "x/y")
    assert head.size == len(data)

    lst = s3_layer.list_objects("gwo", prefix="x/")
    assert [o.name for o in lst.objects] == ["x/y"]

    s3_layer.delete_object("gwo", "x/y")
    with pytest.raises(ObjectNotFound):
        s3_layer.get_object_info("gwo", "x/y")


def test_s3_gateway_internal_meta_tunnel(s3_layer):
    """SSE sealed-key / compression / tagging metadata (x-minio-internal-*,
    x-amz-tagging) must survive the remote hop via the x-amz-meta tunnel."""
    from minio_tpu.objectlayer.interface import PutObjectOptions
    s3_layer.make_bucket("gwi")
    ud = {"x-minio-internal-server-side-encryption-sealed-key": "AAAA",
          "x-minio-internal-compression": "klauspost/compress/s2",
          "x-amz-tagging": "k=v",
          "x-amz-meta-plain": "yes",
          "content-type": "application/x-sealed"}
    s3_layer.put_object("gwi", "enc", b"ciphertext-bytes",
                        PutObjectOptions(user_defined=dict(ud)))
    info = s3_layer.get_object_info("gwi", "enc")
    for k, v in ud.items():
        assert info.user_defined.get(k) == v, k


def test_s3_gateway_suffix_and_tail_ranges(s3_layer):
    s3_layer.make_bucket("gwr")
    s3_layer.put_object("gwr", "r", b"0123456789")
    _, tail = s3_layer.get_object("gwr", "r", offset=-4)
    assert tail == b"6789"
    _, opentail = s3_layer.get_object("gwr", "r", offset=7, length=-1)
    assert opentail == b"789"
    info, empty = s3_layer.get_object("gwr", "r", offset=3, length=0)
    assert empty == b"" and info.size == 10


def test_s3_gateway_pagination(s3_layer):
    s3_layer.make_bucket("gwp")
    for i in range(25):
        s3_layer.put_object("gwp", f"k{i:03d}", b"x")
    seen, marker = [], ""
    for _ in range(10):
        page = s3_layer.list_objects("gwp", marker=marker, max_keys=10)
        seen += [o.name for o in page.objects]
        if not page.is_truncated:
            break
        marker = page.next_continuation_token
    assert seen == [f"k{i:03d}" for i in range(25)]


def test_s3_gateway_multipart(s3_layer):
    s3_layer.make_bucket("gwmp")
    uid = s3_layer.new_multipart_upload("gwmp", "big")
    assert uid
    assert any(m.upload_id == uid
               for m in s3_layer.list_multipart_uploads("gwmp"))
    p1 = s3_layer.put_object_part("gwmp", "big", uid, 1, b"A" * (5 << 20))
    p2 = s3_layer.put_object_part("gwmp", "big", uid, 2, b"B" * 1024)
    parts = s3_layer.list_object_parts("gwmp", "big", uid)
    assert [p.part_number for p in parts] == [1, 2]
    info = s3_layer.complete_multipart_upload(
        "gwmp", "big", uid, [(1, p1.etag), (2, p2.etag)])
    assert info.etag.endswith("-2")
    _, data = s3_layer.get_object("gwmp", "big")
    assert len(data) == (5 << 20) + 1024
    assert data[-1:] == b"B"


def test_s3_gateway_multipart_abort(s3_layer):
    s3_layer.make_bucket("gwab")
    uid = s3_layer.new_multipart_upload("gwab", "zzz")
    s3_layer.put_object_part("gwab", "zzz", uid, 1, b"x" * 1024)
    s3_layer.abort_multipart_upload("gwab", "zzz", uid)
    assert all(m.upload_id != uid
               for m in s3_layer.list_multipart_uploads("gwab"))


def test_s3_gateway_with_disk_cache(upstream, tmp_path):
    """cmd/disk-cache.go:88 — cacheObjects deployed in front of a
    gateway backend: second GET must come from cache."""
    from minio_tpu.objectlayer.diskcache import CacheObjects
    inner = S3GatewayLayer(S3Client(upstream.endpoint, "upkey", "upsecret"))
    cached = CacheObjects(inner, [str(tmp_path / "cache0")])
    cached.make_bucket("gwc")
    cached.put_object("gwc", "obj", b"cache me please" * 100)
    _, d1 = cached.get_object("gwc", "obj")     # miss -> fill
    _, d2 = cached.get_object("gwc", "obj")     # hit
    assert d1 == d2 == b"cache me please" * 100
    assert cached.stats.hits >= 1
