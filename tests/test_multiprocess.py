"""Real multi-process cluster harness (buildscripts/verify-healing.sh
analog, SURVEY.md §4): three OS processes, each owning two drives of one
six-drive erasure set, talking over real internode RPC.  Kill a node,
keep serving; wipe its drives, restart, heal, verify the shards return.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from minio_tpu.s3.client import S3Client
from minio_tpu.s3.sigv4 import Credentials, sign_request

pytestmark = pytest.mark.skipif(
    os.environ.get("MT_SKIP_MULTIPROC") == "1",
    reason="multi-process harness disabled")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_s3(port, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/minio-tpu/metrics",
                timeout=2).close()
            return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError(f"s3 port {port} never came up")


class Cluster3:
    def __init__(self, tmp):
        self.tmp = tmp
        rpc = _free_ports(3)
        s3 = _free_ports(3)
        self.rpc_ports, self.s3_ports = rpc, s3
        self.dirs = {}
        peers = []
        for i, nid in enumerate(("n1", "n2", "n3")):
            ds = [str(tmp / f"{nid}d{j}") for j in range(2)]
            self.dirs[nid] = ds
            peers.append(f"{nid}=127.0.0.1:{rpc[i]}={','.join(ds)}")
        self.peers = peers
        self.procs = {}

    def start(self, nid):
        i = ("n1", "n2", "n3").index(nid)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MT_CLUSTER_SECRET="harness-secret")
        self.procs[nid] = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu", "node",
             "--node-id", nid, "--address",
             f"127.0.0.1:{self.s3_ports[i]}", "--backend", "numpy",
             *self.peers],
            env=env, stdout=open(self.tmp / f"{nid}.log", "wb"),
            stderr=subprocess.STDOUT)

    def kill(self, nid):
        p = self.procs.pop(nid)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)

    def stop_all(self):
        for nid in list(self.procs):
            self.kill(nid)

    def client(self, nid) -> S3Client:
        i = ("n1", "n2", "n3").index(nid)
        return S3Client(f"http://127.0.0.1:{self.s3_ports[i]}",
                        "minioadmin", "minioadmin")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mpcluster")
    c = Cluster3(tmp)
    for nid in ("n1", "n2", "n3"):
        c.start(nid)
    for p in c.s3_ports:
        _wait_s3(p)
    yield c
    c.stop_all()


def test_cross_node_put_get(cluster):
    c1 = cluster.client("n1")
    c1.make_bucket("mpb")
    body = os.urandom(200_000)
    c1.put_object("mpb", "obj1", body)
    # every node serves every object (remote shards over RPC)
    for nid in ("n1", "n2", "n3"):
        assert cluster.client(nid).get_object("mpb", "obj1").body == body


def test_node_loss_then_heal_after_wipe(cluster):
    c1 = cluster.client("n1")
    if not c1.head_bucket("mpb"):
        c1.make_bucket("mpb")
    body = os.urandom(150_000)
    c1.put_object("mpb", "healme", body)

    # hard-kill node 3: 4 of 6 shards remain, reads keep working
    cluster.kill("n3")
    assert cluster.client("n1").get_object("mpb", "healme").body == body
    assert cluster.client("n2").get_object("mpb", "healme").body == body

    # wipe node 3's drives entirely (verify-healing.sh drive wipe)
    import shutil
    for d in cluster.dirs["n3"]:
        shutil.rmtree(d, ignore_errors=True)

    # restart node 3 and heal the bucket through the admin API; the
    # remote-drive clients reconnect after a short cooldown
    # (RPCClient._retry_after), so poll the heal until it completes
    cluster.start("n3")
    _wait_s3(cluster.s3_ports[2])
    url = (f"http://127.0.0.1:{cluster.s3_ports[0]}"
           f"/minio-tpu/admin/v1/heal/mpb")
    deadline = time.monotonic() + 30
    report = None
    while time.monotonic() < deadline:
        hdrs = sign_request(Credentials("minioadmin", "minioadmin"),
                            "POST", url, {}, b"")
        with urllib.request.urlopen(urllib.request.Request(
                url, data=b"", method="POST", headers=hdrs)) as resp:
            report = json.loads(resp.read())
        by_obj = {o["object"]: o for o in report["objects"]}
        if by_obj.get("healme", {}).get("after_ok") == 6:
            break
        time.sleep(1)
    by_obj = {o["object"]: o for o in report["objects"]}
    assert by_obj["healme"]["after_ok"] == 6, report

    # healed shards physically exist on node 3's drives again
    shard_files = []
    for d in cluster.dirs["n3"]:
        for root, _dirs, files in os.walk(os.path.join(d, "mpb")):
            shard_files += [f for f in files if f.startswith("part.")]
    assert shard_files, "node 3 drives hold no healed shard files"

    # and node 3 serves reads again
    assert cluster.client("n3").get_object("mpb", "healme").body == body
