"""Real multi-process cluster harness (buildscripts/verify-healing.sh
analog, SURVEY.md §4): three OS processes, each owning two drives of one
six-drive erasure set, talking over real internode RPC.  Kill a node,
keep serving; wipe its drives, restart, heal, verify the shards return.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from minio_tpu.s3.client import S3Client
from minio_tpu.s3.sigv4 import Credentials, sign_request

# slow: 3-OS-process cluster boot/kill/heal cycles — runs in the full
# tier, not the tier-1 `-m 'not slow'` budget (VERDICT weak #5)
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("MT_SKIP_MULTIPROC") == "1",
        reason="multi-process harness disabled"),
]


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_s3(port, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/minio-tpu/metrics",
                timeout=2).close()
            return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError(f"s3 port {port} never came up")


class Cluster3:
    def __init__(self, tmp):
        self.tmp = tmp
        rpc = _free_ports(3)
        s3 = _free_ports(3)
        self.rpc_ports, self.s3_ports = rpc, s3
        self.dirs = {}
        peers = []
        for i, nid in enumerate(("n1", "n2", "n3")):
            ds = [str(tmp / f"{nid}d{j}") for j in range(2)]
            self.dirs[nid] = ds
            peers.append(f"{nid}=127.0.0.1:{rpc[i]}={','.join(ds)}")
        self.peers = peers
        self.procs = {}

    def start(self, nid):
        i = ("n1", "n2", "n3").index(nid)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MT_CLUSTER_SECRET="harness-secret")
        self.procs[nid] = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu", "node",
             "--node-id", nid, "--address",
             f"127.0.0.1:{self.s3_ports[i]}", "--backend", "numpy",
             *self.peers],
            env=env, stdout=open(self.tmp / f"{nid}.log", "wb"),
            stderr=subprocess.STDOUT)

    def kill(self, nid):
        p = self.procs.pop(nid)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)

    def stop_all(self):
        for nid in list(self.procs):
            self.kill(nid)

    def client(self, nid) -> S3Client:
        i = ("n1", "n2", "n3").index(nid)
        return S3Client(f"http://127.0.0.1:{self.s3_ports[i]}",
                        "minioadmin", "minioadmin")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mpcluster")
    c = Cluster3(tmp)
    for nid in ("n1", "n2", "n3"):
        c.start(nid)
    for p in c.s3_ports:
        _wait_s3(p)
    yield c
    c.stop_all()


def test_cross_node_put_get(cluster):
    c1 = cluster.client("n1")
    c1.make_bucket("mpb")
    body = os.urandom(200_000)
    c1.put_object("mpb", "obj1", body)
    # every node serves every object (remote shards over RPC)
    for nid in ("n1", "n2", "n3"):
        assert cluster.client(nid).get_object("mpb", "obj1").body == body


def test_node_loss_then_heal_after_wipe(cluster):
    c1 = cluster.client("n1")
    if not c1.head_bucket("mpb"):
        c1.make_bucket("mpb")
    body = os.urandom(150_000)
    c1.put_object("mpb", "healme", body)

    # hard-kill node 3: 4 of 6 shards remain, reads keep working
    cluster.kill("n3")
    assert cluster.client("n1").get_object("mpb", "healme").body == body
    assert cluster.client("n2").get_object("mpb", "healme").body == body

    # wipe node 3's drives entirely (verify-healing.sh drive wipe)
    import shutil
    for d in cluster.dirs["n3"]:
        shutil.rmtree(d, ignore_errors=True)

    # restart node 3 and heal the bucket through the admin API; the
    # remote-drive clients reconnect once their circuit breaker's
    # half-open probe succeeds after the cooldown (RPCClient.breaker),
    # so poll the heal until it completes
    cluster.start("n3")
    _wait_s3(cluster.s3_ports[2])
    url = (f"http://127.0.0.1:{cluster.s3_ports[0]}"
           f"/minio-tpu/admin/v1/heal/mpb")
    deadline = time.monotonic() + 30
    report = None
    while time.monotonic() < deadline:
        hdrs = sign_request(Credentials("minioadmin", "minioadmin"),
                            "POST", url, {}, b"")
        with urllib.request.urlopen(urllib.request.Request(
                url, data=b"", method="POST", headers=hdrs)) as resp:
            report = json.loads(resp.read())
        by_obj = {o["object"]: o for o in report["objects"]}
        if by_obj.get("healme", {}).get("after_ok") == 6:
            break
        time.sleep(1)
    by_obj = {o["object"]: o for o in report["objects"]}
    assert by_obj["healme"]["after_ok"] == 6, report

    # healed shards physically exist on node 3's drives again
    shard_files = []
    for d in cluster.dirs["n3"]:
        for root, _dirs, files in os.walk(os.path.join(d, "mpb")):
            shard_files += [f for f in files if f.startswith("part.")]
    assert shard_files, "node 3 drives hold no healed shard files"

    # and node 3 serves reads again
    assert cluster.client("n3").get_object("mpb", "healme").body == body


def test_peer_control_plane_coherence(cluster):
    """A policy/user change on node 1 is enforced on nodes 2 and 3
    IMMEDIATELY via the peer service (cmd/peer-rest-common.go:27-61) —
    no cache-expiry wait, no restart."""
    from minio_tpu.admin.client import AdminClient

    c1 = cluster.client("n1")
    if not c1.head_bucket("peerbkt"):
        c1.make_bucket("peerbkt")
    c1.put_object("peerbkt", "doc", b"coherent")

    admin1 = AdminClient(f"http://127.0.0.1:{cluster.s3_ports[0]}",
                         "minioadmin", "minioadmin")
    # prime every node's IAM view (they loaded at boot, no such user yet)
    from minio_tpu.s3.client import S3ClientError
    for nid in ("n2", "n3"):
        bad = S3Client(
            f"http://127.0.0.1:{cluster.s3_ports[('n1', 'n2', 'n3').index(nid)]}",
            "peeruser", "peersecret123")
        with pytest.raises(S3ClientError):
            bad.get_object("peerbkt", "doc")

    # create policy + user on node 1 only
    admin1.add_policy("peer-read", {
        "Version": "2012-10-17",
        "Statement": [{"Effect": "Allow",
                       "Action": ["s3:GetObject", "s3:ListBucket"],
                       "Resource": ["arn:aws:s3:::peerbkt",
                                    "arn:aws:s3:::peerbkt/*"]}]})
    admin1.add_user("peeruser", "peersecret123", ["peer-read"])

    # peer fan-out is async but immediate; allow a short settle
    deadline = time.monotonic() + 5
    last_err = None
    for nid in ("n2", "n3"):
        port = cluster.s3_ports[("n1", "n2", "n3").index(nid)]
        c = S3Client(f"http://127.0.0.1:{port}",
                     "peeruser", "peersecret123")
        while True:
            try:
                assert c.get_object("peerbkt", "doc").body == b"coherent"
                break
            except Exception as e:  # noqa: BLE001
                last_err = e
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"{nid} never saw the new user: {last_err}")
                time.sleep(0.1)

    # and the user is DENIED outside its grant on a remote node
    c3 = S3Client(f"http://127.0.0.1:{cluster.s3_ports[2]}",
                  "peeruser", "peersecret123")
    with pytest.raises(S3ClientError):
        c3.put_object("peerbkt", "denied", b"x")


def test_peer_trace_aggregation(cluster):
    """`mc admin trace` on one node shows requests served by OTHER nodes
    (peerRESTMethodTrace aggregation, cmd/admin-handlers.go:1082)."""
    import threading

    url = (f"http://127.0.0.1:{cluster.s3_ports[0]}"
           f"/minio-tpu/admin/v1/trace?timeout=6")
    hdrs = sign_request(Credentials("minioadmin", "minioadmin"),
                        "GET", url, {}, b"")
    lines: list[bytes] = []

    def consume():
        with urllib.request.urlopen(urllib.request.Request(
                url, headers=hdrs)) as resp:
            for line in resp:
                lines.append(line)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(1.0)       # stream subscribed
    c2 = cluster.client("n2")
    if not c2.head_bucket("tracebkt"):
        c2.make_bucket("tracebkt")
    c2.put_object("tracebkt", "traced-object", b"t")
    t.join(timeout=12)
    blob = b"".join(lines).decode("utf-8", "replace")
    assert "traced-object" in blob, blob[:2000]
    # the aggregated record names the serving node, not the admin node
    assert '"nodeName": "n2"' in blob or 'n2' in blob


_ACK_CLIENT = r"""
import hashlib, os, sys
sys.path.insert(0, {repo!r})
from minio_tpu.s3.client import S3Client
c = S3Client({endpoint!r}, "minioadmin", "minioadmin")
ack = open({ackfile!r}, "w")
if not c.head_bucket("crashbkt"):
    c.make_bucket("crashbkt")
i = 0
while True:
    body = os.urandom(64_000 + (i % 7) * 9000)
    key = f"obj-{{i}}"
    c.put_object("crashbkt", key, body)     # raises on failure
    # only record after the 200 came back: this is the acknowledged set
    ack.write(f"{{key}} {{hashlib.md5(body).hexdigest()}}\n")
    ack.flush()
    os.fsync(ack.fileno())
    i += 1
"""


def test_crash_consistency_kill9_mid_put(cluster):
    """Crash-consistency (cmd/xl-storage.go:1568,1965 durability contract):
    kill -9 the node serving a PUT stream; every acknowledged object must
    survive, and no xl.meta anywhere may be torn."""
    import hashlib

    ackfile = cluster.tmp / "acked.txt"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _ACK_CLIENT.format(
        repo=repo, endpoint=f"http://127.0.0.1:{cluster.s3_ports[0]}",
        ackfile=str(ackfile))
    client = subprocess.Popen([sys.executable, "-c", script],
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    try:
        # let some PUTs land, then kill the serving node mid-stream
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if ackfile.exists() and len(ackfile.read_text().splitlines()) >= 5:
                break
            time.sleep(0.1)
        cluster.kill("n1")
    finally:
        client.kill()
        client.wait(timeout=10)

    acked = [line.split() for line in ackfile.read_text().splitlines()]
    assert len(acked) >= 5, "client never got going"

    # acknowledged objects survive the crash, served by the other nodes
    c2 = cluster.client("n2")
    for key, md5hex in acked:
        got = c2.get_object("crashbkt", key).body
        assert hashlib.md5(got).hexdigest() == md5hex, key

    # no torn xl.meta anywhere in the cluster (partial PUT left no wreck)
    from minio_tpu.storage.xl_meta import XLMeta
    metas = 0
    for dirs in cluster.dirs.values():
        for d in dirs:
            for root, _dn, files in os.walk(d):
                if "xl.meta" in files:
                    XLMeta.load(open(os.path.join(root, "xl.meta"),
                                     "rb").read())   # raises if torn
                    metas += 1
    assert metas > 0

    # restart the killed node; it serves the acknowledged set again
    cluster.start("n1")
    _wait_s3(cluster.s3_ports[0])
    c1 = cluster.client("n1")
    key, md5hex = acked[-1]
    assert hashlib.md5(c1.get_object("crashbkt", key).body).hexdigest() \
        == md5hex


_MP_ACK_CLIENT = r"""
import hashlib, os, sys
sys.path.insert(0, {repo!r})
from minio_tpu.s3.client import S3Client
c = S3Client({endpoint!r}, "minioadmin", "minioadmin")
ack = open({ackfile!r}, "w")
if not c.head_bucket("mpcrash"):
    c.make_bucket("mpcrash")
i = 0
while True:
    key = f"mp-{{i}}"
    parts_md5 = hashlib.md5()
    r = c.request("POST", f"/mpcrash/{{key}}", "uploads")
    uid = r.xml().findtext(
        "{{http://s3.amazonaws.com/doc/2006-03-01/}}UploadId")
    etags = []
    for pn in (1, 2, 3):
        # S3 minimum part size: 5 MiB except the last part
        size = (5 * 1024 * 1024 + pn * 7000) if pn < 3 else 120_000
        body = os.urandom(size)
        parts_md5.update(body)
        pr = c.request("PUT", f"/mpcrash/{{key}}",
                       f"partNumber={{pn}}&uploadId={{uid}}", body=body)
        etags.append((pn, pr.headers.get("ETag", "")))
    xml = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{{n}}</PartNumber><ETag>{{e}}</ETag></Part>"
        for n, e in etags) + "</CompleteMultipartUpload>"
    c.request("POST", f"/mpcrash/{{key}}", f"uploadId={{uid}}",
              body=xml.encode())
    # only ack after complete returned 200
    ack.write(f"{{key}} {{parts_md5.hexdigest()}}\n")
    ack.flush()
    os.fsync(ack.fileno())
    i += 1
"""


def test_crash_consistency_kill9_mid_multipart(cluster):
    """Multipart crash-consistency (the r5 framed fast path in
    put_object_part + the staged-promote + journal-merge commit,
    cmd/erasure-multipart.go:342,678): kill -9 mid upload-stream;
    every COMPLETED upload must survive bit-exact, no xl.meta may be
    torn, and the in-flight upload must be invisible as an object."""
    import hashlib

    ackfile = cluster.tmp / "mp_acked.txt"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _MP_ACK_CLIENT.format(
        repo=repo, endpoint=f"http://127.0.0.1:{cluster.s3_ports[0]}",
        ackfile=str(ackfile))
    client = subprocess.Popen([sys.executable, "-c", script],
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            if ackfile.exists() and \
                    len(ackfile.read_text().splitlines()) >= 3:
                break
            time.sleep(0.1)
        cluster.kill("n1")
    finally:
        client.kill()
        client.wait(timeout=10)

    acked = [line.split() for line in ackfile.read_text().splitlines()]
    assert len(acked) >= 3, "client never completed an upload"

    c2 = cluster.client("n2")
    for key, md5hex in acked:
        got = c2.get_object("mpcrash", key).body
        assert hashlib.md5(got).hexdigest() == md5hex, key

    # the first never-acked upload: un-acked != uncommitted — the kill
    # can land between the server committing CompleteMultipartUpload
    # and the client receiving the 200, so EITHER clean absence (404)
    # or a durable, readable object is a correct outcome; a 5xx or a
    # torn read is not
    next_key = f"mp-{len(acked)}"
    r = c2.request("GET", f"/mpcrash/{next_key}", expect=())
    assert r.status in (404, 200), r.status
    if r.status == 200:
        assert len(r.body) > 0          # readable, not torn

    # no torn xl.meta anywhere (incl. multipart journals)
    from minio_tpu.storage.xl_meta import XLMeta
    metas = 0
    for dirs in cluster.dirs.values():
        for d in dirs:
            for root, _dn, files in os.walk(d):
                if "xl.meta" in files:
                    XLMeta.load(open(os.path.join(root, "xl.meta"),
                                     "rb").read())
                    metas += 1
    assert metas > 0

    # restart: the acked set still serves from the killed node
    cluster.start("n1")
    _wait_s3(cluster.s3_ports[0])
    c1 = cluster.client("n1")
    key, md5hex = acked[0]
    assert hashlib.md5(c1.get_object("mpcrash", key).body).hexdigest() \
        == md5hex
