"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py and the driver's graft entry;
the test suite must be runnable anywhere, with enough virtual devices to
test the multi-chip sharding paths (SURVEY.md section 7).

The env vars alone are not enough on hosts whose sitecustomize registers
an accelerator PJRT plugin (the axon tunnel re-selects its platform over
JAX_PLATFORMS); jax.config.update pins the platform authoritatively.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running subprocess tests (memory bounds, "
        "cluster harnesses)")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _release_hot_read_caches():
    """Hot-read plane isolation: cached windows hold memory-governor
    charges (kind="cache") for as long as their layer lives, and many
    suites keep layers alive past their test (module fixtures, GC
    cycles).  Releasing every plane's cache after each test keeps the
    strict governor-settles-to-zero assertions sound without each
    suite knowing the plane exists."""
    yield
    from minio_tpu.objectlayer import hotread
    hotread.clear_all_planes()
