"""Storage layer tests — posix drive, xl.meta journal, format, faults.

Mirrors the storage tier of the reference test strategy (SURVEY.md §4:
cmd/xl-storage_test.go, cmd/xl-storage-format_test.go,
cmd/naughty-disk_test.go).
"""

import os

import pytest

from minio_tpu.storage import errors, format as fmt
from minio_tpu.storage.datatypes import (ChecksumInfo, ErasureInfo, FileInfo,
                                         ObjectPartInfo, now_ns)
from minio_tpu.storage.faulty import BadDisk, NaughtyDisk
from minio_tpu.storage.xl_meta import XLMeta
from minio_tpu.storage.xl_storage import SYS_DIR, XLStorage


@pytest.fixture
def disk(tmp_path):
    return XLStorage(str(tmp_path))


def _fi(vid="", mod=None, ddir="d1", deleted=False):
    return FileInfo(volume="b", name="o", version_id=vid, deleted=deleted,
                    data_dir=ddir, mod_time=mod or now_ns(), size=100,
                    erasure=ErasureInfo(data_blocks=2, parity_blocks=1,
                                        block_size=1024, index=1,
                                        distribution=[1, 2, 3]))


# -- volumes ---------------------------------------------------------------

def test_vol_lifecycle(disk):
    disk.make_vol("bucket1")
    with pytest.raises(errors.VolumeExists):
        disk.make_vol("bucket1")
    assert [v.name for v in disk.list_vols()] == ["bucket1"]
    disk.stat_vol("bucket1")
    with pytest.raises(errors.VolumeNotFound):
        disk.stat_vol("nope")
    disk.write_all("bucket1", "x/y", b"data")
    with pytest.raises(errors.VolumeNotEmpty):
        disk.delete_vol("bucket1")
    disk.delete_vol("bucket1", force=True)
    with pytest.raises(errors.VolumeNotFound):
        disk.stat_vol("bucket1")


def test_path_traversal_blocked(disk):
    disk.make_vol("bkt")
    with pytest.raises(errors.FileAccessDenied):
        disk.read_all("bkt", "../../../etc/passwd")


# -- plain files -----------------------------------------------------------

def test_file_ops(disk):
    disk.make_vol("bkt")
    disk.write_all("bkt", "a/b/c.bin", b"hello")
    assert disk.read_all("bkt", "a/b/c.bin") == b"hello"
    assert disk.read_file_stream("bkt", "a/b/c.bin", 1, 3) == b"ell"
    with pytest.raises(errors.FileCorrupt):
        disk.read_file_stream("bkt", "a/b/c.bin", 0, 100)  # short read
    with pytest.raises(errors.FileNotFound):
        disk.read_all("bkt", "missing")
    assert disk.stat_info_file("bkt", "a/b/c.bin") == 5
    disk.append_file("bkt", "a/b/c.bin", b" world")
    assert disk.read_all("bkt", "a/b/c.bin") == b"hello world"
    disk.delete("bkt", "a/b/c.bin")
    # parent dirs pruned back to the volume root
    assert not os.path.exists(os.path.join(disk.root, "bkt", "a"))


def test_create_file_size_check(disk):
    disk.make_vol("bkt")
    disk.create_file("bkt", "f", b"12345", file_size=5)
    with pytest.raises(errors.FileCorrupt):
        disk.create_file("bkt", "g", b"123", file_size=5)


# -- xl.meta journal -------------------------------------------------------

def test_xlmeta_roundtrip():
    m = XLMeta()
    f1 = _fi("v1", mod=100)
    f2 = _fi("v2", mod=200, ddir="d2")
    m.add_version(f1)
    m.add_version(f2)
    m2 = XLMeta.load(m.dump())
    top = m2.to_fileinfo("b", "o")
    assert top.version_id == "v2" and top.is_latest
    old = m2.to_fileinfo("b", "o", "v1")
    assert old.version_id == "v1" and not old.is_latest
    assert old.num_versions == 2
    with pytest.raises(errors.FileVersionNotFound):
        m2.to_fileinfo("b", "o", "nope")


def test_xlmeta_bad_magic():
    with pytest.raises(errors.FileCorrupt):
        XLMeta.load(b"garbage-not-xlmeta")


def test_metadata_ops(disk):
    disk.make_vol("bkt")
    fi = _fi("v1")
    disk.write_metadata("bkt", "obj", fi)
    got = disk.read_version("bkt", "obj")
    assert got.version_id == "v1"
    assert got.erasure.data_blocks == 2
    assert got.erasure.distribution == [1, 2, 3]
    with pytest.raises(errors.FileNotFound):
        disk.read_version("bkt", "missing")

    # second version becomes latest
    fi2 = _fi("v2", mod=fi.mod_time + 10, ddir="d2")
    disk.write_metadata("bkt", "obj", fi2)
    assert disk.read_version("bkt", "obj").version_id == "v2"
    assert [f.version_id for f in disk.list_versions("bkt", "obj")] == \
        ["v2", "v1"]

    # delete specific version
    disk.delete_version("bkt", "obj", fi)
    assert [f.version_id for f in disk.list_versions("bkt", "obj")] == ["v2"]
    # deleting the last version removes xl.meta entirely
    disk.delete_version("bkt", "obj", fi2)
    with pytest.raises(errors.FileNotFound):
        disk.read_version("bkt", "obj")


def test_rename_data_commit(disk):
    disk.make_vol("bkt")
    tmp = disk.tmp_dir()
    disk.create_file(SYS_DIR, f"{tmp}/part.1", b"shard-bytes")
    fi = _fi("v1", ddir="datadir1")
    disk.rename_data(SYS_DIR, tmp, fi, "bkt", "obj")
    assert disk.read_version("bkt", "obj").data_dir == "datadir1"
    assert disk.read_all("bkt", "obj/datadir1/part.1") == b"shard-bytes"
    # overwrite same version with new data dir purges the old one
    tmp2 = disk.tmp_dir()
    disk.create_file(SYS_DIR, f"{tmp2}/part.1", b"new-bytes")
    fi2 = _fi("v1", mod=fi.mod_time + 5, ddir="datadir2")
    disk.rename_data(SYS_DIR, tmp2, fi2, "bkt", "obj")
    assert disk.read_all("bkt", "obj/datadir2/part.1") == b"new-bytes"
    with pytest.raises(errors.FileNotFound):
        disk.read_all("bkt", "obj/datadir1/part.1")


def test_delete_marker(disk):
    disk.make_vol("bkt")
    fi = _fi("v1")
    disk.write_metadata("bkt", "obj", fi)
    dm = _fi("v2", mod=fi.mod_time + 10, ddir="", deleted=True)
    disk.delete_version("bkt", "obj", dm)
    top = disk.read_version("bkt", "obj")
    assert top.deleted and top.version_id == "v2"
    assert len(disk.list_versions("bkt", "obj")) == 2


def test_walk_dir(disk):
    disk.make_vol("bkt")
    for name in ["a/obj1", "a/obj2", "b/c/obj3"]:
        disk.write_metadata("bkt", name, _fi("v1"))
    got = list(disk.walk_dir("bkt"))
    assert got == ["a/obj1", "a/obj2", "b/c/obj3"]
    assert list(disk.walk_dir("bkt", "b")) == ["b/c/obj3"]


# -- bitrot-integrated verify ---------------------------------------------

def test_verify_file(disk):
    from minio_tpu.hashing import bitrot
    disk.make_vol("bkt")
    shard = bytes(range(256)) * 8  # 2048 bytes
    ec = ErasureInfo(data_blocks=2, parity_blocks=1, block_size=4096,
                     index=1, distribution=[1, 2, 3],
                     checksums=[ChecksumInfo(1, bitrot.HIGHWAYHASH256S)])
    # shard_size = ceil(4096/2) = 2048; one block
    framed = bitrot.streaming_encode(shard, 2048)
    fi = FileInfo(version_id="v1", data_dir="dd", mod_time=now_ns(),
                  size=4096, erasure=ec,
                  parts=[ObjectPartInfo(1, 4096, 4096)])
    disk.write_all("bkt", "obj/dd/part.1", framed)
    disk.write_metadata("bkt", "obj", fi)
    disk.verify_file("bkt", "obj", fi)
    disk.check_parts("bkt", "obj", fi)

    # corrupt one byte -> FileCorrupt
    bad = bytearray(framed)
    bad[40] ^= 1
    disk.write_all("bkt", "obj/dd/part.1", bytes(bad))
    with pytest.raises(errors.FileCorrupt):
        disk.verify_file("bkt", "obj", fi)
    # truncation -> CheckParts fails
    disk.write_all("bkt", "obj/dd/part.1", framed[:-3])
    with pytest.raises(errors.FileCorrupt):
        disk.check_parts("bkt", "obj", fi)


# -- format ----------------------------------------------------------------

def test_format_init_and_load(tmp_path):
    disks = []
    for i in range(4):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    ref = fmt.load_or_init_format(disks, set_count=1, set_drive_count=4)
    assert len(ref.sets) == 1 and len(ref.sets[0]) == 4
    ids = [d.get_disk_id() for d in disks]
    assert ids == ref.sets[0]
    # reload keeps identity
    disks2 = [XLStorage(str(tmp_path / f"disk{i}")) for i in range(4)]
    ref2 = fmt.load_or_init_format(disks2, 1, 4)
    assert ref2.id == ref.id
    assert [d.get_disk_id() for d in disks2] == ids


def test_format_mismatch(tmp_path):
    (tmp_path / "d0").mkdir()
    (tmp_path / "d1").mkdir()
    a, b = XLStorage(str(tmp_path / "d0")), XLStorage(str(tmp_path / "d1"))
    fmt.load_or_init_format([a], 1, 1)
    fmt.load_or_init_format([b], 1, 1)  # different deployment
    with pytest.raises(errors.CorruptedFormat):
        fmt.load_or_init_format([a, b], 1, 2)


# -- fault injection -------------------------------------------------------

def test_naughty_disk(disk):
    disk.make_vol("bkt")
    disk.write_all("bkt", "f", b"x")
    nd = NaughtyDisk(disk, errs={2: errors.FaultyDisk("boom")})
    assert nd.read_all("bkt", "f") == b"x"        # call 1 passes
    with pytest.raises(errors.FaultyDisk):
        nd.read_all("bkt", "f")                   # call 2 programmed error
    assert nd.read_all("bkt", "f") == b"x"        # call 3 passes (no default)


def test_bad_disk():
    bd = BadDisk()
    assert not bd.is_online()
    with pytest.raises(errors.FaultyDisk):
        bd.read_all("b", "f")


def test_odirect_round_trip(tmp_path, monkeypatch):
    """MT_ODIRECT path: aligned O_DIRECT reads/writes are bit-identical
    to buffered IO on a real filesystem, and fall back cleanly where
    O_DIRECT is unsupported (tmpfs)."""
    import minio_tpu.storage.xl_storage as xs

    monkeypatch.setattr(xs, "_ODIRECT", True)
    for base in (str(tmp_path), "/dev/shm"):
        if not os.access(base, os.W_OK):
            continue
        root = os.path.join(base, f"od-{os.getpid()}")
        os.makedirs(root, exist_ok=True)
        try:
            d = xs.XLStorage(root)
            d.make_vol("odbkt")
            blob = os.urandom(100 * 1024 + 123)     # unaligned length
            d.create_file("odbkt", "obj/part.1", blob)
            got = d.read_file_stream("odbkt", "obj/part.1", 0,
                                     len(blob))
            assert got == blob
            # unaligned offset + length
            assert d.read_file_stream("odbkt", "obj/part.1",
                                      4097, 8191) == blob[4097:
                                                          4097 + 8191]
            # offset 0 short file
            d.create_file("odbkt", "tiny", b"xyz")
            assert d.read_file_stream("odbkt", "tiny", 0, 3) == b"xyz"
        finally:
            import shutil as _sh
            _sh.rmtree(root, ignore_errors=True)
