"""Single-kernel fused encode+HH256 (ops/rs_fused.py) and the device
multi-buffer MD5 (hashing/md5_device.py): bit-identity is the whole
contract.

* the fused kernel's parity must match the GF(2^8) reference and its
  digests the host HighwayHash-256, across ragged geometries (the
  BASELINE-config k/m matrix), tail blocks (widths not multiples of
  the 32-byte packet or the lane tile), batch padding boundaries, and
  the data-only ``hash_parity=False`` mesh form;
* the mesh data plane's single-kernel path must agree with the proven
  two-kernel pipeline byte for byte, and the production framed path
  must still ride the batcher's ``encode-bitrot`` bucket;
* the device MD5 must agree with hashlib at the md5fast boundary
  lengths (0/1/55/56/63/64/65/4MiB±1) and any update split, through
  the ``md5`` combining bucket included, and the backend ladder must
  degrade with a NAMED reason when no device is usable.
"""

import hashlib
import os
import threading

import numpy as np
import pytest

from minio_tpu.hashing import md5_device, md5fast
from minio_tpu.hashing.highwayhash import MAGIC_KEY, HighwayHash256
from minio_tpu.ops import gf8, gf8_ref, rs_fused
from minio_tpu.parallel import batcher

RNG = np.random.default_rng(12)


def _hh(row) -> bytes:
    h = HighwayHash256(MAGIC_KEY)
    h.update(bytes(row))
    return h.digest()


def _check(blocks, par, dig, k, m):
    B = blocks.shape[0]
    ref_par = np.stack([gf8_ref.encode_parity(blocks[b], m)
                        for b in range(B)])
    assert np.array_equal(np.asarray(par), ref_par)
    dig = np.asarray(dig)
    for b in range(B):
        for s in range(k):
            assert dig[b, s].tobytes() == _hh(blocks[b, s]), (b, s)
        for s in range(m):
            assert dig[b, k + s].tobytes() == _hh(ref_par[b, s]), (b, s)


class TestFusedKernel:
    # the BASELINE-config k/m matrix: config 1 (4+2), config 2 (8+4),
    # the 12+4 headline, plus odd non-dividing geometries
    @pytest.mark.parametrize("k,m", [
        (4, 2), (3, 2), (5, 1),
        # the wide configs compile ~15s each on CPU interpret mode;
        # the slow tier keeps them, tier-1 keeps the 4+2 baseline, the
        # odd non-dividing geometry, and the m=1 floor that catch
        # tiling bugs — 6+3 re-proves the dividing case 4+2 already
        # covers (~9s of compile)
        pytest.param(6, 3, marks=pytest.mark.slow),
        pytest.param(8, 4, marks=pytest.mark.slow),
        pytest.param(12, 4, marks=pytest.mark.slow),
    ])
    def test_bit_identity_ragged_geometry(self, k, m):
        blocks = RNG.integers(0, 256, (3, k, 997), dtype=np.uint8)
        par, dig = rs_fused.encode_with_bitrot_fused(k, m, blocks)
        _check(blocks, par, dig, k, m)

    @pytest.mark.parametrize("n", [31, 32, 33, 256, 2048, 2079, 2080])
    def test_tail_blocks_across_lane_tiles(self, n):
        """Widths below one packet, exactly on packet/lane-tile edges,
        and crossing the 2048-byte tile — the digest must cover
        exactly n bytes, never the kernel's padding."""
        k, m = 4, 2
        blocks = RNG.integers(0, 256, (2, k, n), dtype=np.uint8)
        par, dig = rs_fused.encode_with_bitrot_fused(k, m, blocks)
        _check(blocks, par, dig, k, m)

    @pytest.mark.parametrize("B", [
        1, 2,
        # B=5/9 re-prove the same pad-to-batch rule at larger sizes
        # (~10s each); slow tier keeps them
        pytest.param(5, marks=pytest.mark.slow),
        pytest.param(9, marks=pytest.mark.slow),
    ])
    def test_batch_padding_boundaries(self, B):
        blocks = RNG.integers(0, 256, (B, 6, 300), dtype=np.uint8)
        par, dig = rs_fused.encode_with_bitrot_fused(6, 2, blocks)
        _check(blocks, par, dig, 6, 2)

    def test_hash_parity_false_hashes_data_only(self):
        """The mesh form: per-device parity is partial before the ring
        XOR, so the kernel hashes only the data lanes."""
        k, m, B, n = 6, 2, 4, 500
        blocks = RNG.integers(0, 256, (B, k, n), dtype=np.uint8)
        rows = np.asarray(gf8.rs_matrix(k, k + m))[k:]
        par, dig = rs_fused.encode_hash_device(rows, blocks,
                                               hash_parity=False)
        par, dig = np.asarray(par), np.asarray(dig)
        assert dig.shape == (B, k, 32)
        ref_par = np.stack([gf8_ref.encode_parity(blocks[b], m)
                            for b in range(B)])
        assert np.array_equal(par, ref_par)
        for b in range(B):
            for s in range(k):
                assert dig[b, s].tobytes() == _hh(blocks[b, s])

    def test_plan_rejects_oversized_stripe(self):
        with pytest.raises(ValueError):
            rs_fused.plan(4, 1000, 100, 4096)

    @pytest.mark.slow    # ~108s of interpret-mode mesh compiles;
    # test_mesh.py keeps the fast-tier mesh data-plane coverage and
    # the slow tier still runs this full single-vs-two-kernel proof
    def test_mesh_single_kernel_matches_two_kernel(self, monkeypatch):
        """The mesh data plane's single-kernel path vs the proven
        two-kernel pipeline: byte-identical parity AND digests on a
        sharded mesh (partial-parity ring form) and a stripe-only
        mesh (full in-kernel hash form)."""
        from minio_tpu.ops import rs_mesh
        from minio_tpu.parallel import mesh as pmesh
        monkeypatch.setenv("MT_MESH_PALLAS", "1")
        prev = pmesh._ACTIVE
        try:
            for stripe, shard in ((2, 4), (8, 1)):
                pmesh.set_active_mesh(
                    pmesh.make_mesh(stripe=stripe, shard=shard))
                blocks = RNG.integers(0, 256, (3, 12, 1000),
                                      dtype=np.uint8)
                monkeypatch.setenv("MT_FUSED_SINGLE", "0")
                par0, dig0 = rs_mesh.encode_with_bitrot(12, 4, blocks)
                monkeypatch.setenv("MT_FUSED_SINGLE", "1")
                rs_mesh._SINGLE_STATE["ok"] = None
                par1, dig1 = rs_mesh.encode_with_bitrot(12, 4, blocks)
                # the single-kernel engine must have actually RUN —
                # a silent fallback would make this test vacuous
                assert rs_mesh._SINGLE_STATE["ok"] is True
                assert np.array_equal(par0, par1), (stripe, shard)
                assert np.array_equal(dig0, dig1), (stripe, shard)
                _check(blocks, par1, dig1, 12, 4)
        finally:
            pmesh.set_active_mesh(prev)

    @pytest.mark.slow    # ~77s mesh compile; the batcher-engagement
    # contract stays covered fast-tier by test_batcher.py, and the
    # slow tier runs this full framed production path
    def test_framed_fused_rides_encode_bitrot_bucket(self, monkeypatch):
        """The production mesh PUT path through the batcher's
        ``encode-bitrot`` bucket, single-kernel engine on: coalesced
        AND bit-identical to the unbatched unfused reference."""
        from minio_tpu.ops import rs_mesh
        from minio_tpu.parallel import mesh as pmesh
        monkeypatch.setenv("MT_MESH_PALLAS", "1")
        prev = pmesh._ACTIVE
        cfg = batcher.CONFIG
        saved = (cfg.enable, cfg._loaded)
        pmesh.set_active_mesh(pmesh.make_mesh(stripe=2))
        try:
            cfg._loaded = True
            data = bytes(RNG.integers(0, 256, 3 * 65536 + 17,
                                      dtype=np.uint8))
            monkeypatch.setenv("MT_FUSED_SINGLE", "0")
            cfg.enable = False
            want = rs_mesh.encode_object_framed_fused(4, 2, 65536,
                                                      data)
            monkeypatch.setenv("MT_FUSED_SINGLE", "1")
            cfg.enable = True
            rs_mesh._SINGLE_STATE["ok"] = None
            s0 = batcher.GLOBAL.snapshot()
            got = rs_mesh.encode_object_framed_fused(4, 2, 65536,
                                                     data)
            s1 = batcher.GLOBAL.snapshot()
            assert s1["dispatches"] > s0["dispatches"]
            assert rs_mesh._SINGLE_STATE["ok"] is True  # really ran
            assert np.array_equal(want, got)
        finally:
            (cfg.enable, cfg._loaded) = saved
            pmesh.set_active_mesh(prev)


# -- device MD5 conformance -------------------------------------------------

pytestmark_device = pytest.mark.skipif(
    not md5_device.available(),
    reason=md5_device.unavailable_reason() or "device md5 available")

_4MIB = 4 * (1 << 20)
BOUNDARY_LENGTHS = [0, 1, 55, 56, 63, 64, 65,
                    _4MIB - 1, _4MIB, _4MIB + 1]


def _direct(h, words):
    """Bucket-free dispatch: the raw batched compress."""
    return md5_device.advance(h[None], words[None],
                              np.asarray([words.shape[0]]))[0]


@pytestmark_device
class TestDeviceMD5Conformance:
    @pytest.mark.parametrize("n", BOUNDARY_LENGTHS)
    def test_oneshot_matches_hashlib(self, n):
        data = os.urandom(n)
        h = md5_device.MD5Device(dispatch=_direct)
        h.update(data)
        assert h.hexdigest() == hashlib.md5(data).hexdigest()

    @pytest.mark.parametrize("split", [1, 63, 64, 65, 4096])
    def test_split_updates_match(self, split):
        data = os.urandom(3 * 4096 + 7)
        h = md5_device.MD5Device(dispatch=_direct)
        for off in range(0, len(data), split):
            h.update(data[off:off + split])
        assert h.hexdigest() == hashlib.md5(data).hexdigest()

    def test_digest_keeps_stream_usable_and_copy_forks(self):
        h = md5_device.MD5Device(b"abc", dispatch=_direct)
        assert h.hexdigest() == hashlib.md5(b"abc").hexdigest()
        h.update(b"def")
        c = h.copy()
        c.update(b"x")
        h.update(b"y")
        assert c.hexdigest() == hashlib.md5(b"abcdefx").hexdigest()
        assert h.hexdigest() == hashlib.md5(b"abcdefy").hexdigest()

    def test_ragged_batch_through_advance(self):
        """One dispatch, lanes advancing by DIFFERENT block counts —
        the masked-lane contract."""
        bufs = [os.urandom(64 * nb) for nb in (5, 2, 9, 1)]
        nb_max = 9
        states = np.tile(np.asarray(md5_device._INIT, np.uint32),
                         (len(bufs), 1))
        words = np.zeros((len(bufs), nb_max, 16), np.uint32)
        for i, b in enumerate(bufs):
            words[i, :len(b) // 64] = np.frombuffer(
                b, "<u4").reshape(-1, 16)
        out = md5_device.advance(
            states, words,
            np.asarray([len(b) // 64 for b in bufs], np.int32))
        for i, b in enumerate(bufs):
            h = md5_device.MD5Device(dispatch=_direct)
            h._h = [int(x) for x in out[i]]
            h._n = len(b)
            assert h.hexdigest() == hashlib.md5(b).hexdigest(), i

    def test_concurrent_streams_coalesce_through_md5_bucket(self):
        """Concurrent MD5Device streams through the production ``md5``
        bucket: digests bit-identical, requests coalesced into fewer
        dispatches, and the bucket drains to idle."""
        datas = [os.urandom(200_000 + 13 * i) for i in range(6)]
        outs: list = [None] * len(datas)

        def run(i):
            h = md5_device.MD5Device()       # default: MD5_GLOBAL
            mv = memoryview(datas[i])
            for off in range(0, len(mv), 65536):
                h.update(mv[off:off + 65536])
            outs[i] = h.hexdigest()

        s0 = batcher.MD5_GLOBAL.snapshot()
        ts = [threading.Thread(target=run, args=(i,), daemon=True,
                               name=f"mt-md5dev-{i}")
              for i in range(len(datas))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        s1 = batcher.MD5_GLOBAL.snapshot()
        for i, d in enumerate(datas):
            assert outs[i] == hashlib.md5(d).hexdigest(), i
        assert s1["requests"] - s0["requests"] >= len(datas)
        assert s1["dispatches"] > s0["dispatches"]
        assert batcher.MD5_GLOBAL.idle()

    def test_md5_factory_device_backend(self):
        md5fast.set_backend("device")
        try:
            h = md5fast.md5(b"hello")
            assert isinstance(h, md5_device.MD5Device)
            assert h.hexdigest() == hashlib.md5(b"hello").hexdigest()
        finally:
            md5fast.set_backend("auto")


class TestBackendLadder:
    def test_unavailable_reason_is_named(self, monkeypatch):
        """No usable device must degrade with a NAMED reason (the
        skip/telemetry contract), never a bare False."""
        monkeypatch.setattr(md5_device, "_AVAIL", False)
        monkeypatch.setattr(md5_device, "_REASON",
                            "device MD5 unavailable: RuntimeError: "
                            "jax reports zero devices")
        assert not md5_device.available()
        assert "device MD5 unavailable" in \
            md5_device.unavailable_reason()

    def test_device_backend_falls_back_and_counts(self, monkeypatch):
        """pipeline.md5_backend=device with no device lands on the
        next rung and bumps mt_md5_device_fallback_total."""
        from minio_tpu.admin.metrics import GLOBAL as mtr
        monkeypatch.setattr(md5_device, "_AVAIL", False)
        monkeypatch.setattr(md5_device, "_REASON", "device MD5 "
                            "unavailable: forced by test")
        key = ("mt_md5_device_fallback_total", ())
        md5fast.set_backend("device")
        try:
            before = mtr.snapshot().get(key, 0)
            h = md5fast.md5(b"xyz")
            assert not isinstance(h, md5_device.MD5Device)
            assert h.hexdigest() == hashlib.md5(b"xyz").hexdigest()
            assert mtr.snapshot().get(key, 0) == before + 1
        finally:
            md5fast.set_backend("auto")

    def test_mt_md5_hashlib_outranks_knob(self, monkeypatch):
        monkeypatch.setenv("MT_MD5", "hashlib")
        md5fast.set_backend("device")
        try:
            h = md5fast.md5(b"k")
            assert h.__class__.__module__ == "_hashlib" or \
                not isinstance(h, (md5fast.MD5Fast,
                                   md5_device.MD5Device))
        finally:
            md5fast.set_backend("auto")

    def test_auto_choice_is_cached_and_valid(self):
        md5fast.set_backend("auto")
        choice = md5fast._resolve_backend()
        assert choice in ("device", "native", "hashlib")
        assert md5fast._resolve_backend() == choice

    def test_live_reload_changes_backend(self):
        """reload_pipeline_config -> set_backend: the knob lands on a
        live layer (the SetConfigKV path)."""
        from minio_tpu.utils.kvconfig import Config
        cfg = Config()
        cfg.set("pipeline", "md5_backend", "hashlib")
        try:
            md5fast.set_backend(cfg.get("pipeline", "md5_backend"))
            assert md5fast._resolve_backend() == "hashlib"
        finally:
            md5fast.set_backend("auto")
