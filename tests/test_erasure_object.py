"""Erasure object engine tests.

Mirrors the backend-generic object suite + fault-injection tiers of the
reference (SURVEY.md §4: cmd/object_api_suite_test.go,
cmd/erasure-object_test.go, cmd/erasure-healing_test.go) on tmp-dir drives.
Uses the numpy codec backend (bit-identical with the TPU path, which is
covered by tests/test_codec.py equivalence tests).
"""

import os

import numpy as np
import pytest

from minio_tpu.objectlayer import healing
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.objectlayer.interface import (BucketExists, BucketNotFound,
                                             InvalidRange, MethodNotAllowed,
                                             ObjectNotFound, ObjectOptions,
                                             PutObjectOptions,
                                             ReadQuorumError)
from minio_tpu.storage import errors as serrors
from minio_tpu.storage.faulty import BadDisk
from minio_tpu.storage.xl_storage import XLStorage

BS = 64 * 1024  # small block size so multi-stripe paths get exercised


def make_layer(tmp_path, n=6, parity=2, inline=128 * 1024, bs=BS):
    disks = []
    for i in range(n):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=parity, block_size=bs,
                          backend="numpy", inline_threshold=inline)


@pytest.fixture
def er(tmp_path):
    layer = make_layer(tmp_path)
    layer.make_bucket("bkt")
    return layer


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# -- buckets ---------------------------------------------------------------

def test_bucket_lifecycle(tmp_path):
    er = make_layer(tmp_path)
    er.make_bucket("alpha")
    with pytest.raises(BucketExists):
        er.make_bucket("alpha")
    assert [b.name for b in er.list_buckets()] == ["alpha"]
    er.get_bucket_info("alpha")
    with pytest.raises(BucketNotFound):
        er.get_bucket_info("beta")
    er.delete_bucket("alpha")
    with pytest.raises(BucketNotFound):
        er.get_bucket_info("alpha")


# -- put/get round trips ---------------------------------------------------

@pytest.mark.parametrize("size", [0, 1, 100, BS - 1, BS, BS + 1,
                                  3 * BS + 17, 300 * 1024])
def test_put_get_roundtrip(er, size):
    data = _data(size, seed=size)
    oi = er.put_object("bkt", f"obj-{size}", data)
    assert oi.size == size
    info, got = er.get_object("bkt", f"obj-{size}")
    assert got == data
    assert info.etag == oi.etag
    assert er.get_object_info("bkt", f"obj-{size}").size == size


def test_get_range(er):
    data = _data(3 * BS + 100, seed=9)
    er.put_object("bkt", "obj", data)
    for off, ln in [(0, 10), (BS - 5, 10), (BS, BS), (2 * BS + 7, 93),
                    (0, len(data)), (len(data) - 1, 1)]:
        _, got = er.get_object("bkt", "obj", offset=off, length=ln)
        assert got == data[off:off + ln], (off, ln)
    with pytest.raises(InvalidRange):
        er.get_object("bkt", "obj", offset=len(data), length=1)


def test_get_missing(er):
    with pytest.raises(ObjectNotFound):
        er.get_object("bkt", "nope")
    with pytest.raises(BucketNotFound):
        er.get_object("missing-bucket", "obj")


def test_overwrite(er):
    er.put_object("bkt", "obj", b"first version")
    er.put_object("bkt", "obj", b"second version, longer")
    _, got = er.get_object("bkt", "obj")
    assert got == b"second version, longer"


# -- degraded reads (cmd/erasure-decode.go parallelReader semantics) -------

def test_read_with_offline_disks(tmp_path):
    er = make_layer(tmp_path, n=6, parity=2, inline=0)
    er.make_bucket("bkt")
    data = _data(2 * BS + 333, seed=1)
    er.put_object("bkt", "obj", data)
    # take 2 drives offline -> still readable (k=4 of 6)
    er.disks[1] = None
    er.disks[4] = None
    _, got = er.get_object("bkt", "obj")
    assert got == data
    # third failure exceeds parity -> read quorum error
    er.disks[2] = None
    with pytest.raises((ReadQuorumError, ObjectNotFound)):
        er.get_object("bkt", "obj")


def test_read_with_corrupt_shard(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2, inline=0)
    er.make_bucket("bkt")
    data = _data(BS + 50, seed=2)
    er.put_object("bkt", "obj", data)
    # corrupt one shard file on disk 0 (any part file found)
    corrupted = 0
    for disk in er.disks[:2]:
        root = disk.root
        for dirpath, _, files in os.walk(os.path.join(root, "bkt")):
            for f in files:
                if f.startswith("part."):
                    p = os.path.join(dirpath, f)
                    raw = bytearray(open(p, "rb").read())
                    raw[len(raw) // 2] ^= 0xFF
                    open(p, "wb").write(bytes(raw))
                    corrupted += 1
    assert corrupted == 2
    _, got = er.get_object("bkt", "obj")  # bitrot detected -> reconstruct
    assert got == data


def test_write_quorum_failure(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2)
    er.make_bucket("bkt")
    # 4 drives, k=2, write quorum=2... kill 3 drives
    er.disks[0] = BadDisk()
    er.disks[1] = BadDisk()
    er.disks[2] = BadDisk()
    from minio_tpu.objectlayer.interface import WriteQuorumError
    with pytest.raises(WriteQuorumError):
        er.put_object("bkt", "obj", b"payload")


# -- delete + versioning ---------------------------------------------------

def test_delete_object(er):
    er.put_object("bkt", "obj", b"bytes")
    er.delete_object("bkt", "obj")
    with pytest.raises(ObjectNotFound):
        er.get_object("bkt", "obj")
    # idempotent
    er.delete_object("bkt", "obj")


def test_versioned_put_and_delete_marker(er):
    o1 = er.put_object("bkt", "obj", b"v1",
                       PutObjectOptions(versioned=True))
    o2 = er.put_object("bkt", "obj", b"v2",
                       PutObjectOptions(versioned=True))
    assert o1.version_id and o2.version_id and o1.version_id != o2.version_id
    _, got = er.get_object("bkt", "obj")
    assert got == b"v2"
    _, got = er.get_object("bkt", "obj",
                           opts=ObjectOptions(version_id=o1.version_id))
    assert got == b"v1"
    # delete without version -> delete marker; latest GET now fails
    dm = er.delete_object("bkt", "obj", ObjectOptions(versioned=True))
    assert dm.delete_marker and dm.version_id
    with pytest.raises(MethodNotAllowed):
        er.get_object("bkt", "obj")
    # old version still readable
    _, got = er.get_object("bkt", "obj",
                           opts=ObjectOptions(version_id=o1.version_id))
    assert got == b"v1"
    versions = er.list_object_versions("bkt", "obj")
    assert len(versions) == 3  # v1, v2, delete marker
    # remove the delete marker -> v2 is latest again
    er.delete_object("bkt", "obj", ObjectOptions(version_id=dm.version_id))
    _, got = er.get_object("bkt", "obj")
    assert got == b"v2"


# -- listing ---------------------------------------------------------------

def test_list_objects(er):
    for name in ["a/1.txt", "a/2.txt", "b/x/y.txt", "top.txt"]:
        er.put_object("bkt", name, b"c")
    out = er.list_objects("bkt")
    assert [o.name for o in out.objects] == \
        ["a/1.txt", "a/2.txt", "b/x/y.txt", "top.txt"]
    out = er.list_objects("bkt", prefix="a/")
    assert [o.name for o in out.objects] == ["a/1.txt", "a/2.txt"]
    out = er.list_objects("bkt", delimiter="/")
    assert out.prefixes == ["a/", "b/"]
    assert [o.name for o in out.objects] == ["top.txt"]
    out = er.list_objects("bkt", max_keys=2)
    assert out.is_truncated and len(out.objects) == 2


# -- healing (cmd/erasure-healing.go) --------------------------------------

def test_heal_missing_shard(tmp_path):
    er = make_layer(tmp_path, n=6, parity=2, inline=0)
    er.make_bucket("bkt")
    data = _data(2 * BS + 41, seed=3)
    er.put_object("bkt", "obj", data)
    # wipe the object from two drives entirely
    wiped = []
    for disk in er.disks[:2]:
        p = os.path.join(disk.root, "bkt", "obj")
        import shutil
        shutil.rmtree(p)
        wiped.append(disk.endpoint())
    res = healing.heal_object(er, "bkt", "obj")
    assert res.before_ok == 4 and res.after_ok == 6
    assert sorted(res.healed_disks) == sorted(wiped)
    # all drives now verify clean
    for disk in er.disks:
        fi = disk.read_version("bkt", "obj")
        disk.verify_file("bkt", "obj", fi)
    _, got = er.get_object("bkt", "obj")
    assert got == data


def test_heal_corrupt_shard_deep(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2, inline=0)
    er.make_bucket("bkt")
    data = _data(BS + 5, seed=4)
    er.put_object("bkt", "obj", data)
    victim = er.disks[2]
    for dirpath, _, files in os.walk(os.path.join(victim.root, "bkt")):
        for f in files:
            if f.startswith("part."):
                p = os.path.join(dirpath, f)
                raw = bytearray(open(p, "rb").read())
                raw[-1] ^= 1
                open(p, "wb").write(bytes(raw))
    res = healing.heal_object(er, "bkt", "obj", deep=True)
    assert res.after_ok == 4
    victim_fi = victim.read_version("bkt", "obj")
    victim.verify_file("bkt", "obj", victim_fi)  # healed clean


def test_heal_dangling(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2, inline=0)
    er.make_bucket("bkt")
    er.put_object("bkt", "obj", _data(1000, seed=5))
    # destroy shards beyond repair (3 of 4 drives, k=2 -> 1 shard left)
    import shutil
    for disk in er.disks[:3]:
        shutil.rmtree(os.path.join(disk.root, "bkt", "obj"))
    res = healing.heal_object(er, "bkt", "obj", remove_dangling=True)
    assert res.dangling_purged
    with pytest.raises(ObjectNotFound):
        er.get_object_info("bkt", "obj")


def test_heal_inline_object(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2)  # inline threshold default
    er.make_bucket("bkt")
    data = b"small inline payload"
    er.put_object("bkt", "obj", data)
    # wipe metadata from one drive
    import shutil
    shutil.rmtree(os.path.join(er.disks[1].root, "bkt", "obj"))
    res = healing.heal_object(er, "bkt", "obj")
    assert res.after_ok == 4
    _, got = er.get_object("bkt", "obj")
    assert got == data


def test_heal_delete_marker(tmp_path):
    er = make_layer(tmp_path, n=4, parity=2)
    er.make_bucket("bkt")
    er.put_object("bkt", "obj", b"x", PutObjectOptions(versioned=True))
    dm = er.delete_object("bkt", "obj", ObjectOptions(versioned=True))
    import shutil
    # drop all metadata on one disk
    shutil.rmtree(os.path.join(er.disks[0].root, "bkt", "obj"))
    res = healing.heal_object(er, "bkt", "obj", version_id=dm.version_id)
    assert res.after_ok == 4
    fi = er.disks[0].read_version("bkt", "obj", dm.version_id)
    assert fi.deleted


def test_ranged_read_fuzz_with_dead_disks(er):
    """Random offset/length reads against degraded sets — the
    cmd/erasure-decode_test.go:205 fuzz tier: every ranged read over any
    survivable failure pattern must return exactly data[off:off+ln]."""
    import numpy as np
    er.make_bucket("fuzzb")
    rng = np.random.default_rng(20260730)
    body = rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
    er.put_object("fuzzb", "fz", body)
    saved = list(er.disks)
    n = len(saved)
    m = er.parity
    try:
        for trial in range(40):
            # random survivable failure pattern (0..m dead disks)
            dead = rng.choice(n, size=rng.integers(0, m + 1),
                              replace=False)
            er.disks = list(saved)
            for d in dead:
                er.disks[d] = None
            off = int(rng.integers(0, len(body)))
            ln = int(rng.integers(1, len(body) - off + 1))
            _, got = er.get_object("fuzzb", "fz", off, ln)
            assert got == body[off:off + ln], \
                f"trial {trial}: dead={dead} off={off} ln={ln}"
    finally:
        er.disks = saved


@pytest.mark.parametrize("algo", ["sha256", "blake2b",
                                  "highwayhash256"])
def test_whole_file_bitrot_algos_roundtrip(tmp_path, algo):
    """Non-streaming bitrot algorithms store shards unframed; both the
    inline (msgpack xl.meta) and striped paths must round-trip — a
    numpy row leaking out of streaming_encode_batch breaks msgpack
    serialization of inline data (regression)."""
    from minio_tpu.storage.xl_storage import XLStorage
    disks = []
    for i in range(6):
        d = tmp_path / f"wd{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    layer = ErasureObjects(disks, parity=2, block_size=BS,
                           backend="numpy", inline_threshold=4096,
                           bitrot_algo=algo)
    layer.make_bucket("wfb")
    small, big = _data(1000, seed=3), _data(3 * BS + 17, seed=4)
    layer.put_object("wfb", "inline-obj", small)     # inline path
    layer.put_object("wfb", "striped-obj", big)      # striped path
    _, got_small = layer.get_object("wfb", "inline-obj")
    _, got_big = layer.get_object("wfb", "striped-obj")
    assert bytes(got_small) == small
    assert bytes(got_big) == big
