"""ILM transition/tiering + RestoreObject tests
(cmd/bucket-lifecycle.go:315 transitionObject, restore handler,
x-amz-restore/x-amz-storage-class response semantics).
"""

import time

import pytest

from minio_tpu.objectlayer import tiering as tr
from minio_tpu.objectlayer.erasure_object import ErasureObjects
from minio_tpu.s3.client import S3Client, S3ClientError
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.xl_storage import XLStorage


def make_layer(tmp, name):
    disks = []
    for i in range(4):
        d = tmp / f"{name}{i}"
        d.mkdir()
        disks.append(XLStorage(str(d)))
    return ErasureObjects(disks, parity=2, block_size=64 * 1024,
                          backend="numpy")


@pytest.fixture
def layer(tmp_path):
    return make_layer(tmp_path, "tierdisk")


def test_transition_and_restore_layer_level(layer, tmp_path):
    layer.make_bucket("arch")
    body = b"cold data " * 500
    layer.put_object("arch", "cold.bin", body)
    orig = layer.get_object_info("arch", "cold.bin")

    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("GLACIER", str(tmp_path / "tier")))
    oi = layer.get_object_info("arch", "cold.bin")
    oi.transition_tier = "GLACIER"
    ts.transition("arch", oi)

    stub = layer.get_object_info("arch", "cold.bin")
    assert tr.is_transitioned(stub.user_defined)
    assert stub.user_defined[tr.META_SIZE] == str(len(body))
    assert stub.user_defined[tr.META_ETAG] == orig.etag
    assert stub.size == 0                        # data moved off

    # transition is idempotent
    ts.transition("arch", layer.get_object_info("arch", "cold.bin"))

    assert ts.restore("arch", "cold.bin", days=1) is True
    back = layer.get_object("arch", "cold.bin")
    assert back[1] == body
    assert tr.restore_valid(back[0].user_defined)
    # second restore is a no-op on a valid copy
    assert ts.restore("arch", "cold.bin", days=1) is False


def test_restore_nontransitioned_rejected(layer, tmp_path):
    layer.make_bucket("warm")
    layer.put_object("warm", "hot", b"hot")
    ts = tr.TransitionSys(layer)
    with pytest.raises(tr.TierError, match="not in an archived state"):
        ts.restore("warm", "hot", 1)


def test_sweep_expired_restores(layer, tmp_path, monkeypatch):
    layer.make_bucket("swp")
    layer.put_object("swp", "o", b"z" * 4096)
    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("COLD", str(tmp_path / "t2")))
    oi = layer.get_object_info("swp", "o")
    oi.transition_tier = "COLD"
    ts.transition("swp", oi)
    ts.restore("swp", "o", days=1)
    assert layer.get_object("swp", "o")[1] == b"z" * 4096
    # jump past the restore window
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() + 2 * 86400)
    assert ts.sweep_expired_restores("swp") == 1
    stub = layer.get_object_info("swp", "o")
    assert stub.size == 0 and tr.is_transitioned(stub.user_defined)
    assert tr.META_RESTORE_EXPIRY not in stub.user_defined


def test_crawler_drives_transition(layer, tmp_path):
    from minio_tpu.background.crawler import scan_usage
    from minio_tpu.objectlayer.bucket_meta import BucketMetadataSys
    from minio_tpu.storage.datatypes import now_ns

    bm = BucketMetadataSys(layer)
    layer.make_bucket("ilmb")
    lc_xml = (b'<LifecycleConfiguration><Rule><ID>t</ID>'
              b'<Status>Enabled</Status><Filter><Prefix></Prefix></Filter>'
              b'<Transition><Days>1</Days>'
              b'<StorageClass>ICE</StorageClass></Transition>'
              b'</Rule></LifecycleConfiguration>')
    bm.set_config("ilmb", "lifecycle", lc_xml.decode())
    old = now_ns() - 3 * 24 * 3600 * 10 ** 9
    from minio_tpu.objectlayer.interface import PutObjectOptions
    layer.put_object("ilmb", "aging", b"a" * 2048,
                     PutObjectOptions(mod_time=old))

    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("ICE", str(tmp_path / "ice")))
    res = scan_usage(layer, bm, transition_fn=tr.transition_fn(ts))
    assert ("ilmb", "aging") in res.transitioned
    stub = layer.get_object_info("ilmb", "aging")
    assert stub.user_defined[tr.META_TIER] == "ICE"


def test_noncurrent_version_transition_preserves_head(layer, tmp_path):
    """TRANSITION_VERSION must stub the noncurrent version, never the
    live head object."""
    from minio_tpu.objectlayer.interface import PutObjectOptions
    layer.make_bucket("verb")
    v1 = layer.put_object("verb", "doc", b"old version",
                          PutObjectOptions(versioned=True))
    v2 = layer.put_object("verb", "doc", b"new version",
                          PutObjectOptions(versioned=True))
    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("NC", str(tmp_path / "nc")))
    from minio_tpu.objectlayer.interface import ObjectOptions
    oi = layer.get_object_info("verb", "doc",
                               ObjectOptions(version_id=v1.version_id))
    oi.transition_tier = "NC"
    ts.transition("verb", oi)
    # head untouched, noncurrent stubbed
    head = layer.get_object("verb", "doc")
    assert head[1] == b"new version"
    assert not tr.is_transitioned(head[0].user_defined)
    old = layer.get_object_info("verb", "doc",
                                ObjectOptions(version_id=v1.version_id))
    assert tr.is_transitioned(old.user_defined)
    # restore that specific version
    ts.restore("verb", "doc", 1, version_id=v1.version_id)
    got = layer.get_object("verb", "doc", 0, -1,
                           ObjectOptions(version_id=v1.version_id))
    assert got[1] == b"old version"
    assert layer.get_object("verb", "doc")[1] == b"new version"


def test_transition_storage_class_picks_due_rule():
    from minio_tpu.bucket.lifecycle import Lifecycle, ObjectOpts
    from minio_tpu.storage.datatypes import now_ns
    lc = Lifecycle.parse(
        b'<LifecycleConfiguration>'
        b'<Rule><ID>far</ID><Status>Enabled</Status>'
        b'<Filter><Prefix></Prefix></Filter>'
        b'<Transition><Days>365</Days><StorageClass>FAR</StorageClass>'
        b'</Transition></Rule>'
        b'<Rule><ID>near</ID><Status>Enabled</Status>'
        b'<Filter><Prefix></Prefix></Filter>'
        b'<Transition><Days>1</Days><StorageClass>NEAR</StorageClass>'
        b'</Transition></Rule>'
        b'</LifecycleConfiguration>')
    obj = ObjectOpts(name="o", user_tags={},
                     mod_time_ns=now_ns() - 3 * 24 * 3600 * 10 ** 9,
                     is_latest=True)
    # only the 1-day rule is due: its class must win, not rule order
    assert lc.transition_storage_class(obj) == "NEAR"


# -- server level -------------------------------------------------------------

@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tiersrv")
    layer = make_layer(tmp, "srvd")
    srv = S3Server(layer, access_key="tk", secret_key="ts")
    srv.transition.add_tier(
        tr.DirTier("DEEP", str(tmp / "deeptier")))
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture
def client(server):
    c = S3Client(server.endpoint, "tk", "ts")
    if not c.head_bucket("tierb"):
        c.make_bucket("tierb")
    return c


def _archive(server, bucket, key):
    oi = server.layer.get_object_info(bucket, key)
    oi.transition_tier = "DEEP"
    server.transition.transition(bucket, oi)


def test_archived_get_head_restore_over_api(server, client):
    body = b"archival content " * 100
    client.put_object("tierb", "doc", body, content_type="text/plain")
    orig_etag = client.head_object("tierb", "doc").headers["ETag"]
    _archive(server, "tierb", "doc")

    # GET is rejected until restored
    with pytest.raises(S3ClientError) as ei:
        client.get_object("tierb", "doc")
    assert ei.value.code == "InvalidObjectState" and ei.value.status == 403

    # HEAD reports archived identity
    h = client.head_object("tierb", "doc")
    hl = {k.lower(): v for k, v in h.headers.items()}
    assert hl["x-amz-storage-class"] == "DEEP"
    assert hl["content-length"] == str(len(body))
    assert h.headers["ETag"] == orig_etag
    assert "x-amz-restore" not in hl

    # restore, then read
    r = client.request("POST", "/tierb/doc", "restore",
                       b"<RestoreRequest><Days>2</Days></RestoreRequest>",
                       expect=(200, 202))
    assert r.status == 202
    g = client.get_object("tierb", "doc")
    assert g.body == body
    gl = {k.lower(): v for k, v in g.headers.items()}
    assert 'ongoing-request="false"' in gl["x-amz-restore"]
    assert gl["x-amz-storage-class"] == "DEEP"
    assert g.headers["ETag"] == orig_etag

    # restoring again on a valid copy: 200, not 202
    r2 = client.request("POST", "/tierb/doc", "restore",
                        b"<RestoreRequest><Days>1</Days></RestoreRequest>",
                        expect=(200, 202))
    assert r2.status == 200


def test_archived_range_get_is_403(server, client):
    client.put_object("tierb", "rngdoc", b"r" * 4096)
    _archive(server, "tierb", "rngdoc")
    with pytest.raises(S3ClientError) as ei:
        client.get_object("tierb", "rngdoc", byte_range=(100, 200))
    assert ei.value.code == "InvalidObjectState" and ei.value.status == 403


def test_admin_tier_list_redacts_secrets(server, tmp_path):
    server.transition.add_tier(
        tr.S3Tier("SECRETTIER", "http://h:9", "b", "AKIAX", "supersecret"))
    import json
    listed = json.loads(server.transition.to_json(redact=True))
    ent = next(t for t in listed if t["name"] == "SECRETTIER")
    assert ent["secret_key"] == "REDACTED"
    assert ent["access_key"] == "REDACTED"
    # persistence form keeps them (needed to reconnect after restart)
    full = json.loads(server.transition.to_json())
    ent = next(t for t in full if t["name"] == "SECRETTIER")
    assert ent["secret_key"] == "supersecret"


def test_restore_versioned_latest_without_versionid(layer, tmp_path):
    """POST ?restore without versionId on a versioned bucket must
    restore the transitioned latest version, not mint a null version."""
    from minio_tpu.objectlayer.interface import (ObjectOptions,
                                                 PutObjectOptions)
    layer.make_bucket("vrb")
    v = layer.put_object("vrb", "doc", b"versioned cold",
                         PutObjectOptions(versioned=True))
    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("VT", str(tmp_path / "vt")))
    oi = layer.get_object_info("vrb", "doc")
    oi.transition_tier = "VT"
    ts.transition("vrb", oi)
    assert ts.restore("vrb", "doc", 1) is True
    got = layer.get_object("vrb", "doc", 0, -1,
                           ObjectOptions(version_id=v.version_id))
    assert got[1] == b"versioned cold"
    # no spurious null version appeared
    vers = layer.list_object_versions("vrb")
    assert {o.version_id for o in vers if o.name == "doc"} == \
        {v.version_id}


def test_delete_frees_tier_bytes(server, client, tmp_path):
    import os
    client.put_object("tierb", "gcme", b"G" * 2048)
    _archive(server, "tierb", "gcme")
    tier_dir = server.transition.tiers["DEEP"].path
    assert len(os.listdir(tier_dir)) >= 1
    before = len(os.listdir(tier_dir))
    client.delete_object("tierb", "gcme")
    assert len(os.listdir(tier_dir)) == before - 1


def test_overwrite_frees_tier_bytes(server, client):
    import os
    client.put_object("tierb", "owme", b"O" * 2048)
    _archive(server, "tierb", "owme")
    tier_dir = server.transition.tiers["DEEP"].path
    before = len(os.listdir(tier_dir))
    client.put_object("tierb", "owme", b"fresh bytes")
    assert len(os.listdir(tier_dir)) == before - 1
    assert client.get_object("tierb", "owme").body == b"fresh bytes"


def test_copy_from_archived_source_rejected(server, client):
    client.put_object("tierb", "cpsrc", b"C" * 1024)
    _archive(server, "tierb", "cpsrc")
    with pytest.raises(S3ClientError) as ei:
        client.request("PUT", "/tierb/cpdst",
                       headers={"x-amz-copy-source": "/tierb/cpsrc"})
    assert ei.value.code == "InvalidObjectState"


def test_restore_of_live_object_rejected(client):
    client.put_object("tierb", "live", b"live")
    with pytest.raises(S3ClientError) as ei:
        client.request("POST", "/tierb/live", "restore",
                       b"<RestoreRequest><Days>1</Days></RestoreRequest>")
    assert ei.value.code == "InvalidObjectState"


def test_admin_tier_add_and_list(server, client, tmp_path):
    import json
    import urllib.request
    from minio_tpu.s3.sigv4 import Credentials, sign_request
    url = f"{server.endpoint}/minio-tpu/admin/v1/tier"
    body = json.dumps({"type": "dir", "name": "NEWTIER",
                       "path": str(tmp_path / "nt")}).encode()
    hdrs = sign_request(Credentials("tk", "ts"), "PUT", url, {}, body)
    req = urllib.request.Request(url, data=body, method="PUT",
                                 headers=hdrs)
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200
    hdrs = sign_request(Credentials("tk", "ts"), "GET", url, {}, b"")
    req = urllib.request.Request(url, headers=hdrs)
    with urllib.request.urlopen(req) as resp:
        tiers = json.loads(resp.read())
    assert {"NEWTIER", "DEEP"} <= {t["name"] for t in tiers}


def test_s3_tier_backend(layer, tmp_path):
    """Tier into another S3 endpoint (our own server as remote)."""
    remote_layer = make_layer(tmp_path, "remote")
    remote = S3Server(remote_layer, access_key="rk", secret_key="rs")
    remote.start()
    try:
        rc = S3Client(remote.endpoint, "rk", "rs")
        rc.make_bucket("tierbkt")
        layer.make_bucket("src")
        layer.put_object("src", "x", b"offload me" * 100)
        ts = tr.TransitionSys(layer)
        ts.add_tier(tr.S3Tier("S3COLD", remote.endpoint, "tierbkt",
                              "rk", "rs", prefix="tiered/"))
        oi = layer.get_object_info("src", "x")
        oi.transition_tier = "S3COLD"
        ts.transition("src", oi)
        objs, _ = rc.list_objects("tierbkt", prefix="tiered/")
        assert len(objs) == 1 and objs[0]["size"] == 1000
        assert ts.restore("src", "x", 1)
        assert layer.get_object("src", "x")[1] == b"offload me" * 100
    finally:
        remote.stop()


def test_tier_config_round_trip(layer, tmp_path):
    ts = tr.TransitionSys(layer)
    ts.add_tier(tr.DirTier("A", str(tmp_path / "a")))
    ts.add_tier(tr.S3Tier("B", "http://h:9", "b", "ak", "sk", "p/"))
    ts2 = tr.TransitionSys.from_json(layer, ts.to_json())
    assert set(ts2.tiers) == {"A", "B"}
    assert ts2.tiers["B"].prefix == "p/"
