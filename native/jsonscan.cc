// NDJSON predicate scan — the simdjson role in the reference's Select
// path (pkg/s3select/simdj, go.mod simdjson-go dep).
//
// Strategy: S3 Select's hot queries filter rows with a WHERE of the
// form  <top-level field> <op> <literal>.  Materializing a Python dict
// per row (json.loads) costs ~1 µs/row; this scanner walks the raw
// bytes depth-aware and emits only the byte ranges of rows that MIGHT
// match — survivors alone get parsed and fully evaluated in Python.
//
// Contract (what makes the fast path sound): the scanner is
// CONSERVATIVE-EXACT.  It may keep a row that doesn't match (Python
// re-evaluates the WHERE anyway) but it never drops a row that could
// match: any uncertainty — escaped strings, type mismatches, malformed
// lines — keeps the row.  A row is dropped only when the field is
// provably absent at depth 1 (SQL: MISSING comparison is never true)
// or provably fails the comparison.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cstdlib>

namespace {

enum Op { OP_EQ = 0, OP_NE, OP_LT, OP_LE, OP_GT, OP_GE };

bool cmp_double(double a, int op, double b) {
    switch (op) {
        case OP_EQ: return a == b;
        case OP_NE: return a != b;
        case OP_LT: return a < b;
        case OP_LE: return a <= b;
        case OP_GT: return a > b;
        case OP_GE: return a >= b;
    }
    return true;
}

bool cmp_bytes(const uint8_t* a, size_t alen, int op,
               const uint8_t* b, size_t blen) {
    size_t m = alen < blen ? alen : blen;
    int c = memcmp(a, b, m);
    if (c == 0) c = (alen < blen) ? -1 : (alen > blen ? 1 : 0);
    switch (op) {
        case OP_EQ: return c == 0;
        case OP_NE: return c != 0;
        case OP_LT: return c < 0;
        case OP_LE: return c <= 0;
        case OP_GT: return c > 0;
        case OP_GE: return c >= 0;
    }
    return true;
}

bool ieq(const uint8_t* a, const uint8_t* b, size_t n) {
    for (size_t i = 0; i < n; i++) {
        uint8_t x = a[i], y = b[i];
        if (x >= 'A' && x <= 'Z') x += 32;
        if (y >= 'A' && y <= 'Z') y += 32;
        if (x != y) return false;
    }
    return true;
}

// returns: 1 keep, 0 drop.  The row is dropped ONLY when every
// occurrence of the field at depth 1 provably fails the comparison,
// or the field is provably absent — any uncertainty (escaped keys or
// values, type mixes, duplicates with a passing occurrence, malformed
// bytes) keeps the row for Python's exact evaluation.  The key match
// is ASCII-case-insensitive because the SQL evaluator falls back to a
// lowercase lookup.
int eval_line(const uint8_t* p, size_t n, const uint8_t* field,
              size_t flen, int op, int val_kind, double num_val,
              const uint8_t* sval, size_t slen) {
    size_t i = 0;
    while (i < n && (p[i] == ' ' || p[i] == '\t' || p[i] == '\r')) i++;
    if (i >= n) return 0;                       // blank: reader skips too
    if (p[i] != '{') return 1;                  // not an object: Python
    int depth = 0;
    bool found = false;       // any occurrence seen (incl. uncertain)
    bool keep = false;        // some occurrence passed / was uncertain
    while (i < n) {
        uint8_t c = p[i];
        if (c == '"') {
            // string start: key or value
            size_t start = ++i;
            bool esc_seen = false;
            while (i < n && p[i] != '"') {
                if (p[i] == '\\') { esc_seen = true; i += 2; }
                else i++;
            }
            if (i >= n) return 1;               // truncated: Python
            size_t send = i;
            i++;                                 // past closing quote
            // is this a KEY at depth 1?
            size_t j = i;
            while (j < n && (p[j] == ' ' || p[j] == '\t')) j++;
            if (depth == 1 && j < n && p[j] == ':') {
                if (esc_seen) {
                    // a key with escapes might unescape to the field:
                    // absence is no longer provable
                    return 1;
                }
                bool is_field = (send - start) == flen &&
                    ieq(p + start, field, flen);
                i = j + 1;
                while (i < n && (p[i] == ' ' || p[i] == '\t')) i++;
                if (!is_field) continue;        // value consumed later
                found = true;
                if (keep) continue;             // already keeping
                if (i >= n) return 1;
                if (p[i] == '"') {              // string value
                    size_t vs = ++i;
                    bool vesc = false;
                    while (i < n && p[i] != '"') {
                        if (p[i] == '\\') { vesc = true; i += 2; }
                        else i++;
                    }
                    if (i >= n || vesc || val_kind != 1) {
                        keep = true;            // uncertain
                    } else if (cmp_bytes(p + vs, i - vs, op, sval,
                                         slen)) {
                        keep = true;
                    }
                    continue;
                }
                if ((p[i] >= '0' && p[i] <= '9') || p[i] == '-') {
                    char* end = nullptr;
                    double v = strtod(
                        reinterpret_cast<const char*>(p + i), &end);
                    if (val_kind != 0 ||
                        end == reinterpret_cast<const char*>(p + i) ||
                        cmp_double(v, op, num_val)) {
                        keep = true;            // uncertain or passing
                    }
                    continue;
                }
                keep = true;  // null / bool / object / array: Python
                continue;
            }
            continue;                            // plain string value
        }
        if (c == '{' || c == '[') depth++;
        else if (c == '}' || c == ']') depth--;
        i++;
    }
    if (!found) return 0;   // absent at depth 1: MISSING never matches
    return keep ? 1 : 0;    // every occurrence provably failed: drop
}

}  // namespace

extern "C" long mt_ndjson_filter(
    const uint8_t* data, size_t n, const uint8_t* field, size_t flen,
    int op, int val_kind, double num_val, const uint8_t* sval,
    size_t slen, size_t* out_pairs, long max_pairs) {
    long count = 0;
    size_t line_start = 0;
    for (size_t i = 0; i <= n; i++) {
        if (i == n || data[i] == '\n') {
            size_t len = i - line_start;
            if (len > 0 &&
                eval_line(data + line_start, len, field, flen, op,
                          val_kind, num_val, sval, slen)) {
                if (count >= max_pairs) return -1;   // caller retries big
                out_pairs[2 * count] = line_start;
                out_pairs[2 * count + 1] = i;
                count++;
            }
            line_start = i + 1;
        }
    }
    return count;
}
