// GF(2^8) matrix multiply for the host erasure-codec path.
//
// Role: the reference's hot path is klauspost/reedsolomon's assembly
// (AVX2 VPSHUFB split-nibble multiply, go.mod:41, used from
// cmd/erasure-coding.go:70-107).  On TPU hosts the device codec
// (minio_tpu/ops/rs_kernels.py) carries the bulk work; this library is
// the CPU-side equivalent for paths where a device dispatch is not
// worthwhile (small stripes, numpy backend, environments without an
// accelerator).
//
// The multiplication table is injected from Python (mt_gf8_init) so the
// field semantics are identical to minio_tpu/ops/gf8.py by construction
// — no second implementation of the polynomial to drift.
//
// Kernel: per coefficient c, two 16-entry tables L[x]=mul(c,x) and
// H[x]=mul(c,x<<4); mul(c,b) = L[b&15] ^ H[b>>4].  With AVX2 this is two
// VPSHUFB per 32 bytes — the exact trick the reference's assembly uses.

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define MT_X86 1
#endif

static uint8_t MUL[256][256];
static bool g_have_avx2 = false;

extern "C" void mt_gf8_init(const uint8_t* mul_table) {
    std::memcpy(MUL, mul_table, sizeof(MUL));
#if MT_X86
    g_have_avx2 = __builtin_cpu_supports("avx2");
#endif
}

// out[n] ^= mul(c, src[n]) — scalar split-nibble path
static void mul_xor_scalar(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t n) {
    const uint8_t* row = MUL[c];
    uint8_t lo[16], hi[16];
    for (int x = 0; x < 16; x++) {
        lo[x] = row[x];
        hi[x] = row[x << 4];
    }
    for (size_t i = 0; i < n; i++) {
        uint8_t b = src[i];
        dst[i] ^= (uint8_t)(lo[b & 15] ^ hi[b >> 4]);
    }
}

#if MT_X86
__attribute__((target("avx2")))
static void mul_xor_avx2(uint8_t c, const uint8_t* src, uint8_t* dst,
                         size_t n) {
    const uint8_t* row = MUL[c];
    alignas(32) uint8_t lo[32], hi[32];
    for (int x = 0; x < 16; x++) {
        lo[x] = lo[x + 16] = row[x];
        hi[x] = hi[x + 16] = row[x << 4];
    }
    const __m256i vlo = _mm256_load_si256((const __m256i*)lo);
    const __m256i vhi = _mm256_load_si256((const __m256i*)hi);
    const __m256i mask = _mm256_set1_epi8(0x0f);
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i*)(src + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                     _mm256_shuffle_epi8(vhi, h));
        __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
        _mm256_storeu_si256((__m256i*)(dst + i),
                            _mm256_xor_si256(d, p));
    }
    if (i < n) mul_xor_scalar(c, src + i, dst + i, n - i);
}
#endif

static inline void mul_xor(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t n) {
    if (c == 0) return;
#if MT_X86
    if (g_have_avx2) { mul_xor_avx2(c, src, dst, n); return; }
#endif
    mul_xor_scalar(c, src, dst, n);
}

// dst[n] ^= src[n] — word-wise; the c==1 fast path (identity-heavy
// decode matrices) and XOR-only callers share it
extern "C" void mt_gf8_xor(const uint8_t* src, uint8_t* dst, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, src + i, 8);
        std::memcpy(&b, dst + i, 8);
        b ^= a;
        std::memcpy(dst + i, &b, 8);
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

// out (r, len) = A (r, k)  x  B (k, len)  over GF(2^8), XOR-accumulate.
// B rows and out rows are contiguous with the given strides (in bytes),
// so callers can point straight into a (k, shard) numpy array.
extern "C" void mt_gf8_matmul(const uint8_t* A, size_t r, size_t k,
                              const uint8_t* B, size_t b_stride,
                              uint8_t* out, size_t o_stride, size_t len) {
    for (size_t j = 0; j < r; j++) {
        uint8_t* dst = out + j * o_stride;
        std::memset(dst, 0, len);
        for (size_t i = 0; i < k; i++) {
            uint8_t c = A[j * k + i];
            if (c == 1) {  // common in systematic/decode matrices
                mt_gf8_xor(B + i * b_stride, dst, len);
                continue;
            }
            mul_xor(c, B + i * b_stride, dst, len);
        }
    }
}
