// GF(2^8) matrix multiply for the host erasure-codec path.
//
// Role: the reference's hot path is klauspost/reedsolomon's assembly
// (AVX2 VPSHUFB split-nibble multiply, go.mod:41, used from
// cmd/erasure-coding.go:70-107).  On TPU hosts the device codec
// (minio_tpu/ops/rs_kernels.py) carries the bulk work; this library is
// the CPU-side equivalent for paths where a device dispatch is not
// worthwhile (small stripes, numpy backend, environments without an
// accelerator).
//
// The multiplication table is injected from Python (mt_gf8_init) so the
// field semantics are identical to minio_tpu/ops/gf8.py by construction
// — no second implementation of the polynomial to drift.
//
// Kernel: per coefficient c, two 16-entry tables L[x]=mul(c,x) and
// H[x]=mul(c,x<<4); mul(c,b) = L[b&15] ^ H[b>>4].  With AVX2 this is two
// VPSHUFB per 32 bytes — the exact trick the reference's assembly uses.

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define MT_X86 1
#endif

static uint8_t MUL[256][256];
static bool g_have_avx2 = false;
static bool g_have_gfni = false;

// GFNI path: multiply-by-c is a linear map over GF(2), so it is one
// VGF2P8AFFINEQB against an 8x8 bit matrix.  AFF[c] packs that matrix
// in the instruction's layout, derived from the injected MUL table (so
// any field table Python hands us stays authoritative).  Convention:
// out_bit[i] = parity(matrix.byte[7-i] & in_byte), hence byte 7-i of
// the qword holds, at bit j, bit i of MUL[c][1<<j].
static uint64_t AFF[256];

static void build_affine_tables() {
    for (int c = 0; c < 256; c++) {
        uint8_t bytes[8];
        for (int i = 0; i < 8; i++) {
            uint8_t row = 0;
            for (int j = 0; j < 8; j++)
                row |= (uint8_t)(((MUL[c][1u << j] >> i) & 1) << j);
            bytes[7 - i] = row;
        }
        std::memcpy(&AFF[c], bytes, 8);
    }
}

extern "C" void mt_gf8_init(const uint8_t* mul_table) {
    std::memcpy(MUL, mul_table, sizeof(MUL));
    build_affine_tables();
#if MT_X86
    g_have_avx2 = __builtin_cpu_supports("avx2");
    g_have_gfni = __builtin_cpu_supports("gfni")
        && __builtin_cpu_supports("avx512f")
        && __builtin_cpu_supports("avx512bw");
#endif
}

// out[n] ^= mul(c, src[n]) — scalar split-nibble path
static void mul_xor_scalar(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t n) {
    const uint8_t* row = MUL[c];
    uint8_t lo[16], hi[16];
    for (int x = 0; x < 16; x++) {
        lo[x] = row[x];
        hi[x] = row[x << 4];
    }
    for (size_t i = 0; i < n; i++) {
        uint8_t b = src[i];
        dst[i] ^= (uint8_t)(lo[b & 15] ^ hi[b >> 4]);
    }
}

#if MT_X86
__attribute__((target("avx2")))
static void mul_xor_avx2(uint8_t c, const uint8_t* src, uint8_t* dst,
                         size_t n) {
    const uint8_t* row = MUL[c];
    alignas(32) uint8_t lo[32], hi[32];
    for (int x = 0; x < 16; x++) {
        lo[x] = lo[x + 16] = row[x];
        hi[x] = hi[x + 16] = row[x << 4];
    }
    const __m256i vlo = _mm256_load_si256((const __m256i*)lo);
    const __m256i vhi = _mm256_load_si256((const __m256i*)hi);
    const __m256i mask = _mm256_set1_epi8(0x0f);
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i*)(src + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                     _mm256_shuffle_epi8(vhi, h));
        __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
        _mm256_storeu_si256((__m256i*)(dst + i),
                            _mm256_xor_si256(d, p));
    }
    if (i < n) mul_xor_scalar(c, src + i, dst + i, n - i);
}
#endif

static inline void mul_xor(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t n) {
    if (c == 0) return;
#if MT_X86
    if (g_have_avx2) { mul_xor_avx2(c, src, dst, n); return; }
#endif
    mul_xor_scalar(c, src, dst, n);
}

// dst[n] ^= src[n] — word-wise; the c==1 fast path (identity-heavy
// decode matrices) and XOR-only callers share it
extern "C" void mt_gf8_xor(const uint8_t* src, uint8_t* dst, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, src + i, 8);
        std::memcpy(&b, dst + i, 8);
        b ^= a;
        std::memcpy(dst + i, &b, 8);
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

#if MT_X86
// GFNI kernel: JN output rows fused per pass so each 64-byte source
// vector is loaded once and feeds JN accumulators held in zmm regs —
// source and destination bytes move exactly once per row group.
// Instruction economy: one VGF2P8AFFINEQB + one VPXORQ per (i, j)
// coefficient per 64 bytes (the klauspost GFNI design point,
// reedsolomon galois_amd64.s mulGFNI_*).
template <int JN>
__attribute__((target("gfni,avx512f,avx512bw")))
static void matmul_gfni_rows(const uint8_t* A, size_t r, size_t k,
                             const uint8_t* B, size_t b_stride,
                             uint8_t* out, size_t o_stride,
                             size_t len, size_t j0) {
    size_t pos = 0;
    for (; pos + 64 <= len; pos += 64) {
        __m512i acc[JN];
        for (int j = 0; j < JN; j++) acc[j] = _mm512_setzero_si512();
        for (size_t i = 0; i < k; i++) {
            __m512i v = _mm512_loadu_si512(
                (const void*)(B + i * b_stride + pos));
            for (int j = 0; j < JN; j++) {
                __m512i m = _mm512_set1_epi64(
                    (long long)AFF[A[(j0 + j) * k + i]]);
                acc[j] = _mm512_xor_si512(
                    acc[j], _mm512_gf2p8affine_epi64_epi8(v, m, 0));
            }
        }
        for (int j = 0; j < JN; j++)
            _mm512_storeu_si512((void*)(out + (j0 + j) * o_stride + pos),
                                acc[j]);
    }
    if (pos < len) {                     // scalar tail, < 64 bytes
        for (int j = 0; j < JN; j++) {
            uint8_t* dst = out + (j0 + j) * o_stride + pos;
            std::memset(dst, 0, len - pos);
            for (size_t i = 0; i < k; i++) {
                uint8_t c = A[(j0 + j) * k + i];
                if (c == 1) mt_gf8_xor(B + i * b_stride + pos, dst,
                                       len - pos);
                else mul_xor(c, B + i * b_stride + pos, dst, len - pos);
            }
        }
    }
}

__attribute__((target("gfni,avx512f,avx512bw")))
static void matmul_gfni(const uint8_t* A, size_t r, size_t k,
                        const uint8_t* B, size_t b_stride,
                        uint8_t* out, size_t o_stride, size_t len) {
    size_t j0 = 0;
    for (; j0 + 4 <= r; j0 += 4)
        matmul_gfni_rows<4>(A, r, k, B, b_stride, out, o_stride, len, j0);
    switch (r - j0) {
        case 3: matmul_gfni_rows<3>(A, r, k, B, b_stride, out, o_stride,
                                    len, j0); break;
        case 2: matmul_gfni_rows<2>(A, r, k, B, b_stride, out, o_stride,
                                    len, j0); break;
        case 1: matmul_gfni_rows<1>(A, r, k, B, b_stride, out, o_stride,
                                    len, j0); break;
        default: break;
    }
}
#endif

// out (r, len) = A (r, k)  x  B (k, len)  over GF(2^8), XOR-accumulate.
// B rows and out rows are contiguous with the given strides (in bytes),
// so callers can point straight into a (k, shard) numpy array.
extern "C" void mt_gf8_matmul(const uint8_t* A, size_t r, size_t k,
                              const uint8_t* B, size_t b_stride,
                              uint8_t* out, size_t o_stride, size_t len) {
#if MT_X86
    if (g_have_gfni && r > 0) {
        matmul_gfni(A, r, k, B, b_stride, out, o_stride, len);
        return;
    }
#endif
    for (size_t j = 0; j < r; j++) {
        uint8_t* dst = out + j * o_stride;
        std::memset(dst, 0, len);
        for (size_t i = 0; i < k; i++) {
            uint8_t c = A[j * k + i];
            if (c == 1) {  // common in systematic/decode matrices
                mt_gf8_xor(B + i * b_stride, dst, len);
                continue;
            }
            mul_xor(c, B + i * b_stride, dst, len);
        }
    }
}
