// Snappy block-format codec + CRC32C — the native compression path.
//
// Reference: klauspost/compress v1.11.7 S2 (go.mod:37) provides MinIO's
// transparent object compression (cmd/object-api-utils.go:436,916); its wire
// format is snappy-compatible.  This implements the snappy block format
// (https://github.com/google/snappy/blob/main/format_description.txt):
//   preamble: uncompressed length, little-endian varint
//   elements: tag byte — 00 literal, 01 copy(1-byte offset),
//             10 copy(2-byte LE offset), 11 copy(4-byte LE offset)
// Compression is greedy hash-table LZ77 over 64 KiB fragments (fresh table
// per fragment, offsets within the window), mirroring snappy/S2 structure.
//
// C ABI for ctypes; no dependencies beyond libc.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- crc32c --
// Table built under C++11 magic-statics init (thread-safe once-only by
// the standard).  The previous lazy 'static bool done' flag was a data
// race between concurrent first callers — found by the TSan tier, the
// same class of bug as the highwayhash feature-cache race.
struct Crc32cTable {
    uint32_t t[256];
    Crc32cTable() {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
            t[i] = c;
        }
    }
};

uint32_t mt_crc32c(const uint8_t* data, size_t n) {
    static const Crc32cTable table;
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = table.t[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- compressor --
size_t mt_snappy_max_compressed(size_t n) {
    // snappy's MaxCompressedLength bound
    return 32 + n + n / 6;
}

static uint8_t* emit_uvarint(uint8_t* dst, uint64_t v) {
    while (v >= 0x80) { *dst++ = (uint8_t)(v | 0x80); v >>= 7; }
    *dst++ = (uint8_t)v;
    return dst;
}

static uint8_t* emit_literal(uint8_t* dst, const uint8_t* src, size_t n) {
    size_t m = n - 1;
    if (m < 60) {
        *dst++ = (uint8_t)(m << 2);
    } else if (m < (1u << 8)) {
        *dst++ = 60 << 2; *dst++ = (uint8_t)m;
    } else if (m < (1u << 16)) {
        *dst++ = 61 << 2; *dst++ = (uint8_t)m; *dst++ = (uint8_t)(m >> 8);
    } else if (m < (1u << 24)) {
        *dst++ = 62 << 2; *dst++ = (uint8_t)m; *dst++ = (uint8_t)(m >> 8);
        *dst++ = (uint8_t)(m >> 16);
    } else {
        *dst++ = 63 << 2; *dst++ = (uint8_t)m; *dst++ = (uint8_t)(m >> 8);
        *dst++ = (uint8_t)(m >> 16); *dst++ = (uint8_t)(m >> 24);
    }
    memcpy(dst, src, n);
    return dst + n;
}

static uint8_t* emit_copy(uint8_t* dst, size_t offset, size_t length) {
    // lengths > 64 split into 64-byte copies (2-byte-offset tag)
    while (length >= 68) {
        *dst++ = (uint8_t)((63 << 2) | 2);  // len 64
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
        length -= 64;
    }
    if (length > 64) {
        *dst++ = (uint8_t)((59 << 2) | 2);  // len 60
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
        length -= 60;
    }
    if (length >= 12 || offset >= 2048) {
        *dst++ = (uint8_t)(((length - 1) << 2) | 2);
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
    } else {
        *dst++ = (uint8_t)(((offset >> 8) << 5) | ((length - 4) << 2) | 1);
        *dst++ = (uint8_t)offset;
    }
    return dst;
}

static inline uint32_t load32(const uint8_t* p) {
    uint32_t v; memcpy(&v, p, 4); return v;
}

#define HASH_BITS 14
#define HASH_SIZE (1 << HASH_BITS)

static inline uint32_t hash4(uint32_t v) {
    return (v * 0x1E35A7BDu) >> (32 - HASH_BITS);
}

// compress one fragment (<= 65536 bytes); returns bytes written
static size_t compress_fragment(const uint8_t* src, size_t n, uint8_t* dst) {
    uint8_t* d = dst;
    int32_t table[HASH_SIZE];
    memset(table, -1, sizeof(table));
    size_t lit_start = 0, i = 0;
    if (n >= 15) {
        size_t limit = n - 4;
        i = 1;
        table[hash4(load32(src))] = 0;
        while (i <= limit) {
            uint32_t h = hash4(load32(src + i));
            int32_t cand = table[h];
            table[h] = (int32_t)i;
            if (cand >= 0 && load32(src + cand) == load32(src + i)) {
                // extend match
                size_t len = 4;
                while (i + len < n && src[cand + len] == src[i + len]) len++;
                if (lit_start < i)
                    d = emit_literal(d, src + lit_start, i - lit_start);
                d = emit_copy(d, i - (size_t)cand, len);
                i += len;
                lit_start = i;
                if (i <= limit) table[hash4(load32(src + i - 1))] =
                    (int32_t)(i - 1);
            } else {
                i++;
            }
        }
    }
    if (lit_start < n)
        d = emit_literal(d, src + lit_start, n - lit_start);
    return (size_t)(d - dst);
}

size_t mt_snappy_compress(const uint8_t* src, size_t n, uint8_t* dst) {
    uint8_t* d = emit_uvarint(dst, n);
    const size_t FRAG = 65536;
    for (size_t off = 0; off < n; off += FRAG) {
        size_t m = (n - off < FRAG) ? (n - off) : FRAG;
        d += compress_fragment(src + off, m, d);
    }
    if (n == 0) {} // preamble alone encodes the empty block
    return (size_t)(d - dst);
}

// ----------------------------------------------------------- decompressor --
// returns decompressed size, or (size_t)-1 on corrupt input, or required
// size if dst_cap too small (call with dst=NULL to query via preamble).

long long mt_snappy_uncompressed_length(const uint8_t* src, size_t n) {
    uint64_t v = 0; int shift = 0; size_t i = 0;
    while (i < n && shift < 64) {
        uint8_t b = src[i++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return (long long)v;
        shift += 7;
    }
    return -1;
}

long long mt_snappy_uncompress(const uint8_t* src, size_t n,
                               uint8_t* dst, size_t dst_cap) {
    // parse preamble
    uint64_t want = 0; int shift = 0; size_t i = 0;
    for (;;) {
        if (i >= n || shift >= 64) return -1;
        uint8_t b = src[i++];
        want |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if (want > dst_cap) return -1;
    size_t o = 0;
    while (i < n) {
        uint8_t tag = src[i++];
        uint32_t kind = tag & 3;
        if (kind == 0) {                       // literal
            size_t len = (tag >> 2) + 1;
            if (len > 60) {
                size_t nb = len - 60;          // 1..4 extra length bytes
                if (i + nb > n) return -1;
                len = 0;
                for (size_t k = 0; k < nb; k++)
                    len |= (size_t)src[i + k] << (8 * k);
                len += 1;
                i += nb;
            }
            if (i + len > n || o + len > want) return -1;
            memcpy(dst + o, src + i, len);
            i += len; o += len;
        } else {
            size_t len, offset;
            if (kind == 1) {
                len = ((tag >> 2) & 7) + 4;
                if (i >= n) return -1;
                offset = ((size_t)(tag >> 5) << 8) | src[i++];
            } else if (kind == 2) {
                len = (tag >> 2) + 1;
                if (i + 2 > n) return -1;
                offset = (size_t)src[i] | ((size_t)src[i + 1] << 8);
                i += 2;
            } else {
                len = (tag >> 2) + 1;
                if (i + 4 > n) return -1;
                offset = (size_t)src[i] | ((size_t)src[i + 1] << 8) |
                         ((size_t)src[i + 2] << 16) |
                         ((size_t)src[i + 3] << 24);
                i += 4;
            }
            if (offset == 0 || offset > o || o + len > want) return -1;
            // overlapping copies must run byte-by-byte
            for (size_t k = 0; k < len; k++) {
                dst[o] = dst[o - offset];
                o++;
            }
        }
    }
    if (o != want) return -1;
    return (long long)o;
}

}  // extern "C"
