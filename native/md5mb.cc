// Multi-lane MD5 — the strict-compat ETag hot loop off the Python thread.
//
// The reference's PUT hot path rides assembly-accelerated hash modules
// (SURVEY §2.4, md5-simd's AVX512 16-lane server); this is the host-native
// analog for minio_tpu: an ILP-tuned single-stream core (the one ETag every
// strict PUT must compute is an irreducible serial chain) plus an N-lane
// multi-buffer entry point that advances INDEPENDENT digests in one
// GIL-free call.  MD5 is latency-bound — each step depends on the last —
// so one stream leaves most of a superscalar core idle; interleaving 2-8
// independent lanes fills those issue slots (the md5-simd trick without
// the SIMD: the compiler schedules the independent chains).
//
// Contract (pinned by tests/test_md5fast.py): digests are bit-identical
// to RFC 1321 / hashlib for every lane count, tail length and update
// split.  State layout is opaque to Python (mt_md5_state_size).

#include <stddef.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    uint32_t h[4];
    uint64_t n;          // total message bytes so far
    uint32_t buflen;     // pending tail bytes in buf
    uint8_t buf[64];
} MD5State;

static const uint32_t K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee,
    0xf57c0faf, 0x4787c62a, 0xa8304613, 0xfd469501,
    0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821,
    0xf61e2562, 0xc040b340, 0x265e5a51, 0xe9b6c7aa,
    0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed,
    0xa9e3e905, 0xfcefa3f8, 0x676f02d9, 0x8d2a4c8a,
    0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70,
    0x289b7ec6, 0xeaa127fa, 0xd4ef3085, 0x04881d05,
    0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039,
    0x655b59c3, 0x8f0ccc92, 0xffeff47d, 0x85845dd1,
    0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391,
};

static const uint8_t S[64] = {
    7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
    5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20,
    4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
    6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
};

static inline uint32_t rotl(uint32_t x, int s) {
    return (x << s) | (x >> (32 - s));
}

static inline uint32_t le32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

#define FF(a, b, c, d, m, k, s) \
    a += (((b) & (c)) | (~(b) & (d))) + (m) + (k); a = rotl(a, s) + (b);
#define GG(a, b, c, d, m, k, s) \
    a += (((b) & (d)) | ((c) & ~(d))) + (m) + (k); a = rotl(a, s) + (b);
#define HH(a, b, c, d, m, k, s) \
    a += ((b) ^ (c) ^ (d)) + (m) + (k); a = rotl(a, s) + (b);
#define II(a, b, c, d, m, k, s) \
    a += ((c) ^ ((b) | ~(d))) + (m) + (k); a = rotl(a, s) + (b);

// Fully unrolled single-block compress: the serial-chain core, tuned
// for the shortest dependency path per step (the ETag's irreducible
// cost when only one stream is in flight).
static void compress1(uint32_t h[4], const uint8_t* p) {
    uint32_t m[16];
    for (int i = 0; i < 16; i++) m[i] = le32(p + 4 * i);
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];

    FF(a, b, c, d, m[0],  K[0],  7)  FF(d, a, b, c, m[1],  K[1],  12)
    FF(c, d, a, b, m[2],  K[2],  17) FF(b, c, d, a, m[3],  K[3],  22)
    FF(a, b, c, d, m[4],  K[4],  7)  FF(d, a, b, c, m[5],  K[5],  12)
    FF(c, d, a, b, m[6],  K[6],  17) FF(b, c, d, a, m[7],  K[7],  22)
    FF(a, b, c, d, m[8],  K[8],  7)  FF(d, a, b, c, m[9],  K[9],  12)
    FF(c, d, a, b, m[10], K[10], 17) FF(b, c, d, a, m[11], K[11], 22)
    FF(a, b, c, d, m[12], K[12], 7)  FF(d, a, b, c, m[13], K[13], 12)
    FF(c, d, a, b, m[14], K[14], 17) FF(b, c, d, a, m[15], K[15], 22)

    GG(a, b, c, d, m[1],  K[16], 5)  GG(d, a, b, c, m[6],  K[17], 9)
    GG(c, d, a, b, m[11], K[18], 14) GG(b, c, d, a, m[0],  K[19], 20)
    GG(a, b, c, d, m[5],  K[20], 5)  GG(d, a, b, c, m[10], K[21], 9)
    GG(c, d, a, b, m[15], K[22], 14) GG(b, c, d, a, m[4],  K[23], 20)
    GG(a, b, c, d, m[9],  K[24], 5)  GG(d, a, b, c, m[14], K[25], 9)
    GG(c, d, a, b, m[3],  K[26], 14) GG(b, c, d, a, m[8],  K[27], 20)
    GG(a, b, c, d, m[13], K[28], 5)  GG(d, a, b, c, m[2],  K[29], 9)
    GG(c, d, a, b, m[7],  K[30], 14) GG(b, c, d, a, m[12], K[31], 20)

    HH(a, b, c, d, m[5],  K[32], 4)  HH(d, a, b, c, m[8],  K[33], 11)
    HH(c, d, a, b, m[11], K[34], 16) HH(b, c, d, a, m[14], K[35], 23)
    HH(a, b, c, d, m[1],  K[36], 4)  HH(d, a, b, c, m[4],  K[37], 11)
    HH(c, d, a, b, m[7],  K[38], 16) HH(b, c, d, a, m[10], K[39], 23)
    HH(a, b, c, d, m[13], K[40], 4)  HH(d, a, b, c, m[0],  K[41], 11)
    HH(c, d, a, b, m[3],  K[42], 16) HH(b, c, d, a, m[6],  K[43], 23)
    HH(a, b, c, d, m[9],  K[44], 4)  HH(d, a, b, c, m[12], K[45], 11)
    HH(c, d, a, b, m[15], K[46], 16) HH(b, c, d, a, m[2],  K[47], 23)

    II(a, b, c, d, m[0],  K[48], 6)  II(d, a, b, c, m[7],  K[49], 10)
    II(c, d, a, b, m[14], K[50], 15) II(b, c, d, a, m[5],  K[51], 21)
    II(a, b, c, d, m[12], K[52], 6)  II(d, a, b, c, m[3],  K[53], 10)
    II(c, d, a, b, m[10], K[54], 15) II(b, c, d, a, m[1],  K[55], 21)
    II(a, b, c, d, m[8],  K[56], 6)  II(d, a, b, c, m[15], K[57], 10)
    II(c, d, a, b, m[6],  K[58], 15) II(b, c, d, a, m[13], K[59], 21)
    II(a, b, c, d, m[4],  K[60], 6)  II(d, a, b, c, m[11], K[61], 10)
    II(c, d, a, b, m[2],  K[62], 15) II(b, c, d, a, m[9],  K[63], 21)

    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
}

// L-lane lock-step compress: the SAME fully-unrolled 64-step schedule
// as compress1, but each step's op runs for all L lanes (the inner
// lane loop unrolls — L is a compile-time constant).  Every lane's
// chain is independent, and the message schedule is stored WORD-MAJOR
// (m[word][lane]) so each step's per-lane loads are contiguous — that
// is what lets the compiler auto-vectorize the lane loop into SIMD
// (lane-major m[lane][word] needs a strided gather and measured
// SLOWER than single-stream; transposing measured 4-lane ~2x and
// 8-lane ~2.5x the single-stream rate on the 2-core dev box).
#define STEP_L(OP, A, B, C, D, g, i)                                   \
    for (int l = 0; l < L; l++) {                                      \
        A[l] += OP(B[l], C[l], D[l]) + m[g][l] + K[i];                 \
        A[l] = rotl(A[l], S[i]) + B[l];                                \
    }
#define OPF(x, y, z) (((x) & (y)) | (~(x) & (z)))
#define OPG(x, y, z) (((x) & (z)) | ((y) & ~(z)))
#define OPH(x, y, z) ((x) ^ (y) ^ (z))
#define OPI(x, y, z) ((y) ^ ((x) | ~(z)))

template <int L>
static void compressL(MD5State* const* st, const uint8_t* const* blk) {
    uint32_t a[L], b[L], c[L], d[L], m[16][L];
    for (int l = 0; l < L; l++) {
        a[l] = st[l]->h[0]; b[l] = st[l]->h[1];
        c[l] = st[l]->h[2]; d[l] = st[l]->h[3];
        for (int i = 0; i < 16; i++) m[i][l] = le32(blk[l] + 4 * i);
    }
    STEP_L(OPF, a, b, c, d, 0, 0)   STEP_L(OPF, d, a, b, c, 1, 1)
    STEP_L(OPF, c, d, a, b, 2, 2)   STEP_L(OPF, b, c, d, a, 3, 3)
    STEP_L(OPF, a, b, c, d, 4, 4)   STEP_L(OPF, d, a, b, c, 5, 5)
    STEP_L(OPF, c, d, a, b, 6, 6)   STEP_L(OPF, b, c, d, a, 7, 7)
    STEP_L(OPF, a, b, c, d, 8, 8)   STEP_L(OPF, d, a, b, c, 9, 9)
    STEP_L(OPF, c, d, a, b, 10, 10) STEP_L(OPF, b, c, d, a, 11, 11)
    STEP_L(OPF, a, b, c, d, 12, 12) STEP_L(OPF, d, a, b, c, 13, 13)
    STEP_L(OPF, c, d, a, b, 14, 14) STEP_L(OPF, b, c, d, a, 15, 15)

    STEP_L(OPG, a, b, c, d, 1, 16)  STEP_L(OPG, d, a, b, c, 6, 17)
    STEP_L(OPG, c, d, a, b, 11, 18) STEP_L(OPG, b, c, d, a, 0, 19)
    STEP_L(OPG, a, b, c, d, 5, 20)  STEP_L(OPG, d, a, b, c, 10, 21)
    STEP_L(OPG, c, d, a, b, 15, 22) STEP_L(OPG, b, c, d, a, 4, 23)
    STEP_L(OPG, a, b, c, d, 9, 24)  STEP_L(OPG, d, a, b, c, 14, 25)
    STEP_L(OPG, c, d, a, b, 3, 26)  STEP_L(OPG, b, c, d, a, 8, 27)
    STEP_L(OPG, a, b, c, d, 13, 28) STEP_L(OPG, d, a, b, c, 2, 29)
    STEP_L(OPG, c, d, a, b, 7, 30)  STEP_L(OPG, b, c, d, a, 12, 31)

    STEP_L(OPH, a, b, c, d, 5, 32)  STEP_L(OPH, d, a, b, c, 8, 33)
    STEP_L(OPH, c, d, a, b, 11, 34) STEP_L(OPH, b, c, d, a, 14, 35)
    STEP_L(OPH, a, b, c, d, 1, 36)  STEP_L(OPH, d, a, b, c, 4, 37)
    STEP_L(OPH, c, d, a, b, 7, 38)  STEP_L(OPH, b, c, d, a, 10, 39)
    STEP_L(OPH, a, b, c, d, 13, 40) STEP_L(OPH, d, a, b, c, 0, 41)
    STEP_L(OPH, c, d, a, b, 3, 42)  STEP_L(OPH, b, c, d, a, 6, 43)
    STEP_L(OPH, a, b, c, d, 9, 44)  STEP_L(OPH, d, a, b, c, 12, 45)
    STEP_L(OPH, c, d, a, b, 15, 46) STEP_L(OPH, b, c, d, a, 2, 47)

    STEP_L(OPI, a, b, c, d, 0, 48)  STEP_L(OPI, d, a, b, c, 7, 49)
    STEP_L(OPI, c, d, a, b, 14, 50) STEP_L(OPI, b, c, d, a, 5, 51)
    STEP_L(OPI, a, b, c, d, 12, 52) STEP_L(OPI, d, a, b, c, 3, 53)
    STEP_L(OPI, c, d, a, b, 10, 54) STEP_L(OPI, b, c, d, a, 1, 55)
    STEP_L(OPI, a, b, c, d, 8, 56)  STEP_L(OPI, d, a, b, c, 15, 57)
    STEP_L(OPI, c, d, a, b, 6, 58)  STEP_L(OPI, b, c, d, a, 13, 59)
    STEP_L(OPI, a, b, c, d, 4, 60)  STEP_L(OPI, d, a, b, c, 11, 61)
    STEP_L(OPI, c, d, a, b, 2, 62)  STEP_L(OPI, b, c, d, a, 9, 63)

    for (int l = 0; l < L; l++) {
        st[l]->h[0] += a[l]; st[l]->h[1] += b[l];
        st[l]->h[2] += c[l]; st[l]->h[3] += d[l];
    }
}

extern "C" {

size_t mt_md5_state_size(void) { return sizeof(MD5State); }

void mt_md5_init(void* vst) {
    MD5State* st = (MD5State*)vst;
    st->h[0] = 0x67452301u; st->h[1] = 0xefcdab89u;
    st->h[2] = 0x98badcfeu; st->h[3] = 0x10325476u;
    st->n = 0;
    st->buflen = 0;
}

void mt_md5_update(void* vst, const uint8_t* p, size_t n) {
    MD5State* st = (MD5State*)vst;
    st->n += n;
    if (st->buflen) {            // drain the buffered tail first
        size_t want = 64 - st->buflen;
        size_t take = n < want ? n : want;
        memcpy(st->buf + st->buflen, p, take);
        st->buflen += (uint32_t)take;
        p += take; n -= take;
        if (st->buflen < 64) return;
        compress1(st->h, st->buf);
        st->buflen = 0;
    }
    while (n >= 64) {
        compress1(st->h, p);
        p += 64; n -= 64;
    }
    if (n) {
        memcpy(st->buf, p, n);
        st->buflen = (uint32_t)n;
    }
}

void mt_md5_final(void* vst, uint8_t out[16]) {
    MD5State* st = (MD5State*)vst;
    uint64_t bits = st->n * 8;
    uint8_t pad[72];
    size_t padlen = (st->buflen < 56) ? (56 - st->buflen)
                                      : (120 - st->buflen);
    memset(pad, 0, sizeof(pad));
    pad[0] = 0x80;
    for (int i = 0; i < 8; i++) pad[padlen + i] = (uint8_t)(bits >> (8 * i));
    mt_md5_update(st, pad, padlen + 8);
    for (int i = 0; i < 4; i++) {
        out[4 * i + 0] = (uint8_t)(st->h[i]);
        out[4 * i + 1] = (uint8_t)(st->h[i] >> 8);
        out[4 * i + 2] = (uint8_t)(st->h[i] >> 16);
        out[4 * i + 3] = (uint8_t)(st->h[i] >> 24);
    }
}

void mt_md5_oneshot(const uint8_t* p, size_t n, uint8_t out[16]) {
    MD5State st;
    mt_md5_init(&st);
    mt_md5_update(&st, p, n);
    mt_md5_final(&st, out);
}

// Multi-buffer update: advance ``nlanes`` independent streams, each by
// its own (ptr, len).  Whole 64-byte blocks run lock-step through the
// widest compressL the still-active lane set fills (8/4/2); odd lanes
// and sub-block tails ride the single-stream core / state buffer, so
// ANY mix of lengths is legal and bit-identical to per-lane updates.
void mt_md5mb_update(int nlanes, void* const* vstates,
                     const uint8_t* const* ptrs, const size_t* lens) {
    enum { MAXL = 64 };
    if (nlanes <= 0) return;
    if (nlanes == 1) {
        mt_md5_update(vstates[0], ptrs[0], lens[0]);
        return;
    }
    if (nlanes > MAXL) {         // split oversized batches
        mt_md5mb_update(MAXL, vstates, ptrs, lens);
        mt_md5mb_update(nlanes - MAXL, vstates + MAXL, ptrs + MAXL,
                        lens + MAXL);
        return;
    }
    const uint8_t* p[MAXL];
    size_t nblk[MAXL];
    for (int l = 0; l < nlanes; l++) {
        MD5State* st = (MD5State*)vstates[l];
        const uint8_t* q = ptrs[l];
        size_t n = lens[l];
        st->n += n;
        if (st->buflen) {
            size_t want = 64 - st->buflen;
            size_t take = n < want ? n : want;
            memcpy(st->buf + st->buflen, q, take);
            st->buflen += (uint32_t)take;
            q += take; n -= take;
            if (st->buflen == 64) {
                compress1(st->h, st->buf);
                st->buflen = 0;
            }
        }
        p[l] = q;
        nblk[l] = n / 64;
        // stash the tail now; the block loop below never touches it
        size_t tail = n - nblk[l] * 64;
        if (tail) {
            memcpy(st->buf, q + nblk[l] * 64, tail);
            st->buflen = (uint32_t)tail;
        }
    }
    for (;;) {
        MD5State* act_st[MAXL];
        const uint8_t* act_p[MAXL];
        int act_idx[MAXL];
        int na = 0;
        for (int l = 0; l < nlanes; l++) {
            if (nblk[l]) {
                act_st[na] = (MD5State*)vstates[l];
                act_p[na] = p[l];
                act_idx[na] = l;
                na++;
            }
        }
        if (na == 0) break;
        int done = 0;
        while (na - done >= 8) {
            compressL<8>(act_st + done, act_p + done);
            done += 8;
        }
        while (na - done >= 4) {
            compressL<4>(act_st + done, act_p + done);
            done += 4;
        }
        while (na - done >= 2) {
            compressL<2>(act_st + done, act_p + done);
            done += 2;
        }
        while (done < na) {
            compress1(act_st[done]->h, act_p[done]);
            done++;
        }
        for (int i = 0; i < na; i++) {
            int l = act_idx[i];
            p[l] += 64;
            nblk[l]--;
        }
    }
}

}  // extern "C"
