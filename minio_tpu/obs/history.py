"""Bounded multi-resolution telemetry history — the watchdog plane's
memory (cmd/metrics.go keeps no history at all; the reference leans on
an external Prometheus for "what did this look like ten minutes ago").

A background sampler (:class:`HistorySampler`, one ``mt-obs-history``
thread, ``watchdog`` kvconfig subsystem) snapshots selected ``mt_*``
families out of the node's own exposition document into fixed-size
downsampling rings:

  ======  =====  ========
  step    slots  coverage
  ======  =====  ========
  10 s    36     6 min
  1 min   120    2 h
  10 min  144    24 h
  ======  =====  ========

Counters are stored as **rates** (the delta between consecutive
samples over their spacing — a reset clamps to zero and re-baselines),
gauges as last/min/max/avg per bucket.  Everything is bounded:
``max_series`` caps distinct series, the rings never grow, and a
disabled watchdog subsystem means no sampler thread and no
``mt_history_*`` family in the scrape (the idle contract).

Three consumers share the same rings:

* the admin ``metrics-history`` route (``?family=&window=&step=``),
  peer-aggregated into one ``server``-labelled exposition document
  exactly like ``metrics?scope=cluster``;
* the rule engine (obs/watchdog.py), which evaluates burn rates and
  drift over the rings each sampler tick;
* forensic bundles (obs/forensic.py), which embed the last 30 minutes
  as ``history.json`` — a bundle shows the road TO the breach, not
  just the instant.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

from ..admin.metrics import _fmt_value

# (step_s, slots): 6 minutes fine, 2 hours medium, 24 hours coarse
RESOLUTIONS: Tuple[Tuple[int, int], ...] = ((10, 36), (60, 120),
                                            (600, 144))

# family prefixes sampled by default — the signals the rule catalog
# (obs/watchdog.py) evaluates, plus the capacity/usage trends worth
# remembering.  ``watchdog.families`` appends operator-chosen prefixes.
DEFAULT_FAMILIES: Tuple[str, ...] = (
    "mt_s3_requests_api_total",
    "mt_s3_requests_errors_total",
    "mt_s3_api_last_minute_requests",
    "mt_s3_api_last_minute_avg_ns",
    "mt_s3_api_last_minute_p99_ns",
    "mt_node_disk_latency_p50_ns",
    "mt_node_disk_latency_p99_ns",
    "mt_node_disk_slow",
    "mt_target_dead_letter_total",
    "mt_target_queue_length",
    "mt_rebalance_moved_bytes_total",
    "mt_rebalance_cycle_active",
    "mt_pool_usage_bytes",
    "mt_cluster_capacity_raw_total_bytes",
    "mt_cluster_capacity_raw_free_bytes",
    "mt_heal_mrf_queued_total",
    "mt_mem_inuse_bytes",
    "mt_rpc_breaker_opens_total",
    # workload attribution plane (obs/metering.py): per-tenant rates
    # feed the tenant_burn / noisy_neighbor watchdog rules; label
    # cardinality is bounded at the source (top-K sketch gating)
    "mt_tenant_requests_total",
    "mt_tenant_errors_total",
    "mt_tenant_rx_bytes_total",
    "mt_tenant_tx_bytes_total",
)

_TYPE_RE = re.compile(r"^# TYPE (\S+) (\S+)$")
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                        r"(?:\{(.*)\})? (\S+)$")

# bucket slot layout
_B_MARK, _B_LAST, _B_MIN, _B_MAX, _B_SUM, _B_CNT = range(6)


def select_samples(doc: str, prefixes: Iterable[str]
                   ) -> Dict[Tuple[str, str], Tuple[float, str]]:
    """Parse one exposition document into
    ``{(family, raw_label_string): (value, kind)}`` keeping only
    families matching a prefix.  Histogram families are skipped — the
    rings store scalars; the lastminute gauges already carry the
    percentiles worth remembering."""
    pref = tuple(prefixes)
    out: Dict[Tuple[str, str], Tuple[float, str]] = {}
    kinds: Dict[str, str] = {}
    for ln in doc.splitlines():
        if ln.startswith("#"):
            m = _TYPE_RE.match(ln)
            if m:
                kinds[m.group(1)] = m.group(2)
            continue
        if not ln:
            continue
        m = _SAMPLE_RE.match(ln)
        if not m:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        if not name.startswith(pref):
            continue
        kind = kinds.get(name, "gauge")
        if kind == "histogram":
            continue
        # histogram child samples (_bucket/_count/_sum) carry the
        # BASE family's # TYPE — skip them too
        base = name.rsplit("_", 1)[0]
        if name.endswith(("_bucket", "_count", "_sum")) \
                and kinds.get(base) == "histogram":
            continue
        try:
            out[(name, labels)] = (float(raw), kind)
        except ValueError:
            continue
    return out


class _SeriesRings:
    """One series' buckets across every resolution."""

    __slots__ = ("rings",)

    def __init__(self, resolutions: Tuple[Tuple[int, int], ...]):
        self.rings = [[None] * slots for _, slots in resolutions]

    def observe(self, resolutions, now_s: float, value: float) -> None:
        for ri, (step, slots) in enumerate(resolutions):
            mark = int(now_s) // step
            ring = self.rings[ri]
            slot = ring[mark % slots]
            if slot is None or slot[_B_MARK] != mark:
                ring[mark % slots] = [mark, value, value, value,
                                      value, 1]
            else:
                slot[_B_LAST] = value
                if value < slot[_B_MIN]:
                    slot[_B_MIN] = value
                if value > slot[_B_MAX]:
                    slot[_B_MAX] = value
                slot[_B_SUM] += value
                slot[_B_CNT] += 1

    def points(self, resolutions, ri: int, now_s: float,
               window_s: float, agg: str) -> list:
        """[(bucket_epoch_s, value)] oldest first for the live window."""
        step, slots = resolutions[ri]
        hi = int(now_s) // step
        lo = max(hi - slots + 1, int(int(now_s - window_s) // step))
        out = []
        for mark in range(lo, hi + 1):
            slot = self.rings[ri][mark % slots]
            if slot is None or slot[_B_MARK] != mark:
                continue
            if agg == "min":
                v = slot[_B_MIN]
            elif agg == "max":
                v = slot[_B_MAX]
            elif agg == "avg":
                v = slot[_B_SUM] / max(1, slot[_B_CNT])
            elif agg == "sum":
                v = slot[_B_SUM]
            else:
                v = slot[_B_LAST]
            out.append((mark * step, v))
        return out


class TelemetryHistory:
    """The bounded series store.  Writes come from ONE sampler thread;
    reads (admin route, rule engine, bundle writer) take the same lock
    the writer does — the write path is a handful of list mutations
    per series every ``interval``, nowhere near the request path."""

    def __init__(self, resolutions: Tuple[Tuple[int, int], ...]
                 = RESOLUTIONS, max_series: int = 512):
        self.resolutions = tuple(resolutions)
        self.max_series = max(1, max_series)
        self._mu = threading.Lock()
        self._series: Dict[Tuple[str, str], _SeriesRings] = {}
        # counter baselines: (value, t) per series for rate conversion
        self._prev: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self.samples_total = 0
        self.dropped_series = 0

    def observe(self, now_s: float,
                samples: Dict[Tuple[str, str], Tuple[float, str]]
                ) -> None:
        with self._mu:
            for key, (value, kind) in samples.items():
                if kind == "counter":
                    prev = self._prev.get(key)
                    self._prev[key] = (value, now_s)
                    if prev is None:
                        continue
                    dv, dt = value - prev[0], now_s - prev[1]
                    if dt <= 0:
                        continue
                    # a reset (restarted source) reads as a negative
                    # delta: clamp and re-baseline instead of writing
                    # a bogus huge negative rate
                    value = max(0.0, dv) / dt
                rings = self._series.get(key)
                if rings is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    rings = self._series[key] = _SeriesRings(
                        self.resolutions)
                rings.observe(self.resolutions, now_s, value)
                self.samples_total += 1

    # -- queries --------------------------------------------------------------

    def _pick_resolution(self, window_s: float, step_s: float) -> int:
        """Finest resolution that honors the requested step AND covers
        the window; falls back to the coarsest ring."""
        candidates = [ri for ri, (step, _) in enumerate(self.resolutions)
                      if step >= step_s] or \
            [len(self.resolutions) - 1]
        for ri in candidates:
            step, slots = self.resolutions[ri]
            if step * slots >= window_s:
                return ri
        return candidates[-1]

    def query(self, family: str = "", window_s: float = 1800.0,
              step_s: float = 60.0, agg: str = "last",
              now_s: float | None = None
              ) -> Dict[Tuple[str, str], list]:
        """{(family, raw_labels): [(epoch_s, value), ...]} for every
        series whose family starts with ``family`` (all when empty)."""
        now_s = time.time() if now_s is None else now_s
        ri = self._pick_resolution(window_s, step_s)
        with self._mu:
            keys = [k for k in self._series if k[0].startswith(family)]
            return {k: self._series[k].points(self.resolutions, ri,
                                              now_s, window_s, agg)
                    for k in sorted(keys)}

    def series_count(self) -> int:
        with self._mu:
            return len(self._series)

    def stats(self) -> dict:
        with self._mu:
            return {"series": len(self._series),
                    "samplesTotal": self.samples_total,
                    "droppedSeries": self.dropped_series}


def render_history(history: TelemetryHistory, family: str = "",
                   window_s: float = 1800.0, step_s: float = 60.0,
                   agg: str = "last", now_s: float | None = None) -> str:
    """The ``metrics-history`` document: exposition-style text, one
    ``# TYPE`` per family, each point a sample with a ``ts`` label
    (epoch seconds of its bucket) — the strict text-format grammar has
    no room for native timestamps on gauge points, and a label keeps
    the cluster merge + ``server`` stamping machinery unchanged."""
    data = history.query(family=family, window_s=window_s,
                         step_s=step_s, agg=agg, now_s=now_s)
    lines: list[str] = []
    current = None
    for (fam, labels), points in data.items():
        if fam != current:
            lines.append(f"# TYPE {fam} gauge")
            current = fam
        for t, v in points:
            inner = f'{labels},ts="{int(t)}"' if labels \
                else f'ts="{int(t)}"'
            lines.append(f"{fam}{{{inner}}} {_fmt_value(v)}")
    return "\n".join(lines) + "\n" if lines else "\n"


def snapshot_dict(history: Optional[TelemetryHistory],
                  window_s: float = 1800.0, step_s: float = 60.0,
                  now_s: float | None = None) -> dict:
    """The forensic-bundle ``history.json`` shape: every sampled
    series' last ``window_s`` as [epoch_s, value] pairs — the road to
    the breach, readable without a scraper."""
    if history is None:
        return {"enabled": False, "series": []}
    data = history.query(window_s=window_s, step_s=step_s, now_s=now_s)
    return {
        "enabled": True,
        "windowSeconds": window_s,
        "stepSeconds": step_s,
        "stats": history.stats(),
        "series": [{"family": fam, "labels": labels,
                    "points": [[t, v] for t, v in points]}
                   for (fam, labels), points in data.items() if points],
    }


class HistorySampler:
    """The ``mt-obs-history`` thread: every ``interval_s`` render the
    node's own exposition document, fold the selected families into
    the rings, then hand the tick to the registered listeners (the
    rule engine).  Clock and collector are injectable so the watchdog
    unit tier drives deterministic seeded series without sleeping."""

    def __init__(self, collect: Callable[[], str],
                 history: TelemetryHistory,
                 interval_s: float = 10.0,
                 families: Tuple[str, ...] = DEFAULT_FAMILIES,
                 extra: Callable[[], dict] | None = None,
                 clock: Callable[[], float] = time.time):
        self.collect = collect
        self.history = history
        self.interval_s = max(1.0, interval_s)
        self.families = tuple(families)
        self.extra = extra
        self.clock = clock
        self.listeners: list[Callable[[float], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self, now_s: float | None = None) -> None:
        """One sample + evaluate round (the thread body's unit; tests
        call it directly with a fake clock)."""
        now_s = self.clock() if now_s is None else now_s
        try:
            samples = select_samples(self.collect(), self.families)
        except Exception:  # noqa: BLE001 — a failing scrape loses one
            samples = {}   # sample, never the sampler
        if self.extra is not None:
            try:
                samples.update(self.extra())
            except Exception:  # noqa: BLE001 — same contract
                pass
        self.history.observe(now_s, samples)
        for listener in list(self.listeners):
            try:
                listener(now_s)
            except Exception:  # noqa: BLE001 — a rule bug must not
                pass           # stop the sampler

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                self.tick()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="mt-obs-history")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None


def breaker_sample() -> dict:
    """Synthetic counter series for signals with no scrape family of
    their own: the internode breaker's lifetime open count (the
    breaker_flapping rule's source)."""
    from ..parallel import rpc as _rpc
    return {("mt_rpc_breaker_opens_total", ""):
            (float(_rpc.BREAKER_OPEN_COUNT), "counter")}
