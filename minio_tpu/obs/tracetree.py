"""Causal trace trees — assembly, query, and OTLP export.

The span ring (obs/trace.py) records flat compact tuples; every span
carries ``span_id``/``parent_id``, so one request's drive ops, kernel
dispatches, batcher waits, quorum reductions, and peer-side twins can
be reassembled into ONE tree after the fact — the Dapper model, but
always-on and bounded (the ring, not a sampler).  This module is the
read side:

  * :func:`local_spans` — render this node's ring into flat span dicts
    (each stamped with the node name, so cross-node merges stay
    attributable);
  * :func:`assemble` — group flat spans (local or peer-fetched) by
    request id and knit parent→children trees.  A span whose parent
    was overwritten in the ring re-attaches under the root with an
    ``orphan`` marker — a lossy ring must degrade to a shallower tree,
    never to a dropped span;
  * :func:`tree_reply` — one node's admin ``trace-tree`` reply (THE
    builder: the route's local leg and the peer RPC both call it, the
    xray_reply discipline);
  * :func:`to_otlp` — the OTLP/JSON (resourceSpans→scopeSpans→spans)
    shape for export through the egress plane.  IDs are derived
    deterministically (md5 of the internal ids, truncated to OTLP's
    16-byte trace / 8-byte span hex), so re-exports of the same tree
    are idempotent at the collector.

Aggregation protocol: the admin route merges the caller's local spans
with every peer's ``trace_tree_query`` reply.  Peers return spans for
(a) their OWN matching roots and (b) any ``rids`` the caller names —
so trees rooted on the caller always arrive complete, and a specific
``?rid=`` query is complete from any node.  (On ≥3-node clusters a
peer-rooted tree's third-node children need the rid-scoped form; the
one-round listing trades that corner for bounded fan-out.)

Idle contract: nothing here runs on the request path — assembly and
export are admin-route work over ring snapshots.
"""

from __future__ import annotations

import hashlib
import time

from ..admin.metrics import GLOBAL as _metrics
from . import critpath as _critpath
from . import trace as _trace

# bounds shared by the route, the peer RPC, and the forensic attach —
# a tree query must never ship the whole 16k-slot ring per peer
MAX_TREES = 100
DEFAULT_TREES = 20


# -- flat span rendering ------------------------------------------------------

def render_span(rec: tuple, node: str = "") -> dict:
    """One ring tuple → the wire/json span dict (flat; no children)."""
    out = {
        "requestID": rec[_trace._R_RID],
        "spanID": rec[_trace._R_SID],
        "parentID": rec[_trace._R_PARENT],
        "type": rec[_trace._R_TYPE],
        "name": rec[_trace._R_NAME],
        "startNs": rec[_trace._R_START],
        "durationNs": rec[_trace._R_DUR],
    }
    if node:
        out["node"] = node
    if rec[_trace._R_ERR]:
        out["error"] = rec[_trace._R_ERR]
    if rec[_trace._R_LABEL]:
        out["label"] = rec[_trace._R_LABEL]
    extra = rec[_trace._R_EXTRA]
    if isinstance(extra, tuple):         # a quorum.* gating row
        out["gating"] = _critpath.render_row(extra)
    elif isinstance(extra, int) and extra:
        out["status"] = extra            # the http root's status code
    return out


def local_spans(rid: str = "", rids: tuple = (),
                node: str = "") -> list[dict]:
    """This node's ring as flat span dicts, oldest first.  ``rid``
    narrows to one request; ``rids`` to a named set (the peer-merge
    protocol); both empty means everything resident."""
    want = set(rids) if rids else None
    out = []
    for rec in _trace.SPANS.snapshot():
        r = rec[_trace._R_RID]
        if rid and r != rid:
            continue
        if want is not None and not rid and r not in want:
            continue
        out.append(render_span(rec, node=node))
    return out


# -- tree assembly ------------------------------------------------------------

def assemble(spans: list[dict]) -> list[dict]:
    """Flat spans (any mix of nodes) → one tree per request id,
    oldest-root first.  The root is the span whose id equals the
    request id (minted in s3/server._dispatch); a request whose root
    aged out of every ring gets a synthetic ``partial`` root so its
    surviving children remain queryable."""
    by_rid: dict[str, dict[str, dict]] = {}
    order: list[str] = []
    for s in spans:
        rid = s.get("requestID", "")
        if not rid:
            continue
        nodes = by_rid.get(rid)
        if nodes is None:
            nodes = by_rid[rid] = {}
            order.append(rid)
        sid = s.get("spanID", "")
        if sid in nodes:                 # ring overlap across peers
            continue
        nodes[sid] = dict(s, children=[])
    trees = []
    for rid in order:
        nodes = by_rid[rid]
        root = nodes.get(rid)
        if root is None:
            root = nodes[rid] = {
                "requestID": rid, "spanID": rid, "parentID": "",
                "type": "http", "name": "(root evicted)", "startNs": 0,
                "durationNs": 0, "partial": True, "children": []}
        for s in nodes.values():
            if s is root:
                continue
            parent = nodes.get(s.get("parentID", ""))
            if parent is None or parent is s:
                s["orphan"] = True       # parent lost to ring overwrite
                parent = root
            parent["children"].append(s)
        _sort_children(root)
        trees.append(root)
    return trees


def _sort_children(node: dict, _depth: int = 0) -> None:
    kids = node.get("children", ())
    for k in kids:
        if _depth < 64:                  # orphan rewires cap real depth
            _sort_children(k, _depth + 1)
    node["children"] = sorted(kids, key=lambda s: s.get("startNs", 0))


def span_count(tree: dict) -> int:
    return 1 + sum(span_count(c) for c in tree.get("children", ()))


def _tree_error(tree: dict) -> bool:
    if tree.get("error") or tree.get("status", 0) >= 400:
        return True
    return any(_tree_error(c) for c in tree.get("children", ()))


def filter_trees(trees: list[dict], api: str = "",
                 min_duration_ms: float = 0.0,
                 errors_only: bool = False,
                 limit: int = DEFAULT_TREES) -> list[dict]:
    """Newest-root-first filtered trees (the xray filter vocabulary,
    applied to roots)."""
    min_ns = int(min_duration_ms * 1e6)
    out = []
    for tree in sorted(trees, key=lambda t: t.get("startNs", 0),
                       reverse=True):
        if api and tree.get("name") != api:
            continue
        if min_ns and tree.get("durationNs", 0) < min_ns:
            continue
        if errors_only and not _tree_error(tree):
            continue
        out.append(tree)
        if len(out) >= limit:
            break
    return out


# -- the admin reply builder --------------------------------------------------

def tree_reply(srv, rid: str = "", api: str = "",
               min_duration_ms: float = 0.0, errors_only: bool = False,
               limit: int = DEFAULT_TREES, rids: tuple = ()) -> dict:
    """One node's trace-tree reply — flat ``spans`` for the merge path
    plus assembled local ``trees`` for the single-node / ?local=true
    read.  ``rids`` is the peer-merge protocol: spans for the caller's
    roots ride along so its trees assemble complete."""
    try:
        limit = max(1, min(int(limit), MAX_TREES))
    except (TypeError, ValueError):
        limit = DEFAULT_TREES
    node = getattr(srv, "node_name", "")
    _metrics.inc("mt_trace_tree_query_total", {}, 1)
    if rid:
        spans = local_spans(rid=rid, node=node)
    else:
        local = local_spans(node=node)
        roots = filter_trees(
            assemble(local), api=api, min_duration_ms=min_duration_ms,
            errors_only=errors_only, limit=limit)
        keep = {t["requestID"] for t in roots} | set(rids or ())
        spans = [s for s in local if s.get("requestID") in keep]
    return {
        "node": node,
        "spans": spans,
        "trees": filter_trees(
            assemble(spans), api=api, min_duration_ms=min_duration_ms,
            errors_only=errors_only, limit=limit),
    }


def merge_replies(local_reply: dict, peer_replies: list,
                  api: str = "", min_duration_ms: float = 0.0,
                  errors_only: bool = False,
                  limit: int = DEFAULT_TREES) -> list[dict]:
    """Cluster view: every node's flat spans pooled, then assembled —
    a frontend root adopts its peer-side children here."""
    spans = list(local_reply.get("spans", ()))
    for r in peer_replies:
        if isinstance(r, dict):
            spans.extend(r.get("spans", ()))
    return filter_trees(assemble(spans), api=api,
                        min_duration_ms=min_duration_ms,
                        errors_only=errors_only, limit=limit)


# -- OTLP export --------------------------------------------------------------

def _otlp_trace_id(rid: str) -> str:
    return hashlib.md5(rid.encode()).hexdigest()          # 16 bytes hex

def _otlp_span_id(sid: str) -> str:
    return hashlib.md5(sid.encode()).hexdigest()[:16]     # 8 bytes hex


def _otlp_span(tree: dict, trace_id: str, out: list) -> None:
    attrs = [{"key": "mt.type",
              "value": {"stringValue": tree.get("type", "")}}]
    for key, akey in (("node", "host.name"), ("label", "mt.label"),
                      ("error", "mt.error")):
        if tree.get(key):
            attrs.append({"key": akey,
                          "value": {"stringValue": str(tree[key])}})
    if tree.get("status"):
        attrs.append({"key": "http.status_code",
                      "value": {"intValue": int(tree["status"])}})
    if tree.get("gating"):
        attrs.append({"key": "mt.gating",
                      "value": {"stringValue": str(tree["gating"])}})
    start = tree.get("startNs", 0)
    span = {
        "traceId": trace_id,
        "spanId": _otlp_span_id(tree.get("spanID", "")),
        "name": tree.get("name", ""),
        "kind": 2 if tree.get("type") == "http" else 1,
        "startTimeUnixNano": str(start),
        "endTimeUnixNano": str(start + tree.get("durationNs", 0)),
        "attributes": attrs,
        "status": {"code": 2 if tree.get("error") else 0},
    }
    parent = tree.get("parentID", "")
    if parent:
        span["parentSpanId"] = _otlp_span_id(parent)
    out.append(span)
    for c in tree.get("children", ()):
        _otlp_span(c, trace_id, out)


def to_otlp(trees: list[dict], node: str = "") -> dict:
    """Assembled trees → one OTLP/JSON ExportTraceServiceRequest-shaped
    document (resourceSpans → scopeSpans → spans)."""
    spans: list[dict] = []
    for tree in trees:
        _otlp_span(tree, _otlp_trace_id(tree.get("requestID", "")),
                   spans)
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "minio-tpu"}},
            {"key": "host.name", "value": {"stringValue": node}},
        ]},
        "scopeSpans": [{
            "scope": {"name": "minio_tpu.tracetree", "version": "1"},
            "spans": spans,
        }],
    }]}


def export_trees(srv, trees: list[dict]) -> int:
    """Push one OTLP document per tree through every ``logger``-type
    egress target (store-and-forward, breaker-guarded — the audit
    pipeline's delivery engine).  Returns documents handed off."""
    egress = getattr(srv, "egress", None)
    targets = [t for t in (egress.targets() if egress else ())
               if t.target_type == "logger"]
    if not targets or not trees:
        return 0
    node = getattr(srv, "node_name", "")
    n = 0
    for tree in trees:
        doc = to_otlp([tree], node=node)
        doc["time"] = time.time()        # queue-store replay ordering
        for t in targets:
            t.send(doc)
        n += 1
    _metrics.inc("mt_trace_tree_export_total", {}, n)
    return n
