"""Structured logging (cmd/logger/logger.go, cmd/logger/message/log,
cmd/consolelogger.go, cmd/logger/logonce.go).

A process-global :class:`Logger` fans structured entries out to targets:

* console (stderr, text or JSON mode);
* an in-memory ring buffer serving the console-UI / ``mc admin logs``
  stream (cmd/consolelogger.go keeps the last N entries and doubles as a
  pub/sub for live log streaming);
* HTTP webhook targets (cmd/logger/target/http) delivering each entry as
  one JSON document.

``log_once`` deduplicates repeated errors per (message, dedup-key), the
way cmd/logger/logonce.go rate-limits identical drive errors.
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Dict, List

from ..utils.pubsub import PubSub

FATAL = "FATAL"
ERROR = "ERROR"
WARNING = "WARNING"
INFO = "INFO"


class HTTPLogTarget:
    """cmd/logger/target/http: entries go into a bounded in-memory queue
    drained by one background sender thread (the reference buffers 10000
    entries in a channel); a full queue or failed POST drops the entry —
    log/audit delivery must never add latency to the request path."""

    QUEUE_SIZE = 10000

    def __init__(self, endpoint: str, auth_token: str = "",
                 timeout: float = 3.0, sync: bool = False):
        self.endpoint = endpoint
        self.auth_token = auth_token
        self.timeout = timeout
        self.dropped = 0
        self._sync = sync            # tests: deliver inline
        self._q: "queue.Queue[Dict[str, Any]]" = queue.Queue(
            self.QUEUE_SIZE)
        self._worker: threading.Thread | None = None

    def _post(self, entry: Dict[str, Any]) -> None:
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(entry).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": self.auth_token}
                        if self.auth_token else {})})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()

    def _drain(self) -> None:
        while True:
            entry = self._q.get()
            try:
                self._post(entry)
            except Exception:   # noqa: BLE001 — drop, never propagate
                self.dropped += 1

    def send(self, entry: Dict[str, Any]) -> None:
        if self._sync:
            self._post(entry)
            return
        if self._worker is None:
            self._worker = threading.Thread(target=self._drain,
                                            daemon=True)
            self._worker.start()
        try:
            self._q.put_nowait(entry)
        except queue.Full:
            self.dropped += 1

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort wait for the queue to empty (tests/shutdown)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while not self._q.empty() and _time.monotonic() < deadline:
            _time.sleep(0.01)
        _time.sleep(0.05)   # let the in-flight POST (already dequeued)
        # finish; flush is best-effort by contract


class Logger:
    def __init__(self, node_name: str = "", ring_size: int = 1000,
                 json_console: bool = False, quiet: bool = False):
        self.node_name = node_name
        self.json_console = json_console
        self.quiet = quiet
        self.ring: deque = deque(maxlen=ring_size)
        self.pubsub = PubSub(max_queue=2000)   # live `mc admin logs` stream
        self.targets: List[HTTPLogTarget] = []
        self._once: Dict[str, float] = {}
        self._mu = threading.Lock()

    # -- emit ----------------------------------------------------------

    def _entry(self, level: str, message: str,
               source: str = "", **kv) -> Dict[str, Any]:
        return {
            "level": level,
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "node": self.node_name,
            "source": source,
            "message": message,
            **({"kv": kv} if kv else {}),
        }

    def log(self, level: str, message: str, source: str = "", **kv) -> None:
        entry = self._entry(level, message, source, **kv)
        with self._mu:
            self.ring.append(entry)
        self.pubsub.publish(entry)
        if not self.quiet:
            if self.json_console:
                print(json.dumps(entry), file=sys.stderr)
            else:
                print(f"{entry['time']} {level}: {message}",
                      file=sys.stderr)
        for t in list(self.targets):
            try:
                t.send(entry)
            except Exception:       # noqa: BLE001 — logging never throws
                pass

    def info(self, message: str, **kv) -> None:
        self.log(INFO, message, **kv)

    def error(self, message: str, **kv) -> None:
        self.log(ERROR, message, **kv)

    def warning(self, message: str, **kv) -> None:
        self.log(WARNING, message, **kv)

    def log_once(self, level: str, message: str, dedup_key: str = "",
                 interval_s: float = 30.0, **kv) -> bool:
        """Emit unless the same (key) fired within interval_s
        (cmd/logger/logonce.go).  Returns True when emitted."""
        key = dedup_key or message
        now = time.monotonic()
        with self._mu:
            last = self._once.get(key, 0.0)
            if now - last < interval_s:
                return False
            self._once[key] = now
        self.log(level, message, **kv)
        return True

    # -- read back -----------------------------------------------------

    def recent(self, n: int = 100) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self.ring)[-n:]


GLOBAL = Logger(quiet=True)
