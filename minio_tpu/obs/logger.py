"""Structured logging (cmd/logger/logger.go, cmd/logger/message/log,
cmd/consolelogger.go, cmd/logger/logonce.go).

A process-global :class:`Logger` fans structured entries out to targets:

* console (stderr, text or JSON mode);
* an in-memory ring buffer serving the console-UI / ``mc admin logs``
  stream (cmd/consolelogger.go keeps the last N entries and doubles as a
  pub/sub for live log streaming);
* HTTP webhook targets (cmd/logger/target/http) delivering each entry as
  one JSON document over the store-and-forward egress engine
  (obs/egress.py): bounded queue, optional disk store, backoff, and the
  online/offline/probing state machine.

``log_once`` deduplicates repeated errors per (message, dedup-key), the
way cmd/logger/logonce.go rate-limits identical drive errors — and like
logonce.go it FORGETS: expired dedup entries are swept so the map stays
bounded no matter how many distinct keys a long-lived process sees.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Dict, List

from ..utils.pubsub import PubSub
from .egress import DeliveryTarget

FATAL = "FATAL"
ERROR = "ERROR"
WARNING = "WARNING"
INFO = "INFO"


class HTTPLogTarget(DeliveryTarget):
    """cmd/logger/target/http on the shared egress engine: entries ride
    a bounded in-memory queue drained by one background sender; failed
    or offline-time entries spill to the optional disk store and replay
    on recovery — log/audit delivery must never add latency to the
    request path."""

    def __init__(self, endpoint: str, auth_token: str = "",
                 timeout: float = 3.0, sync: bool = False,
                 target_type: str = "logger", **engine):
        super().__init__(target_type, endpoint, sync=sync, **engine)
        self.endpoint = endpoint
        self.auth_token = auth_token
        self.timeout = timeout

    def _deliver(self, entry: Dict[str, Any]) -> None:
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(entry).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": self.auth_token}
                        if self.auth_token else {})})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()


class Logger:
    # log_once dedup map bound: a sweep runs when the map outgrows this
    # (or on the periodic timer), dropping entries whose interval has
    # already lapsed — they would emit again anyway, so forgetting them
    # is semantically free (cmd/logger/logonce.go periodic forget)
    ONCE_MAX = 1024
    ONCE_SWEEP_S = 300.0

    def __init__(self, node_name: str = "", ring_size: int = 1000,
                 json_console: bool = False, quiet: bool = False):
        self.node_name = node_name
        self.json_console = json_console
        self.quiet = quiet
        self.ring: deque = deque(maxlen=ring_size)
        self.pubsub = PubSub(max_queue=2000)   # live `mc admin logs` stream
        self.targets: List[HTTPLogTarget] = []
        # dedup key -> (last emit, interval); injectable clock so tests
        # drive expiry without sleeping
        self._once: Dict[str, tuple] = {}
        self._once_sweep_at = 0.0
        self._once_sweep_size = self.ONCE_MAX
        self._clock = time.monotonic
        self._mu = threading.Lock()

    # -- emit ----------------------------------------------------------

    def _entry(self, level: str, message: str,
               source: str = "", **kv) -> Dict[str, Any]:
        return {
            "level": level,
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "node": self.node_name,
            "source": source,
            "message": message,
            **({"kv": kv} if kv else {}),
        }

    def log(self, level: str, message: str, source: str = "", **kv) -> None:
        entry = self._entry(level, message, source, **kv)
        with self._mu:
            self.ring.append(entry)
        self.pubsub.publish(entry)
        if not self.quiet:
            if self.json_console:
                print(json.dumps(entry), file=sys.stderr)
            else:
                print(f"{entry['time']} {level}: {message}",
                      file=sys.stderr)
        for t in list(self.targets):
            try:
                t.send(entry)
            except Exception:       # noqa: BLE001 — logging never throws
                pass

    def info(self, message: str, **kv) -> None:
        self.log(INFO, message, **kv)

    def error(self, message: str, **kv) -> None:
        self.log(ERROR, message, **kv)

    def warning(self, message: str, **kv) -> None:
        self.log(WARNING, message, **kv)

    def log_once(self, level: str, message: str, dedup_key: str = "",
                 interval_s: float = 30.0, **kv) -> bool:
        """Emit unless the same (key) fired within interval_s
        (cmd/logger/logonce.go).  Returns True when emitted."""
        key = dedup_key or message
        now = self._clock()
        with self._mu:
            ent = self._once.get(key)
            if ent is not None and now - ent[0] < ent[1]:
                return False
            self._once[key] = (now, interval_s)
            self._sweep_once(now)
        self.log(level, message, **kv)
        return True

    def _sweep_once(self, now: float) -> None:
        """Forget expired dedup entries (size- or time-triggered) so
        ``_once`` never grows one entry per distinct key forever.
        Caller holds ``_mu``.  The size trigger re-arms at 2x whatever
        survived the sweep: a map of mostly-LIVE keys cannot re-fire an
        O(n) rebuild on every insert — the map stays within 2x the live
        set, amortized O(1) per call."""
        if len(self._once) < self._once_sweep_size \
                and now < self._once_sweep_at:
            return
        self._once = {k: (t, iv) for k, (t, iv) in self._once.items()
                      if now - t < iv}
        self._once_sweep_at = now + self.ONCE_SWEEP_S
        self._once_sweep_size = max(self.ONCE_MAX, 2 * len(self._once))

    # -- read back -----------------------------------------------------

    def recent(self, n: int = 100) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self.ring)[-n:]


GLOBAL = Logger(quiet=True)
