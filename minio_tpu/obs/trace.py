"""Request tracing (pkg/trace/trace.go:26-40, cmd/http-tracer.go:164).

Every S3/admin request is summarised as a ``trace.Info``-shaped dict and
published to the global :data:`HTTP_TRACE` pub/sub.  ``mc admin trace``
equivalents subscribe via the admin ``trace`` route and stream JSON lines;
on a cluster the admin node aggregates peer streams over the internode RPC
(peerRESTMethodTrace, cmd/peer-rest-common.go:54).

Beyond the HTTP frontend, the deep-tracing plane publishes SUBSYSTEM
spans to the same hub (``mc admin trace -a`` analog, trace types per
pkg/trace.Type):

  ``storage``    per-drive-call spans (storage/xl_storage.py + remote.py)
  ``internode``  RPC client/server spans (parallel/rpc.py)
  ``tpu``        erasure-kernel spans: encode/decode/matmul/fused-hash
                 with shard geometry and bytes (ops/codec.py + friends)
  ``scanner``    data-crawler per-bucket spans (background/crawler.py)
  ``healing``    heal-sweep / MRF per-object spans (background/heal.py)
  ``replication``  per-object replication spans
                 (background/replication.py)

Every span carries the originating request ID (Dapper-style correlation,
Sigelman et al. 2010): the S3 frontend mints one per request into a
contextvar; internode RPC forwards it in an ``X-Request-ID`` header so
spans emitted on a *peer* node still name the frontend request.

Publishing is skipped entirely when nobody is subscribed, mirroring the
reference's ``globalHTTPTrace.NumSubscribers() > 0`` guard — the hot
path pays a single predicate (:func:`active`), no dict construction.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from typing import Any, Dict

from ..utils.pubsub import PubSub

# global trace hub (reference: globalHTTPTrace)
HTTP_TRACE = PubSub(max_queue=4000)

# subsystem trace types (pkg/trace.Type); "http" stays the default so
# existing `admin trace` consumers see no change without ?type=.
# scanner/healing/replication are the background planes (pkg/trace
# TraceScanner/TraceHealing/TraceReplication) — per-object spans from
# the autonomous loops, same zero-subscriber idle contract as the rest.
TRACE_TYPES = ("http", "storage", "internode", "tpu",
               "scanner", "healing", "replication", "watchdog")

# headers never to leak into traces (cmd/http-tracer.go redacts these;
# the reference strips ALL SSE-C key material — including the key MD5 —
# and browser cookies)
_REDACTED_HEADERS = {"authorization", "x-amz-security-token",
                     "cookie", "set-cookie",
                     "x-amz-server-side-encryption-customer-key",
                     "x-amz-server-side-encryption-customer-key-md5",
                     "x-amz-copy-source-server-side-encryption-customer-key",
                     "x-amz-copy-source-server-side-encryption-customer"
                     "-key-md5"}

# the request ID minted at the S3 frontend, visible to every subsystem
# call made on behalf of that request (threads started per-request see
# it via explicit propagation: erasure fan-out and RPC header)
_REQUEST_ID: contextvars.ContextVar[str] = contextvars.ContextVar(
    "mt_request_id", default="")

# this process's node name for span attribution (set once at server
# boot; cluster nodes use their node_id).  Process-global by design —
# one process IS one node in every real deployment, exactly like the
# reference's globalHTTPTrace; embedded multi-server tests that share a
# process disambiguate spans by their detail payload (drive path /
# endpoint), not nodeName.
NODE_NAME = ""


def set_node_name(name: str) -> None:
    global NODE_NAME
    NODE_NAME = name


def set_request_id(request_id: str) -> None:
    _REQUEST_ID.set(request_id)


def get_request_id() -> str:
    return _REQUEST_ID.get()


# -- causal span trees --------------------------------------------------------
#
# Beyond flat request-ID correlation, every span carries a span_id and
# a parent_id so a request's drive ops, kernel dispatches, batcher
# waits, and peer-side twins assemble into ONE tree (Dapper's causal
# model, not just its correlation model).  The parent rides beside the
# request ID: explicitly into fan-out pool threads and writer-plane
# queues (contextvars do not cross threads), and over the internode
# wire in an X-Span-Parent header beside X-Request-ID.  The request
# root's span id IS the request id, so a tree is addressable by the
# same key as its flight-recorder row.
_SPAN_PARENT: contextvars.ContextVar[str] = contextvars.ContextVar(
    "mt_span_parent", default="")

# span-id mint: a per-process prefix + counter — two allocation-free
# int ops per id, unique across the nodes of a test cluster sharing
# one process (the NODE_NAME caveat does not bite: ids, not names)
_SID_PREFIX = f"{(os.getpid() ^ time.time_ns()) & 0xffffffff:08x}"
_SID_COUNTER = itertools.count(1)


def new_span_id() -> str:
    return f"{_SID_PREFIX}-{next(_SID_COUNTER):x}"


def set_span_parent(span_id: str) -> None:
    _SPAN_PARENT.set(span_id)


def get_span_parent() -> str:
    return _SPAN_PARENT.get()


def push_span_parent(span_id: str):
    """Make ``span_id`` the parent for spans emitted in this context;
    returns a token for :func:`pop_span_parent` (the internode client
    leg brackets its roundtrip with this so peer-side spans nest under
    the client-side internode span)."""
    return _SPAN_PARENT.set(span_id)


def pop_span_parent(token) -> None:
    _SPAN_PARENT.reset(token)


# Always-on causal span ring: compact tuples, appended even with zero
# subscribers (the flight-recorder discipline — the evidence for a
# breach-window request must already be on hand when the forensic
# trigger fires).  Slot layout:
#   (start_ns, request_id, span_id, parent_id, type, name, dur_ns,
#    error, label, extra)
# ``label`` is the one attribution string worth paying for idle (drive
# endpoint / peer endpoint / plane); ``extra`` is None except for
# quorum-gating spans, which carry their compact gating tuple.
SPAN_RING_CAP = 16384

_R_START, _R_RID, _R_SID, _R_PARENT, _R_TYPE, _R_NAME, _R_DUR, \
    _R_ERR, _R_LABEL, _R_EXTRA = range(10)


class _SpanRing:
    """Fixed-slot overwrite ring (the lastminute lock-cheap model):
    appends are a list store + one int add under the GIL; a racing
    pair of appends can overwrite one slot, which minute-granularity
    tree assembly tolerates — span capture must never serialize the
    drive hot path on an observability lock."""

    __slots__ = ("_buf", "_cap", "_n")

    def __init__(self, cap: int):
        self._buf: list = [None] * cap
        self._cap = cap
        self._n = 0

    def append(self, rec: tuple) -> None:
        n = self._n
        self._buf[n % self._cap] = rec
        self._n = n + 1

    def snapshot(self) -> list:
        """Live records, oldest first (query time only)."""
        n = self._n
        if n <= self._cap:
            out = self._buf[:n]
        else:
            i = n % self._cap
            out = self._buf[i:] + self._buf[:i]
        return [r for r in out if r is not None]

    def appended_total(self) -> int:
        return self._n

    def clear(self) -> None:
        self._buf = [None] * self._cap
        self._n = 0


SPANS = _SpanRing(SPAN_RING_CAP)


def ring_append(rid: str, span_id: str, parent_id: str, trace_type: str,
                name: str, start_ns: int, dur_ns: int, error: str = "",
                label: str = "", extra=None) -> None:
    """Append one compact causal-span tuple (the idle-path emit: span
    dict construction stays behind :func:`active`)."""
    SPANS.append((start_ns, rid, span_id, parent_id, trace_type, name,
                  dur_ns, error, label, extra))


# deep-span activation bookkeeping: a default (http-only) `admin trace`
# stream must not light up subsystem-span construction — locally or on
# peers — just to have the filter drop everything.  Consumers that only
# want http records register an opt-out; peer ring polls declare their
# wanted types and only lease deep capture when they include one.
_DEEP_OPT_OUT = 0
_deep_mu = threading.Lock()
_deep_ring_until = 0.0

DEEP_RING_LEASE_S = 10.0


@contextlib.contextmanager
def http_only_consumer():
    """Mark one hub subscriber as http-only for its lifetime: it keeps
    http traces flowing (PubSub.active) without paying for subsystem
    spans it would filter out anyway."""
    global _DEEP_OPT_OUT
    with _deep_mu:
        _DEEP_OPT_OUT += 1
    try:
        yield
    finally:
        with _deep_mu:
            _DEEP_OPT_OUT -= 1


def lease_deep_ring(seconds: float = DEEP_RING_LEASE_S) -> None:
    """A peer poll wants subsystem spans: capture them for a while
    (the trace ring's lease pattern, utils/pubsub.py since())."""
    global _deep_ring_until
    _deep_ring_until = time.monotonic() + seconds


def active() -> bool:
    """Single-predicate guard for SUBSYSTEM span emission: True only
    when a consumer that wants deep spans exists — a hub subscriber
    that did not opt out, or a recent peer poll that asked for deep
    types.  HTTP traces gate on PubSub.active instead (any consumer)."""
    if HTTP_TRACE._n_subs > _DEEP_OPT_OUT:
        return True
    until = _deep_ring_until
    if not until:
        return False
    return time.monotonic() < until


# query parameters never to leak into traces/audit: presigned-URL
# credentials (SigV4 X-Amz-Signature/X-Amz-Credential + the session
# token, SigV2 Signature) are replayable until they expire — the same
# contract as the header redaction above, applied to the query string
_REDACTED_QUERY = {"x-amz-signature", "x-amz-credential",
                   "x-amz-security-token", "signature"}


def redact_headers(headers: Dict[str, str]) -> Dict[str, str]:
    return {k: ("*REDACTED*" if k.lower() in _REDACTED_HEADERS else v)
            for k, v in headers.items()}


def redact_query(query: Dict[str, str]) -> Dict[str, str]:
    return {k: ("*REDACTED*" if k.lower() in _REDACTED_QUERY else v)
            for k, v in query.items()}


def redact_query_string(raw: str) -> str:
    """``k=v&k=v`` form of :func:`redact_query` (trace rawQuery)."""
    if not raw:
        return raw
    out = []
    for kv in raw.split("&"):
        k, sep, v = kv.partition("=")
        if sep and k.lower() in _REDACTED_QUERY:
            v = "*REDACTED*"
        out.append(f"{k}{sep}{v}")
    return "&".join(out)


def make_trace(node_name: str, func_name: str, *, method: str, path: str,
               raw_query: str, client: str, req_headers: Dict[str, str],
               status_code: int, resp_headers: Dict[str, str],
               input_bytes: int, output_bytes: int,
               start_ns: int, ttfb_ns: int, duration_ns: int,
               trace_type: str = "http", error: str = "",
               request_id: str = "",
               detail: Dict[str, Any] | None = None) -> Dict[str, Any]:
    """Build a trace.Info-shaped record (pkg/trace/trace.go:26-40).
    ``detail`` (when present) lands under the ``detail`` key — the
    request X-ray publishes its per-stage timeline there
    (``detail.stages``, obs/stages.py)."""
    return {
        **({"detail": detail} if detail else {}),
        "type": trace_type,
        "nodeName": node_name,
        "funcName": func_name,
        "time": start_ns,
        "requestID": request_id or get_request_id(),
        "reqInfo": {
            "time": start_ns,
            "method": method,
            "path": path,
            "rawQuery": redact_query_string(raw_query),
            "client": client,
            "headers": redact_headers(req_headers),
        },
        "respInfo": {
            "time": start_ns + duration_ns,
            "statusCode": status_code,
            "headers": dict(resp_headers),
        },
        "callStats": {
            "inputBytes": input_bytes,
            "outputBytes": output_bytes,
            "latency_ns": duration_ns,
            "timeToFirstByte_ns": ttfb_ns,
        },
        **({"error": error} if error else {}),
    }


def make_span(trace_type: str, func_name: str, *, start_ns: int,
              duration_ns: int, input_bytes: int = 0,
              output_bytes: int = 0, error: str = "",
              detail: Dict[str, Any] | None = None,
              span_id: str = "",
              parent_id: str | None = None,
              _ring: bool = True) -> Dict[str, Any]:
    """Subsystem span (the ``mc admin trace -a`` record shape):
    smaller than an HTTP trace.Info but keyed the same so one consumer
    handles both.  ``detail`` lands under the trace-type key, e.g.
    ``{"storage": {"drive": ..., "volume": ..., "path": ...}}``.

    Every span is a causal-tree node: ``spanID`` (minted here unless
    the caller pre-minted one to propagate, e.g. the internode client
    leg) and ``parentID`` (the contextvar parent unless overridden).
    The span is also appended to the always-on causal ring, so active
    consumers and the ring see the same ids."""
    rid = get_request_id()
    sid = span_id or new_span_id()
    par = get_span_parent() if parent_id is None else parent_id
    if rid and _ring:
        label = ""
        if detail:
            label = str(detail.get("drive") or detail.get("endpoint")
                        or "")
        SPANS.append((start_ns, rid, sid, par, trace_type, func_name,
                      duration_ns, error, label, None))
    return {
        "type": trace_type,
        "nodeName": NODE_NAME,
        "funcName": func_name,
        "time": start_ns,
        "requestID": rid,
        "spanID": sid,
        "parentID": par,
        "callStats": {
            "inputBytes": input_bytes,
            "outputBytes": output_bytes,
            "latency_ns": duration_ns,
        },
        **({trace_type: detail} if detail else {}),
        **({"error": error} if error else {}),
    }


def publish(info: Dict[str, Any]) -> None:
    HTTP_TRACE.publish(info)


def publish_span(span: Dict[str, Any]) -> None:
    HTTP_TRACE.publish(span)


def subscribers() -> int:
    return HTTP_TRACE.num_subscribers


def now_ns() -> int:
    return time.time_ns()
