"""HTTP request tracing (pkg/trace/trace.go:26-40, cmd/http-tracer.go:164).

Every S3/admin request is summarised as a ``trace.Info``-shaped dict and
published to the global :data:`HTTP_TRACE` pub/sub.  ``mc admin trace``
equivalents subscribe via the admin ``trace`` route and stream JSON lines;
on a cluster the admin node aggregates peer streams over the internode RPC
(peerRESTMethodTrace, cmd/peer-rest-common.go:54).

Publishing is skipped entirely when nobody is subscribed, mirroring the
reference's ``globalHTTPTrace.NumSubscribers() > 0`` guard.
"""

from __future__ import annotations

import time
from typing import Any, Dict

from ..utils.pubsub import PubSub

# global trace hub (reference: globalHTTPTrace)
HTTP_TRACE = PubSub(max_queue=4000)

# headers never to leak into traces (cmd/http-tracer.go redacts these)
_REDACTED_HEADERS = {"authorization", "x-amz-security-token",
                     "x-amz-server-side-encryption-customer-key",
                     "x-amz-copy-source-server-side-encryption-customer-key"}


def redact_headers(headers: Dict[str, str]) -> Dict[str, str]:
    return {k: ("*REDACTED*" if k.lower() in _REDACTED_HEADERS else v)
            for k, v in headers.items()}


def make_trace(node_name: str, func_name: str, *, method: str, path: str,
               raw_query: str, client: str, req_headers: Dict[str, str],
               status_code: int, resp_headers: Dict[str, str],
               input_bytes: int, output_bytes: int,
               start_ns: int, ttfb_ns: int, duration_ns: int,
               trace_type: str = "http", error: str = "") -> Dict[str, Any]:
    """Build a trace.Info-shaped record (pkg/trace/trace.go:26-40)."""
    return {
        "type": trace_type,
        "nodeName": node_name,
        "funcName": func_name,
        "time": start_ns,
        "reqInfo": {
            "time": start_ns,
            "method": method,
            "path": path,
            "rawQuery": raw_query,
            "client": client,
            "headers": redact_headers(req_headers),
        },
        "respInfo": {
            "time": start_ns + duration_ns,
            "statusCode": status_code,
            "headers": dict(resp_headers),
        },
        "callStats": {
            "inputBytes": input_bytes,
            "outputBytes": output_bytes,
            "latency_ns": duration_ns,
            "timeToFirstByte_ns": ttfb_ns,
        },
        **({"error": error} if error else {}),
    }


def publish(info: Dict[str, Any]) -> None:
    HTTP_TRACE.publish(info)


def subscribers() -> int:
    return HTTP_TRACE.num_subscribers


def now_ns() -> int:
    return time.time_ns()
