"""Health/hardware diagnostics (cmd/healthinfo.go, cmd/admin-handlers.go:1301
HealthInfoHandler; drive probes mirror peerRESTMethodDriveInfo).

Collects OS, CPU, memory, per-drive capacity/latency and accelerator info
into one JSON document for `mc admin obd`-style support bundles.
"""

from __future__ import annotations

import os
import platform
import shutil
import time
from typing import Any, Dict, List


def _meminfo() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if parts[0].rstrip(":") in ("MemTotal", "MemFree",
                                            "MemAvailable"):
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024
    except OSError:
        pass
    return out


def _loadavg() -> List[float]:
    try:
        return list(os.getloadavg())
    except OSError:
        return []


def drive_perf(path: str, probe_bytes: int = 1 << 20) -> Dict[str, Any]:
    """Tiny write+read latency/throughput probe on one drive root
    (peerRESTMethodDriveInfo / pkg/disk perf analog)."""
    fn = os.path.join(path, ".healthprobe.tmp")
    blob = os.urandom(probe_bytes)
    t0 = time.perf_counter()
    with open(fn, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    t1 = time.perf_counter()
    with open(fn, "rb") as f:
        f.read()
    t2 = time.perf_counter()
    os.remove(fn)
    return {
        "path": path,
        "writeThroughputBps": int(probe_bytes / max(t1 - t0, 1e-9)),
        "readThroughputBps": int(probe_bytes / max(t2 - t1, 1e-9)),
        "writeLatencyMs": round((t1 - t0) * 1000, 3),
    }


def drive_usage(path: str) -> Dict[str, Any]:
    try:
        u = shutil.disk_usage(path)
        return {"path": path, "totalBytes": u.total, "usedBytes": u.used,
                "freeBytes": u.free}
    except OSError as e:
        return {"path": path, "error": str(e)}


def accelerators() -> List[Dict[str, Any]]:
    """TPU/accelerator inventory — the build's analog of SMART/NVMe info."""
    try:
        import jax
        return [{"id": d.id, "platform": d.platform,
                 "kind": getattr(d, "device_kind", "")}
                for d in jax.devices()]
    except Exception as e:  # noqa: BLE001 — diagnostics must never fail
        return [{"error": str(e)}]


def collect(drive_paths: List[str] | None = None,
            perf: bool = False) -> Dict[str, Any]:
    info: Dict[str, Any] = {
        "version": "1",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "os": {
            "platform": platform.platform(),
            "kernel": platform.release(),
            "python": platform.python_version(),
        },
        "cpu": {
            "count": os.cpu_count(),
            "loadavg": _loadavg(),
        },
        "mem": _meminfo(),
        "accelerators": accelerators(),
    }
    if drive_paths:
        info["drives"] = [drive_usage(p) for p in drive_paths]
        if perf:
            info["drivePerf"] = []
            for p in drive_paths:
                try:
                    info["drivePerf"].append(drive_perf(p))
                except OSError as e:
                    info["drivePerf"].append({"path": p, "error": str(e)})
    return info
