"""Health/hardware diagnostics (cmd/healthinfo.go, cmd/admin-handlers.go:1301
HealthInfoHandler; drive probes mirror peerRESTMethodDriveInfo).

Collects OS, CPU, memory, per-drive capacity/latency and accelerator info
into one JSON document for `mc admin obd`-style support bundles.
"""

from __future__ import annotations

import os
import platform
import shutil
import time
from typing import Any, Dict, List


def _meminfo() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if parts[0].rstrip(":") in ("MemTotal", "MemFree",
                                            "MemAvailable"):
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024
    except OSError:
        pass
    return out


def _loadavg() -> List[float]:
    try:
        return list(os.getloadavg())
    except OSError:
        return []


def drive_perf(path: str, probe_bytes: int = 1 << 20) -> Dict[str, Any]:
    """Tiny write+read latency/throughput probe on one drive root
    (peerRESTMethodDriveInfo / pkg/disk perf analog)."""
    fn = os.path.join(path, ".healthprobe.tmp")
    blob = os.urandom(probe_bytes)
    t0 = time.perf_counter()
    with open(fn, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    t1 = time.perf_counter()
    with open(fn, "rb") as f:
        f.read()
    t2 = time.perf_counter()
    os.remove(fn)
    return {
        "path": path,
        "writeThroughputBps": int(probe_bytes / max(t1 - t0, 1e-9)),
        "readThroughputBps": int(probe_bytes / max(t2 - t1, 1e-9)),
        "writeLatencyMs": round((t1 - t0) * 1000, 3),
    }


def drive_usage(path: str) -> Dict[str, Any]:
    try:
        u = shutil.disk_usage(path)
        return {"path": path, "totalBytes": u.total, "usedBytes": u.used,
                "freeBytes": u.free}
    except OSError as e:
        return {"path": path, "error": str(e)}


def _read(path: str) -> str:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return ""


def smart_info(drive_path: str) -> Dict[str, Any]:
    """Per-drive hardware identity + IO counters (pkg/smart/smart.go
    analog).  The reference issues raw NVMe/SCSI ioctls; inside VMs and
    containers those fail on virtio disks, so this reads the same
    facts the kernel already exports: sysfs identity (model, serial,
    rotational, size) and /proc/diskstats IO/error-adjacent counters.
    Degrades to partial info exactly like the reference does when the
    passthrough is unsupported."""
    out: Dict[str, Any] = {"path": drive_path}
    try:
        st = os.stat(drive_path)
        major, minor = os.major(st.st_dev), os.minor(st.st_dev)
    except OSError:
        return out
    out["device_major_minor"] = f"{major}:{minor}"
    # resolve the owning block device via sysfs dev numbers
    base = None
    try:
        for name in os.listdir("/sys/block"):
            if _read(f"/sys/block/{name}/dev") == f"{major}:{minor}":
                base = name
                break
            # partition of this block device?
            pdir = f"/sys/block/{name}/{name}"
            for sub in os.listdir(f"/sys/block/{name}"):
                if sub.startswith(name) and _read(
                        f"/sys/block/{name}/{sub}/dev") \
                        == f"{major}:{minor}":
                    base = name
                    break
            if base:
                break
    except OSError:
        pass
    if base is None:
        return out
    sys = f"/sys/block/{base}"
    out["device"] = f"/dev/{base}"
    out["model"] = _read(f"{sys}/device/model")
    out["serial"] = _read(f"{sys}/device/serial") or \
        _read(f"{sys}/device/wwid")
    out["firmware"] = _read(f"{sys}/device/firmware_rev") or \
        _read(f"{sys}/device/rev")
    out["rotational"] = _read(f"{sys}/queue/rotational") == "1"
    try:
        out["size_bytes"] = int(_read(f"{sys}/size") or 0) * 512
    except ValueError:
        pass
    # IO counters (reads/writes completed, sectors, ms, in-flight) —
    # the health signal SMART attributes proxy for
    stats = _read(f"{sys}/stat").split()
    if len(stats) >= 11:
        out["io"] = {
            "reads_completed": int(stats[0]),
            "read_sectors": int(stats[2]),
            "read_ms": int(stats[3]),
            "writes_completed": int(stats[4]),
            "write_sectors": int(stats[6]),
            "write_ms": int(stats[7]),
            "in_flight": int(stats[8]),
            "io_ms": int(stats[9]),
        }
    return out


def accelerators() -> List[Dict[str, Any]]:
    """TPU/accelerator inventory — the build's analog of SMART/NVMe info."""
    try:
        import jax
        return [{"id": d.id, "platform": d.platform,
                 "kind": getattr(d, "device_kind", "")}
                for d in jax.devices()]
    except Exception as e:  # noqa: BLE001 — diagnostics must never fail
        return [{"error": str(e)}]


def collect(drive_paths: List[str] | None = None,
            perf: bool = False) -> Dict[str, Any]:
    info: Dict[str, Any] = {
        "version": "1",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "os": {
            "platform": platform.platform(),
            "kernel": platform.release(),
            "python": platform.python_version(),
        },
        "cpu": {
            "count": os.cpu_count(),
            "loadavg": _loadavg(),
        },
        "mem": _meminfo(),
        "accelerators": accelerators(),
    }
    if drive_paths:
        info["drives"] = [drive_usage(p) for p in drive_paths]
        info["smart"] = [smart_info(p) for p in drive_paths]
        if perf:
            info["drivePerf"] = []
            for p in drive_paths:
                try:
                    info["drivePerf"].append(drive_perf(p))
                except OSError as e:
                    info["drivePerf"].append({"path": p, "error": str(e)})
    return info
