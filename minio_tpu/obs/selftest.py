"""Cluster self-measurement probes (cmd/admin-handlers.go
SpeedtestHandler / DriveSpeedtestHandler, cmd/speedtest.go autotune
loop).

Three probes, each runnable on one node and fanned to every peer by the
admin ``speedtest*`` routes so one call measures the whole cluster:

* :func:`drive_speedtest`   — per-drive sequential write/read against
  every local drive root (madmin DriveSpeedtest role; buffered I/O, so
  read numbers on a warm cache read as memory bandwidth — the WRITE leg
  is the honest drive figure, same caveat the reference documents for
  filesystems without O_DIRECT).
* :func:`object_speedtest`  — end-to-end PUT/GET through the object
  layer with concurrency autotune: ramp workers geometrically while
  throughput still improves, keep the best round (the reference's
  speedTestOnce doubling loop).
* :func:`tpu_codec_speedtest` — erasure-codec encode/reconstruct rates
  via ops/codec.py's normal dispatch paths (Erasure.speedtest), so the
  BENCH trajectory numbers become an admin API instead of a hand-run
  script.

:func:`bench_record` folds per-node results into the same
``{metric, value, unit, detail}`` shape as the repo's ``BENCH_*.json``
records, so bench.py output and the admin API report comparable
numbers.
"""

from __future__ import annotations

import os
import threading
import time

GiB = 1 << 30

# autotune knobs (cmd/speedtest.go: double while the uplift clears the
# noise floor, stop at the first non-improving round)
AUTOTUNE_MAX_CONCURRENCY = 32
AUTOTUNE_MIN_UPLIFT = 0.025


def local_drive_paths(layer) -> list:
    """Local drive roots across every topology shape (pools/sets/flat);
    remote drives have no ``root`` and are measured by their owning
    node — shared by healthinfo and the drive speedtest."""
    paths = []

    def walk(node):
        for pool in getattr(node, "pools", []) or []:
            walk(pool)
        for s in getattr(node, "sets", []) or []:
            walk(s)
        for d in getattr(node, "disks", []) or []:
            root = getattr(d, "root", None)
            if root:
                paths.append(root)
        root = getattr(node, "root", None)      # FS backend / bare drive
        if root and not getattr(node, "disks", None):
            paths.append(root)

    walk(layer)
    return paths


def drive_speedtest(paths: list, file_size: int = 4 << 20,
                    block_size: int = 1 << 20) -> list[dict]:
    """Sequential write+read probe per drive root.  The probe file
    lives under the drive's system dir and is always removed; write is
    fsync'd once at the end so the figure includes the flush the data
    plane pays on commit."""
    from ..storage.xl_storage import SYS_DIR
    block = os.urandom(min(block_size, file_size))
    out = []
    for root in paths:
        probe_dir = os.path.join(root, SYS_DIR, "speedtest")
        probe = os.path.join(probe_dir, f"probe-{os.getpid()}")
        entry = {"drive": root, "bytes": file_size}
        try:
            os.makedirs(probe_dir, exist_ok=True)
            t0 = time.monotonic()
            written = 0
            with open(probe, "wb") as f:
                while written < file_size:
                    written += f.write(block[:file_size - written])
                f.flush()
                os.fsync(f.fileno())
            entry["writeGiBps"] = round(
                written / max(time.monotonic() - t0, 1e-9) / GiB, 3)
            t0 = time.monotonic()
            got = 0
            with open(probe, "rb") as f:
                while True:
                    c = f.read(block_size)
                    if not c:
                        break
                    got += len(c)
            entry["readGiBps"] = round(
                got / max(time.monotonic() - t0, 1e-9) / GiB, 3)
        except OSError as e:
            entry["error"] = str(e)
        finally:
            try:
                os.unlink(probe)
            except OSError:
                pass
        out.append(entry)
    return out


def _put_get_round(layer, bucket: str, size: int, duration_s: float,
                   concurrency: int) -> dict:
    """One timed round at fixed concurrency: all workers PUT distinct
    objects until the deadline, then GET the written set round-robin
    until the deadline."""
    payload = os.urandom(size)
    written: list[list[str]] = [[] for _ in range(concurrency)]
    errors = [0]

    def put_worker(wi: int):
        i = 0
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            name = f"st-{wi}-{i}"
            try:
                layer.put_object(bucket, name, payload)
                written[wi].append(name)
            except Exception:  # noqa: BLE001 — counted, probe goes on
                errors[0] += 1
            i += 1

    t0 = time.monotonic()
    threads = [threading.Thread(target=put_worker, args=(wi,),
                                daemon=True,
                                name=f"mt-selftest-put-{wi}")
               for wi in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    put_s = max(time.monotonic() - t0, 1e-9)
    put_ops = sum(len(w) for w in written)

    names = [n for w in written for n in w]
    got = [0] * concurrency

    def get_worker(wi: int):
        i = wi
        deadline = time.monotonic() + duration_s
        while names and time.monotonic() < deadline:
            try:
                layer.get_object(bucket, names[i % len(names)])
                got[wi] += 1
            except Exception:  # noqa: BLE001
                errors[0] += 1
            i += concurrency

    t0 = time.monotonic()
    threads = [threading.Thread(target=get_worker, args=(wi,),
                                daemon=True,
                                name=f"mt-selftest-get-{wi}")
               for wi in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    get_s = max(time.monotonic() - t0, 1e-9)
    get_ops = sum(got)
    return {
        "concurrency": concurrency,
        "putGiBps": round(put_ops * size / put_s / GiB, 6),
        "getGiBps": round(get_ops * size / get_s / GiB, 6),
        "putOps": put_ops,
        "getOps": get_ops,
        "errors": errors[0],
        "objectSize": size,
        "durationSeconds": duration_s,
    }


def object_speedtest(layer, size: int = 1 << 20,
                     duration_s: float = 1.0,
                     concurrency: int = 0) -> dict:
    """End-to-end object PUT/GET speedtest against ``layer``.

    ``concurrency`` 0 means autotune: run rounds at 1, 2, 4, ...
    workers while PUT throughput still improves by at least
    ``AUTOTUNE_MIN_UPLIFT`` and report the best round — the plateau
    finder from the reference's speedtest loop.  A fixed concurrency
    runs exactly one round.  The probe bucket and every object are
    deleted before returning."""
    bucket = f"mt-speedtest-{os.urandom(4).hex()}"
    layer.make_bucket(bucket)
    try:
        if concurrency > 0:
            best = _put_get_round(layer, bucket, size, duration_s,
                                  concurrency)
            best["autotuned"] = False
            return best
        best = None
        c = 1
        while c <= AUTOTUNE_MAX_CONCURRENCY:
            r = _put_get_round(layer, bucket, size, duration_s, c)
            if best is not None:
                uplift = (r["putGiBps"] - best["putGiBps"]) \
                    / max(best["putGiBps"], 1e-9)
                if r["putGiBps"] > best["putGiBps"]:
                    best = r
                if uplift < AUTOTUNE_MIN_UPLIFT:
                    break       # plateau: more workers stopped helping
            else:
                best = r
            c *= 2
        best["autotuned"] = True
        return best
    finally:
        _cleanup_bucket(layer, bucket)


def _cleanup_bucket(layer, bucket: str) -> None:
    try:
        out = layer.list_objects(bucket, max_keys=100000)
        for oi in out.objects:
            try:
                layer.delete_object(bucket, oi.name)
            except Exception:  # noqa: BLE001 — probe-object cleanup is
                pass           # best-effort; force-delete follows
        layer.delete_bucket(bucket, force=True)
    except Exception:  # noqa: BLE001 — a leftover probe bucket must
        pass           # never fail the measurement it served


def tpu_codec_speedtest(size: int = 4 << 20, k: int = 4, m: int = 2,
                        block_size: int = 1 << 20,
                        backend: str = "auto") -> dict:
    """Erasure-codec throughput via the production dispatch paths."""
    from ..ops.codec import Erasure
    codec = Erasure(k, m, block_size, backend=backend)
    return codec.speedtest(size=size)


def aggregate(results: list[dict], keys: tuple[str, ...]) -> dict:
    """Sum the per-node GiB/s figures (each node drove its own load, so
    cluster throughput is the sum — same shape as the reference's
    aggregated speedTestResult)."""
    out = {}
    for key in keys:
        out[key] = round(sum(r.get(key) or 0 for r in results
                             if isinstance(r, dict)), 6)
    return out


def bench_record(metric: str, value: float, detail: dict) -> dict:
    """The repo's BENCH_*.json record shape (bench.py result dict) so
    admin-API numbers and bench-harness numbers diff cleanly."""
    return {
        "metric": metric,
        "value": round(value, 6),
        "unit": "GiB/s",
        "detail": detail,
    }
