"""Heavy-hitter sketches for the workload attribution plane.

Two classic streaming summaries, both seeded and deterministic so the
unit tier can pin their error bounds without statistical slack:

* ``SpaceSaving`` — Metwally et al.'s top-K summary.  Memory is
  strictly O(K).  The guarantee the tests pin: after N offers, any key
  whose true count exceeds ``N / K`` is present in the table, and every
  tabled estimate is an overestimate by at most its recorded ``error``
  (``count - error <= true <= count``).
* ``CountMin`` — Cormode/Muthukrishnan count-min sketch over a fixed
  ``depth x width`` grid of counters (``array('q')`` rows, so the
  memory footprint is a flat ``depth * width * 8`` bytes regardless of
  how many distinct keys flow through).  Estimates are overestimate-
  only: ``true <= estimate <= true + eps * N`` with
  ``eps = e / width`` at probability ``1 - exp(-depth)``; the seeded
  unit tier asserts the one-sided bound exactly and the epsilon bound
  on a fixed stream.

Both support ``decay`` (halving, so "heat" means *recent* heat) and
``merge`` for peer aggregation of the admin ``top`` v2 route.  Hashing
is ``zlib.crc32`` with per-row seed prefixes — Python's builtin
``hash()`` is process-randomized and would break cross-node merge and
seeded tests.
"""

from __future__ import annotations

import zlib
from array import array
from typing import Dict, Iterable, List, Tuple


def _h(seed: int, key: str) -> int:
    """Deterministic 32-bit hash of ``key`` under ``seed``."""
    return zlib.crc32(key.encode("utf-8", "surrogatepass"),
                      seed & 0xFFFFFFFF)


class SpaceSaving:
    """Top-K heavy hitters with O(K) memory.

    The table maps key -> [count, error]; ``count`` is an upper bound
    on the key's true frequency and ``error`` the worst-case
    overcharge it inherited when it evicted the previous minimum.
    """

    def __init__(self, k: int, seed: int = 0):
        self.k = max(1, int(k))
        self.seed = seed
        self.n = 0                       # total offered mass
        self._table: Dict[str, List[int]] = {}

    def offer(self, key: str, inc: int = 1) -> None:
        self.n += inc
        cell = self._table.get(key)
        if cell is not None:
            cell[0] += inc
            return
        if len(self._table) < self.k:
            self._table[key] = [inc, 0]
            return
        # replace the current minimum; the newcomer inherits its count
        # as both estimate floor and error ceiling
        mkey = min(self._table, key=lambda kk: self._table[kk][0])
        mcount = self._table[mkey][0]
        del self._table[mkey]
        self._table[key] = [mcount + inc, mcount]

    def __contains__(self, key: str) -> bool:
        return key in self._table

    def __len__(self) -> int:
        return len(self._table)

    def estimate(self, key: str) -> Tuple[int, int]:
        """(count, error) — count is an upper bound, count - error a
        lower bound; (0, 0) for untabled keys."""
        cell = self._table.get(key)
        return (cell[0], cell[1]) if cell is not None else (0, 0)

    def top(self, n: int | None = None) -> List[Tuple[str, int, int]]:
        """(key, count, error) rows, largest count first; ties broken
        by key so the order is deterministic."""
        rows = sorted(((k, c, e) for k, (c, e) in self._table.items()),
                      key=lambda r: (-r[1], r[0]))
        return rows if n is None else rows[:n]

    def threshold(self) -> float:
        """Any key with true count above this is guaranteed tabled."""
        return self.n / self.k

    def decay(self, factor: float = 0.5) -> None:
        """Scale every count/error (and N) down; zeroed keys drop out
        so stale heavy hitters age away instead of squatting slots."""
        self.n = int(self.n * factor)
        dead = []
        for key, cell in self._table.items():
            cell[0] = int(cell[0] * factor)
            cell[1] = int(cell[1] * factor)
            if cell[0] <= 0:
                dead.append(key)
        for key in dead:
            del self._table[key]

    def merge(self, other: "SpaceSaving") -> None:
        """Fold ``other`` in (peer aggregation).  Union the tables
        summing counts/errors, keep the K largest.  Approximate — a
        key absent from one table contributes nothing for that node —
        but overestimate-only is preserved and any key heavy in the
        combined stream stays tabled."""
        for key, (c, e) in other._table.items():
            cell = self._table.get(key)
            if cell is not None:
                cell[0] += c
                cell[1] += e
            else:
                self._table[key] = [c, e]
        self.n += other.n
        if len(self._table) > self.k:
            keep = self.top(self.k)
            self._table = {k: [c, e] for k, c, e in keep}

    def to_doc(self) -> dict:
        return {"k": self.k, "n": self.n,
                "table": {k: [c, e]
                          for k, (c, e) in self._table.items()}}

    @classmethod
    def from_doc(cls, doc: dict) -> "SpaceSaving":
        ss = cls(int(doc.get("k", 1)))
        ss.n = int(doc.get("n", 0))
        ss._table = {str(k): [int(v[0]), int(v[1])]
                     for k, v in (doc.get("table") or {}).items()}
        return ss


class CountMin:
    """Count-min sketch: fixed-size counter grid, overestimate-only
    point queries, elementwise merge."""

    def __init__(self, width: int = 2048, depth: int = 4,
                 seed: int = 0):
        self.width = max(8, int(width))
        self.depth = max(1, int(depth))
        self.seed = seed
        self.n = 0
        self._rows = [array("q", [0]) * self.width
                      for _ in range(self.depth)]

    def _slots(self, key: str) -> Iterable[Tuple[int, int]]:
        for d in range(self.depth):
            yield d, _h(self.seed * 0x9E3779B1 + d + 1, key) \
                % self.width

    def add(self, key: str, inc: int = 1) -> None:
        self.n += inc
        for d, slot in self._slots(key):
            self._rows[d][slot] += inc

    def estimate(self, key: str) -> int:
        return min(self._rows[d][slot]
                   for d, slot in self._slots(key))

    def epsilon(self) -> float:
        """est <= true + epsilon() * n with prob 1 - exp(-depth)."""
        return 2.718281828459045 / self.width

    def decay(self, factor: float = 0.5) -> None:
        self.n = int(self.n * factor)
        for row in self._rows:
            for i in range(self.width):
                row[i] = int(row[i] * factor)

    def merge(self, other: "CountMin") -> None:
        if (other.width, other.depth, other.seed) != \
                (self.width, self.depth, self.seed):
            raise ValueError("count-min dimensions/seed mismatch")
        self.n += other.n
        for d in range(self.depth):
            mine, theirs = self._rows[d], other._rows[d]
            for i in range(self.width):
                mine[i] += theirs[i]

    def memory_bytes(self) -> int:
        return sum(row.itemsize * len(row) for row in self._rows)
