"""Observability: HTTP tracing, structured/audit logging, profiling,
health diagnostics (reference: pkg/trace, cmd/http-tracer.go, cmd/logger/,
cmd/utils.go:286 profilers, cmd/healthinfo.go)."""

from . import (audit, healthinfo, lastminute, logger,  # noqa: F401 — public API
               profiling, trace)
