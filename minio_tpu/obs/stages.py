"""Request X-ray — per-stage latency attribution (the diagnosis half
of the obs plane the PR-2..4 trace/stats work could not answer).

``mc admin trace`` and the last-minute p50/p99 families say *what* is
slow; this module says *why*: every S3 request carries a
:class:`StageClock` (a contextvar, minted in ``_dispatch`` beside the
request ID) and the instrumented layers charge their wall time to
named stages as the request crosses them:

  ``admission``      request-pool semaphore wait (cmd/handler-api.go
                     maxClients analog)
  ``auth``           SigV4/SigV2 verification incl. aws-chunked
                     signature checking
  ``policy``         authorization: bucket policy + IAM + the external
                     OPA webhook when configured
  ``body_read``      reading the request body off the socket
  ``lock_wait``      namespace-lock acquisition (local or dsync)
  ``memgov``         memory-governor admission accounting
  ``cache``          hot-read plane serve (hit validation included)
  ``encode``         erasure encode + bitrot framing (PUT)
  ``decode``         shard assembly / erasure decode (GET)
  ``batch_wait``     cross-request codec batcher queue wait
  ``drive_read``     shard-segment fan-out wall time (GET)
  ``drive_commit``   commit fan-out wall time (PUT)
  ``write_enqueue``  writer-plane enqueue stalls (pipelined PUT)
  ``write_drain``    writer-plane drain wait (pipelined PUT)
  ``body_write``     writing the response body to the socket
  ``rpc``            internode RPC legs (async detail — overlaps the
                     request thread by design)
  ``other``          the unattributed remainder, computed at finish

Stages recorded on the clock's OWNER thread (the request handler) are
*serial* and exclusive: the clock keeps a stack, a nested stage's time
is subtracted from its parent, so the serial stage vector plus
``other`` reconciles with the measured request total exactly (the
reconciliation contract tests/test_xray.py pins).  The same ``stage``
/ ``add`` sites called from a pool, writer, or readahead thread (the
clock rides into them next to the request ID) route automatically to
the *async detail* vector — attributed but deliberately outside the
serial sum, because overlapping wall intervals cannot both be part of
one request's wall clock.

Idle/always-on contract (the PR-2 discipline): with no clock armed
every instrumented site pays one contextvar read and a None check.
With a clock armed the cost is monotonic reads plus in-place updates
of two small per-request dicts — no per-event allocation, bounded by
the stage-name catalog however many batches a huge PUT streams.
``ENABLED`` exists for the ``bench.py xray`` A/B leg and test
isolation; production always runs armed.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from typing import Optional

# the full stage catalog — every name the instrumented sites may emit.
# The analysis docs-drift rule (obs-docs-drift) checks each appears in
# docs/observability.md; the xray tests check emitted names stay inside
# this set.
STAGE_NAMES = (
    "admission", "auth", "policy", "body_read", "lock_wait", "memgov",
    "cache", "encode", "decode", "batch_wait", "drive_read",
    "drive_commit", "write_enqueue", "write_drain", "body_write",
    "rpc", "other",
)

# bench A/B switch (MT_XRAY_DISABLE=1 runs the hot paths with the
# clock never armed — the overhead-measurement baseline)
ENABLED = os.environ.get("MT_XRAY_DISABLE", "") not in ("1", "true")

_CLOCK: contextvars.ContextVar[Optional["StageClock"]] = \
    contextvars.ContextVar("mt_stage_clock", default=None)


class StageClock:
    """One request's stage accumulator.

    The OWNER thread (whoever constructed the clock) records serial
    stages through :meth:`push`/:meth:`pop`; nesting is handled with a
    stack so recorded times are exclusive self-times summing to at
    most the request wall time.  Any other thread holding the clock
    lands in ``async_detail`` — plain in-place dict adds whose rare
    cross-thread races could only under-count attribution detail,
    never corrupt the serial reconciliation.
    """

    __slots__ = ("t0_ns", "owner", "_stack", "serial", "async_detail",
                 "gatings")

    def __init__(self):
        self.t0_ns = time.monotonic_ns()
        self.owner = threading.get_ident()
        # stack entries: [name, start_ns, child_ns]
        self._stack: list = []
        self.serial: dict = {}
        self.async_detail: dict = {}
        # quorum critical-path rows (obs/critpath.py): compact tuples
        # (plane, k, n, gating_label, kth_label, kth_ns, wall_ns,
        # trail_ns), appended at each quorum reduction the request
        # crossed and rendered into its flight-recorder row — a list
        # append per reduction, no dicts on the hot path
        self.gatings: list = []

    # -- serial stages (owner thread only) -----------------------------------

    def push(self, name: str) -> None:
        self._stack.append([name, time.monotonic_ns(), 0])

    def pop(self) -> None:
        name, start, child = self._stack.pop()
        dur = time.monotonic_ns() - start
        if self._stack:
            self._stack[-1][2] += dur
        self_ns = dur - child
        if self_ns > 0:
            self.serial[name] = self.serial.get(name, 0) + self_ns

    def add(self, name: str, dur_ns: int) -> None:
        """Record an already-measured interval: serial on the owner
        thread (charged against the enclosing stage so nothing double
        counts), async detail from anywhere else."""
        if threading.get_ident() != self.owner:
            self.add_async(name, dur_ns)
            return
        if self._stack:
            self._stack[-1][2] += dur_ns
        self.serial[name] = self.serial.get(name, 0) + dur_ns

    # -- async detail (any thread) -------------------------------------------

    def add_async(self, name: str, dur_ns: int) -> None:
        d = self.async_detail
        d[name] = d.get(name, 0) + dur_ns

    # -- finish ---------------------------------------------------------------

    def finish(self, total_ns: int | None = None
               ) -> tuple[dict, dict, int]:
        """Close out: returns ``(serial, async, unattributed)`` where
        ``serial`` maps stage -> ns with ``other`` = total -
        sum(serial) (clamped at 0) appended, so the serial stages plus
        ``other`` reconcile with the total exactly; ``async`` is the
        parallel detail; ``unattributed`` is the raw remainder before
        clamping (negative would mean a double-count — the
        reconciliation tests assert it never is)."""
        while self._stack:              # abandoned mid-stage (error path)
            self.pop()
        if total_ns is None:
            total_ns = time.monotonic_ns() - self.t0_ns
        serial = dict(self.serial)
        unattributed = total_ns - sum(serial.values())
        serial["other"] = max(0, unattributed)
        return serial, dict(self.async_detail), unattributed


# -- module-level plumbing ----------------------------------------------------

def begin() -> Optional[StageClock]:
    """Mint + arm a clock for the current context (the S3 dispatcher);
    returns None when the plane is disabled (bench baseline)."""
    if not ENABLED:
        return None
    clock = StageClock()
    _CLOCK.set(clock)
    return clock


def clear() -> None:
    _CLOCK.set(None)


def current() -> Optional[StageClock]:
    return _CLOCK.get()


def set_clock(clock: Optional[StageClock]) -> None:
    """Explicit propagation into pool/writer/readahead threads
    (contextvars do not cross thread boundaries) — the request-ID
    discipline from obs/trace.py.  Non-owner threads route to async
    detail automatically."""
    _CLOCK.set(clock)


class _Stage:
    """Tiny reusable context manager: ``with stage("auth"): ...`` —
    one contextvar read and a None check when no clock is armed; on a
    non-owner thread the interval lands in async detail."""

    __slots__ = ("name", "_clock", "_serial", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._clock = None
        self._serial = False
        self._t0 = 0

    def __enter__(self):
        c = _CLOCK.get()
        self._clock = c
        if c is not None:
            if threading.get_ident() == c.owner:
                self._serial = True
                c.push(self.name)
            else:
                self._serial = False
                self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        c = self._clock
        self._clock = None
        if c is not None:
            if self._serial:
                c.pop()
            else:
                c.add_async(self.name,
                            time.monotonic_ns() - self._t0)
        return False


def stage(name: str) -> _Stage:
    return _Stage(name)


def add(name: str, dur_ns: int) -> None:
    """Add an already-measured interval against the armed clock, if
    any (owner thread -> serial, others -> async detail)."""
    c = _CLOCK.get()
    if c is not None:
        c.add(name, dur_ns)


def add_async(name: str, dur_ns: int) -> None:
    """Async-detail add against the armed clock, if any."""
    c = _CLOCK.get()
    if c is not None:
        c.add_async(name, dur_ns)


def note_gating(row: tuple) -> None:
    """Attach one quorum critical-path row to the armed clock, if any
    (list append under the GIL — safe from writer/pool threads the
    clock rode into, same discipline as add_async)."""
    c = _CLOCK.get()
    if c is not None:
        c.gatings.append(row)
