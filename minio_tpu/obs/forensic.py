"""SLO-breach forensic bundles — the trigger engine + bundle writer.

When the cluster misbehaves (a breaker storm, governor shedding, a
flagged slow drive, an error-ceiling crossing, heal backlog growth)
the evidence is perishable: the flight-recorder rings (obs/flightrec)
hold the last N requests and the breakers/governor hold their state
*now*, not when an operator gets paged.  This module watches for
breach-shaped signals and snapshots everything into one support
bundle — a zip of the rings, a live metrics scrape, a health document
and the *redacted* config — the `mc admin obd` support-bundle story
(cmd/healthinfo.go) made automatic.

Design constraints:

* **cheap when healthy** — the engine piggybacks on the request path
  (``observe_request``): integer window bookkeeping per request, and a
  full trigger evaluation at most once per second;
* **bounded on disk** — the bundle dir is reaped oldest-first to
  ``forensic.max_bundles`` / ``forensic.max_bytes``;
* **storm-proof** — each trigger carries a cooldown
  (``forensic.cooldown``): one breach window produces one bundle, not
  one per failing request;
* **secret-free** — the config section passes through
  :func:`redact_config` (key-name fence) and nothing else in a bundle
  ever held credentials (flight records carry no headers; the scrape
  and healthinfo are public surfaces already).  Pinned by
  tests/test_forensic.py grepping a real bundle for planted markers.

Knobs live in the ``forensic`` kvconfig subsystem; thresholds are
deliberately conservative so the ordinary chaos the soak matrix
injects (brief 503 bursts, breaker flaps at exact quorum) never fires
— only a genuine breach (sustained majority-5xx, a flagged drive with
the trigger armed) does.  The soak drill lowers them via env to prove
the path end to end (``require_no_forensics`` pins the clean-scenario
zero).
"""

from __future__ import annotations

import io
import json
import os
import re
import threading
import time
import zipfile
from typing import Optional

from ..admin.metrics import GLOBAL as _metrics

# trigger names (the ``trigger`` label on mt_forensic_dumps_total)
TRIGGERS = ("error_ceiling", "breaker_burst", "shed_burst",
            "slow_drive", "heal_backlog", "manual")

_CHECK_INTERVAL_S = 1.0

# breach-window trace trees attached per bundle (newest error rids
# first) — bounds the tracetrees.json section, not the evidence rings
_TREE_BUNDLE_CAP = 32

# config keys whose VALUES are secret material; matched on the key
# name so a future knob with a secret-shaped name is redacted by
# default (fail closed) — the PR-2 header-redaction contract applied
# to config dumps
_SECRET_KEY_RE = re.compile(
    r"secret|token|password|passwd|credential|dsn|private", re.I)

REDACTED = "*REDACTED*"


def redact_config(subsystems: dict) -> dict:
    """{subsys: {key: value}} with secret-shaped keys blanked."""
    out: dict = {}
    for subsys, kv in subsystems.items():
        out[subsys] = {
            k: (REDACTED if _SECRET_KEY_RE.search(k) and v else v)
            for k, v in kv.items()}
    return out


class ForensicSys:
    """One node's trigger engine + bundle store."""

    def __init__(self, srv, out_dir: str, *, max_bundles: int = 8,
                 max_bytes: int = 64 << 20, cooldown_s: float = 60.0,
                 triggers: tuple = ("error_ceiling",),
                 error_rate: float = 0.5, error_min_samples: int = 100,
                 window_s: float = 10.0, breaker_burst: int = 10,
                 shed_burst: int = 50, backlog_growth: int = 500):
        self.srv = srv
        self.dir = out_dir
        self.max_bundles = max(1, max_bundles)
        self.max_bytes = max(1 << 20, max_bytes)
        self.cooldown_s = cooldown_s
        self.triggers = tuple(triggers)
        self.error_rate = error_rate
        self.error_min_samples = max(1, error_min_samples)
        self.window_s = max(1.0, window_s)
        self.breaker_burst = max(1, breaker_burst)
        self.shed_burst = max(1, shed_burst)
        self.backlog_growth = max(1, backlog_growth)
        self._mu = threading.Lock()
        # two-slot rotating request window: [epoch, total, errors] x2 —
        # a full window plus the live one covers >= window_s of traffic
        self._slots = [[0, 0, 0], [0, 0, 0]]
        self._last_check = 0.0
        self._fired: dict[str, float] = {}       # trigger -> monotonic
        self.dumped = 0                           # lifetime bundles
        self._writer: Optional[threading.Thread] = None
        # deltas baseline for the cumulative sources
        self._base_breaker_opens = self._breaker_opens()
        self._base_sheds = self._shed_total()
        self._seen_breaker_opens = self._base_breaker_opens
        self._seen_sheds = self._base_sheds
        self._mrf_baseline: Optional[int] = None

    # -- config ---------------------------------------------------------------

    @classmethod
    def from_server(cls, srv) -> "Optional[ForensicSys]":
        """Build from the server's ``forensic`` kvconfig subsystem;
        None when disabled or no bundle dir is resolvable."""
        from ..utils.kvconfig import parse_duration
        from ..utils.memgov import parse_size
        cfg = srv.config
        try:
            if (cfg.get("forensic", "enable") or "on") == "off":
                return None
            out_dir = cfg.get("forensic", "dir") or ""
            if not out_dir:
                from .selftest import local_drive_paths
                roots = local_drive_paths(srv.layer)
                if not roots:
                    return None
                out_dir = os.path.join(roots[0], ".minio-tpu.sys",
                                       "forensics")
            trig = tuple(
                t for t in (cfg.get("forensic", "triggers")
                            or "error_ceiling").replace(" ", "")
                .split(",") if t)
            return cls(
                srv, out_dir,
                max_bundles=int(cfg.get("forensic", "max_bundles")
                                or 8),
                max_bytes=parse_size(cfg.get("forensic", "max_bytes")
                                     or "64MiB", 64 << 20),
                cooldown_s=parse_duration(
                    cfg.get("forensic", "cooldown") or "60s", 60.0),
                triggers=trig,
                error_rate=float(cfg.get("forensic", "error_rate")
                                 or 0.5),
                error_min_samples=int(
                    cfg.get("forensic", "error_min_samples") or 100),
                window_s=parse_duration(
                    cfg.get("forensic", "window") or "10s", 10.0),
                breaker_burst=int(cfg.get("forensic", "breaker_burst")
                                  or 10),
                shed_burst=int(cfg.get("forensic", "shed_burst")
                               or 50),
                backlog_growth=int(
                    cfg.get("forensic", "backlog_growth") or 500))
        except Exception:  # noqa: BLE001 — a bad knob or an exotic
            return None    # layer shape must not take the server down

    # -- cumulative sources ---------------------------------------------------

    @staticmethod
    def _breaker_opens() -> int:
        from ..parallel import rpc as _rpc
        return _rpc.BREAKER_OPEN_COUNT

    @staticmethod
    def _shed_total() -> int:
        from ..utils.memgov import GOVERNOR
        return sum(GOVERNOR.stats()["shed"].values())

    # -- the request-path tap -------------------------------------------------

    def observe_request(self, status: int,
                        backpressure: bool = False) -> None:
        """Called once per completed request (the flight-recorder
        append site): window bookkeeping + an at-most-1/s check.

        ``backpressure`` marks DELIBERATE shedding (503s carrying
        Retry-After: request-pool admission, governor sheds) — bounded
        self-protection the soak SLO budgets separately, not a breach;
        the error ceiling counts only breach-shaped 5xx (quorum
        failures, lock losses, internal errors)."""
        now = time.monotonic()
        half = self.window_s / 2.0
        epoch = int(now / half)
        slot = self._slots[epoch % 2]
        if slot[0] != epoch:
            slot[0], slot[1], slot[2] = epoch, 0, 0
        slot[1] += 1
        if status >= 500 and not backpressure:
            slot[2] += 1
        if now - self._last_check >= _CHECK_INTERVAL_S:
            self._last_check = now
            try:
                self.check(now)
            except Exception:  # noqa: BLE001 — the trigger engine must
                pass           # never fail a request

    def _window_counts(self, now: float) -> tuple[int, int]:
        half = self.window_s / 2.0
        epoch = int(now / half)
        total = errors = 0
        for slot in self._slots:
            if slot[0] in (epoch, epoch - 1):
                total += slot[1]
                errors += slot[2]
        return total, errors

    # -- trigger evaluation ---------------------------------------------------

    def check(self, now: float | None = None) -> Optional[str]:
        """Evaluate every armed trigger; fires at most one bundle per
        call.  Returns the fired trigger name (tests) or None."""
        now = time.monotonic() if now is None else now
        if "error_ceiling" in self.triggers:
            total, errors = self._window_counts(now)
            if total >= self.error_min_samples and \
                    errors / total >= self.error_rate:
                return self.fire("error_ceiling", {
                    "windowSeconds": self.window_s,
                    "requests": total, "errors5xx": errors,
                    "rate": round(errors / total, 4),
                    "threshold": self.error_rate})
        if "breaker_burst" in self.triggers:
            opens = self._breaker_opens()
            if opens - self._seen_breaker_opens >= self.breaker_burst:
                prev, self._seen_breaker_opens = \
                    self._seen_breaker_opens, opens
                return self.fire("breaker_burst", {
                    "opens": opens - prev,
                    "threshold": self.breaker_burst})
        if "shed_burst" in self.triggers:
            sheds = self._shed_total()
            if sheds - self._seen_sheds >= self.shed_burst:
                prev, self._seen_sheds = self._seen_sheds, sheds
                return self.fire("shed_burst", {
                    "sheds": sheds - prev,
                    "threshold": self.shed_burst})
        if "slow_drive" in self.triggers:
            flagged = self._flagged_drives()
            if flagged:
                return self.fire("slow_drive", {"drives": flagged})
        if "heal_backlog" in self.triggers:
            mrf = getattr(self.srv, "mrf", None)
            if mrf is not None:
                depth = mrf._q.qsize()
                if self._mrf_baseline is None:
                    self._mrf_baseline = depth
                elif depth - self._mrf_baseline >= self.backlog_growth:
                    self._mrf_baseline = depth
                    return self.fire("heal_backlog", {
                        "queueDepth": depth,
                        "threshold": self.backlog_growth})
        return None

    def _flagged_drives(self) -> list[str]:
        from ..storage.health import (slow_drive_knobs,
                                      slow_drives_for_layer)
        mult, mins = slow_drive_knobs(getattr(self.srv, "config", None))
        verdicts = slow_drives_for_layer(self.srv.layer, multiple=mult,
                                         min_samples=mins)
        return sorted(d for d, v in verdicts.items() if v.get("slow"))

    # -- firing ---------------------------------------------------------------

    def fire(self, trigger: str, detail: dict,
             sync: bool = False) -> Optional[str]:
        """Write one bundle for ``trigger`` unless it is cooling down.
        Async by default (a request thread must not serialize a zip
        write); ``sync=True`` for tests/admin-manual.  Returns the
        trigger name when a bundle was scheduled, else None."""
        now = time.monotonic()
        with self._mu:
            last = self._fired.get(trigger)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._fired[trigger] = now
        if sync:
            self._write_bundle(trigger, detail)
            return trigger
        t = threading.Thread(target=self._write_bundle,
                             args=(trigger, detail), daemon=True,
                             name="mt-forensic-dump")
        self._writer = t
        t.start()
        return trigger

    def join(self, timeout: float = 10.0) -> None:
        """Wait for an in-flight bundle write (teardown/tests)."""
        t = self._writer
        if t is not None:
            t.join(timeout=timeout)

    # -- the bundle -----------------------------------------------------------

    def _write_bundle(self, trigger: str, detail: dict) -> None:
        try:
            payload = self._bundle_bytes(trigger, detail)
            os.makedirs(self.dir, exist_ok=True)
            seq = self.dumped + 1
            name = f"forensic-{time.strftime('%Y%m%dT%H%M%SZ', time.gmtime())}" \
                   f"-{trigger}-{os.getpid()}-{seq}.zip"
            tmp = os.path.join(self.dir, f".{name}.tmp")
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, os.path.join(self.dir, name))
            # counted only once the bundle is durably on disk — the
            # metric must never claim evidence that was never written
            self.dumped = seq
            _metrics.inc("mt_forensic_dumps_total", {"trigger": trigger})
            self._reap()
        except Exception:  # noqa: BLE001 — a failing dump must never
            # hurt the serving path it diagnoses; clearing the
            # cooldown lets the NEXT trigger evaluation retry instead
            # of going dark for cooldown_s with nothing on disk
            with self._mu:
                self._fired.pop(trigger, None)

    def _bundle_bytes(self, trigger: str, detail: dict) -> bytes:
        srv = self.srv
        from . import healthinfo as _hi
        from .flightrec import system_snapshot
        docs: dict[str, bytes] = {}

        def put(name: str, doc) -> None:
            try:
                docs[name] = json.dumps(doc, default=str,
                                        indent=1).encode()
            except Exception as e:  # noqa: BLE001 — one bad section
                docs[name] = json.dumps(               # != no bundle
                    {"error": str(e)}).encode()

        put("trigger.json", {
            "trigger": trigger, "detail": detail,
            "node": getattr(srv, "node_name", ""),
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
        rec = getattr(srv, "flightrec", None)
        if rec is not None:
            try:
                rec.snapshot_now()       # the breach-instant snapshot
            except Exception:  # noqa: BLE001 — rings still dump below
                pass
            put("flightrec.json", rec.dump())
            try:
                # assembled causal trees for the breach-window
                # requests (the error ring's rids): the span ring is
                # still resident at bundle time, so the trees capture
                # exactly the requests the SLO row tripped over
                from . import tracetree as _tt
                from .flightrec import _F_RID
                rids = []
                for r in reversed(rec.errors):
                    if r[_F_RID] and r[_F_RID] not in rids:
                        rids.append(r[_F_RID])
                    if len(rids) >= _TREE_BUNDLE_CAP:
                        break
                spans = _tt.local_spans(
                    rids=tuple(rids),
                    node=getattr(srv, "node_name", ""))
                put("tracetrees.json", {
                    "rids": rids,
                    "trees": _tt.assemble(spans)})
            except Exception as e:  # noqa: BLE001 — one bad section
                put("tracetrees.json", {"error": str(e)})
        put("system.json", system_snapshot())
        try:
            from .selftest import local_drive_paths
            put("healthinfo.json",
                _hi.collect(local_drive_paths(srv.layer)))
        except Exception as e:  # noqa: BLE001
            put("healthinfo.json", {"error": str(e)})
        cfg = getattr(srv, "config", None)
        if cfg is not None:
            try:
                put("config.json", redact_config(
                    {s: cfg.get_subsys(s) for s in cfg.subsystems()}))
            except Exception as e:  # noqa: BLE001
                put("config.json", {"error": str(e)})
        try:
            # the watchdog's telemetry history: the 30 minutes BEFORE
            # the breach, so the bundle shows the road to it, not just
            # the instant ({"enabled": False} when no watchdog runs)
            from .history import snapshot_dict
            put("history.json", snapshot_dict(
                getattr(getattr(srv, "watchdog", None), "history",
                        None)))
        except Exception as e:  # noqa: BLE001
            put("history.json", {"error": str(e)})
        try:
            # per-bucket usage accounting at the breach instant: the
            # crawler snapshot plus in-flight quota deltas, and the
            # metering plane's tenant/key heavy hitters — WHO was
            # doing WHAT when the trigger tripped
            usage = getattr(srv, "usage", None)
            metering = getattr(srv, "metering", None)
            put("usage.json", {
                "cache": usage.snapshot_doc()
                if usage is not None else None,
                "metering": metering.top_doc()
                if metering is not None else None})
        except Exception as e:  # noqa: BLE001
            put("usage.json", {"error": str(e)})
        try:
            from ..admin.handlers import _render_local
            docs["metrics.prom"] = _render_local(srv).encode()
        except Exception as e:  # noqa: BLE001
            docs["metrics.prom"] = f"# scrape failed: {e}\n".encode()
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for name, data in docs.items():
                z.writestr(name, data)
        return buf.getvalue()

    # -- the bounded store ----------------------------------------------------

    def bundles(self) -> list[dict]:
        """Resident bundles, oldest first (admin route + SLO rows)."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("forensic-")
                           and n.endswith(".zip"))
        except OSError:
            return []
        out = []
        for n in names:
            p = os.path.join(self.dir, n)
            try:
                out.append({"name": n, "bytes": os.path.getsize(p),
                            "trigger": n.split("-")[2]
                            if n.count("-") >= 2 else ""})
            except OSError:
                continue
        return out

    def _reap(self) -> None:
        bundles = self.bundles()
        total = sum(b["bytes"] for b in bundles)
        # oldest-first, but the NEWEST bundle always survives — a
        # single bundle larger than max_bytes is still the only copy
        # of the breach evidence
        while len(bundles) > 1 and (len(bundles) > self.max_bundles
                                    or total > self.max_bytes):
            victim = bundles.pop(0)
            total -= victim["bytes"]
            try:
                os.remove(os.path.join(self.dir, victim["name"]))
            except OSError:
                pass
