"""Workload attribution plane — who is the load?

Per-(bucket, api, access-key) usage metering charged from
``s3/server.py`` at completion-record time, with label cardinality
bounded by construction:

* the bucket x api table holds at most ``max_buckets`` distinct
  buckets — overflow traffic folds into the ``_other`` row;
* tenant (access-key) rows exist only while the key is tabled in a
  seeded :class:`~minio_tpu.obs.sketch.SpaceSaving` top-K — an evicted
  tenant's row folds into ``_other``, so the registry can never grow a
  row per request-derived value;
* object keys/prefixes never become metric labels at all: they live
  only in a fixed-footprint count-min + space-saving pair feeding the
  admin ``top`` v2 route and the hot-read cache's per-key heat
  estimate (:meth:`Metering.key_heat`).

Recording follows the obs/lastminute.py "lock-cheap" discipline: plain
dict/int mutations under the GIL, no lock on the charge path — a
concurrent race can lose a sample, which minute-granularity
attribution tolerates; the S3 hot path must never serialize on an
observability lock.  Sketches decay (halve) every ``decay_interval``
so "heat" means *recent* heat; the bucket/tenant cells stay cumulative
counters (the telemetry history rings store counters as rates).

Idle contract: ``metering.enable=off`` (the default) means
``srv.metering is None`` — no charge branch, no ``mt_bucket_*`` /
``mt_tenant_*`` / ``mt_metering_*`` family in the scrape, no ``top``
v2 sections, and the hot-read cache falls back to the PR-13 global
GetObject rate.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List, Tuple

from .lastminute import Window
from .sketch import CountMin, SpaceSaving
from ..utils.kvconfig import parse_duration, register_subsys

OTHER = "_other"

register_subsys("metering", {
    # workload attribution (obs/metering.py): ``enable=on`` arms the
    # per-(bucket, api, access-key) registry charged at completion-
    # record time, the mt_bucket_*/mt_tenant_* scrape families, the
    # admin ``top`` v2 sections (hot keys/prefixes, top tenants), and
    # the hot-read cache's per-key heat signal.  Memory is strictly
    # bounded: at most ``max_buckets`` bucket rows and ``tenant_k``
    # tenant rows (overflow folds into ``_other``); object keys live
    # only in a ``cm_width`` x ``cm_depth`` count-min grid plus
    # ``key_k``/``prefix_k`` space-saving tables.  Sketches halve
    # every ``decay_interval`` so heat is recent heat.  ``seed`` makes
    # every sketch deterministic (tests, cross-node merge).
    # Live-reloadable (S3Server.reload_metering_config on admin
    # SetConfigKV; a reload rebuilds the plane, counters reset).
    "enable": "off",
    "max_buckets": "48",
    "tenant_k": "24",
    "key_k": "64",
    "prefix_k": "32",
    "cm_width": "2048",
    "cm_depth": "4",
    "seed": "1",
    "decay_interval": "60s",
})


class _Cell:
    """One bucket x api accounting row."""

    __slots__ = ("requests", "errors", "rx", "tx")

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.rx = 0
        self.tx = 0


class _TenantCell(_Cell):
    """One tenant row: the counters plus a last-minute latency ring."""

    __slots__ = ("window",)

    def __init__(self):
        super().__init__()
        self.window = Window()


class Metering:
    """One node's bounded attribution registry."""

    def __init__(self, *, max_buckets: int = 48, tenant_k: int = 24,
                 key_k: int = 64, prefix_k: int = 32,
                 cm_width: int = 2048, cm_depth: int = 4,
                 seed: int = 1, decay_interval_s: float = 60.0,
                 node_name: str = "",
                 clock: Callable[[], float] = time.time):
        self.max_buckets = max(1, max_buckets)
        self.node_name = node_name
        self.clock = clock
        self.decay_interval_s = max(1.0, decay_interval_s)
        self._bapi: Dict[Tuple[str, str], _Cell] = {}
        self._bucket_names: set = set()
        self._tenants: Dict[str, _TenantCell] = {}
        self._tenant_top = SpaceSaving(tenant_k, seed)
        self._key_cm = CountMin(cm_width, cm_depth, seed)
        self._key_top = SpaceSaving(key_k, seed + 1)
        self._prefix_top = SpaceSaving(prefix_k, seed + 2)
        self._last_decay = clock()
        self.decays = 0

    # -- the charge path (hot; lock-cheap) --------------------------------

    def charge(self, *, bucket: str, api: str, tenant: str = "",
               key: str = "", status: int = 200, rx: int = 0,
               tx: int = 0, dur_ns: int = 0,
               now_s: float | None = None) -> None:
        now = self.clock() if now_s is None else now_s
        if now - self._last_decay >= self.decay_interval_s:
            self._decay(now)
        err = 1 if status >= 500 else 0
        # bucket x api row (bounded: overflow buckets fold to _other)
        b = bucket or OTHER
        if b not in self._bucket_names:
            if len(self._bucket_names) >= self.max_buckets:
                b = OTHER
            else:
                self._bucket_names.add(b)
        cell = self._bapi.get((b, api))
        if cell is None:
            cell = self._bapi[(b, api)] = _Cell()
        cell.requests += 1
        cell.errors += err
        cell.rx += rx
        cell.tx += tx
        # tenant row, gated by the space-saving table: only a current
        # heavy hitter owns a named row
        t = tenant or OTHER
        if t != OTHER:
            self._tenant_top.offer(t)
            if t not in self._tenant_top:
                t = OTHER
            elif t not in self._tenants:
                self._fold_evicted_tenants()
        trow = self._tenants.get(t)
        if trow is None:
            trow = self._tenants[t] = _TenantCell()
        trow.requests += 1
        trow.errors += err
        trow.rx += rx
        trow.tx += tx
        trow.window.record(dur_ns, rx + tx)
        # object-key heat: sketches only, never labels
        if key:
            composite = b + "/" + key
            self._key_cm.add(composite)
            self._key_top.offer(composite)
            seg = key.split("/", 1)[0]
            self._prefix_top.offer(b + "/" + seg + "/")

    def _fold_evicted_tenants(self) -> None:
        """A new heavy hitter evicted someone from the sketch table —
        fold the loser's row into ``_other`` so named rows and the
        sketch stay in lockstep (rows are strictly <= tenant_k + 1)."""
        dead = [t for t in self._tenants
                if t != OTHER and t not in self._tenant_top]
        if not dead:
            return
        other = self._tenants.get(OTHER)
        if other is None:
            other = self._tenants[OTHER] = _TenantCell()
        for t in dead:
            row = self._tenants.pop(t)
            other.requests += row.requests
            other.errors += row.errors
            other.rx += row.rx
            other.tx += row.tx

    def _decay(self, now: float) -> None:
        self._last_decay = now
        self.decays += 1
        self._tenant_top.decay()
        self._key_cm.decay()
        self._key_top.decay()
        self._prefix_top.decay()

    # -- read back --------------------------------------------------------

    def key_heat(self, bucket: str, key: str) -> int:
        """Overestimate-only recent-GET heat for one object — the
        hot-read cache admission signal (decays with the sketches)."""
        return self._key_cm.estimate((bucket or OTHER) + "/" + key)

    def memory_bytes(self) -> int:
        """Rough live footprint of the sketch grid + tables — a gauge,
        and the number the memory-fence test holds under its ceiling."""
        tables = (len(self._tenant_top._table)
                  + len(self._key_top._table)
                  + len(self._prefix_top._table))
        return (self._key_cm.memory_bytes() + tables * 128
                + len(self._bapi) * sys.getsizeof(_Cell())
                + len(self._tenants) * 1024)

    def metrics_state(self) -> dict:
        """Scrape-time snapshot for admin/metrics.py
        ``_metering_gauges`` (mt_bucket_*/mt_tenant_* families)."""
        bucket_rows = [
            (b, api, c.requests, c.errors, c.rx, c.tx)
            for (b, api), c in sorted(self._bapi.items())]
        tenant_rows = [
            (t, c.requests, c.errors, c.rx, c.tx,
             c.window.p50(), c.window.p99())
            for t, c in sorted(self._tenants.items())]
        return {"bucketRows": bucket_rows, "tenantRows": tenant_rows,
                "memoryBytes": self.memory_bytes(),
                "decays": self.decays}

    def top_doc(self) -> dict:
        """One node's ``top`` v2 sections, shared by the local admin
        route and the ``metering_top`` peer RPC (peer aggregation
        merges these docs with :func:`merge_top_docs`)."""
        tenants = [
            {"tenant": t, "requests": c.requests, "errors": c.errors,
             "rxBytes": c.rx, "txBytes": c.tx,
             "p50Ns": c.window.p50(), "p99Ns": c.window.p99()}
            for t, c in sorted(self._tenants.items())]
        tenants.sort(key=lambda r: -(r["rxBytes"] + r["txBytes"]))
        hot_keys = [
            {"key": k, "count": c, "error": e}
            for k, c, e in self._key_top.top()]
        hot_prefixes = [
            {"prefix": k, "count": c, "error": e}
            for k, c, e in self._prefix_top.top()]
        return {
            "node": self.node_name,
            "tenants": tenants,
            "hotKeys": hot_keys,
            "hotPrefixes": hot_prefixes,
            "sketch": {
                "n": self._key_top.n,
                "keyK": self._key_top.k,
                "thresholdCount": round(self._key_top.threshold(), 1),
                "epsilon": self._key_cm.epsilon(),
                "memoryBytes": self.memory_bytes(),
                "decays": self.decays,
            },
        }

    # -- construction ------------------------------------------------------

    @classmethod
    def from_server(cls, srv) -> "Metering | None":
        """Build from the ``metering`` kvconfig subsystem; None when
        disabled (the idle contract) or on any bad knob."""
        cfg = srv.config
        try:
            if (cfg.get("metering", "enable") or "off") != "on":
                return None

            def num(key: str, default: int) -> int:
                return int(cfg.get("metering", key) or default)

            return cls(
                max_buckets=num("max_buckets", 48),
                tenant_k=num("tenant_k", 24),
                key_k=num("key_k", 64),
                prefix_k=num("prefix_k", 32),
                cm_width=num("cm_width", 2048),
                cm_depth=num("cm_depth", 4),
                seed=num("seed", 1),
                decay_interval_s=parse_duration(
                    cfg.get("metering", "decay_interval") or "60s",
                    60.0),
                node_name=getattr(srv, "node_name", ""))
        except Exception:  # noqa: BLE001 — a bad knob must not take
            return None    # the server down


def merge_top_docs(docs: List[dict]) -> dict:
    """Aggregate per-node ``top_doc`` sections into one cluster view:
    tenant counters sum (p99 takes the max — a tenant is as slow as
    its slowest node), hot keys/prefixes sum per key and re-rank."""
    tenants: Dict[str, dict] = {}
    keys: Dict[str, dict] = {}
    prefixes: Dict[str, dict] = {}
    nodes = []
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        if doc.get("node"):
            nodes.append(doc["node"])
        for row in doc.get("tenants") or []:
            agg = tenants.setdefault(row["tenant"], {
                "tenant": row["tenant"], "requests": 0, "errors": 0,
                "rxBytes": 0, "txBytes": 0, "p50Ns": 0, "p99Ns": 0})
            agg["requests"] += row.get("requests", 0)
            agg["errors"] += row.get("errors", 0)
            agg["rxBytes"] += row.get("rxBytes", 0)
            agg["txBytes"] += row.get("txBytes", 0)
            agg["p50Ns"] = max(agg["p50Ns"], row.get("p50Ns", 0))
            agg["p99Ns"] = max(agg["p99Ns"], row.get("p99Ns", 0))
        for row in doc.get("hotKeys") or []:
            agg = keys.setdefault(row["key"], {
                "key": row["key"], "count": 0, "error": 0})
            agg["count"] += row.get("count", 0)
            agg["error"] += row.get("error", 0)
        for row in doc.get("hotPrefixes") or []:
            agg = prefixes.setdefault(row["prefix"], {
                "prefix": row["prefix"], "count": 0, "error": 0})
            agg["count"] += row.get("count", 0)
            agg["error"] += row.get("error", 0)
    out_tenants = sorted(tenants.values(),
                         key=lambda r: -(r["rxBytes"] + r["txBytes"]))
    out_keys = sorted(keys.values(),
                      key=lambda r: (-r["count"], r["key"]))
    out_prefixes = sorted(prefixes.values(),
                          key=lambda r: (-r["count"], r["prefix"]))
    return {"nodes": nodes, "tenants": out_tenants,
            "hotKeys": out_keys, "hotPrefixes": out_prefixes}
