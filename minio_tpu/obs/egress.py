"""Telemetry egress plane — the store-and-forward delivery core shared
by log, audit, and bucket-event targets (cmd/logger/target/http +
pkg/event/target/queuestore.go unified).

A :class:`DeliveryTarget` owns one destination (a webhook endpoint, a
broker) and guarantees the request path never waits on it:

* ``send()`` is a bounded in-memory enqueue (``put_nowait``) — a full
  queue spills to the disk store when one is configured, else the
  record is counted dropped;
* ONE background sender thread per target drains the queue, retrying
  each record with the shared jittered-exponential backoff from
  ``utils/retry.py``;
* an online → offline → probing state machine (the RPC circuit
  breaker's shape, parallel/rpc.py): ``offline_after`` CONSECUTIVE
  failures take the target offline — further records go straight to
  the disk store without touching the network; after ``cooldown_s``
  exactly one delivery is admitted as the half-open probe, whose
  success flips the target back online and triggers background replay
  of the store;
* records that exhaust ``max_attempts`` (or arrive while offline)
  persist to the bounded disk :class:`QueueStore`; with no store — or
  a full one — they are DEAD-LETTERED: counted, never blocking, never
  raising into the caller;
* offline/online transitions go through ``Logger.log_once`` so a
  flapping endpoint shows up in the logs without storming them.

Every target keeps its own delivery counters and latency histogram;
the scrape-time exporter (admin/metrics.py ``_egress_metrics``) reads
them through the :class:`EgressRegistry`, so a server with ZERO
configured targets has no sender threads, no queues, and no
``mt_target_*`` families in its scrape — the hot path stays free when
egress is unconfigured.

Everything nondeterministic is injectable (``rng``, ``sleep``,
``clock``) so tests drive the state machine without wall-clock races.
"""

from __future__ import annotations

import json
import os
import queue
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ..utils.retry import RetryPolicy
from ..utils.locktrace import mtlock

ONLINE = "online"
OFFLINE = "offline"
PROBING = "probing"

# delivery is a network round trip: ms-scale when healthy, the target
# timeout when not
DELIVERY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_CLOSE = object()       # sender-thread shutdown sentinel


class QueueStoreFull(Exception):
    """The bounded disk queue is at its limit (dead-letter trigger)."""


def config_queue_limit(cfg, subsys: str, key: str,
                       default: int = 10000) -> int:
    """Parse a queue/store bound from a kvconfig subsystem, clamped to
    >= 1 — the ONE parser for every egress queue knob (logger/audit
    ``queue_size``, notify ``queue_limit``), so the planes can never
    drift on defaults or clamping."""
    try:
        return max(1, int(cfg.get(subsys, key) or default))
    except (KeyError, ValueError):
        return default


class QueueStore:
    """Disk-backed record queue (pkg/event/target/queuestore.go): one
    JSON file per undelivered record, replayed in timestamp order,
    bounded count."""

    def __init__(self, directory: str, limit: int = 10000):
        self.dir = directory
        self.limit = limit
        self._mu = mtlock("egress.store")
        os.makedirs(directory, exist_ok=True)
        # cached entry count: the sender polls the backlog every loop
        # pass and status()/the scrape read it under the send-path lock
        # — neither may cost a directory scan
        self._count = sum(1 for n in os.listdir(directory)
                          if not n.startswith("."))

    def put(self, record: dict) -> str:
        with self._mu:
            if self._count >= self.limit:
                raise QueueStoreFull("queue store full")
            key = f"{time.time_ns():020d}-{uuid.uuid4().hex[:8]}.json"
            tmp = os.path.join(self.dir, f".{key}.tmp")
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, os.path.join(self.dir, key))
            self._count += 1
            return key

    def list(self) -> list[str]:
        with self._mu:
            return sorted(n for n in os.listdir(self.dir)
                          if not n.startswith("."))

    def get(self, key: str) -> dict:
        with open(os.path.join(self.dir, key)) as f:
            return json.load(f)

    def delete(self, key: str) -> None:
        with self._mu:
            try:
                os.remove(os.path.join(self.dir, key))
            except FileNotFoundError:
                return
            self._count -= 1

    def __len__(self) -> int:
        with self._mu:
            return self._count


class DeliveryTarget:
    """Store-and-forward delivery engine for ONE egress destination.

    Subclasses implement ``_deliver(record)`` (raise on failure);
    construction wires the knobs.  ``target_type`` names the plane
    (``logger`` / ``audit`` / ``notify``), ``name`` the destination
    (endpoint or ARN) — together they label every metric and status
    row."""

    QUEUE_SIZE = 10000

    # inline-mode failures with nowhere to store are wrapped in this
    # (events targets set it to TargetError — the type their callers
    # historically caught)
    ERROR_CLS = Exception

    def __init__(self, target_type: str, name: str, *,
                 queue_limit: int | None = None,
                 store_dir: Optional[str] = None,
                 store_limit: int = 10000,
                 max_attempts: int = 3, offline_after: int = 3,
                 cooldown_s: float = 3.0,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 sync: bool = False,
                 rng: random.Random | None = None,
                 sleep=time.sleep, clock=time.monotonic, log=None):
        self.target_type = target_type
        self.name = name
        self.store = QueueStore(store_dir, limit=store_limit) \
            if store_dir else None
        self.max_attempts = max(1, int(max_attempts))
        self.offline_after = max(1, int(offline_after))
        self.cooldown_s = cooldown_s
        self._policy = RetryPolicy(attempts=self.max_attempts,
                                   base_s=backoff_base_s,
                                   cap_s=backoff_cap_s, rng=rng,
                                   sleep=sleep)
        self._sync = sync            # tests: deliver inline, raise through
        self._clock = clock
        self._log = log              # log_once-shaped callable or None
        self._q: "queue.Queue" = queue.Queue(queue_limit
                                             or self.QUEUE_SIZE)
        self._mu = mtlock("egress.target")
        # serializes every delivery attempt (worker loop, auto-replay,
        # and the admin-triggered sync replay()) so one record is never
        # delivered twice by two drains racing over the store
        self._deliver_mu = mtlock("egress.deliver")
        self._state = ONLINE
        self._consecutive = 0
        self._opened_at = 0.0
        # records accepted into the queue but not yet fully processed
        # (delivered/spilled/dead-lettered) — counted at ENQUEUE time so
        # flush() can never observe the dequeued-but-unmarked window
        self._pending = 0
        self._closed = False
        self._worker: threading.Thread | None = None
        self.sent = 0
        self.failed = 0              # failed delivery ATTEMPTS
        self.dropped = 0             # discarded before any attempt
        self.dead_letter = 0         # abandoned after attempts/store-full
        self.store_errors = 0        # store I/O faults (NOT deliveries)
        self.last_error = ""
        self.last_error_at = 0.0     # wall clock, status reporting
        self.last_success_at = 0.0
        self._hist = [0] * (len(DELIVERY_BUCKETS) + 1) + [0.0]

    # -- the one method subclasses provide -----------------------------

    def _deliver(self, record: dict) -> None:  # pragma: no cover - iface
        raise NotImplementedError

    # -- request-path entry --------------------------------------------

    def send(self, record: Dict[str, Any]) -> None:
        """Non-blocking enqueue; never raises into the caller (except
        in sync mode, which exists for tests only)."""
        if self._sync:
            self._send_inline(record)
            return
        # closed-check + worker-start + enqueue are one atomic decision:
        # a send racing close() must either land before the drain (the
        # worker spills it) or be counted dropped — never sit uncounted
        # in a queue nothing will ever empty
        with self._mu:
            if self._closed:
                self.dropped += 1
                return
            self._ensure_worker_locked()
            self._pending += 1
            try:
                self._q.put_nowait(record)
                return
            except queue.Full:
                self._pending -= 1
        # bounded spill straight to disk keeps the record; only a
        # storeless (or store-full) target drops under overload
        if not self._spill(record):
            with self._mu:
                self.dropped += 1

    def _send_inline(self, record: Dict[str, Any]) -> None:
        """Sync mode (tests + wire-conformance tiers): the pre-engine
        StoreForwardTarget semantics — deliver now on the caller's
        thread, store on failure, raise when there is nowhere to keep
        the record."""
        t0 = time.perf_counter()
        try:
            self._deliver(record)
        except Exception as e:  # noqa: BLE001 — any failure is a miss
            self._on_failure(e)
            if self._spill(record):
                return
            if isinstance(e, self.ERROR_CLS):
                raise
            raise self.ERROR_CLS(str(e)) from e
        self._observe(time.perf_counter() - t0)
        self._on_success()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Registration-time hook (EgressRegistry.register): a disk
        backlog left by a previous process starts replaying without
        waiting for new traffic to wake a sender.  Deliberately NOT
        called from __init__ — the sender must not race a subclass
        constructor still wiring its endpoint fields."""
        if self.store is not None and len(self.store):
            self._ensure_worker()

    def _ensure_worker(self) -> None:
        with self._mu:
            self._ensure_worker_locked()

    def _ensure_worker_locked(self) -> None:
        if self._worker is not None or self._closed:
            return
        self._worker = threading.Thread(
            target=self._run, daemon=True,
            name=f"mt-egress-{self.target_type}")
        self._worker.start()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the sender (sentinel + join); queued records spill to
        the store when one exists so shutdown never silently loses a
        store-backed record."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            w = self._worker
        if w is None:
            return
        try:
            self._q.put_nowait(_CLOSE)
        except queue.Full:
            pass        # worker is draining; it checks _closed per loop
        w.join(timeout=timeout)

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort wait for the in-memory queue (and the in-flight
        record) to finish processing — delivered, spilled, or
        dead-lettered."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                idle = self._pending == 0
            if idle:
                return
            time.sleep(0.01)

    # -- sender loop -----------------------------------------------------

    def _run(self) -> None:
        while True:
            if self._closed:
                self._drain_close()
                return
            try:
                timeout = self._idle_timeout()
                try:
                    item = self._q.get(timeout=timeout) \
                        if timeout is not None else self._q.get()
                except queue.Empty:
                    item = None
                if item is _CLOSE:
                    self._drain_close()
                    return
                if item is not None:
                    try:
                        self._process(item)
                    finally:
                        with self._mu:
                            self._pending -= 1
                self._replay_ready()
            except Exception as e:  # noqa: BLE001 — a store I/O surprise
                # (deleted queue_dir, ENOSPC) must not silently kill the
                # sender forever.  Delivery catches its own errors, so
                # this only sees store/bookkeeping faults — counted and
                # logged SEPARATELY, never fed into the delivery state
                # machine (the endpoint may be perfectly healthy)
                self._note_store_error(e)
                time.sleep(0.25)

    def _idle_timeout(self) -> float | None:
        """How long the worker may park on the queue: forever when
        online with an empty store; briefly when a probe window or a
        replay backlog needs servicing without new traffic."""
        with self._mu:
            state = self._state
            opened = self._opened_at
        backlog = self.store is not None and len(self.store) > 0
        if state == ONLINE:
            return 0.05 if backlog else None
        remaining = self.cooldown_s - (self._clock() - opened)
        if remaining <= 0 and not backlog:
            # cooled down with nothing to replay: park — the next
            # record to arrive is the half-open probe (an offline
            # storeless target must not spin at the poll floor forever)
            return None
        return max(0.01, min(remaining, 0.25))

    def _process(self, record: dict) -> None:
        attempt = 0
        while True:
            with self._deliver_mu:
                if attempt == 0 and not self._may_attempt():
                    self._spill_or_dead_letter(record)
                    return
                if self._try_deliver(record):
                    return
            attempt += 1
            with self._mu:
                still_online = self._state == ONLINE
                closing = self._closed
            # a close() mid-retry bounds shutdown to the attempt in
            # flight: the record spills NOW instead of burning the
            # remaining attempts/backoffs past the close timeout
            if closing or not still_online \
                    or attempt >= self.max_attempts:
                break
            # backoff OUTSIDE the delivery mutex (lock-discipline):
            # each attempt is still single-flight, but a synchronous
            # replay()/admin drain no longer stalls behind this
            # record's whole retry schedule
            self._policy.wait(attempt - 1)
        with self._deliver_mu:
            self._spill_or_dead_letter(record)

    def _may_attempt(self) -> bool:
        """Online always; offline only once the cooldown elapsed — that
        one admitted delivery IS the half-open probe.

        Deliberately NOT parallel/rpc.py's CircuitBreaker: that one
        latches its half-open probe because RPC callers race for it;
        here ``_deliver_mu`` makes delivery single-flight already, and
        a latch would wedge the machine whenever an admitted probe
        reports nothing (e.g. a store drain that dead-letters every
        corrupt record without a delivery attempt)."""
        with self._mu:
            if self._state == ONLINE:
                return True
            if self._clock() - self._opened_at >= self.cooldown_s:
                self._state = PROBING
                return True
            return False

    def _try_deliver(self, record: dict) -> bool:
        t0 = time.perf_counter()
        try:
            self._deliver(record)
        except Exception as e:  # noqa: BLE001 — any failure is a miss
            self._on_failure(e)
            return False
        self._observe(time.perf_counter() - t0)
        self._on_success()
        return True

    def _on_failure(self, e: Exception) -> None:
        with self._mu:
            self.failed += 1
            self._consecutive += 1
            self.last_error = f"{type(e).__name__}: {e}"
            self.last_error_at = time.time()
            went_offline = False
            if self._state == PROBING or (
                    self._state == ONLINE
                    and self._consecutive >= self.offline_after):
                went_offline = self._state == ONLINE
                self._state = OFFLINE
                self._opened_at = self._clock()
        if went_offline:
            self._log_transition(offline=True)

    def _on_success(self) -> None:
        with self._mu:
            self.sent += 1
            self._consecutive = 0
            self.last_success_at = time.time()
            recovered = self._state != ONLINE
            self._state = ONLINE
        if recovered:
            self._log_transition(offline=False)

    def _observe(self, seconds: float) -> None:
        with self._mu:
            for i, ub in enumerate(DELIVERY_BUCKETS):
                if seconds <= ub:
                    self._hist[i] += 1
            self._hist[len(DELIVERY_BUCKETS)] += 1
            self._hist[-1] += seconds

    def _spill(self, record: dict) -> bool:
        """Persist a record to the disk store; True when it got there.
        A full store is the expected dead-letter path; any OTHER put
        failure is a store I/O fault — counted and logged so a climbing
        dead-letter count is diagnosable (overflow vs broken store)."""
        if self.store is None:
            return False
        try:
            self.store.put(record)
            return True
        except QueueStoreFull:
            return False
        except Exception as e:  # noqa: BLE001 — unwritable store
            self._note_store_error(e)
            return False

    def _note_store_error(self, e: Exception) -> None:
        with self._mu:
            self.store_errors += 1
            self.last_error = f"{type(e).__name__}: {e}"
            self.last_error_at = time.time()
        self._log_once("ERROR",
                       f"egress target {self.target_type}/{self.name} "
                       f"store error: {e}",
                       f"egress-store-{self.target_type}-{self.name}")

    def _spill_or_dead_letter(self, record: dict) -> None:
        """A record that exhausted its attempts (or arrived offline):
        keep it in the store, else dead-letter it — counted, never
        blocking, never raised."""
        if not self._spill(record):
            with self._mu:
                self.dead_letter += 1

    # -- replay -----------------------------------------------------------

    def _replay_ready(self) -> None:
        """Background replay: drain the store while deliveries succeed.
        When offline, the first attempt is the half-open probe; fresh
        queue traffic preempts the drain (the store resumes next
        round)."""
        if self.store is None or self._closed:
            return
        if not len(self.store):
            return
        with self._deliver_mu:
            if not self._may_attempt():
                return
            self._drain_store(preempt_on_traffic=True)

    def replay(self) -> int:
        """Synchronous drain of the disk store (the admin
        ``targets/replay`` action and tests); returns how many records
        got through, stopping at the first failure."""
        if self.store is None:
            return 0
        with self._deliver_mu:
            return self._drain_store(preempt_on_traffic=False)

    def _drain_store(self, preempt_on_traffic: bool) -> int:
        """Deliver stored records in order until one fails; corrupt
        entries dead-letter.  Caller holds ``_deliver_mu`` (the listing
        must not race another drain).  With ``preempt_on_traffic``,
        fresh queue records interrupt the drain — live telemetry beats
        backlog; the store resumes next round."""
        n = 0
        for key in self.store.list():
            try:
                rec = self.store.get(key)
            except Exception:  # noqa: BLE001 — corrupt store entry
                self.store.delete(key)
                with self._mu:
                    self.dead_letter += 1
                continue
            if not self._try_deliver(rec):
                break
            self.store.delete(key)
            n += 1
            if preempt_on_traffic and self._q.qsize():
                break
        return n

    def _drain_close(self) -> None:
        """Shutdown drain: move queued records to the store (counted
        dropped when there is none)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is _CLOSE:
                continue
            try:
                if not self._spill(item):
                    with self._mu:
                        self.dropped += 1
            finally:
                with self._mu:
                    self._pending -= 1

    # -- introspection ----------------------------------------------------

    def _log_once(self, level: str, message: str, key: str) -> None:
        log = self._log
        if log is None:
            from .logger import GLOBAL as _lg
            log = _lg.log_once
        try:
            log(level, message, dedup_key=key)
        except Exception:  # noqa: BLE001 — logging never breaks delivery
            pass

    def _log_transition(self, offline: bool) -> None:
        ident = f"{self.target_type}/{self.name}"
        if offline:
            self._log_once("ERROR",
                           f"egress target {ident} is offline: "
                           f"{self.last_error}",
                           f"egress-offline-{ident}")
        else:
            self._log_once("INFO",
                           f"egress target {ident} is back online",
                           f"egress-online-{ident}")

    @property
    def online(self) -> bool:
        with self._mu:
            return self._state == ONLINE

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def delivery_hist(self) -> tuple:
        """(buckets, cumulative counts + count, sum) for the scrape."""
        with self._mu:
            return DELIVERY_BUCKETS, list(self._hist[:-1]), self._hist[-1]

    def status(self) -> Dict[str, Any]:
        """One row of the admin ``targets`` route (`mc admin info`
        target-status analog)."""

        def iso(ts: float) -> str:
            if not ts:
                return ""
            return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))

        with self._mu:
            return {
                "type": self.target_type,
                "target": self.name,
                "state": self._state,
                "online": self._state == ONLINE,
                "queued": self._q.qsize(),
                "stored": len(self.store) if self.store is not None else 0,
                "sent": self.sent,
                "failed": self.failed,
                "dropped": self.dropped,
                "deadLettered": self.dead_letter,
                "storeErrors": self.store_errors,
                "lastError": self.last_error,
                "lastErrorTime": iso(self.last_error_at),
                "lastSuccessTime": iso(self.last_success_at),
            }


class EgressRegistry:
    """The server's directory of live delivery targets — what the
    scrape exports and the admin ``targets``/``targets/replay`` routes
    walk.  Empty registry ⇒ zero egress cost and zero ``mt_target_*``
    families (the idle contract)."""

    def __init__(self):
        self._mu = mtlock("egress.registry")
        self._targets: Dict[tuple, DeliveryTarget] = {}

    def register(self, target: DeliveryTarget) -> DeliveryTarget:
        with self._mu:
            self._targets[(target.target_type, target.name)] = target
        target.start()      # boot-time disk backlog replays immediately
        return target

    def remove(self, target: DeliveryTarget) -> None:
        with self._mu:
            self._targets.pop((target.target_type, target.name), None)

    def targets(self) -> List[DeliveryTarget]:
        with self._mu:
            return [self._targets[k] for k in sorted(self._targets)]

    def status(self) -> List[Dict[str, Any]]:
        return [t.status() for t in self.targets()]

    def replay_all(self) -> Dict[str, int]:
        """Kick a synchronous replay on every store-backed target;
        {"type/name": records delivered}."""
        return {f"{t.target_type}/{t.name}": t.replay()
                for t in self.targets() if t.store is not None}

    def close_all(self) -> None:
        for t in self.targets():
            try:
                t.close()
            except Exception:  # noqa: BLE001 — shutdown must proceed
                pass
