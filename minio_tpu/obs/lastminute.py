"""Last-minute rolling latency stats (cmd/last-minute.go lastMinuteLatencies
+ madmin TopAPIs/TopDrives role).

A 60x1s sliding window of (count, total-ns, bytes) per labelled
operation — one :class:`OpWindows` per drive (keyed by storage op) and
one per S3 server (keyed by API name).  The windows drive:

  * the ``mt_node_disk_latency_*`` / ``mt_s3_api_last_minute_*`` gauge
    families computed at scrape time (admin/metrics.py);
  * slow-drive detection (storage/health.py slow_drives): a drive whose
    p50 exceeds a configurable multiple of the set median is FLAGGED in
    health/metrics, never ejected;
  * the admin ``top`` endpoint (hottest APIs, slowest drives).

Recording is lock-free by design ("lock-cheap"): slot updates are plain
list-int mutations under the GIL; a concurrent slot rotation can lose a
handful of samples, which is fine for minute-granularity statistics —
the storage hot path must never serialize on an observability lock.
p50 comes from a 64-sample overwrite ring per window; it reads as 0
whenever the last minute saw no traffic, so an idle-but-once-slow drive
is never flagged forever.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Tuple

_SLOTS = 60
_RESERVOIR = 64


class Window:
    """One operation's 60x1s window + latency sample ring."""

    __slots__ = ("marks", "counts", "totals", "nbytes", "samples",
                 "sample_marks", "_si")

    def __init__(self):
        self.marks = [-1] * _SLOTS      # epoch second owning each slot
        self.counts = [0] * _SLOTS
        self.totals = [0] * _SLOTS      # ns
        self.nbytes = [0] * _SLOTS
        self.samples = [0] * _RESERVOIR
        self.sample_marks = [-1] * _RESERVOIR
        self._si = 0

    def record(self, duration_ns: int, nbytes: int = 0,
               now_s: float | None = None) -> None:
        sec = int(time.monotonic() if now_s is None else now_s)
        i = sec % _SLOTS
        if self.marks[i] != sec:        # slot aged out: reclaim it
            self.marks[i] = sec
            self.counts[i] = 0
            self.totals[i] = 0
            self.nbytes[i] = 0
        self.counts[i] += 1
        self.totals[i] += duration_ns
        self.nbytes[i] += nbytes
        si = self._si
        self.samples[si] = duration_ns
        self.sample_marks[si] = sec
        self._si = (si + 1) % _RESERVOIR

    def total(self, now_s: float | None = None) -> Tuple[int, int, int]:
        """(count, total_ns, bytes) over the live 60s window."""
        sec = int(time.monotonic() if now_s is None else now_s)
        lo = sec - (_SLOTS - 1)
        c = t = b = 0
        for i in range(_SLOTS):
            m = self.marks[i]
            if m >= 0 and lo <= m <= sec:   # -1 = never-written sentinel
                c += self.counts[i]
                t += self.totals[i]
                b += self.nbytes[i]
        return c, t, b

    def live_samples(self, now_s: float | None = None) -> list[int]:
        sec = int(time.monotonic() if now_s is None else now_s)
        lo = sec - (_SLOTS - 1)
        return [self.samples[i] for i in range(_RESERVOIR)
                if self.sample_marks[i] >= 0
                and lo <= self.sample_marks[i] <= sec]

    def p50(self, now_s: float | None = None) -> int:
        """Median of the last-minute latency samples (0 when idle)."""
        live = self.live_samples(now_s)
        if not live:
            return 0
        live.sort()
        return live[len(live) // 2]

    def p99(self, now_s: float | None = None) -> int:
        """Nearest-rank tail estimate from the same 64-sample
        reservoir as p50 (0 when idle) — an estimate by construction
        (the reservoir overwrites), good enough for the burn-rate
        rules that only need 'the tail moved'."""
        live = self.live_samples(now_s)
        if not live:
            return 0
        live.sort()
        return live[min(len(live) - 1, int(0.99 * (len(live) - 1) + 0.5))]


class OpWindows:
    """A labelled family of windows: one per operation/API name."""

    __slots__ = ("label", "windows")

    def __init__(self, label: str = ""):
        self.label = label
        self.windows: Dict[str, Window] = {}

    def record(self, op: str, duration_ns: int, nbytes: int = 0,
               now_s: float | None = None) -> None:
        w = self.windows.get(op)
        if w is None:
            # racing creators: last assignment wins, one lost sample
            w = self.windows[op] = Window()
        w.record(duration_ns, nbytes, now_s)

    def totals(self, now_s: float | None = None
               ) -> Dict[str, Tuple[int, int, int]]:
        """{op: (count, total_ns, bytes)} for ops live in the window."""
        out = {}
        for op, w in list(self.windows.items()):
            c, t, b = w.total(now_s)
            if c:
                out[op] = (c, t, b)
        return out

    def p50_all(self, now_s: float | None = None) -> int:
        """Median over every op's live samples combined — the per-drive
        latency figure slow-drive detection compares across a set."""
        merged: list[int] = []
        for w in list(self.windows.values()):
            merged.extend(w.live_samples(now_s))
        if not merged:
            return 0
        merged.sort()
        return merged[len(merged) // 2]

    def p99_all(self, now_s: float | None = None) -> int:
        """Nearest-rank tail over every op's live samples combined —
        the per-drive tail figure beside :meth:`p50_all`."""
        merged: list[int] = []
        for w in list(self.windows.values()):
            merged.extend(w.live_samples(now_s))
        if not merged:
            return 0
        merged.sort()
        return merged[min(len(merged) - 1,
                          int(0.99 * (len(merged) - 1) + 0.5))]


def top_entries(stats: OpWindows, now_s: float | None = None
                ) -> list[dict]:
    """Scrape-shaped summary rows sorted hottest-first (by count)."""
    rows = []
    for op, (c, t, b) in stats.totals(now_s).items():
        rows.append({"name": op, "count": c, "avg_ns": t // max(c, 1),
                     "bytes": b})
    rows.sort(key=lambda r: r["count"], reverse=True)
    return rows


def drive_windows(disks: Iterable) -> Dict[str, OpWindows]:
    """{endpoint: OpWindows} for every LOCAL drive in ``disks`` that
    records latencies (remote drives report on their owning node,
    exactly like the reference's per-node disk metrics)."""
    out: Dict[str, OpWindows] = {}
    for d in disks:
        if d is None:
            continue
        lm = getattr(d, "latency", None)
        if isinstance(lm, OpWindows):
            out[lm.label] = lm
    return out
