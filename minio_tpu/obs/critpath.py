"""Quorum critical-path attribution (the tail-at-scale discipline).

Quorum systems have a distinctive latency law: the k-th fastest of n
children determines completion, so mean per-drive latency is the wrong
signal — what matters is which child *gated* each fan-out and how far
the stragglers trailed the quorum point (Dean & Barroso's tail-at-scale
argument applied to erasure fan-outs; Dapper's critical-path analysis
applied to span trees).

Every quorum reduction point — the erasure write fan-out and read
quorum (objectlayer/erasure_object.py), the writer-plane drain
(storage/writers.py), peer fan-outs over internode RPC
(parallel/peer.py) — calls :func:`record` with its children's
completion times.  One call produces the three surfaces the ISSUE
names:

  * scrape families ``mt_quorum_gating_total{plane,drive}`` (which
    child the fan-out wall ended on) and
    ``mt_quorum_straggler_seconds{plane}`` (how far the tail trailed
    the quorum-deciding k-th completion — the time a quorum-aware
    commit plane could shave, the evidence ROADMAP's group-commit item
    needs);
  * a ``gating`` span in the causal tree (compact ring tuple always;
    a full span dict only when a deep-trace consumer is active);
  * a compact per-request row on the armed StageClock, rendered into
    the request's flight-recorder record.

Reconciliation contract: ``wall_ns`` is measured with the same
monotonic clock as the StageClock stage that encloses the reduction,
and the recorded child durations are offsets inside it — so
``kth_ns <= wall_ns <= stage_ns`` holds exactly (pinned by
tests/test_trace_tree.py) the same way the serial stage vector plus
``other`` reconciles with the request total.

Idle contract: with no deep-trace consumer, one :func:`record` call is
a sort of the (few) completion offsets, two metric updates, one
compact ring append, and one list append on the clock — no dict is
built on the hot path.
"""

from __future__ import annotations

import time

from ..admin.metrics import GLOBAL as _metrics
from . import stages as _stages
from . import trace as _trace

# straggler-trail buckets: trails run from microseconds (tmpfs) to the
# hundreds of ms a genuinely sick drive adds
STRAGGLER_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

# compact gating-row layout (StageClock.gatings + the span ring's
# ``extra`` slot; dict-shaped rows are rendered at query time)
G_PLANE, G_K, G_N, G_DRIVE, G_KTH_DRIVE, G_KTH_NS, G_WALL_NS, \
    G_TRAIL_NS = range(8)


def drive_label(disk) -> str:
    """One attribution string per child: a local drive's endpoint, a
    remote drive/peer client's endpoint, else the repr tail."""
    for attr in ("_endpoint", "endpoint"):
        v = getattr(disk, attr, None)
        if callable(v):        # wrapper disks (HealthDisk, SlowDisk,
            try:               # RemoteStorage) expose endpoint()
                v = v()
            except Exception:  # noqa: BLE001 — label only, never fail an op
                continue
        if isinstance(v, str) and v:
            return v
    return type(disk).__name__


def render_row(row: tuple) -> dict:
    """Query-time dict shape for one compact gating row (flight
    recorder, trace-tree route, forensic bundles)."""
    return {
        "plane": row[G_PLANE],
        "k": row[G_K],
        "n": row[G_N],
        "drive": row[G_DRIVE],
        "kthDrive": row[G_KTH_DRIVE],
        "kthNs": row[G_KTH_NS],
        "wallNs": row[G_WALL_NS],
        "trailNs": row[G_TRAIL_NS],
    }


def record(plane: str, k: int, labels: list, ends_ns: list,
           t0_ns: int, errs: list | None = None) -> tuple | None:
    """Record one quorum reduction.

    ``labels[i]`` names child i; ``ends_ns[i]`` is its completion in
    absolute monotonic ns (0/None = never completed); ``errs[i]``
    (when given) excludes failed children from the quorum ordering —
    an erroring drive cannot have been the quorum decider.  ``k`` is
    the reduction's quorum; ``t0_ns`` the fan-out start on the same
    monotonic clock.

    Returns the compact gating row, or None when fewer than k children
    completed (the reduction failed — there is no critical path to
    attribute)."""
    done = []
    for i, end in enumerate(ends_ns):
        if not end:
            continue
        if errs is not None and errs[i] is not None:
            continue
        # drain-style reductions (writer-plane settle vectors) may see
        # children that completed BEFORE the reduction began; clamping
        # to t0 keeps offsets non-negative and the reconciliation
        # invariant kth_ns <= wall_ns <= enclosing-stage_ns intact
        done.append((end if end > t0_ns else t0_ns, labels[i]))
    k = max(1, min(k, len(done))) if done else k
    if len(done) < max(1, k):
        return None
    done.sort()
    kth_end, kth_label = done[k - 1]
    last_end, last_label = done[-1]
    row = (plane, k, len(labels), last_label, kth_label,
           kth_end - t0_ns, last_end - t0_ns, last_end - kth_end)
    _metrics.inc("mt_quorum_gating_total",
                 {"plane": plane, "drive": last_label})
    _metrics.observe("mt_quorum_straggler_seconds", {"plane": plane},
                     row[G_TRAIL_NS] / 1e9, buckets=STRAGGLER_BUCKETS)
    _stages.note_gating(row)
    rid = _trace.get_request_id()
    if rid:
        sid = _trace.new_span_id()
        start = _trace.now_ns() - row[G_WALL_NS]
        # the gating row rides the ring's ``extra`` slot so assembled
        # trees carry it even when nobody subscribed during the breach
        _trace.ring_append(rid, sid, _trace.get_span_parent(),
                           "storage", f"quorum.{plane}", start,
                           row[G_WALL_NS], "", last_label, row)
        if _trace.active():
            _trace.publish_span(_trace.make_span(
                "storage", f"quorum.{plane}", start_ns=start,
                duration_ns=row[G_WALL_NS], span_id=sid,
                detail={"gating": render_row(row)}, _ring=False))
    return row


def now_ns() -> int:
    """The reduction clock: monotonic, shared with the StageClock so
    gating offsets reconcile with the stage vector."""
    return time.monotonic_ns()
