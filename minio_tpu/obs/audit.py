"""Audit logging (cmd/logger/audit.go).

One audit entry per completed API request, containing full request/response
metadata (but never payloads or credentials), delivered to configured
webhook targets.  Shape mirrors cmd/logger/message/audit.Entry: version,
deploymentid, time, trigger, api{name,bucket,object,status,statusCode,
rx,tx,timeToResponse}, remotehost, requestID, userAgent, requestQuery,
requestHeader, responseHeader.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

from .logger import HTTPLogTarget
from .trace import redact_headers, redact_query

VERSION = "1"


class AuditLog:
    def __init__(self, deployment_id: str = ""):
        self.deployment_id = deployment_id
        self.targets: List[HTTPLogTarget] = []
        self._mu = threading.Lock()
        # in-memory tail so tests and the admin API can inspect entries
        # without an HTTP target; DISARMED until someone actually reads
        # it (tail()), so a target-less server never builds audit dicts
        # per request just to fill a list nobody consumes.  Arming is a
        # LEASE, not a latch (the trace ring's _ring_until pattern): a
        # consumer that stops polling stops the per-request cost too.
        self.recent: List[Dict[str, Any]] = []
        self._recent_max = 256
        self._tail_until = 0.0

    TAIL_LEASE_S = 60.0

    @property
    def enabled(self) -> bool:
        """Entry construction is gated on this: a webhook target exists
        or the in-memory tail was read within the lease window."""
        if self.targets:
            return True
        until = self._tail_until
        return bool(until) and self._recent_max > 0 and \
            time.monotonic() < until

    def tail(self, n: int = 0) -> List[Dict[str, Any]]:
        """Read (and lease-arm) the in-memory tail — the admin
        ``audit-recent`` route and tests consume entries through this.
        The first call may return [] (nothing was recorded while
        disarmed); each call renews the lease."""
        self._tail_until = time.monotonic() + self.TAIL_LEASE_S
        with self._mu:
            return self.recent[-n:] if n > 0 else list(self.recent)

    def entry(self, *, api_name: str, bucket: str, obj: str,
              status_code: int, rx: int, tx: int, duration_ns: int,
              remote_host: str, request_id: str, user_agent: str,
              access_key: str, query: Dict[str, str],
              req_headers: Dict[str, str],
              resp_headers: Dict[str, str]) -> Dict[str, Any]:
        return {
            "version": VERSION,
            "deploymentid": self.deployment_id,
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "trigger": "incoming",
            "api": {
                "name": api_name,
                "bucket": bucket,
                "object": obj,
                "status": "OK" if status_code < 300 else "Failed",
                "statusCode": status_code,
                "rx": rx,
                "tx": tx,
                "timeToResponse": f"{duration_ns}ns",
            },
            "remotehost": remote_host,
            "requestID": request_id,
            "userAgent": user_agent,
            "accessKey": access_key,
            # presigned-URL credentials ride the query string — an
            # audit sink must never see a replayable signature
            "requestQuery": redact_query(query),
            "requestHeader": redact_headers(req_headers),
            "responseHeader": dict(resp_headers),
        }

    def publish(self, entry: Dict[str, Any]) -> None:
        with self._mu:
            self.recent.append(entry)
            if len(self.recent) > self._recent_max:
                del self.recent[: len(self.recent) - self._recent_max]
        for t in list(self.targets):
            try:
                t.send(entry)
            except Exception:   # noqa: BLE001 — audit delivery is best-effort
                pass


GLOBAL = AuditLog()
