"""Cluster flight recorder — always-on bounded forensic rings.

The black-box half of the request X-ray (obs/stages.py): when an SLO
row trips mid-soak the evidence (what the last thousand requests were,
which of them failed, what the threads/breakers/governor looked like)
must already be on hand — a trace subscription started *after* the
incident records the recovery, not the breach.  Each node keeps three
bounded rings, appended on the request path and queryable live through
the admin ``xray`` route (peer-aggregated like ``top``):

  * **request ring** — the last N completed requests as compact tuples
    (time, request-id, api, status, rx/tx bytes, duration, the serial
    stage vector from the StageClock);
  * **error ring** — the subset with status >= 400 (longer memory for
    rare failures: a 0.1% error rate would otherwise age out of the
    request ring in seconds under load);
  * **snapshot ring** — periodic system snapshots: all-thread stacks
    (the PR-3 sampler's dump primitive), memory-governor accounting,
    RPC breaker states, codec-batcher queue depths, thread count.

Idle contract: recording one request is two deque appends (bounded,
O(1), preallocated ring slots) plus integer bookkeeping — no dict is
built on the hot path; dict-shaped records are rendered at QUERY time.
Snapshots are taken at most once per ``snap_interval_s`` and on a
transient helper thread, so no request ever pays the stack walk and an
idle node takes no snapshots at all.
"""

from __future__ import annotations

import threading
import time
from collections import deque

_REQ_RING = 1024
_ERR_RING = 256
_SNAP_RING = 16
SNAP_INTERVAL_S = 60.0

# record tuple layout (compact on purpose — the hot path appends, the
# admin route renders):  (wall_ns, req_id, api, status, dur_ns, rx,
# tx, stages, async_stages, error, gating) where ``gating`` is the
# request's quorum critical-path rows (obs/critpath.py compact tuples)
_F_TIME, _F_RID, _F_API, _F_STATUS, _F_DUR, _F_RX, _F_TX, _F_STAGES, \
    _F_ASYNC, _F_ERR, _F_GATING = range(11)

# a giant streaming request crosses one reduction per batch — cap what
# one flight-recorder row renders so the xray reply stays bounded
_GATING_RENDER_CAP = 16


def system_snapshot(brief: bool = False) -> dict:
    """One point-in-time system snapshot: the evidence a forensic
    bundle or an OBD document wants about *this process right now*.
    ``brief`` skips the thread-stack dump (the xray route's default —
    stacks are big and usually only wanted inside bundles)."""
    from ..parallel import batcher as _batcher
    from ..parallel.rpc import breaker_states
    from ..utils.locktrace import render_metrics as _lock_metrics
    from ..utils.memgov import GOVERNOR
    snap: dict = {
        "time_ns": time.time_ns(),
        "threads": threading.active_count(),
        "memgov": GOVERNOR.stats(),
        "breakers": breaker_states(),
    }
    try:
        snap["codec_batch_depths"] = _batcher.GLOBAL.queue_depths() \
            if _batcher.GLOBAL.started() else {}
    except Exception:  # noqa: BLE001 — a snapshot must never fail
        snap["codec_batch_depths"] = {}
    try:
        snap["lock_graph"] = bool(_lock_metrics())
    except Exception:  # noqa: BLE001 — a snapshot must never fail
        snap["lock_graph"] = False
    if not brief:
        from . import profiling
        try:
            snap["stacks"] = profiling._threads_dump().decode(
                "utf-8", "replace")
        except Exception:  # noqa: BLE001 — a snapshot must never fail
            snap["stacks"] = ""
    return snap


class FlightRecorder:
    """One node's always-on rings (constructed per S3Server so embedded
    multi-server tests keep nodes apart, exactly like the audit log)."""

    def __init__(self, req_ring: int = _REQ_RING,
                 err_ring: int = _ERR_RING,
                 snap_ring: int = _SNAP_RING,
                 snap_interval_s: float = SNAP_INTERVAL_S):
        self.requests: deque = deque(maxlen=req_ring)
        self.errors: deque = deque(maxlen=err_ring)
        self.snapshots: deque = deque(maxlen=snap_ring)
        self.snap_interval_s = snap_interval_s
        self.records_total = 0          # lifetime (scrape counter)
        self.errors_total = 0
        self._last_snap = 0.0           # monotonic; 0 = never
        # held for the duration of one helper snapshot: the
        # non-blocking acquire makes the interval check race-free
        # (two requests crossing the interval spawn ONE helper)
        self._snap_mu = threading.Lock()

    # -- the hot path ---------------------------------------------------------

    def record(self, req_id: str, api: str, status: int, dur_ns: int,
               rx: int, tx: int, stages: tuple = (),
               async_stages: tuple = (), error: str = "",
               gating: tuple = ()) -> None:
        """Append one completed request (two bounded deque appends)."""
        rec = (time.time_ns(), req_id, api, status, dur_ns, rx, tx,
               stages, async_stages, error, gating)
        self.requests.append(rec)
        self.records_total += 1
        if status >= 400 or error:
            self.errors.append(rec)
            self.errors_total += 1
        now = time.monotonic()
        if now - self._last_snap >= self.snap_interval_s and \
                self._snap_mu.acquire(blocking=False):
            # at most one helper in flight (the lock is released by
            # the helper); the request thread never walks stacks
            self._last_snap = now
            threading.Thread(target=self._take_snapshot, daemon=True,
                             name="mt-flightrec-snap").start()

    def _take_snapshot(self) -> None:
        try:
            self.snapshots.append(system_snapshot())
        except Exception:  # noqa: BLE001 — never surface from a helper
            pass
        finally:
            self._snap_mu.release()

    def snapshot_now(self, brief: bool = False) -> dict:
        """Synchronous snapshot (forensic bundles, xray ?snapshot=true):
        captured fresh and appended to the ring."""
        snap = system_snapshot(brief=brief)
        self.snapshots.append(snap)
        self._last_snap = time.monotonic()
        return snap

    # -- query ----------------------------------------------------------------

    @staticmethod
    def _render(rec: tuple) -> dict:
        gating = rec[_F_GATING] if len(rec) > _F_GATING else ()
        out = {
            "timeNs": rec[_F_TIME],
            "requestID": rec[_F_RID],
            "api": rec[_F_API],
            "status": rec[_F_STATUS],
            "durationNs": rec[_F_DUR],
            "rxBytes": rec[_F_RX],
            "txBytes": rec[_F_TX],
            "stages": dict(rec[_F_STAGES]),
            "asyncStages": dict(rec[_F_ASYNC]),
            **({"error": rec[_F_ERR]} if rec[_F_ERR] else {}),
        }
        if gating:
            from . import critpath as _critpath
            out["gating"] = [_critpath.render_row(g)
                             for g in gating[:_GATING_RENDER_CAP]]
        return out

    def query(self, api: str = "", min_duration_ms: float = 0.0,
              errors_only: bool = False, limit: int = 100) -> list[dict]:
        """Newest-first filtered records (the admin ``xray`` shape)."""
        ring = self.errors if errors_only else self.requests
        min_ns = int(min_duration_ms * 1e6)
        out: list[dict] = []
        for rec in reversed(ring):
            if api and rec[_F_API] != api:
                continue
            if min_ns and rec[_F_DUR] < min_ns:
                continue
            out.append(self._render(rec))
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        return {
            "requests": len(self.requests),
            "errors": len(self.errors),
            "snapshots": len(self.snapshots),
            "recordsTotal": self.records_total,
            "errorsTotal": self.errors_total,
        }

    def dump(self) -> dict:
        """Everything, rendered — the forensic-bundle payload."""
        return {
            "stats": self.stats(),
            "requests": [self._render(r) for r in self.requests],
            "errors": [self._render(r) for r in self.errors],
            "snapshots": list(self.snapshots),
        }
