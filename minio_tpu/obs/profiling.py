"""Cluster profiling (cmd/admin-handlers.go:496 StartProfilingHandler,
cmd/utils.go:286-340 getProfileData).

The reference starts pprof CPU/heap/block/mutex/goroutine profilers on
every node via peer RPC and later downloads a zip of the dumps.  The
Python-host equivalents:

* ``cpu``    -> cProfile (pstats dump)
* ``mem``    -> tracemalloc snapshot (top allocations, text)
* ``threads``-> live stack dump of all threads (goroutine-profile analog)

A profile session is process-global, like the reference's globalProfiler
map; starting a new session stops the previous one.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import sys
import threading
import traceback
import zipfile
from typing import Dict, Optional

PROFILER_TYPES = ("cpu", "mem", "threads")


class _Session:
    def __init__(self, kinds):
        self.kinds = kinds
        self.cpu: Optional[cProfile.Profile] = None
        self.mem_started = False


_current: Optional[_Session] = None
_mu = threading.Lock()


def start(kinds_csv: str = "cpu") -> list:
    """Start profilers; returns the list of started kinds."""
    global _current
    kinds = [k.strip() for k in kinds_csv.split(",") if k.strip()]
    bad = [k for k in kinds if k not in PROFILER_TYPES]
    if bad:
        raise ValueError(f"unknown profiler type(s): {','.join(bad)}")
    with _mu:
        if _current is not None:
            _stop_locked()
        sess = _Session(kinds)
        if "cpu" in kinds:
            sess.cpu = cProfile.Profile()
            sess.cpu.enable()
        if "mem" in kinds:
            import tracemalloc
            if not tracemalloc.is_tracing():
                tracemalloc.start()
            sess.mem_started = True
        _current = sess
    return kinds


def _threads_dump() -> bytes:
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon}) ---\n")
        frame = frames.get(t.ident or -1)
        if frame is not None:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue().encode()


def _stop_locked() -> Dict[str, bytes]:
    global _current
    sess, _current = _current, None
    dumps: Dict[str, bytes] = {}
    if sess is None:
        return dumps
    if sess.cpu is not None:
        sess.cpu.disable()
        buf = io.StringIO()
        pstats.Stats(sess.cpu, stream=buf).sort_stats(
            "cumulative").print_stats(100)
        dumps["profile-cpu.txt"] = buf.getvalue().encode()
        raw = io.BytesIO()
        # marshaled stats for offline tooling (pstats.Stats can reload it)
        sess.cpu.create_stats()
        import marshal
        marshal.dump(sess.cpu.stats, raw)
        dumps["profile-cpu.pstats"] = raw.getvalue()
    if sess.mem_started:
        import tracemalloc
        snap = tracemalloc.take_snapshot()
        lines = [str(s) for s in snap.statistics("lineno")[:100]]
        dumps["profile-mem.txt"] = "\n".join(lines).encode()
        tracemalloc.stop()
    if "threads" in sess.kinds:
        dumps["profile-threads.txt"] = _threads_dump()
    return dumps


def stop_zip() -> bytes:
    """Stop the session, return a zip of all dumps (cmd/utils.go:318
    builds the same shape: one file per node per profiler type)."""
    with _mu:
        dumps = _stop_locked()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for name, data in dumps.items():
            z.writestr(name, data)
    return buf.getvalue()


def running() -> list:
    with _mu:
        return list(_current.kinds) if _current else []
