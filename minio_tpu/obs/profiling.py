"""Cluster profiling (cmd/admin-handlers.go:496 StartProfilingHandler,
cmd/utils.go:286-340 getProfileData).

The reference starts pprof CPU/heap/block/mutex/goroutine profilers on
every node via peer RPC and later downloads a zip of the dumps.  The
Python-host equivalents:

* ``cpu``    -> cProfile (pstats dump; calling thread only) PLUS a
              sampling profiler over ``sys._current_frames()`` that
              covers EVERY thread — S3 workers, RPC handlers, the
              background planes — and emits a collapsed-stack
              (flamegraph-ready) ``profile-cpu-sampled.txt``
* ``mem``    -> tracemalloc snapshot (top allocations, text)
* ``threads``-> live stack dump of all threads (goroutine-profile analog)

cProfile hooks only the thread that enables it (the admin handler
thread), so a pstats dump alone shows an idle server no matter how hot
the worker pool runs; the wall-clock sampler is what sees the real
process, at a fixed ~5 ms stride whose cost is bounded by thread count,
not by request rate.

A profile session is process-global, like the reference's globalProfiler
map; starting a new session stops the previous one.
"""

from __future__ import annotations

import cProfile
import io
import os.path
import pstats
import sys
import threading
import traceback
import zipfile
from typing import Dict, Optional

PROFILER_TYPES = ("cpu", "mem", "threads")

SAMPLE_INTERVAL_S = 0.005


class _Sampler:
    """Wall-clock stack sampler over every live thread.

    Walks ``sys._current_frames()`` at a fixed interval and accumulates
    collapsed stacks (``frame;frame;frame count`` — the flamegraph.pl /
    speedscope input format).  Sampling is statistical: a thread parked
    in a C call (socket recv, device dispatch) is attributed to the
    Python frame that issued it, which is exactly the "where is the
    process spending wall time" answer cProfile cannot give for threads
    it never hooked."""

    def __init__(self, interval_s: float = SAMPLE_INTERVAL_S):
        self.interval_s = interval_s
        self.counts: Dict[str, int] = {}
        self.samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="mt-profile-sampler",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        own = threading.get_ident()
        names = {}      # thread ident -> name, refreshed per pass
        while not self._stop.wait(self.interval_s):
            names = {t.ident: t.name for t in threading.enumerate()}
            for tid, frame in list(sys._current_frames().items()):
                if tid == own:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < 128:
                    code = f.f_code
                    stack.append(
                        f"{os.path.basename(code.co_filename)}:"
                        f"{code.co_name}")
                    f = f.f_back
                stack.append(names.get(tid, f"thread-{tid}"))
                key = ";".join(reversed(stack))
                self.counts[key] = self.counts.get(key, 0) + 1
                self.samples += 1

    def stop(self) -> bytes:
        """Stop sampling, return the collapsed-stack dump."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        lines = [f"# {self.samples} samples @ {self.interval_s * 1e3:g}"
                 f" ms interval (collapsed stacks; feed to flamegraph)"]
        for stack in sorted(self.counts,
                            key=self.counts.get, reverse=True):
            lines.append(f"{stack} {self.counts[stack]}")
        return ("\n".join(lines) + "\n").encode()


class _Session:
    def __init__(self, kinds):
        self.kinds = kinds
        self.cpu: Optional[cProfile.Profile] = None
        self.sampler: Optional[_Sampler] = None
        self.mem_started = False


_current: Optional[_Session] = None
_mu = threading.Lock()


def start(kinds_csv: str = "cpu") -> list:
    """Start profilers; returns the list of started kinds."""
    global _current
    kinds = [k.strip() for k in kinds_csv.split(",") if k.strip()]
    bad = [k for k in kinds if k not in PROFILER_TYPES]
    if bad:
        raise ValueError(f"unknown profiler type(s): {','.join(bad)}")
    with _mu:
        if _current is not None:
            _stop_locked()
        sess = _Session(kinds)
        if "cpu" in kinds:
            sess.cpu = cProfile.Profile()
            sess.cpu.enable()
            sess.sampler = _Sampler()
            sess.sampler.start()
        if "mem" in kinds:
            import tracemalloc
            if not tracemalloc.is_tracing():
                tracemalloc.start()
            sess.mem_started = True
        _current = sess
    return kinds


def _threads_dump() -> bytes:
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon}) ---\n")
        frame = frames.get(t.ident or -1)
        if frame is not None:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue().encode()


def _stop_locked() -> Dict[str, bytes]:
    global _current
    sess, _current = _current, None
    dumps: Dict[str, bytes] = {}
    if sess is None:
        return dumps
    if sess.cpu is not None:
        sess.cpu.disable()
        buf = io.StringIO()
        pstats.Stats(sess.cpu, stream=buf).sort_stats(
            "cumulative").print_stats(100)
        dumps["profile-cpu.txt"] = buf.getvalue().encode()
        raw = io.BytesIO()
        # marshaled stats for offline tooling (pstats.Stats can reload it)
        sess.cpu.create_stats()
        import marshal
        marshal.dump(sess.cpu.stats, raw)
        dumps["profile-cpu.pstats"] = raw.getvalue()
    if sess.sampler is not None:
        # all-thread coverage: S3 workers / RPC / background planes
        dumps["profile-cpu-sampled.txt"] = sess.sampler.stop()
    if sess.mem_started:
        import tracemalloc
        snap = tracemalloc.take_snapshot()
        lines = [str(s) for s in snap.statistics("lineno")[:100]]
        dumps["profile-mem.txt"] = "\n".join(lines).encode()
        tracemalloc.stop()
    if "threads" in sess.kinds:
        dumps["profile-threads.txt"] = _threads_dump()
    return dumps


def stop_dumps() -> Dict[str, bytes]:
    """Stop the session, return {filename: dump} — the peer-RPC shape:
    the aggregating node renames each file ``<base>.<node>.<ext>`` and
    zips the whole cluster's dumps together (cmd/utils.go:286
    getProfileData)."""
    with _mu:
        return _stop_locked()


def zip_dumps(dumps: Dict[str, bytes]) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for name, data in dumps.items():
            z.writestr(name, data)
    return buf.getvalue()


def stop_zip() -> bytes:
    """Stop the session, return a zip of all dumps (cmd/utils.go:318
    builds the same shape: one file per node per profiler type)."""
    return zip_dumps(stop_dumps())


def running() -> list:
    with _mu:
        return list(_current.kinds) if _current else []
