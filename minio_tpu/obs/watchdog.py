"""SLO watchdog — the rule engine over the telemetry history rings.

Where the forensic engine (obs/forensic.py) explains a breach after
the fact, the watchdog predicts: declarative rules evaluated each
sampler tick (obs/history.py) over the history rings, with a
pending→firing→resolved alert state machine, per-rule cooldown/dedup,
JSON alert events through the egress ``DeliveryTarget`` plane
(``alert_webhook`` kvconfig target — store-and-forward and replay for
free), and a ``firing→forensic`` bridge so a configured rule invokes
the trigger engine with the rule name as trigger.

The rule catalog (``RULE_NAMES``):

* ``slo_burn_fast`` / ``slo_burn_slow`` — multi-window SLO burn rate
  (Google-SRE style): per-API error rate over the 5m/1h window
  divided by ``watchdog.slo_objective``; the fast window pages on a
  sharp burn (factor 14 ≈ 2% of a 30-day budget in one hour), the
  slow window on a sustained simmer (factor 6).
* ``drive_degrading`` — per-drive latency drift: each drive's
  last-minute p50 is smoothed with an EWMA and scored against the
  drive population with a robust (median + MAD) z-score, so a
  drifting-but-not-yet-slow drive raises an alert BEFORE the
  leave-one-out ``slow_drives()`` multiple flags it.  Firing also
  escalates the healer's bitrotscan scheduling (``request_deep``).
* ``breaker_flapping`` — internode breaker opens in the fast window.
* ``deadletter_growth`` — egress dead-letter growth per target.
* ``rebalance_stall`` — a rebalance cycle active across the stall
  window with zero byte progress.
* ``pool_days_to_full`` — linear trend on ``mt_pool_usage_bytes``
  against the pool's capacity share.
* ``tenant_burn`` — per-tenant SLO burn over the fast window: the
  metering plane's ``mt_tenant_errors_total`` mass divided by its
  ``mt_tenant_requests_total`` mass, against the same
  ``slo_objective``; one misbehaving access key pages by NAME
  instead of smearing its errors across the per-API burn rules.
* ``noisy_neighbor`` — per-tenant byte-share over the fast window:
  a tenant moving ≥ ``noisy_share`` of all metered bytes while at
  least ``noisy_min_tenants`` tenants are active (a lone tenant owns
  100% of the cluster by construction — that is not noise).

Idle contract: ``watchdog.enable=off`` (the default) means no engine,
no sampler thread, no ``mt_alert_*``/``mt_history_*`` family in the
scrape, and no ``watchdog.*`` span.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Tuple

from . import trace as _trace
from .history import (DEFAULT_FAMILIES, HistorySampler, TelemetryHistory,
                      breaker_sample)

# the rule catalog (the ``rule`` label on every mt_alert_* family; the
# obs-docs-drift analysis rule pins each name into docs/observability.md)
RULE_NAMES = (
    "slo_burn_fast",
    "slo_burn_slow",
    "drive_degrading",
    "breaker_flapping",
    "deadletter_growth",
    "rebalance_stall",
    "pool_days_to_full",
    "tenant_burn",
    "noisy_neighbor",
)

_RECENT_CAP = 64

_API_RE = re.compile(r'api="((?:[^"\\]|\\.)*)"')
_STATUS_RE = re.compile(r'status="(\d+)"')
_DRIVE_RE = re.compile(r'drive="((?:[^"\\]|\\.)*)"')
_TARGET_RE = re.compile(r'target="((?:[^"\\]|\\.)*)"')
_POOL_RE = re.compile(r'pool="((?:[^"\\]|\\.)*)"')
_TENANT_RE = re.compile(r'tenant="((?:[^"\\]|\\.)*)"')


def _mean(points: list) -> float:
    return sum(v for _, v in points) / len(points) if points else 0.0


def _median(values: list) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    return s[len(s) // 2]


class WatchdogSys:
    """One node's rule engine + alert store.  Owns the telemetry
    history and its sampler thread; every hook (clock, collector,
    delivery targets, forensic bridge, heal escalation) is injectable
    so the unit tier drives seeded series with no sleeps."""

    def __init__(self, *, history: TelemetryHistory | None = None,
                 interval_s: float = 10.0,
                 rules: Tuple[str, ...] = RULE_NAMES,
                 slo_objective: float = 0.01,
                 burn_fast_window_s: float = 300.0,
                 burn_slow_window_s: float = 3600.0,
                 burn_fast_factor: float = 14.0,
                 burn_slow_factor: float = 6.0,
                 burn_min_rps: float = 1.0,
                 drift_z: float = 3.5,
                 drift_alpha: float = 0.3,
                 drift_floor_ns: float = 1e6,
                 flap_threshold: float = 6.0,
                 deadletter_growth: float = 10.0,
                 stall_window_s: float = 300.0,
                 days_to_full: float = 7.0,
                 tenant_burn_factor: float = 6.0,
                 tenant_min_rps: float = 1.0,
                 noisy_share: float = 0.5,
                 noisy_min_tenants: int = 2,
                 noisy_min_bps: float = 1e6,
                 pending_for: int = 2,
                 cooldown_s: float = 300.0,
                 forensic_rules: Tuple[str, ...] = (),
                 collect: Callable[[], str] | None = None,
                 families: Tuple[str, ...] = DEFAULT_FAMILIES,
                 targets_fn: Callable[[], list] | None = None,
                 forensic_fn: Callable[[str, dict], object]
                 | None = None,
                 escalate_fn: Callable[[str], None] | None = None,
                 node_name: str = "",
                 clock: Callable[[], float] = time.time):
        self.history = history if history is not None \
            else TelemetryHistory()
        self.rules = tuple(r for r in rules if r in RULE_NAMES)
        self.slo_objective = max(1e-6, slo_objective)
        self.burn_fast_window_s = burn_fast_window_s
        self.burn_slow_window_s = burn_slow_window_s
        self.burn_fast_factor = burn_fast_factor
        self.burn_slow_factor = burn_slow_factor
        self.burn_min_rps = burn_min_rps
        self.drift_z = drift_z
        self.drift_alpha = min(1.0, max(0.01, drift_alpha))
        self.drift_floor_ns = max(1.0, drift_floor_ns)
        self.flap_threshold = flap_threshold
        self.deadletter_growth = deadletter_growth
        self.stall_window_s = stall_window_s
        self.days_to_full = days_to_full
        self.tenant_burn_factor = tenant_burn_factor
        self.tenant_min_rps = tenant_min_rps
        self.noisy_share = min(1.0, max(0.0, noisy_share))
        self.noisy_min_tenants = max(2, noisy_min_tenants)
        self.noisy_min_bps = max(0.0, noisy_min_bps)
        self.pending_for = max(1, pending_for)
        self.cooldown_s = cooldown_s
        self.forensic_rules = tuple(forensic_rules)
        self.targets_fn = targets_fn or (lambda: [])
        self.forensic_fn = forensic_fn
        self.escalate_fn = escalate_fn
        self.node_name = node_name
        self.clock = clock
        self.sampler = HistorySampler(
            collect or (lambda: ""), self.history,
            interval_s=interval_s, families=families,
            extra=breaker_sample, clock=clock)
        self.sampler.listeners.append(self.evaluate)
        self._mu = threading.Lock()
        # (rule, subject) -> live alert dict (state pending|firing)
        self._active: Dict[Tuple[str, str], dict] = {}
        self._resolved_at: Dict[Tuple[str, str], float] = {}
        self.recent: deque = deque(maxlen=_RECENT_CAP)
        self.evals: Dict[str, int] = {}
        self.transitions: Dict[Tuple[str, str], int] = {}
        self._ewma: Dict[str, float] = {}

    # -- construction ---------------------------------------------------------

    @classmethod
    def from_server(cls, srv) -> "WatchdogSys | None":
        """Build from the ``watchdog`` kvconfig subsystem; None when
        disabled (the idle contract) or on any bad knob."""
        from ..utils.kvconfig import parse_duration
        cfg = srv.config
        try:
            if (cfg.get("watchdog", "enable") or "off") != "on":
                return None

            def dur(key: str, default: str) -> float:
                return parse_duration(cfg.get("watchdog", key)
                                      or default,
                                      parse_duration(default, 10.0))

            def num(key: str, default: float) -> float:
                return float(cfg.get("watchdog", key) or default)

            rules = tuple(
                r for r in (cfg.get("watchdog", "rules") or "")
                .replace(" ", "").split(",") if r) or RULE_NAMES
            fams = DEFAULT_FAMILIES + tuple(
                f for f in (cfg.get("watchdog", "families") or "")
                .replace(" ", "").split(",") if f)
            forensic_rules = tuple(
                r for r in (cfg.get("watchdog", "forensic_rules")
                            or "").replace(" ", "").split(",") if r)
            from ..admin.handlers import _render_local

            def _targets() -> list:
                eg = getattr(srv, "egress", None)
                return [t for t in (eg.targets() if eg else [])
                        if getattr(t, "target_type", "") == "alert"]

            def _forensic(rule: str, detail: dict):
                fx = getattr(srv, "forensic", None)
                return fx.fire(rule, detail) if fx is not None else None

            def _escalate(drive: str) -> None:
                healer = getattr(srv, "healer", None)
                candidates = [healer] if healer is not None else [
                    s for s in getattr(srv, "_background", [])
                    if hasattr(s, "request_deep")]
                for h in candidates:
                    req = getattr(h, "request_deep", None)
                    if req is not None:
                        req(drive)

            return cls(
                interval_s=dur("interval", "10s"),
                rules=rules,
                slo_objective=num("slo_objective", 0.01),
                burn_fast_window_s=dur("burn_fast_window", "5m"),
                burn_slow_window_s=dur("burn_slow_window", "1h"),
                burn_fast_factor=num("burn_fast_factor", 14.0),
                burn_slow_factor=num("burn_slow_factor", 6.0),
                burn_min_rps=num("burn_min_rps", 1.0),
                drift_z=num("drift_z", 3.5),
                drift_alpha=num("drift_alpha", 0.3),
                drift_floor_ns=dur("drift_floor", "1ms") * 1e9,
                flap_threshold=num("flap_threshold", 6.0),
                deadletter_growth=num("deadletter_growth", 10.0),
                stall_window_s=dur("stall_window", "5m"),
                days_to_full=num("days_to_full", 7.0),
                tenant_burn_factor=num("tenant_burn_factor", 6.0),
                tenant_min_rps=num("tenant_min_rps", 1.0),
                noisy_share=num("noisy_share", 0.5),
                noisy_min_tenants=int(num("noisy_min_tenants", 2)),
                noisy_min_bps=num("noisy_min_bps", 1e6),
                pending_for=int(num("pending_for", 2)),
                cooldown_s=dur("cooldown", "5m"),
                forensic_rules=forensic_rules,
                collect=lambda: _render_local(srv),
                families=fams,
                targets_fn=_targets,
                forensic_fn=_forensic,
                escalate_fn=_escalate,
                node_name=getattr(srv, "node_name", ""))
        except Exception:  # noqa: BLE001 — a bad knob must not take
            return None    # the server down

    def start(self) -> None:
        self.sampler.start()

    def stop(self, timeout: float = 5.0) -> None:
        self.sampler.stop(timeout=timeout)

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, now_s: float | None = None) -> list:
        """One rule-engine pass over the rings; returns the state
        transitions it produced as (rule, subject, to) tuples (tests).
        Registered as a sampler tick listener."""
        now_s = self.clock() if now_s is None else now_s
        t0 = time.monotonic_ns()
        breaches: Dict[Tuple[str, str], Tuple[float, dict]] = {}
        for rule in self.rules:
            self.evals[rule] = self.evals.get(rule, 0) + 1
            fn = getattr(self, f"_rule_{rule}", None)
            if fn is None:
                continue
            try:
                for subject, value, detail in fn(now_s):
                    breaches[(rule, subject)] = (value, detail)
            except Exception:  # noqa: BLE001 — one rule's bug must not
                continue       # starve the others
        transitions = self._apply(now_s, breaches)
        if _trace.active():
            dur = time.monotonic_ns() - t0
            _trace.publish_span(_trace.make_span(
                "watchdog", "watchdog.evaluate",
                start_ns=_trace.now_ns() - dur, duration_ns=dur,
                detail={"rules": len(self.rules),
                        "breaches": len(breaches),
                        "transitions": len(transitions)}))
        return transitions

    def _apply(self, now_s: float, breaches) -> list:
        """The pending→firing→resolved state machine + cooldown/dedup.
        Delivery/bridging happens OUTSIDE the lock — a slow webhook
        queue must not block the admin alerts route."""
        fired: list[dict] = []
        resolved: list[dict] = []
        transitions: list[tuple] = []
        with self._mu:
            for key, (value, detail) in breaches.items():
                rule, subject = key
                alert = self._active.get(key)
                if alert is not None:
                    alert["value"] = value
                    alert["detail"] = detail
                    alert["lastSeen"] = now_s
                    if alert["state"] == "pending":
                        alert["ticks"] += 1
                        if alert["ticks"] >= self.pending_for:
                            alert["state"] = "firing"
                            alert["firedAt"] = now_s
                            self._count(rule, "firing")
                            transitions.append((rule, subject,
                                                "firing"))
                            fired.append(dict(alert))
                    continue
                # dedup: a just-resolved alert re-breaching inside the
                # cooldown stays silent (no pending churn either)
                res = self._resolved_at.get(key)
                if res is not None and now_s - res < self.cooldown_s:
                    continue
                alert = {"rule": rule, "subject": subject,
                         "state": "pending", "ticks": 1,
                         "value": value, "detail": detail,
                         "since": now_s, "lastSeen": now_s,
                         "firedAt": None}
                self._active[key] = alert
                self._count(rule, "pending")
                transitions.append((rule, subject, "pending"))
                if alert["ticks"] >= self.pending_for:
                    alert["state"] = "firing"
                    alert["firedAt"] = now_s
                    self._count(rule, "firing")
                    transitions.append((rule, subject, "firing"))
                    fired.append(dict(alert))
            for key in [k for k in self._active if k not in breaches]:
                rule, subject = key
                alert = self._active.pop(key)
                if alert["state"] == "firing":
                    alert["state"] = "resolved"
                    alert["resolvedAt"] = now_s
                    self._resolved_at[key] = now_s
                    self._count(rule, "resolved")
                    transitions.append((rule, subject, "resolved"))
                    self.recent.append(alert)
                    resolved.append(dict(alert))
                # a pending alert that un-breached just evaporates
        for alert in fired:
            self._deliver("firing", alert)
            if alert["rule"] in self.forensic_rules and \
                    self.forensic_fn is not None:
                try:
                    self.forensic_fn(alert["rule"], alert["detail"])
                except Exception:  # noqa: BLE001 — bridge is best-effort
                    pass
            if alert["rule"] == "drive_degrading" and \
                    self.escalate_fn is not None:
                try:
                    self.escalate_fn(alert["subject"])
                except Exception:  # noqa: BLE001 — same contract
                    pass
        for alert in resolved:
            self._deliver("resolved", alert)
        return transitions

    def _count(self, rule: str, to: str) -> None:
        self.transitions[(rule, to)] = \
            self.transitions.get((rule, to), 0) + 1

    def _deliver(self, state: str, alert: dict) -> None:
        event = {"type": "alert", "state": state,
                 "rule": alert["rule"], "subject": alert["subject"],
                 "value": alert["value"], "detail": alert["detail"],
                 "node": self.node_name,
                 "time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime())}
        for t in self.targets_fn():
            try:
                t.send(event)
            except Exception:  # noqa: BLE001 — alerting must never
                pass           # throw into the sampler

    # -- the rules ------------------------------------------------------------

    def _burn(self, now_s: float, window_s: float
              ) -> list[tuple[str, float, dict]]:
        """Per-API burn rate over one window: total 5xx mass / total
        request mass / objective.  Counters live in the rings as
        rates sampled by the SAME thread at the same ticks, so the
        ratio of window SUMs is the window's true error fraction —
        and an error series younger than the window (a counter is
        born on its first 5xx) implicitly contributes zeros for the
        ticks before its birth instead of inflating a mean computed
        over its own short support."""
        errors = self.history.query("mt_s3_requests_errors_total",
                                    window_s=window_s, step_s=1,
                                    agg="sum", now_s=now_s)
        totals = self.history.query("mt_s3_requests_api_total",
                                    window_s=window_s, step_s=1,
                                    agg="sum", now_s=now_s)
        rates = self.history.query("mt_s3_requests_api_total",
                                   window_s=window_s, step_s=1,
                                   agg="avg", now_s=now_s)
        err_by_api: Dict[str, float] = {}
        for (_, labels), points in errors.items():
            m = _STATUS_RE.search(labels)
            if m is None or int(m.group(1)) < 500:
                continue
            am = _API_RE.search(labels)
            api = am.group(1) if am else ""
            err_by_api[api] = err_by_api.get(api, 0.0) + \
                sum(v for _, v in points)
        out = []
        for key, points in totals.items():
            am = _API_RE.search(key[1])
            api = am.group(1) if am else ""
            rps = _mean(rates.get(key, []))
            mass = sum(v for _, v in points)
            if rps < self.burn_min_rps or mass <= 0:
                continue
            ratio = err_by_api.get(api, 0.0) / mass
            burn = ratio / self.slo_objective
            out.append((api, burn, {
                "windowSeconds": window_s, "requestsPerSecond": rps,
                "errorRate": round(ratio, 5),
                "objective": self.slo_objective,
                "burnRate": round(burn, 2)}))
        return out

    def _rule_slo_burn_fast(self, now_s: float):
        for api, burn, detail in self._burn(now_s,
                                            self.burn_fast_window_s):
            if burn >= self.burn_fast_factor:
                detail["threshold"] = self.burn_fast_factor
                yield api, burn, detail

    def _rule_slo_burn_slow(self, now_s: float):
        for api, burn, detail in self._burn(now_s,
                                            self.burn_slow_window_s):
            if burn >= self.burn_slow_factor:
                detail["threshold"] = self.burn_slow_factor
                yield api, burn, detail

    def _rule_drive_degrading(self, now_s: float):
        """EWMA-smoothed per-drive p50 scored against the population
        with a robust z (median + MAD, normal-consistency 0.6745);
        only the slower side alerts.  The MAD is floored by
        ``drift_floor_ns`` so a healthy all-identical population
        cannot turn measurement noise into infinite z."""
        data = self.history.query("mt_node_disk_latency_p50_ns",
                                  window_s=self.sampler.interval_s * 3,
                                  step_s=1, agg="last", now_s=now_s)
        latest: Dict[str, float] = {}
        for (_, labels), points in data.items():
            m = _DRIVE_RE.search(labels)
            if m is None or not points:
                continue
            latest[m.group(1)] = points[-1][1]
        for drive, v in latest.items():
            prev = self._ewma.get(drive)
            self._ewma[drive] = v if prev is None else \
                prev + self.drift_alpha * (v - prev)
        # drives that left the scrape stop contributing to the
        # population (their windows idled out)
        for drive in [d for d in self._ewma if d not in latest]:
            del self._ewma[drive]
        if len(self._ewma) < 3:
            return
        values = list(self._ewma.values())
        med = _median(values)
        mad = _median([abs(x - med) for x in values]) / 0.6745
        scale = max(mad, self.drift_floor_ns)
        for drive, x in sorted(self._ewma.items()):
            z = (x - med) / scale
            if x > med and z >= self.drift_z:
                yield drive, round(z, 2), {
                    "ewmaNs": int(x), "medianNs": int(med),
                    "madNs": int(mad), "z": round(z, 2),
                    "threshold": self.drift_z}

    def _rule_breaker_flapping(self, now_s: float):
        points_map = self.history.query(
            "mt_rpc_breaker_opens_total",
            window_s=self.burn_fast_window_s, step_s=1, agg="avg",
            now_s=now_s)
        opens = sum(_mean(p) for p in points_map.values()) \
            * self.burn_fast_window_s
        if opens >= self.flap_threshold:
            yield "", round(opens, 1), {
                "windowSeconds": self.burn_fast_window_s,
                "opens": round(opens, 1),
                "threshold": self.flap_threshold}

    def _rule_deadletter_growth(self, now_s: float):
        data = self.history.query("mt_target_dead_letter_total",
                                  window_s=self.burn_fast_window_s,
                                  step_s=1, agg="avg", now_s=now_s)
        for (_, labels), points in data.items():
            growth = _mean(points) * self.burn_fast_window_s
            if growth >= self.deadletter_growth:
                m = _TARGET_RE.search(labels)
                yield (m.group(1) if m else ""), round(growth, 1), {
                    "windowSeconds": self.burn_fast_window_s,
                    "deadLettered": round(growth, 1),
                    "threshold": self.deadletter_growth}

    def _rule_rebalance_stall(self, now_s: float):
        active = self.history.query("mt_rebalance_cycle_active",
                                    window_s=self.stall_window_s,
                                    step_s=1, agg="min", now_s=now_s)
        act_points = [p for pts in active.values() for p in pts]
        if len(act_points) < 3 or not all(v >= 1 for _, v in
                                          act_points):
            return
        span = act_points[-1][0] - act_points[0][0]
        if span < self.stall_window_s * 0.8:
            return          # not yet observed across the whole window
        moved = self.history.query("mt_rebalance_moved_bytes_total",
                                   window_s=self.stall_window_s,
                                   step_s=1, agg="avg", now_s=now_s)
        rate = sum(_mean(p) for p in moved.values())
        if rate <= 0:
            yield "", 0.0, {"windowSeconds": self.stall_window_s,
                            "bytesPerSecond": rate}

    def _rule_pool_days_to_full(self, now_s: float):
        """Least-squares slope over the coarse ring; capacity share is
        the cluster raw total split across pools — an approximation,
        but the alert is a trend warning, not an accountant."""
        usage = self.history.query("mt_pool_usage_bytes",
                                   window_s=86400.0, step_s=600,
                                   agg="last", now_s=now_s)
        usage = {k: v for k, v in usage.items() if len(v) >= 4}
        if not usage:
            return
        cap = self.history.query("mt_cluster_capacity_raw_total_bytes",
                                 window_s=3600.0, step_s=1, agg="last",
                                 now_s=now_s)
        cap_points = [p for pts in cap.values() for p in pts]
        if not cap_points:
            return
        cap_share = cap_points[-1][1] / max(1, len(usage))
        for (_, labels), points in usage.items():
            n = len(points)
            ts = [t for t, _ in points]
            vs = [v for _, v in points]
            tm, vm = sum(ts) / n, sum(vs) / n
            denom = sum((t - tm) ** 2 for t in ts)
            if denom <= 0:
                continue
            slope = sum((t - tm) * (v - vm)
                        for t, v in points) / denom   # bytes/s
            if slope <= 0:
                continue
            days = (cap_share - vs[-1]) / slope / 86400.0
            if 0 <= days <= self.days_to_full:
                m = _POOL_RE.search(labels)
                yield (m.group(1) if m else ""), round(days, 2), {
                    "daysToFull": round(days, 2),
                    "bytesPerDay": int(slope * 86400),
                    "capacityShareBytes": int(cap_share),
                    "usedBytes": int(vs[-1]),
                    "threshold": self.days_to_full}

    def _rule_tenant_burn(self, now_s: float):
        """Per-tenant burn rate over the fast window, same algebra as
        ``_burn`` but over the metering plane's tenant counters (which
        only count 5xx, so no status filter).  The ``_other`` overflow
        row is skipped — an alert naming nobody pages nobody."""
        errors = self.history.query("mt_tenant_errors_total",
                                    window_s=self.burn_fast_window_s,
                                    step_s=1, agg="sum", now_s=now_s)
        totals = self.history.query("mt_tenant_requests_total",
                                    window_s=self.burn_fast_window_s,
                                    step_s=1, agg="sum", now_s=now_s)
        rates = self.history.query("mt_tenant_requests_total",
                                   window_s=self.burn_fast_window_s,
                                   step_s=1, agg="avg", now_s=now_s)
        err_by_tenant: Dict[str, float] = {}
        for (_, labels), points in errors.items():
            m = _TENANT_RE.search(labels)
            if m is None:
                continue
            err_by_tenant[m.group(1)] = \
                err_by_tenant.get(m.group(1), 0.0) + \
                sum(v for _, v in points)
        for key, points in totals.items():
            m = _TENANT_RE.search(key[1])
            if m is None or m.group(1) == "_other":
                continue
            tenant = m.group(1)
            rps = _mean(rates.get(key, []))
            mass = sum(v for _, v in points)
            if rps < self.tenant_min_rps or mass <= 0:
                continue
            ratio = err_by_tenant.get(tenant, 0.0) / mass
            burn = ratio / self.slo_objective
            if burn >= self.tenant_burn_factor:
                yield tenant, round(burn, 2), {
                    "windowSeconds": self.burn_fast_window_s,
                    "requestsPerSecond": rps,
                    "errorRate": round(ratio, 5),
                    "objective": self.slo_objective,
                    "burnRate": round(burn, 2),
                    "threshold": self.tenant_burn_factor}

    def _rule_noisy_neighbor(self, now_s: float):
        """Per-tenant byte-share (rx+tx) over the fast window.  The
        ``_other`` overflow row counts toward the denominator (it IS
        traffic) but never alerts; a share only means anything once
        ``noisy_min_tenants`` distinct tenants are moving bytes."""
        bps_by_tenant: Dict[str, float] = {}
        for fam in ("mt_tenant_rx_bytes_total",
                    "mt_tenant_tx_bytes_total"):
            data = self.history.query(fam,
                                      window_s=self.burn_fast_window_s,
                                      step_s=1, agg="avg", now_s=now_s)
            for (_, labels), points in data.items():
                m = _TENANT_RE.search(labels)
                if m is None:
                    continue
                bps_by_tenant[m.group(1)] = \
                    bps_by_tenant.get(m.group(1), 0.0) + _mean(points)
        active = {t: b for t, b in bps_by_tenant.items() if b > 0}
        total_bps = sum(active.values())
        if len(active) < self.noisy_min_tenants or \
                total_bps < self.noisy_min_bps:
            return
        for tenant, bps in sorted(active.items()):
            if tenant == "_other":
                continue
            share = bps / total_bps
            if share >= self.noisy_share:
                yield tenant, round(share, 3), {
                    "windowSeconds": self.burn_fast_window_s,
                    "bytesPerSecond": int(bps),
                    "totalBytesPerSecond": int(total_bps),
                    "share": round(share, 3),
                    "activeTenants": len(active),
                    "threshold": self.noisy_share}

    # -- read back ------------------------------------------------------------

    def alerts(self) -> dict:
        """The admin ``alerts`` route body (active + recent), shared
        by the local route and the peer RPC."""
        with self._mu:
            active = sorted((dict(a) for a in self._active.values()),
                            key=lambda a: (a["rule"], a["subject"]))
            recent = list(self.recent)
        return {"active": active, "recent": recent,
                "rules": list(self.rules)}

    def metrics_state(self) -> dict:
        """Scrape-time snapshot for the mt_alert_*/mt_history_*
        families (admin/metrics.py _watchdog_metrics)."""
        with self._mu:
            firing = [(a["rule"], a["subject"])
                      for a in self._active.values()
                      if a["state"] == "firing"]
            return {"firing": firing,
                    "transitions": dict(self.transitions),
                    "evals": dict(self.evals),
                    "history": self.history.stats()}
