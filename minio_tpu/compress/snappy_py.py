"""Pure-Python snappy block codec + CRC32C — fallback engine.

Byte-identical wire format with native/snappy.cc (same greedy hash-table
matcher, same emit rules), so streams written by either engine decode in
the other and tests can cross-check them.
"""

from __future__ import annotations

_HASH_BITS = 14
_HASH_MUL = 0x1E35A7BD


def _emit_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _emit_literal(out: bytearray, src: bytes, start: int, end: int) -> None:
    n = end - start
    m = n - 1
    if m < 60:
        out.append(m << 2)
    elif m < (1 << 8):
        out.append(60 << 2)
        out.append(m)
    elif m < (1 << 16):
        out.append(61 << 2)
        out += m.to_bytes(2, "little")
    elif m < (1 << 24):
        out.append(62 << 2)
        out += m.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += m.to_bytes(4, "little")
    out += src[start:end]


def _emit_copy(out: bytearray, offset: int, length: int) -> None:
    while length >= 68:
        out.append((63 << 2) | 2)
        out += offset.to_bytes(2, "little")
        length -= 64
    if length > 64:
        out.append((59 << 2) | 2)
        out += offset.to_bytes(2, "little")
        length -= 60
    if length >= 12 or offset >= 2048:
        out.append(((length - 1) << 2) | 2)
        out += offset.to_bytes(2, "little")
    else:
        out.append(((offset >> 8) << 5) | ((length - 4) << 2) | 1)
        out.append(offset & 0xFF)


def _compress_fragment(src: bytes, out: bytearray) -> None:
    n = len(src)
    table: dict[int, int] = {}
    lit_start = 0
    if n >= 15:
        limit = n - 4
        table[(int.from_bytes(src[0:4], "little") * _HASH_MUL &
               0xFFFFFFFF) >> (32 - _HASH_BITS)] = 0
        i = 1
        while i <= limit:
            v = int.from_bytes(src[i:i + 4], "little")
            h = (v * _HASH_MUL & 0xFFFFFFFF) >> (32 - _HASH_BITS)
            cand = table.get(h, -1)
            table[h] = i
            if cand >= 0 and src[cand:cand + 4] == src[i:i + 4]:
                length = 4
                while i + length < n and \
                        src[cand + length] == src[i + length]:
                    length += 1
                if lit_start < i:
                    _emit_literal(out, src, lit_start, i)
                _emit_copy(out, i - cand, length)
                i += length
                lit_start = i
                if i <= limit:
                    v2 = int.from_bytes(src[i - 1:i + 3], "little")
                    table[(v2 * _HASH_MUL & 0xFFFFFFFF) >>
                          (32 - _HASH_BITS)] = i - 1
            else:
                i += 1
    if lit_start < n:
        _emit_literal(out, src, lit_start, n)


def compress_block_py(data: bytes) -> bytes:
    out = bytearray()
    _emit_uvarint(out, len(data))
    for off in range(0, len(data), 65536):
        _compress_fragment(data[off:off + 65536], out)
    return bytes(out)


def uncompressed_length_py(data: bytes) -> int:
    v, shift, i = 0, 0, 0
    while i < len(data) and shift < 64:
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v
        shift += 7
    raise ValueError("bad snappy preamble")


def decompress_block_py(data: bytes) -> bytes:
    # preamble
    want, shift, i = 0, 0, 0
    while True:
        if i >= len(data) or shift >= 64:
            raise ValueError("bad snappy preamble")
        b = data[i]
        i += 1
        want |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    n = len(data)
    while i < n:
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:
            length = (tag >> 2) + 1
            if length > 60:
                nb = length - 60
                if i + nb > n:
                    raise ValueError("truncated literal length")
                length = int.from_bytes(data[i:i + nb], "little") + 1
                i += nb
            if i + length > n or len(out) + length > want:
                raise ValueError("corrupt literal")
            out += data[i:i + length]
            i += length
        else:
            if kind == 1:
                length = ((tag >> 2) & 7) + 4
                if i >= n:
                    raise ValueError("truncated copy")
                offset = ((tag >> 5) << 8) | data[i]
                i += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                if i + 2 > n:
                    raise ValueError("truncated copy")
                offset = int.from_bytes(data[i:i + 2], "little")
                i += 2
            else:
                length = (tag >> 2) + 1
                if i + 4 > n:
                    raise ValueError("truncated copy")
                offset = int.from_bytes(data[i:i + 4], "little")
                i += 4
            o = len(out)
            if offset == 0 or offset > o or o + length > want:
                raise ValueError("corrupt copy")
            if offset >= length:
                out += out[o - offset:o - offset + length]
            else:
                for _ in range(length):      # overlapping copy
                    out.append(out[-offset])
    if len(out) != want:
        raise ValueError("snappy length mismatch")
    return bytes(out)


_CRC_TABLE: list[int] | None = None


def _crc_table() -> list[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c_py(data: bytes) -> int:
    tbl = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF
