"""Transparent object compression (cmd/object-api-utils.go:436-449,916).

Snappy block format + snappy/S2 framing format, with two engines:

* native C++ (`native/snappy.cc`), built on demand with g++ into
  `native/build/libmtsnappy.so` and bound via ctypes — the role the
  assembly-accelerated klauspost/compress S2 module plays in the
  reference (go.mod:37);
* a pure-Python mirror used when no compiler is available.

The stored stream is the snappy *framing* format (stream identifier +
per-chunk masked CRC32C), so every 64 KiB chunk is independently
verifiable — the compression analog of the bitrot layer's per-block
hashes.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading

from ..utils import nativelib
from .snappy_py import (compress_block_py, crc32c_py,
                        decompress_block_py)

_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "snappy.cc")
_NATIVE_SO = os.path.join(os.path.dirname(_NATIVE_SRC), "build",
                          "libmtsnappy.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False


class CompressionError(Exception):
    pass


def _load_native():
    """Build (once) and load the native codec; None when unavailable."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    with _lock:
        if _lib_tried:
            return _lib
        lib = nativelib.load(_NATIVE_SRC, _NATIVE_SO)
        if lib is not None:
            try:
                lib.mt_snappy_max_compressed.restype = ctypes.c_size_t
                lib.mt_snappy_max_compressed.argtypes = [ctypes.c_size_t]
                lib.mt_snappy_compress.restype = ctypes.c_size_t
                lib.mt_snappy_compress.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
                lib.mt_snappy_uncompress.restype = ctypes.c_longlong
                lib.mt_snappy_uncompress.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                    ctypes.c_size_t]
                lib.mt_snappy_uncompressed_length.restype = \
                    ctypes.c_longlong
                lib.mt_snappy_uncompressed_length.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t]
                lib.mt_crc32c.restype = ctypes.c_uint32
                lib.mt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            except Exception:  # noqa: BLE001
                lib = None
        _lib = lib
        _lib_tried = True
        return _lib


def native_available() -> bool:
    return _load_native() is not None


# -- block codec ------------------------------------------------------------

def compress_block(data: bytes) -> bytes:
    lib = _load_native()
    if lib is None:
        return compress_block_py(data)
    cap = lib.mt_snappy_max_compressed(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.mt_snappy_compress(data, len(data), out)
    return out.raw[:n]


# a snappy op emits at most 64 bytes from at most 1 tag byte, so a
# valid block can never expand beyond ~64x its compressed size (+ the
# length header).  A declared length past this bound is a corrupt or
# MALICIOUS header — allocating it would let a tiny request commit
# gigabytes (decompression-bomb DoS, found by the fuzz tier).
_MAX_EXPANSION = 64


def _check_declared(want: int, compressed_len: int) -> None:
    if want > max(1 << 16, compressed_len * _MAX_EXPANSION):
        raise CompressionError(
            f"declared length {want} implausible for "
            f"{compressed_len} compressed bytes")


def decompress_block(data: bytes) -> bytes:
    lib = _load_native()
    if lib is None:
        return decompress_block_py(data)
    want = lib.mt_snappy_uncompressed_length(data, len(data))
    if want < 0:
        raise CompressionError("corrupt snappy block")
    _check_declared(int(want), len(data))
    out = ctypes.create_string_buffer(max(int(want), 1))
    n = lib.mt_snappy_uncompress(data, len(data), out, int(want))
    if n < 0:
        raise CompressionError("corrupt snappy block")
    return out.raw[:n]


def crc32c(data: bytes) -> int:
    lib = _load_native()
    if lib is None:
        return crc32c_py(data)
    return lib.mt_crc32c(data, len(data))


# -- framing format (snappy framing / S2-compatible container) --------------

_STREAM_IDENT = b"\xff\x06\x00\x00sNaPpY"
# klauspost/s2 streams carry their own identifier chunk.  The S2 BLOCK
# format adds opcodes (repeat offsets, >64 KiB blocks) whose byte-level
# spec is not available in this offline environment and for which no
# oracle encoder exists here — a guessed decoder validated only by its
# own round trip would be self-confirming and could silently corrupt
# data, so S2-extended blocks are rejected LOUDLY instead (see
# decompress_stream / snappy_py error paths).  Reference: go.mod:37,
# decompress call at cmd/object-api-utils.go:676.
_S2_IDENT = b"\xff\x06\x00\x00S2sTwO"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_FRAME_MAX = 65536                  # max uncompressed bytes per chunk


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15) | (c << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def compress_stream(data: bytes) -> bytes:
    """Frame `data` as a snappy-framing stream; chunks that don't shrink
    are stored uncompressed (the >2 GiB/s skip path for pre-compressed
    input, docs/compression/README.md:86)."""
    out = bytearray(_STREAM_IDENT)
    for off in range(0, len(data), _FRAME_MAX) or [0]:
        chunk = data[off:off + _FRAME_MAX]
        crc = _masked_crc(chunk)
        comp = compress_block(chunk)
        if len(comp) < len(chunk):
            body = struct.pack("<I", crc)[:4] + comp
            out += bytes([_CHUNK_COMPRESSED]) + \
                struct.pack("<I", len(body))[:3] + body
        else:
            body = struct.pack("<I", crc)[:4] + chunk
            out += bytes([_CHUNK_UNCOMPRESSED]) + \
                struct.pack("<I", len(body))[:3] + body
    return bytes(out)


def decompress_stream(data: bytes) -> bytes:
    """Whole-buffer decode — one join over the incremental decoder, so
    the framing/CRC/S2 rules have a single implementation."""
    return b"".join(decompress_chunks((data,)))


def decompress_chunks(chunks):
    """Incremental :func:`decompress_stream` over an iterator of stream
    slices: framing chunks are decoded AS THEY COMPLETE, so a consumer
    (the streaming Select scanner, chunked GET transforms) holds one
    ~64 KiB frame plus the undecoded remainder — never the whole
    object.  Same validation and errors as the whole-buffer decoder;
    a source that ends mid-frame raises ``truncated chunk``."""
    buf = bytearray()
    checked_ident = False
    s2 = False
    try:
        for piece in chunks:
            if piece:
                buf += piece
            if not checked_ident:
                if len(buf) < len(_STREAM_IDENT):
                    continue
                s2 = bytes(buf[:len(_S2_IDENT)]) == _S2_IDENT
                if not (bytes(buf[:len(_STREAM_IDENT)]) == _STREAM_IDENT
                        or s2):
                    raise CompressionError(
                        "missing snappy stream identifier")
                del buf[:len(_STREAM_IDENT)]
                checked_ident = True
            while len(buf) >= 4:
                kind = buf[0]
                ln = buf[1] | (buf[2] << 8) | (buf[3] << 16)
                if len(buf) < 4 + ln:
                    break
                body = bytes(buf[4:4 + ln])
                del buf[:4 + ln]
                plain = _decode_frame(kind, ln, body, s2)
                if plain:
                    yield plain
        if not checked_ident:
            raise CompressionError("missing snappy stream identifier")
        if buf:
            raise CompressionError(
                "truncated chunk header" if len(buf) < 4
                else "truncated chunk")
    finally:
        from ..utils import close_quietly
        close_quietly(chunks)


def _decode_frame(kind: int, ln: int, body: bytes, s2: bool) -> bytes:
    """Decode + CRC-check ONE framing chunk (shared by the whole-buffer
    and incremental decoders); returns b'' for skippable chunks."""
    if kind in (_CHUNK_COMPRESSED, _CHUNK_UNCOMPRESSED):
        if ln < 4:
            raise CompressionError("short chunk")
        crc = struct.unpack("<I", body[:4])[0]
        payload = body[4:]
        try:
            plain = decompress_block(payload) \
                if kind == _CHUNK_COMPRESSED else payload
        except (CompressionError, ValueError) as e:
            if s2:
                raise CompressionError(
                    "S2-extended block opcodes (repeat offsets / "
                    "large blocks) are not supported by this "
                    "decoder; re-write the object with snappy-"
                    "compatible compression") from e
            raise
        if _masked_crc(plain) != crc:
            raise CompressionError("chunk CRC mismatch")
        return plain
    if kind == 0xFF or 0x80 <= kind <= 0xFD:
        return b""                      # repeated ident / skippable
    raise CompressionError(f"unknown chunk type {kind:#x}")


# -- eligibility (cmd/object-api-utils.go:436-449) --------------------------

# already-compressed content that must bypass compression
DEFAULT_EXCLUDE_EXTENSIONS = [
    ".gz", ".bz2", ".zst", ".zip", ".7z", ".rar", ".xz", ".lz4", ".snappy",
    ".mp4", ".mkv", ".mov", ".jpg", ".jpeg", ".png", ".gif", ".webp",
    ".mp3", ".aac", ".ogg",
]
DEFAULT_EXCLUDE_TYPES = [
    "video/", "audio/", "image/",
    "application/zip", "application/x-gzip", "application/x-bzip2",
    "application/x-compress", "application/x-xz", "application/zstd",
]
MIN_COMPRESSIBLE_SIZE = 4096   # small objects gain nothing

META_COMPRESSION = "x-minio-internal-compression"
COMPRESSION_ALGO = "klauspost/compress/s2"   # reference's marker value


def is_compressible(object_name: str, content_type: str, size: int,
                    include_extensions: list[str] | None = None,
                    include_types: list[str] | None = None) -> bool:
    """Eligibility: explicit include lists win; otherwise everything not
    excluded by extension/MIME and not tiny (isCompressible analog).

    include lists mirror MINIO_COMPRESS_EXTENSIONS / MIME_TYPES config —
    when set, ONLY matching objects compress.
    """
    if 0 <= size < MIN_COMPRESSIBLE_SIZE:
        return False
    name = object_name.lower()
    ctype = (content_type or "").lower()
    if include_extensions or include_types:
        ok = False
        for ext in include_extensions or []:
            if ext and name.endswith(ext.lower()):
                ok = True
        for t in include_types or []:
            if t and ctype.startswith(t.lower().rstrip("*")):
                ok = True
        return ok
    for ext in DEFAULT_EXCLUDE_EXTENSIONS:
        if name.endswith(ext):
            return False
    for t in DEFAULT_EXCLUDE_TYPES:
        if ctype.startswith(t):
            return False
    return True
