"""Object healing (cmd/erasure-healing.go:233 healObject,
cmd/erasure-lowlevel-heal.go Erasure.Heal).

Classify each drive for a given object version as ok / outdated / offline
(listOnlineDisks + disksWithAllParts analog, cmd/erasure-healing-common.go),
then rebuild the missing shards: read the k healthiest shard files, run the
decode matmul on device for the *wanted* shard indices (one batched dispatch
covers every stripe), re-frame with bitrot, and commit to the stale drives
with tmp+rename_data.  Dangling objects (fewer than k shards anywhere) are
purged, as in purgeObjectDangling (cmd/erasure-healing.go:692).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..hashing import bitrot
from ..ops import gf8
from ..storage import errors as serrors
from ..storage.datatypes import ErasureInfo, FileInfo
from ..storage.xl_storage import SYS_DIR
from . import metadata as meta
from .interface import ObjectNotFound
from .erasure_object import ErasureObjects


@dataclass
class HealResult:
    """mirror of madmin.HealResultItem essentials."""
    bucket: str
    object_name: str
    version_id: str = ""
    before_ok: int = 0
    after_ok: int = 0
    healed_disks: list[str] = field(default_factory=list)
    dangling_purged: bool = False


class DiskState:
    OK = "ok"
    OFFLINE = "offline"
    MISSING = "missing"          # no metadata / no parts
    OUTDATED = "outdated"        # stale version
    CORRUPT = "corrupt"          # bitrot / bad part sizes


def classify_disks(er: ErasureObjects, bucket: str, object_name: str,
                   fi: FileInfo, fis: list[FileInfo | None],
                   errs: list[Exception | None],
                   deep: bool = False) -> list[str]:
    """Per-disk state for the quorum version ``fi``
    (listOnlineDisks/disksWithAllParts semantics)."""
    states = []
    shuffled = meta.shuffle_disks(er.disks, fi.erasure.distribution)
    s_fis = meta.shuffle_parts_metadata(fis, fi.erasure.distribution)
    s_errs = meta.shuffle_parts_metadata(errs, fi.erasure.distribution)
    for disk, dfi, derr in zip(shuffled, s_fis, s_errs):
        if disk is None or isinstance(derr, serrors.DiskNotFound):
            states.append(DiskState.OFFLINE)
            continue
        if isinstance(derr, (serrors.FileNotFound,
                             serrors.FileVersionNotFound,
                             serrors.VolumeNotFound)):
            states.append(DiskState.MISSING)
            continue
        if derr is not None:
            states.append(DiskState.CORRUPT)
            continue
        if dfi is None or dfi.mod_time != fi.mod_time:
            states.append(DiskState.OUTDATED)
            continue
        if dfi.inline_data is not None:
            states.append(DiskState.OK)
            continue
        try:
            if deep:
                disk.verify_file(bucket, object_name, dfi)
            else:
                disk.check_parts(bucket, object_name, dfi)
            states.append(DiskState.OK)
        except serrors.StorageError:
            states.append(DiskState.CORRUPT)
    return states


def heal_object(er: ErasureObjects, bucket: str, object_name: str,
                version_id: Optional[str] = None, deep: bool = False,
                dry_run: bool = False, remove_dangling: bool = False
                ) -> HealResult:
    """HealObject for one version (cmd/erasure-healing.go:803,233)."""
    fis, errs = er._fanout(
        lambda d: d.read_version(bucket, object_name, version_id))
    ok_reads = [fi for fi in fis if fi is not None]
    if not ok_reads:
        raise ObjectNotFound(f"{bucket}/{object_name}")
    try:
        fi = meta.find_file_info_in_quorum(fis, max(1, len(er.disks) // 2))
    except meta.ReadQuorumError:
        # metadata below quorum: the object can never be served again —
        # dangling (purgeObjectDangling, cmd/erasure-healing.go:692)
        fi = ok_reads[0]
        res = HealResult(bucket, object_name, fi.version_id)
        res.before_ok = len(ok_reads)
        if remove_dangling and not dry_run:
            er._fanout(lambda d: d.delete_version(bucket, object_name, fi))
            res.dangling_purged = True
        res.after_ok = res.before_ok
        return res
    k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
    res = HealResult(bucket, object_name, fi.version_id)

    states = classify_disks(er, bucket, object_name, fi, fis, errs, deep)
    res.before_ok = states.count(DiskState.OK)
    healable = [i for i, s in enumerate(states)
                if s in (DiskState.MISSING, DiskState.OUTDATED,
                         DiskState.CORRUPT)]

    if res.before_ok < k:
        # dangling: not enough shards anywhere to ever reconstruct
        if remove_dangling and not dry_run:
            er._fanout(lambda d: d.delete_version(bucket, object_name, fi))
            res.dangling_purged = True
        res.after_ok = res.before_ok
        return res

    if not healable or dry_run:
        res.after_ok = res.before_ok
        return res

    shuffled = meta.shuffle_disks(er.disks, fi.erasure.distribution)
    s_fis = meta.shuffle_parts_metadata(fis, fi.erasure.distribution)
    ssize = fi.erasure.shard_size()

    # heal the bucket volume first (healBucket, cmd/erasure-healing.go:56)
    for i in healable:
        try:
            shuffled[i].stat_vol(bucket)
        except serrors.VolumeNotFound:
            try:
                shuffled[i].make_vol(bucket)
            except serrors.StorageError:
                pass
        except serrors.StorageError:
            pass

    # delete markers / zero-byte objects: metadata-only heal
    if fi.deleted or fi.size == 0 or not fi.parts:
        for i in healable:
            dfi = _disk_fileinfo(fi, i)
            shuffled[i].write_metadata(bucket, object_name, dfi)
            res.healed_disks.append(shuffled[i].endpoint())
        res.after_ok = res.before_ok + len(healable)
        return res

    ok_idx = [i for i, s in enumerate(states) if s == DiskState.OK]
    inline = any(f is not None and f.inline_data is not None
                 for f in s_fis)
    # packed small objects live in per-drive segment files; the healed
    # shard re-packs on the TARGET drive (its own segment, its own
    # extent) so the object stays uniformly packed across the set
    packed = any(f is not None and getattr(f, "seg", None) is not None
                 for f in s_fis)

    # stage every part into ONE tmp dir per drive as it is rebuilt,
    # commit with a single rename_data per drive at the end:
    # rename_data REPLACES the object's data dir, so a per-part commit
    # would clobber previously healed parts and leave a multipart
    # object permanently CORRUPT on the target drive (only its last
    # part present).  Staging goes straight to the drive, so heal
    # memory stays O(one part's shards), not O(all parts).
    staged: dict[int, str] = {}          # shard idx -> tmp dir
    stage_errs: dict[int, Exception] = {}
    try:
        for part in fi.parts:
            sfsize = fi.erasure.shard_file_size(part.size)
            # read k healthy shard files (verified)
            shards: dict[int, np.ndarray] = {}
            for i in ok_idx:
                if len(shards) == k:
                    break
                try:
                    dfi = s_fis[i]
                    if dfi is not None and dfi.inline_data is not None:
                        framed = dfi.inline_data
                    elif dfi is not None and \
                            getattr(dfi, "seg", None) is not None:
                        framed = shuffled[i].read_segment(
                            dfi.seg["sid"], dfi.seg["off"], dfi.seg["len"])
                    else:
                        framed = shuffled[i].read_all(
                            bucket,
                            f"{object_name}/{fi.data_dir}"
                            f"/part.{part.number}")
                    r = bitrot.StreamingBitrotReader(framed, ssize,
                                                     er.bitrot_algo)
                    shards[i] = np.frombuffer(r.read_at(0, sfsize),
                                              dtype=np.uint8)
                except (serrors.StorageError, bitrot.BitrotError):
                    continue
            if len(shards) < k:
                res.after_ok = res.before_ok
                return res
            present = sorted(shards)[:k]
            wanted = healable
            rebuilt = _reconstruct_shards(er, fi, present,
                                          [shards[i] for i in present],
                                          wanted, part.size)
            for j, i in enumerate(wanted):
                if i in stage_errs:
                    continue            # drive already failed staging
                framed = bitrot.streaming_encode(rebuilt[j].tobytes(),
                                                 ssize, er.bitrot_algo)
                disk = shuffled[i]
                if inline or fi.size <= er.inline_threshold:
                    dfi = _disk_fileinfo(fi, i)
                    dfi.inline_data = framed
                    dfi.data_dir = ""
                    disk.write_metadata(bucket, object_name, dfi)
                    if disk.endpoint() not in res.healed_disks:
                        res.healed_disks.append(disk.endpoint())
                    continue
                if packed:
                    dfi = _disk_fileinfo(fi, i)
                    dfi.data_dir = ""
                    disk.write_packed(bucket, object_name, dfi, framed)
                    if disk.endpoint() not in res.healed_disks:
                        res.healed_disks.append(disk.endpoint())
                    continue
                try:
                    tmp = staged.get(i)
                    if tmp is None:
                        tmp = staged[i] = disk.tmp_dir()
                    disk.create_file(SYS_DIR,
                                     f"{tmp}/part.{part.number}", framed)
                except (serrors.StorageError, OSError) as e:
                    # one drive failing to stage must not sink the
                    # others' heal; its error surfaces after commit
                    stage_errs[i] = e
        writes = [(shuffled[i], _disk_fileinfo(fi, i), staged[i])
                  for i in healable
                  if i in staged and i not in stage_errs]
        _commit_healed_shards(er, writes, bucket, object_name, res)
        if stage_errs:
            raise next(iter(stage_errs.values()))
    finally:
        for i, tmp in staged.items():
            try:
                shuffled[i].clean_tmp(tmp)
            except Exception:  # noqa: BLE001 — cleanup best-effort
                pass
    res.after_ok = res.before_ok + len(healable)
    return res


def _commit_healed_shards(er: ErasureObjects, writes: list,
                          bucket: str, object_name: str, res) -> None:
    """Commit fully-staged shard tmp dirs on the stale drives: ONE
    rename_data per drive swaps its data dir atomically (the parts
    were already streamed into the tmp dir as they were rebuilt).
    Rides the shared per-drive writer plane when the pipeline is on,
    so remote drives' commit RPCs overlap; falls back to the serial
    loop otherwise.  The first failure aborts the heal (as the serial
    loop always did) — but only after every drive's commit settled,
    and drives that DID succeed are still recorded as healed.
    ``writes`` rows are (disk, dfi, tmp_dir)."""
    if not writes:
        return

    def heal_one(disk, dfi, tmp) -> None:
        disk.rename_data(SYS_DIR, tmp, dfi, bucket, object_name)

    if er._pipeline_on() and len(writes) > 1:
        sw = er._write_plane.stream([d for d, _, _ in writes])
        for pos, (disk, dfi, tmp) in enumerate(writes):
            # the plane hands fn its (idx, disk); the heal write is
            # already bound to ITS target drive, so ignore them
            sw.submit(pos, lambda *_, d=disk, i=dfi, t=tmp:
                      heal_one(d, i, t))
        sw.drain()
        first_err = None
        for pos, (disk, _, _) in enumerate(writes):
            if sw.errs[pos] is None:
                if disk.endpoint() not in res.healed_disks:
                    res.healed_disks.append(disk.endpoint())
            elif first_err is None:
                first_err = sw.errs[pos]
        if first_err is not None:
            raise first_err
        return
    for disk, dfi, tmp in writes:
        heal_one(disk, dfi, tmp)
        if disk.endpoint() not in res.healed_disks:
            res.healed_disks.append(disk.endpoint())


def _disk_fileinfo(fi: FileInfo, shard_idx: int) -> FileInfo:
    dfi = FileInfo(**{**fi.__dict__})
    dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
    dfi.erasure.index = shard_idx + 1
    dfi.inline_data = None
    # seg extents are per-drive: the quorum fi's extent points into the
    # SOURCE drive's segment file; the target re-packs (write_packed
    # assigns its own extent) or stages regular part files
    dfi.seg = None
    return dfi


def _reconstruct_shards(er: ErasureObjects, fi: FileInfo, present: list[int],
                        surviving: list[np.ndarray], wanted: list[int],
                        part_size: int) -> list[np.ndarray]:
    """Rebuild full shard files for ``wanted`` indices (data or parity),
    batching all full stripes into one device dispatch."""
    from ..ops import rs_kernels
    k = fi.erasure.data_blocks
    bs = fi.erasure.block_size
    ssize = fi.erasure.shard_size()
    nfull = part_size // bs
    tail = part_size - nfull * bs
    sfsize = fi.erasure.shard_file_size(part_size)
    # matrix for the OBJECT's geometry: storage-class parity may differ
    # from the layer default
    codec = er._codec_for(fi.erasure.parity_blocks)
    rows = rs_kernels.decode_rows(codec.matrix, k, present, wanted)
    outs = [np.empty(sfsize, dtype=np.uint8) for _ in wanted]
    if nfull:
        surv = np.stack([s[: nfull * ssize].reshape(nfull, ssize)
                         for s in surviving], axis=1)
        if codec.is_device:
            reb = codec.apply_matrix(rows, surv)
        else:
            reb = np.stack([gf8.gf_matmul(rows, surv[b])
                            for b in range(nfull)])
        for j in range(len(wanted)):
            outs[j][: nfull * ssize] = reb[:, j].reshape(-1)
    if tail:
        t_ssize = gf8.ceil_frac(tail, k)
        surv_t = np.stack([s[nfull * ssize: nfull * ssize + t_ssize]
                           for s in surviving])
        reb_t = codec.apply_matrix(rows, surv_t) if codec.is_device \
            else gf8.gf_matmul(rows, surv_t)
        for j in range(len(wanted)):
            outs[j][nfull * ssize:] = reb_t[j]
    return outs
