"""Standalone single-drive FS backend (cmd/fs-v1.go:53 FSObjects).

The reference's non-erasure mode: objects live as plain files at
``<root>/<bucket>/<key>``; per-object metadata (etag, content-type, user
metadata, multipart part table) lives in an ``fs.json`` sidecar under
``<root>/.minio.sys/buckets/<bucket>/<key>/fs.json``
(cmd/fs-v1-metadata.go), and multipart uploads stage under
``<root>/.minio.sys/multipart/<sha256(bucket/object)>/<uploadID>/``
(cmd/fs-v1-multipart.go).  Writes go to ``.minio.sys/tmp`` first and
commit with an atomic rename, mirroring the reference's fsCreateFile +
fsRenameFile commit discipline.

Versioning is not supported in FS mode (as in the reference, which
returns NotImplemented); version ids are always the null version.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import uuid
from typing import Optional

from ..storage.datatypes import now_ns
from .interface import (BucketExists, BucketInfo, BucketNameInvalid,
                        BucketNotEmpty, BucketNotFound, InvalidPart,
                        InvalidPartOrder, InvalidRange, InvalidUploadID,
                        ListObjectsInfo, ObjectInfo, ObjectLayer,
                        ObjectNotFound, ObjectOptions, PutObjectOptions)
from .multipart import (MAX_PARTS, MIN_PART_SIZE, MultipartInfo, PartInfo)

SYS = ".minio.sys"


class _FSSysDisk:
    """Single-drive stand-in for StorageAPI's read_all/write_all, scoped
    to system volumes (config/IAM/KMS persistence)."""

    def __init__(self, root: str):
        self.root = root

    def _p(self, volume: str, path: str) -> str:
        return os.path.join(self.root, volume, path)

    def read_all(self, volume: str, path: str) -> bytes:
        from ..storage import errors as serrors
        try:
            with open(self._p(volume, path), "rb") as f:
                return f.read()
        except OSError:
            raise serrors.FileNotFound(path) from None

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        p = self._p(volume, path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)


def _valid_bucket(name: str) -> bool:
    return (3 <= len(name) <= 63 and name != SYS
            and all(c.islower() or c.isdigit() or c in "-." for c in name)
            and not name.startswith("-"))


class FSObjects(ObjectLayer):
    """Single-drive, non-erasure ObjectLayer (cmd/fs-v1.go:53)."""

    enforce_min_part_size = True

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(os.path.join(self.root, SYS, "tmp"), exist_ok=True)
        os.makedirs(os.path.join(self.root, SYS, "buckets"), exist_ok=True)
        os.makedirs(os.path.join(self.root, SYS, "multipart"), exist_ok=True)
        self._lock = threading.RLock()

    # -- path helpers -------------------------------------------------------

    def _bucket_path(self, bucket: str) -> str:
        return os.path.join(self.root, bucket)

    def _obj_path(self, bucket: str, key: str) -> str:
        bp = self._bucket_path(bucket)
        p = os.path.normpath(os.path.join(bp, key))
        # containment must be separator-aware: "<root>/data-private"
        # startswith "<root>/data" — a bare prefix check lets keys escape
        # into sibling buckets (or, with a ".." bucket, out of the root)
        if not p.startswith(bp + os.sep) or p == bp:
            raise ObjectNotFound(key)
        return p

    def _meta_path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, SYS, "buckets", bucket, key,
                            "fs.json")

    def _tmp_path(self) -> str:
        return os.path.join(self.root, SYS, "tmp", uuid.uuid4().hex)

    def _check_bucket(self, bucket: str) -> None:
        # every entry point revalidates the name: "..", "a/b" or "" must
        # never reach the filesystem as a path segment
        if not _valid_bucket(bucket):
            raise BucketNotFound(bucket)
        if not os.path.isdir(self._bucket_path(bucket)):
            raise BucketNotFound(bucket)

    # -- buckets ------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        if not _valid_bucket(bucket):
            raise BucketNameInvalid(bucket)
        with self._lock:
            if os.path.isdir(self._bucket_path(bucket)):
                raise BucketExists(bucket)
            os.makedirs(self._bucket_path(bucket))

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        self._check_bucket(bucket)
        st = os.stat(self._bucket_path(bucket))
        return BucketInfo(bucket, int(st.st_ctime * 1e9))

    def list_buckets(self) -> list[BucketInfo]:
        out = []
        for n in sorted(os.listdir(self.root)):
            if n == SYS or not os.path.isdir(self._bucket_path(n)):
                continue
            out.append(self.get_bucket_info(n))
        return out

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._check_bucket(bucket)
        bp = self._bucket_path(bucket)
        if not force and any(os.scandir(bp)):
            raise BucketNotEmpty(bucket)
        shutil.rmtree(bp)
        shutil.rmtree(os.path.join(self.root, SYS, "buckets", bucket),
                      ignore_errors=True)

    # -- objects ------------------------------------------------------------

    def put_object(self, bucket: str, object_name: str, data: bytes,
                   opts: Optional[PutObjectOptions] = None) -> ObjectInfo:
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)
        etag = hashlib.md5(data).hexdigest()
        mod_time = opts.mod_time or now_ns()
        tmp = self._tmp_path()
        with open(tmp, "wb") as f:
            f.write(data)
        dst = self._obj_path(bucket, object_name)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(tmp, dst)
        meta = {"etag": etag, "mod_time": mod_time, "size": len(data),
                "user_defined": dict(opts.user_defined), "parts": []}
        self._write_meta(bucket, object_name, meta)
        return self._info(bucket, object_name, meta)

    def _write_meta(self, bucket: str, key: str, meta: dict) -> None:
        mp = self._meta_path(bucket, key)
        os.makedirs(os.path.dirname(mp), exist_ok=True)
        tmp = self._tmp_path()
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, mp)

    def _read_meta(self, bucket: str, key: str) -> dict:
        try:
            with open(self._meta_path(bucket, key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            # object written out-of-band: synthesize metadata (the
            # reference serves bare files with defaultFsJSON)
            p = self._obj_path(bucket, key)
            st = os.stat(p)
            return {"etag": "", "mod_time": int(st.st_mtime * 1e9),
                    "size": st.st_size, "user_defined": {}, "parts": []}

    def _info(self, bucket: str, key: str, meta: dict) -> ObjectInfo:
        ud = dict(meta.get("user_defined", {}))
        return ObjectInfo(
            bucket=bucket, name=key, mod_time=meta["mod_time"],
            size=meta["size"], etag=meta.get("etag", ""),
            version_id="", is_latest=True,
            content_type=ud.get("content-type", ""),
            user_defined=ud,
            parts=[tuple(p) for p in meta.get("parts", [])])

    def get_object_info(self, bucket: str, object_name: str,
                        opts: Optional[ObjectOptions] = None) -> ObjectInfo:
        self._check_bucket(bucket)
        p = self._obj_path(bucket, object_name)
        if not os.path.isfile(p):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        return self._info(bucket, object_name,
                          self._read_meta(bucket, object_name))

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[ObjectOptions] = None
                   ) -> tuple[ObjectInfo, bytes]:
        oi = self.get_object_info(bucket, object_name, opts)
        if offset < 0 or offset > oi.size:
            raise InvalidRange(f"offset {offset}")
        with open(self._obj_path(bucket, object_name), "rb") as f:
            f.seek(offset)
            data = f.read() if length < 0 else f.read(length)
        return oi, data

    def delete_object(self, bucket: str, object_name: str,
                      opts: Optional[ObjectOptions] = None) -> ObjectInfo:
        self._check_bucket(bucket)
        p = self._obj_path(bucket, object_name)
        try:
            os.remove(p)
        except FileNotFoundError:
            pass  # S3 DELETE is idempotent
        shutil.rmtree(os.path.dirname(self._meta_path(bucket, object_name)),
                      ignore_errors=True)
        # prune now-empty parent dirs up to the bucket root
        d = os.path.dirname(p)
        while d != self._bucket_path(bucket):
            try:
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)
        return ObjectInfo(bucket=bucket, name=object_name)

    def put_object_metadata(self, bucket: str, object_name: str,
                            version_id: Optional[str],
                            updates: dict[str, str],
                            removes: tuple[str, ...] = ()) -> ObjectInfo:
        self.get_object_info(bucket, object_name)
        meta = self._read_meta(bucket, object_name)
        ud = meta.setdefault("user_defined", {})
        for k in removes:
            ud.pop(k, None)
        ud.update(updates)
        self._write_meta(bucket, object_name, meta)
        return self._info(bucket, object_name, meta)

    # -- listing ------------------------------------------------------------

    def _walk(self, bucket: str) -> list[str]:
        bp = self._bucket_path(bucket)
        out = []
        for dirpath, _dirs, files in os.walk(bp):
            rel = os.path.relpath(dirpath, bp)
            for f in files:
                out.append(f if rel == "." else f"{rel}/{f}".replace(
                    os.sep, "/"))
        return sorted(out)

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000
                     ) -> ListObjectsInfo:
        self._check_bucket(bucket)
        out = ListObjectsInfo()
        prefixes: set[str] = set()
        for name in self._walk(bucket):
            if prefix and not name.startswith(prefix):
                continue
            rest = name[len(prefix):]
            item = prefix + rest.split(delimiter, 1)[0] + delimiter \
                if delimiter and delimiter in rest else None
            # marker compares against the rolled-up item so that resuming
            # from a CommonPrefix NextMarker skips the whole prefix instead
            # of re-emitting it every page
            if marker and (item or name) <= marker:
                continue
            if item is not None:
                if item in prefixes:
                    continue
                prefixes.add(item)
                # prefixes count toward max-keys too (S3 semantics)
                if len(out.objects) + len(prefixes) >= max_keys:
                    out.is_truncated = True
                    out.next_marker = item
                    break
                continue
            out.objects.append(self._info(bucket, name,
                                          self._read_meta(bucket, name)))
            if len(out.objects) + len(prefixes) >= max_keys:
                out.is_truncated = True
                out.next_marker = name
                break
        out.prefixes = sorted(prefixes)
        return out

    def list_object_versions(self, bucket: str, prefix: str = ""):
        """FS mode has no versions; each object is its own null version."""
        return self.list_objects(bucket, prefix, max_keys=10**9).objects

    # -- multipart (cmd/fs-v1-multipart.go) ----------------------------------

    def _mp_dir(self, bucket: str, object_name: str, upload_id: str) -> str:
        h = hashlib.sha256(f"{bucket}/{object_name}".encode()).hexdigest()
        return os.path.join(self.root, SYS, "multipart", h, upload_id)

    def new_multipart_upload(self, bucket: str, object_name: str,
                             opts: Optional[PutObjectOptions] = None) -> str:
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)
        upload_id = uuid.uuid4().hex
        d = self._mp_dir(bucket, object_name, upload_id)
        os.makedirs(d)
        with open(os.path.join(d, "fs.json"), "w") as f:
            json.dump({"bucket": bucket, "object": object_name,
                       "user_defined": dict(opts.user_defined)}, f)
        return upload_id

    def _mp_meta(self, bucket: str, object_name: str, upload_id: str) -> dict:
        d = self._mp_dir(bucket, object_name, upload_id)
        try:
            with open(os.path.join(d, "fs.json")) as f:
                return json.load(f)
        except OSError:
            raise InvalidUploadID(upload_id) from None

    def put_object_part(self, bucket: str, object_name: str, upload_id: str,
                        part_number: int, data: bytes) -> PartInfo:
        if not 1 <= part_number <= MAX_PARTS:
            raise InvalidPart(f"part number {part_number}")
        self._check_bucket(bucket)
        self._mp_meta(bucket, object_name, upload_id)
        d = self._mp_dir(bucket, object_name, upload_id)
        etag = hashlib.md5(data).hexdigest()
        tmp = self._tmp_path()
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(d, f"part.{part_number}"))
        with open(os.path.join(d, f"part.{part_number}.meta"), "w") as f:
            f.write(f"{etag}:{len(data)}")
        return PartInfo(part_number, etag, len(data), len(data), now_ns())

    def get_multipart_info(self, bucket: str, object_name: str,
                           upload_id: str) -> MultipartInfo:
        self._check_bucket(bucket)
        meta = self._mp_meta(bucket, object_name, upload_id)
        return MultipartInfo(bucket, object_name, upload_id,
                             meta.get("user_defined", {}))

    def list_object_parts(self, bucket: str, object_name: str,
                          upload_id: str) -> list[PartInfo]:
        self._check_bucket(bucket)
        self._mp_meta(bucket, object_name, upload_id)
        d = self._mp_dir(bucket, object_name, upload_id)
        parts = []
        for n in os.listdir(d):
            if n.startswith("part.") and n.endswith(".meta"):
                num = int(n[5:-5])
                with open(os.path.join(d, n)) as f:
                    etag, size = f.read().split(":")
                parts.append(PartInfo(num, etag, int(size), int(size)))
        return sorted(parts, key=lambda p: p.part_number)

    def abort_multipart_upload(self, bucket: str, object_name: str,
                               upload_id: str) -> None:
        self._check_bucket(bucket)
        self._mp_meta(bucket, object_name, upload_id)
        shutil.rmtree(self._mp_dir(bucket, object_name, upload_id))

    def list_multipart_uploads(self, bucket: str,
                               prefix: str = "") -> list[MultipartInfo]:
        self._check_bucket(bucket)
        mproot = os.path.join(self.root, SYS, "multipart")
        out = []
        for h in os.listdir(mproot):
            for uid in os.listdir(os.path.join(mproot, h)):
                try:
                    with open(os.path.join(mproot, h, uid, "fs.json")) as f:
                        meta = json.load(f)
                except OSError:
                    continue
                if meta.get("bucket") == bucket and \
                        meta.get("object", "").startswith(prefix):
                    out.append(MultipartInfo(bucket, meta["object"], uid,
                                             meta.get("user_defined", {})))
        return sorted(out, key=lambda m: m.object_name)

    def complete_multipart_upload(self, bucket: str, object_name: str,
                                  upload_id: str,
                                  parts: list[tuple[int, str]]) -> ObjectInfo:
        self._check_bucket(bucket)
        self._mp_meta(bucket, object_name, upload_id)
        if not parts:
            raise InvalidPart("no parts specified")
        if [p[0] for p in parts] != sorted({p[0] for p in parts}):
            raise InvalidPartOrder("parts not in ascending order")
        uploaded = {p.part_number: p
                    for p in self.list_object_parts(bucket, object_name,
                                                    upload_id)}
        d = self._mp_dir(bucket, object_name, upload_id)
        md5s = b""
        total = 0
        part_table = []
        tmp = self._tmp_path()
        with open(tmp, "wb") as out:
            for i, (num, etag) in enumerate(parts):
                got = uploaded.get(num)
                if got is None or got.etag != etag.strip('"'):
                    raise InvalidPart(f"part {num}")
                if got.size < MIN_PART_SIZE and i != len(parts) - 1 \
                        and self.enforce_min_part_size:
                    raise InvalidPart(f"part {num} too small")
                with open(os.path.join(d, f"part.{num}"), "rb") as f:
                    out.write(f.read())
                md5s += bytes.fromhex(got.etag)
                total += got.size
                part_table.append((num, got.size))
        etag = hashlib.md5(md5s).hexdigest() + f"-{len(parts)}"
        meta = self._mp_meta(bucket, object_name, upload_id)
        dst = self._obj_path(bucket, object_name)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(tmp, dst)
        mod_time = now_ns()
        doc = {"etag": etag, "mod_time": mod_time, "size": total,
               "user_defined": meta.get("user_defined", {}),
               "parts": part_table}
        self._write_meta(bucket, object_name, doc)
        shutil.rmtree(d)
        return self._info(bucket, object_name, doc)

    # -- system-volume shim --------------------------------------------------
    # Subsystems (config, IAM, KMS) persist state through the object layer
    # via `_fanout(lambda d: d.read_all/write_all(SYS_DIR, path))`; in FS
    # mode there is exactly one "drive": the root directory itself.

    def _fanout(self, fn):
        try:
            return [fn(_FSSysDisk(self.root))], [None]
        except Exception as e:  # mirrored from ErasureObjects._fanout
            return [None], [e]

    # -- heal (no-op in FS mode, as in the reference) ------------------------

    def heal_object(self, bucket, object_name, version_id=None, deep=False,
                    dry_run=False, remove_dangling=False):
        from .healing import HealResult
        self.get_object_info(bucket, object_name)
        return HealResult(bucket, object_name, before_ok=1, after_ok=1)

    def heal_bucket(self, bucket: str) -> int:
        self._check_bucket(bucket)
        return 0
