"""Quorum metadata logic (cmd/erasure-metadata.go, erasure-metadata-utils.go).

Given per-disk FileInfo reads (some failed), agree on the authoritative
version: latest common mod-time, then majority vote over a content hash of
(parts, distribution), requiring >= read quorum, exactly as
findFileInfoInQuorum (cmd/erasure-metadata.go:229-270).
"""

from __future__ import annotations

import hashlib
import zlib
from collections import Counter

from ..storage import errors as serrors
from ..storage.datatypes import FileInfo
from .interface import ReadQuorumError, WriteQuorumError


def hash_order(key: str, cardinality: int) -> list[int]:
    """Deterministic disk ordering for an object
    (cmd/erasure-metadata-utils.go:100-114, CRC32-IEEE based)."""
    if cardinality <= 0:
        return []
    key_crc = zlib.crc32(key.encode()) & 0xFFFFFFFF
    start = key_crc % cardinality
    return [1 + ((start + i) % cardinality) for i in range(1, cardinality + 1)]


def object_quorum_from_meta(fi: FileInfo) -> tuple[int, int]:
    """(readQuorum, writeQuorum) per cmd/erasure-metadata.go:337-359."""
    data, parity = fi.erasure.data_blocks, fi.erasure.parity_blocks
    write = data
    if data == parity:
        write += 1
    return data, write


def _meta_hash(fi: FileInfo) -> str:
    h = hashlib.sha256()
    for part in fi.parts:
        h.update(f"part.{part.number}".encode())
    h.update(str(fi.erasure.distribution).encode())
    h.update(fi.data_dir.encode())
    h.update(b"1" if fi.deleted else b"0")
    return h.hexdigest()


def find_latest_mod_time(fis: list[FileInfo | None]) -> int:
    """commonTime: the mod-time shared by the most disks
    (cmd/erasure-metadata.go commonTime)."""
    times = Counter(fi.mod_time for fi in fis if fi is not None)
    if not times:
        return 0
    # max count wins; ties break to the later time
    best = max(times.items(), key=lambda kv: (kv[1], kv[0]))
    return best[0]


def find_file_info_in_quorum(fis: list[FileInfo | None],
                             quorum: int) -> FileInfo:
    """Pick the FileInfo agreed by >= quorum disks
    (cmd/erasure-metadata.go:229)."""
    mod_time = find_latest_mod_time(fis)
    hashes: list[str | None] = []
    for fi in fis:
        if fi is not None and fi.mod_time == mod_time:
            hashes.append(_meta_hash(fi))
        else:
            hashes.append(None)
    counts = Counter(h for h in hashes if h)
    if not counts:
        raise ReadQuorumError("no valid metadata")
    best_hash, best_count = counts.most_common(1)[0]
    if best_count < quorum:
        raise ReadQuorumError(
            f"metadata agreement {best_count} < quorum {quorum}")
    for fi, h in zip(fis, hashes):
        if h == best_hash:
            return fi
    raise ReadQuorumError("unreachable")  # pragma: no cover


def reduce_errs(errs: list[Exception | None], quorum: int,
                quorum_error: type[Exception]) -> None:
    """reduceQuorumErrs (cmd/erasure-metadata-utils.go): raise the majority
    error if >= quorum disks failed identically; raise quorum_error if
    successes fall short of quorum."""
    ok = sum(1 for e in errs if e is None)
    if ok >= quorum:
        return
    kinds = Counter(type(e).__name__ for e in errs if e is not None)
    if kinds:
        name, count = kinds.most_common(1)[0]
        if count >= quorum:
            for e in errs:
                if e is not None and type(e).__name__ == name:
                    raise e
    raise quorum_error(f"{ok} successes < quorum {quorum}: "
                       f"{[str(e) for e in errs if e]}")


def shuffle_disks(disks: list, distribution: list[int]) -> list:
    """Place disks into distribution order (shuffleDisks,
    cmd/erasure-metadata-utils.go): shuffled[dist[i]-1] = disks[i]."""
    if not distribution:
        return list(disks)
    shuffled = [None] * len(disks)
    for i, d in enumerate(disks):
        shuffled[distribution[i] - 1] = d
    return shuffled


def shuffle_parts_metadata(parts_meta: list, distribution: list[int]) -> list:
    if not distribution:
        return list(parts_meta)
    shuffled = [None] * len(parts_meta)
    for i, p in enumerate(parts_meta):
        shuffled[distribution[i] - 1] = p
    return shuffled


__all__ = [
    "hash_order", "object_quorum_from_meta", "find_file_info_in_quorum",
    "find_latest_mod_time", "reduce_errs", "shuffle_disks",
    "shuffle_parts_metadata", "ReadQuorumError", "WriteQuorumError",
    "serrors",
]
