"""ObjectLayer facade types and errors (cmd/object-api-interface.go:84,
cmd/object-api-errors.go).

ObjectInfo is the S3-facing view of a stored object; the typed errors map
1:1 onto S3 error codes in the API layer.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Optional


class ObjectLayerError(Exception):
    pass


class BucketNotFound(ObjectLayerError):
    pass


class BucketExists(ObjectLayerError):
    pass


class BucketNotEmpty(ObjectLayerError):
    pass


class BucketNameInvalid(ObjectLayerError):
    pass


class ObjectNotFound(ObjectLayerError):
    pass


class VersionNotFound(ObjectLayerError):
    pass


class MethodNotAllowed(ObjectLayerError):
    """e.g. GET on a delete marker."""


class ObjectNameInvalid(ObjectLayerError):
    pass


class InvalidRange(ObjectLayerError):
    pass


class ReadQuorumError(ObjectLayerError):
    """errErasureReadQuorum: not enough disks agree to read."""


class WriteQuorumError(ObjectLayerError):
    """errErasureWriteQuorum: not enough successful writes."""


class InvalidUploadID(ObjectLayerError):
    pass


class InvalidPart(ObjectLayerError):
    pass


class InvalidPartOrder(ObjectLayerError):
    pass


class PreconditionFailed(ObjectLayerError):
    pass


@dataclass
class ObjectInfo:
    """cmd/object-api-datatypes.go ObjectInfo equivalent."""
    bucket: str = ""
    name: str = ""
    mod_time: int = 0            # unix ns
    size: int = 0
    etag: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    content_type: str = ""
    user_defined: dict[str, str] = field(default_factory=dict)
    parity: int = 0
    data_blocks: int = 0
    num_versions: int = 0
    is_dir: bool = False
    # multipart part table [(part_number, size), ...] — drives SSE ranged
    # decrypt across per-part DARE streams (ObjectInfo.Parts in reference)
    parts: list[tuple[int, int]] = field(default_factory=list)


@dataclass
class BucketInfo:
    name: str
    created: int = 0


@dataclass
class ListObjectsInfo:
    """ListObjects result page (cmd/object-api-datatypes.go)."""
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)
    is_truncated: bool = False
    next_marker: str = ""
    next_continuation_token: str = ""


@dataclass
class ListObjectVersionsInfo:
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)
    is_truncated: bool = False
    next_key_marker: str = ""
    next_version_id_marker: str = ""


@dataclass
class PutObjectOptions:
    user_defined: dict[str, str] = field(default_factory=dict)
    versioned: bool = False
    version_id: str = ""
    mod_time: int = 0
    # per-request parity from x-amz-storage-class (cmd/erasure-object.go:631
    # applying cmd/config/storageclass); None = the layer's default
    parity: Optional[int] = None
    # client-sent Content-MD5 as hex; when set the body MUST hash to it
    # and the ETag is that md5 (pkg/hash/reader.go:186).  When unset and
    # the server runs in no-compat mode (the reference's hidden
    # --no-compat perf flag, cmd/common-main.go:208-210), md5 is skipped
    # and the ETag is random-with-hyphen (cmd/object-api-utils.go:843)
    content_md5: Optional[str] = None
    # rebalance/decommission moves: stamp this ETag verbatim instead of
    # minting one, so the destination copy carries the source version's
    # commit-time identity bit-identically (Content-MD5 verification
    # still applies when both are set — that IS the copy-verify step)
    preserve_etag: Optional[str] = None


@dataclass
class ObjectOptions:
    version_id: Optional[str] = None
    versioned: bool = False
    version_suspended: bool = False
    delete_marker: bool = False
    mod_time: int = 0


class ObjectLayer(abc.ABC):
    """The namespace facade every topology implements
    (cmd/object-api-interface.go:84): single set, sets, server pools."""

    def health(self, maintenance: bool = False) -> dict:
        """Cluster-health heuristic (cmd/object-api-interface.go Health,
        cmd/erasure-server-pool.go:1462).  Erasure topologies override
        with per-set quorum accounting; single-backend layers (FS,
        gateways) are healthy while reachable."""
        return {"healthy": True, "write_quorum": 0,
                "healing_drives": 0, "online_drives": 1}

    @staticmethod
    def aggregate_health(children: list["ObjectLayer"],
                         maintenance: bool) -> dict:
        """Shared set/pool aggregation: healthy only if EVERY child
        keeps write quorum (cmd/erasure-server-pool.go:1509)."""
        results = [c.health(maintenance) for c in children]
        return {
            "healthy": all(r["healthy"] for r in results),
            "write_quorum": max(r["write_quorum"] for r in results),
            "healing_drives": sum(r["healing_drives"] for r in results),
            "online_drives": sum(r["online_drives"] for r in results),
        }

    @abc.abstractmethod
    def make_bucket(self, bucket: str) -> None: ...

    @abc.abstractmethod
    def get_bucket_info(self, bucket: str) -> BucketInfo: ...

    @abc.abstractmethod
    def list_buckets(self) -> list[BucketInfo]: ...

    @abc.abstractmethod
    def delete_bucket(self, bucket: str, force: bool = False) -> None: ...

    @abc.abstractmethod
    def put_object(self, bucket: str, object_name: str, data: bytes,
                   opts: Optional[PutObjectOptions] = None) -> ObjectInfo: ...

    @abc.abstractmethod
    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[ObjectOptions] = None
                   ) -> tuple[ObjectInfo, bytes]: ...

    @abc.abstractmethod
    def get_object_info(self, bucket: str, object_name: str,
                        opts: Optional[ObjectOptions] = None
                        ) -> ObjectInfo: ...

    @abc.abstractmethod
    def delete_object(self, bucket: str, object_name: str,
                      opts: Optional[ObjectOptions] = None) -> ObjectInfo: ...

    @abc.abstractmethod
    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000
                     ) -> ListObjectsInfo: ...

    # -- streaming entry points (cmd/object-api-interface.go GetObjectNInfo
    # reader pipeline / PutObject with hash.Reader).  Backends that can
    # stream override these; the defaults buffer through the bytes paths
    # so FS/gateway layers keep working unchanged. ------------------------

    def put_object_stream(self, bucket: str, object_name: str, reader,
                          opts: Optional[PutObjectOptions] = None
                          ) -> ObjectInfo:
        """PUT from a file-like ``reader`` (has .read(n)).  Default
        buffers; ErasureObjects overrides with O(batch) memory."""
        return self.put_object(bucket, object_name, reader.read(), opts)

    def get_object_reader(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          opts: Optional[ObjectOptions] = None):
        """Range GET as (ObjectInfo, iterator-of-chunks).  Default wraps
        the buffered get_object; ErasureObjects streams covering blocks
        only (cmd/erasure-decode.go:229-246)."""
        info, data = self.get_object(bucket, object_name, offset, length,
                                     opts)
        return info, iter((data,) if data else ())
