"""Per-bucket metadata/config store (cmd/bucket-metadata-sys.go).

The reference persists one msgp blob per bucket under
``.minio.sys/buckets/<bucket>/.metadata.bin`` caching versioning, policy,
lifecycle, replication, ... configs.  Here: a JSON blob written to every
drive's system volume with quorum, cached in memory, holding the config
sub-documents as they land (versioning first; policy/lifecycle/etc. attach
to the same document).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

from ..storage import errors as serrors
from ..storage.xl_storage import SYS_DIR


class BucketMetadataSys:
    def __init__(self, er):
        self._er = er            # ErasureObjects (or sets facade)
        self._cache: dict[str, dict] = {}
        self._parsed_cache: dict[tuple[str, str], tuple[str, Any]] = {}
        self._mu = threading.Lock()
        # peer fan-out hook: set by attach_peers so config changes reload
        # on every node immediately (peerRESTMethodLoadBucketMetadata)
        self.on_change = None

    def invalidate(self, bucket: str) -> None:
        """Drop the in-memory caches for one bucket (peer reload path):
        the next access re-reads the quorum document from the drives."""
        with self._mu:
            self._cache.pop(bucket, None)
            for key in [k for k in self._parsed_cache if k[0] == bucket]:
                self._parsed_cache.pop(key, None)

    def _path(self, bucket: str) -> str:
        return f"buckets/{bucket}/bucket-meta.json"

    def get(self, bucket: str) -> dict:
        with self._mu:
            if bucket in self._cache:
                return self._cache[bucket]
        res, _ = self._er._fanout(
            lambda d: d.read_all(SYS_DIR, self._path(bucket)))
        # newest revision wins: a drive that missed the last quorum write
        # must not roll the config back (e.g. silently disable versioning)
        doc = {}
        for r in res:
            if r is None:
                continue
            try:
                cand = json.loads(r)
            except json.JSONDecodeError:
                continue
            if cand.get("_rev", 0) >= doc.get("_rev", 0):
                doc = cand
        if doc:
            # never cache empty docs: anonymous probes of random bucket
            # names must not grow the cache without bound
            with self._mu:
                self._cache[bucket] = doc
        return doc

    def update(self, bucket: str, key: str, value: Any) -> None:
        doc = dict(self.get(bucket))
        if value is None:
            doc.pop(key, None)
        else:
            doc[key] = value
        doc["_rev"] = doc.get("_rev", 0) + 1
        blob = json.dumps(doc).encode()
        _, errs = self._er._fanout(
            lambda d: d.write_all(SYS_DIR, self._path(bucket), blob))
        ok = sum(1 for e in errs if e is None)
        if ok < len(errs) // 2 + 1:
            raise serrors.FaultyDisk(
                f"bucket metadata write reached only {ok} drives")
        with self._mu:
            self._cache[bucket] = doc
        if self.on_change is not None:
            self.on_change(bucket)

    def drop(self, bucket: str) -> None:
        self._er._fanout(
            lambda d: d.delete(SYS_DIR, f"buckets/{bucket}",
                               recursive=True))
        with self._mu:
            self._cache.pop(bucket, None)

    # -- typed accessors ---------------------------------------------------

    def get_config(self, bucket: str, name: str) -> Optional[str]:
        """Raw stored config document (XML/JSON string) or None."""
        v = self.get(bucket).get(name)
        if isinstance(v, dict):
            return v.get("raw")
        return v

    def get_parsed(self, bucket: str, name: str, parser):
        """Parsed form of a stored config, cached keyed on the raw
        document — request paths must not re-parse XML/JSON per call."""
        raw = self.get_config(bucket, name)
        if raw is None:
            return None
        key = (bucket, name)
        with self._mu:
            cached = self._parsed_cache.get(key)
            if cached is not None and cached[0] == raw:
                return cached[1]
        parsed = parser(raw.encode())
        with self._mu:
            self._parsed_cache[key] = (raw, parsed)
        return parsed

    def get_bucket_policy(self, bucket: str):
        from ..bucket.policy import BucketPolicy
        return self.get_parsed(bucket, "policy", BucketPolicy.parse)

    def set_config(self, bucket: str, name: str,
                   raw: Optional[str]) -> None:
        self.update(bucket, name, raw)

    def versioning_enabled(self, bucket: str) -> bool:
        return self.get(bucket).get("versioning", {}).get(
            "status") == "Enabled"

    def set_versioning(self, bucket: str, enabled: bool) -> None:
        self.update(bucket, "versioning",
                    {"status": "Enabled" if enabled else "Suspended"})
