"""erasureObjects — object CRUD on one erasure set (cmd/erasure-object.go).

The TPU-first redesign of the reference's hot path:

  * PUT (ref: cmd/erasure-object.go:614 + cmd/erasure-encode.go): the whole
    object is encoded as ONE batched device dispatch (all stripes at once,
    minio_tpu/ops/codec.encode_object) instead of a per-10MiB-block loop;
    bitrot framing is applied per shard file; staged writes then an atomic
    quorum rename_data commit, exactly the reference's tmp+rename contract.
  * GET (ref: cmd/erasure-object.go:242 + cmd/erasure-decode.go): read the
    k cheapest shard files, verify bitrot per block, and if any shard is
    missing/corrupt reconstruct ALL stripes in one batched device call
    (same missing pattern across a part's stripes -> one compiled kernel).
  * HEAL (ref: cmd/erasure-healing.go:233): decode + re-encode on device,
    write healed shards to stale disks with quorum-1 tolerance.

Fan-out to drives uses a thread pool (goroutine-per-disk analog,
cmd/erasure-encode.go:36 parallelWriter) with quorum error reduction.
"""

from __future__ import annotations

import collections
import hashlib
import os
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..hashing import bitrot, md5fast
from ..obs import critpath as _critpath
from ..obs import trace as _trace
from ..ops import gf8
from ..ops.codec import Erasure
from ..storage import errors as serrors
from ..storage.api import StorageAPI
from ..storage.writers import WriterPlane
from ..utils import bufpool
from ..storage.datatypes import (ChecksumInfo, ErasureInfo, FileInfo,
                                 ObjectPartInfo, now_ns)
from ..storage.xl_storage import SYS_DIR
from . import metadata as meta
from .interface import (BucketExists, BucketInfo, BucketNotEmpty,
                        BucketNotFound, ListObjectsInfo, MethodNotAllowed,
                        ObjectInfo, ObjectLayer, ObjectNotFound,
                        ObjectOptions, PutObjectOptions, ReadQuorumError,
                        VersionNotFound, WriteQuorumError)
from .multipart import MultipartOps

# local drive fan-out runs serially on single-core hosts (the pool only
# adds queue/lock churn there); MT_FORCE_POOL=1 restores the pool.
# Remote drives always keep the pool: their RPCs overlap network waits
# regardless of core count (see _serial_fanout in __init__).
_SINGLE_CORE = (os.cpu_count() or 2) <= 1 and \
    os.environ.get("MT_FORCE_POOL", "0") == "0"


def _strict_compat() -> bool:
    """True unless the reference's hidden --no-compat perf mode is on
    (cmd/common-main.go:208-210).  Empty/whitespace/cased values of
    MT_NO_COMPAT mean OFF — only an explicit truthy value disables
    strict S3 compatibility."""
    return os.environ.get("MT_NO_COMPAT", "0").strip().lower() in (
        "", "0", "off", "false", "no")

DEFAULT_BLOCK_SIZE = 10 * 1024 * 1024   # blockSizeV1 (cmd/object-api-common.go:32)
INLINE_THRESHOLD = 128 * 1024           # small-object inline into xl.meta
ETAG_KEY = "etag"
# streaming pipeline batch: stripes are encoded/decoded this many bytes at
# a time so memory is O(batch * n/k) regardless of object size, while each
# device dispatch still carries enough stripes to fill the MXU
# (cmd/erasure-encode.go:80-107 block loop, widened for TPU batching)
STREAM_BATCH_BYTES = int(os.environ.get("MT_STREAM_BATCH",
                                        64 * 1024 * 1024))


class _LockedStream:
    """Iterator holding a DRWMutex until exhausted/closed/GC'd; the
    unlock runs exactly once (see _locked_stream)."""

    def __init__(self, lk, inner, on_close=None):
        self._lk = lk
        self._inner = inner
        self._on_close = on_close
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        try:
            return next(self._inner)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._done:
            return
        self._done = True
        try:
            close = getattr(self._inner, "close", None)
            if close is not None:
                close()
        finally:
            try:
                self._lk.unlock()
            finally:
                if self._on_close is not None:
                    self._on_close()

    def __del__(self):
        self.close()


def _read_full(source, n: int) -> bytes:
    """Read exactly n bytes from a file-like source unless EOF comes
    first (sockets and chunked decoders return short reads)."""
    chunks = []
    remaining = n
    while remaining > 0:
        c = source.read(remaining)
        if not c:
            break
        chunks.append(c)
        remaining -= len(c)
    return chunks[0] if len(chunks) == 1 else b"".join(chunks)


def default_parity_count(drive_count: int) -> int:
    """Default parity by set size (cmd/format-erasure.go:896-906)."""
    if drive_count <= 1:
        return 0
    if drive_count <= 3:
        return 1
    if drive_count <= 5:
        return 2
    if drive_count <= 7:
        return 3
    return 4


class ErasureObjects(MultipartOps, ObjectLayer):
    """One erasure set over `len(disks)` drives (cmd/erasure.go:48)."""

    def __init__(self, disks: list[Optional[StorageAPI]],
                 parity: Optional[int] = None,
                 block_size: int = DEFAULT_BLOCK_SIZE,
                 backend: str = "auto",
                 bitrot_algo: str = bitrot.DEFAULT_BITROT_ALGORITHM,
                 inline_threshold: int = INLINE_THRESHOLD,
                 enforce_min_part_size: bool = True,
                 ns_lock=None):
        if not disks:
            raise ValueError("no disks")
        self.disks = list(disks)
        n = len(disks)
        self.parity = default_parity_count(n) if parity is None else parity
        self.data_blocks = n - self.parity
        if self.data_blocks <= 0:
            raise ValueError("parity too large for drive count")
        self.block_size = block_size
        self.backend = backend
        if not bitrot.available(bitrot_algo):
            # fail at construction, not on the first read: an unknown
            # algo would write shards that can never be verified back
            raise ValueError(f"unknown bitrot algorithm {bitrot_algo!r}")
        self.bitrot_algo = bitrot_algo
        self.inline_threshold = inline_threshold
        self.enforce_min_part_size = enforce_min_part_size
        if ns_lock is None:
            from ..parallel.dsync import NamespaceLock
            ns_lock = NamespaceLock()
        self.ns_lock = ns_lock
        # sized for REQUEST concurrency x drive fan-out: the reference
        # runs a goroutine per disk per request (parallelWriter,
        # cmd/erasure-encode.go:36); a pool of exactly n workers would
        # serialize concurrent PUTs behind one request's drive writes
        self._pool = ThreadPoolExecutor(max_workers=min(4 * max(4, n), 64))
        self._codec = Erasure(self.data_blocks, self.parity, block_size,
                              backend=backend) if self.parity > 0 else None
        # per-storage-class codecs (x-amz-storage-class picks parity per
        # object; geometry persists in each version's ErasureInfo)
        self._codecs: dict[int, "Erasure"] = {}
        # MRF hook (cmd/erasure-object.go:1141 addPartial): a background
        # MRFQueue attaches here; post-quorum partial writes are enqueued
        self.mrf = None
        # serial fan-out only when single-core AND all drives are local:
        # remote RPCs overlap network waits in threads on any core count
        self._serial_fanout = _SINGLE_CORE and all(
            d is None or getattr(d, "is_local", lambda: True)()
            for d in self.disks)
        # listing cache (cmd/metacache-manager.go): snapshots persist
        # through the drives' system volume; local writes invalidate
        from .metacache import MetacacheManager
        self.metacache = MetacacheManager(
            disks=[d for d in self.disks if d is not None],
            sys_volume=SYS_DIR)
        # bucket-existence cache (bucketMetadataSys role for the hot
        # path): a 16-drive stat fan-out per request re-verifies a fact
        # that changes only through make/delete_bucket.  TTL-bounded for
        # out-of-band wipes; a majority VolumeNotFound at commit time
        # also evicts and surfaces BucketNotFound (see _commit_put).
        self._bucket_ttl = 3.0
        self._buckets_seen: dict[str, float] = {}
        # pipelined PUT data plane (storage/writers.py): one persistent
        # writer thread per drive with a bounded in-order queue, shared
        # by streaming PUT, the overlapped bytes commit, multipart part
        # uploads and heal writes.  Knobs come from the ``pipeline``
        # kvconfig subsystem (env-overridable at construction; the
        # server re-reads them on admin SetConfigKV) and are consulted
        # live — the queue bound is a callable into this layer.
        self._pipe_depth = 2
        self._pipe_queue_depth = 2
        try:
            from ..utils.kvconfig import Config as _KVConfig
            self.reload_pipeline_config(_KVConfig())
        except Exception:  # noqa: BLE001 — defaults above already set
            self._pipe_depth = 0 if self._serial_fanout else 2
        self._write_plane = WriterPlane(
            queue_depth=lambda: self._pipe_queue_depth)
        # last streaming PUT's overlap numbers (mt_put_pipeline_* scrape
        # + bench.py's pipelined leg read these)
        self._pipe_stats: dict = {}
        # hot-read plane (objectlayer/hotread.py): single-flight GET
        # coalescing + the hot-object cache.  Zero owned threads;
        # knobs ride the process-global ``cache`` kvconfig subsystem
        # (S3Server.reload_cache_config pushes admin SetConfigKV and
        # wires the api_stats admission heat source)
        from .hotread import HotReadPlane
        self.hotread = HotReadPlane(self)

    def reload_pipeline_config(self, config) -> None:
        """(Re)read the ``pipeline`` kvconfig knobs — at construction
        (env > defaults) and from the server after admin SetConfigKV so
        depth changes retune a live layer.  Single-core all-local hosts
        keep the serial fan-out (same reasoning as _serial_fanout: the
        threads only add churn there); tests force the pipeline by
        assigning _pipe_depth directly."""
        try:
            depth = int(config.get("pipeline", "depth"))
        except (KeyError, ValueError):
            depth = 2
        try:
            qd = int(config.get("pipeline", "queue_depth"))
        except (KeyError, ValueError):
            qd = 2
        self._pipe_depth = 0 if self._serial_fanout else max(0, depth)
        self._pipe_queue_depth = max(1, qd)
        try:
            self._mesh_batch_cap = max(
                STREAM_BATCH_BYTES,
                int(config.get("pipeline", "mesh_batch_bytes")))
        except (KeyError, ValueError):
            # the registered default, not a guess: a malformed knob
            # value must not silently shrink the mesh batch cap
            self._mesh_batch_cap = max(STREAM_BATCH_BYTES, 268435456)
        try:
            md5fast.SCHED.set_lanes(int(config.get("pipeline",
                                                   "md5_lanes")))
        except (KeyError, ValueError):
            pass
        try:
            md5fast.set_backend(config.get("pipeline", "md5_backend"))
        except KeyError:
            pass

    def _pipeline_on(self) -> bool:
        return self._pipe_depth > 0

    # -- drive fan-out helpers --------------------------------------------

    def _fanout_items(self, fn, items, ends=None):
        """Run fn(item) concurrently over arbitrary items; returns
        (results, errs) aligned with items (parallelWriter/Reader
        analog, cmd/erasure-encode.go:36).  On a single-core host the
        thread pool buys nothing (local drive ops barely release the
        GIL) and costs queue/lock churn per item — run serially there.

        ``ends`` (optional, pre-sized to ``len(items)``): each child's
        completion time in monotonic ns lands at its item position —
        the completion vector the quorum critical-path engine
        (obs/critpath.py) reduces."""

        def run(x):
            try:
                return fn(x), None
            except Exception as e:  # noqa: BLE001 — per-item isolation
                return None, e

        if ends is None:
            runner, seq = run, items
        else:
            def runner(pair):
                out = run(pair[1])
                ends[pair[0]] = time.monotonic_ns()
                return out
            seq = list(enumerate(items))
        if self._serial_fanout:
            out = [runner(x) for x in seq]
        else:
            out = list(self._pool.map(self._with_request_id(runner),
                                      seq))
        return [r for r, _ in out], [e for _, e in out]

    @staticmethod
    def _with_request_id(run):
        """Carry the caller's request ID (plus its X-ray stage clock
        and causal span parent) into pool threads: contextvars do not
        cross thread boundaries, and pool workers are REUSED — setting
        unconditionally (even to ""/None) also clears a previous
        request's context, so per-drive spans never mislabel, stage
        detail never lands on the wrong request, and drive-op spans
        parent under the submitting span in the request's tree (the
        span-discipline lint pins this shape)."""
        from ..obs import stages as _stages
        rid = _trace.get_request_id()
        parent = _trace.get_span_parent()
        clock = _stages.current()

        def run_ctx(x):
            _trace.set_request_id(rid)
            _trace.set_span_parent(parent)
            _stages.set_clock(clock)
            return run(x)

        return run_ctx

    def _fanout(self, fn, disks=None, ends=None):
        """fn(disk) on every drive concurrently; offline (None) drives
        report DiskNotFound in the aligned error list."""

        def run(d):
            if d is None:
                raise serrors.DiskNotFound("offline")
            return fn(d)

        return self._fanout_items(run,
                                  self.disks if disks is None else disks,
                                  ends=ends)

    def _fanout_indexed(self, fn, shuffled_disks, ends=None):
        """fn((shard_idx, disk)) per drive, aligned errors; offline drives
        report DiskNotFound.  ``ends`` as in :meth:`_fanout_items`."""

        def run(pair):
            if pair[1] is None:
                return None, serrors.DiskNotFound("offline")
            try:
                out = fn(pair), None
            except Exception as e:  # noqa: BLE001
                out = None, e
            if ends is not None:
                ends[pair[0]] = time.monotonic_ns()
            return out

        if self._serial_fanout:
            out = [run(p) for p in enumerate(shuffled_disks)]
        else:
            out = list(self._pool.map(self._with_request_id(run),
                                      enumerate(shuffled_disks)))
        return [r for r, _ in out], [e for _, e in out]

    @staticmethod
    def _drive_labels(disks) -> list[str]:
        return [_critpath.drive_label(d) if d is not None else "offline"
                for d in disks]

    def _geometry(self, parity_override: int | None) -> tuple[int, int]:
        """(k, m) for a write: the layer default or a per-request parity
        from the storage class (cmd/erasure-object.go:631-642)."""
        n = len(self.disks)
        if parity_override is None:
            return self.data_blocks, self.parity
        m = parity_override
        if not 0 < m <= n // 2:
            raise ValueError(f"parity {m} out of range for {n} drives")
        return n - m, m

    def _codec_for(self, parity: int) -> "Erasure":
        """Codec for a parity count (cached; default reuses the layer's)."""
        if parity == self.parity and self._codec is not None:
            return self._codec
        codec = self._codecs.get(parity)
        if codec is None:
            n = len(self.disks)
            codec = Erasure(n - parity, parity, self.block_size,
                            backend=self.backend)
            self._codecs[parity] = codec
        return codec

    def _write_quorum(self, fi: FileInfo | None = None) -> int:
        if fi is not None:
            _, wq = meta.object_quorum_from_meta(fi)
            return wq
        wq = self.data_blocks
        if self.data_blocks == self.parity:
            wq += 1
        return wq

    # -- bucket ops (cmd/erasure-bucket.go) --------------------------------

    def make_bucket(self, bucket: str) -> None:
        _, errs = self._fanout(lambda d: d.make_vol(bucket))
        if sum(1 for e in errs if isinstance(e, serrors.VolumeExists)) \
                >= self._write_quorum():
            raise BucketExists(bucket)
        try:
            meta.reduce_errs(
                [None if isinstance(e, serrors.VolumeExists) else e
                 for e in errs],
                self._write_quorum(), WriteQuorumError)
        except serrors.StorageError as e:
            raise WriteQuorumError(str(e)) from e

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        res, errs = self._fanout(lambda d: d.stat_vol(bucket))
        for r in res:
            if r is not None:
                return BucketInfo(r.name, r.created)
        raise BucketNotFound(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        res, _ = self._fanout(lambda d: d.list_vols())
        seen: dict[str, BucketInfo] = {}
        for vols in res:
            if vols is None:
                continue
            for v in vols:
                seen.setdefault(v.name, BucketInfo(v.name, v.created))
        return sorted(seen.values(), key=lambda b: b.name)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._buckets_seen.pop(bucket, None)
        self.get_bucket_info(bucket)
        _, errs = self._fanout(lambda d: d.delete_vol(bucket, force))
        if any(isinstance(e, serrors.VolumeNotEmpty) for e in errs) \
                and not force:
            raise BucketNotEmpty(bucket)
        # the whole namespace went away: fence + release every cached
        # hot-read window of the bucket (hits were already safe — their
        # quorum revalidation now raises — this frees the bytes)
        plane = getattr(self, "hotread", None)
        if plane is not None:
            plane.invalidate_bucket(bucket)

    def _check_bucket(self, bucket: str) -> None:
        exp = self._buckets_seen.get(bucket)
        if exp is not None and time.monotonic() < exp:
            return
        self.get_bucket_info(bucket)
        self._buckets_seen[bucket] = time.monotonic() + self._bucket_ttl

    # -- PUT (cmd/erasure-object.go:614 putObject) ------------------------

    def put_object(self, bucket: str, object_name: str, data,
                   opts: Optional[PutObjectOptions] = None) -> ObjectInfo:
        """PUT from bytes or a file-like reader.  Anything larger than one
        stream batch goes through the block-batched pipeline so memory
        stays O(batch) (cmd/erasure-encode.go:80-107); smaller bodies take
        the single-dispatch fast path."""
        opts = opts or PutObjectOptions()
        if hasattr(data, "read"):
            return self.put_object_stream(bucket, object_name, data, opts)
        data = bytes(data) if not isinstance(data, bytes) else data
        if len(data) > STREAM_BATCH_BYTES:
            # zero-copy hand-off: feed the streaming pipeline memoryview
            # slices of the body instead of re-buffering the whole
            # object through io.BytesIO (one full-object copy saved)
            batch = self._stream_batch_size()
            mv = memoryview(data)
            chunks = (mv[o:o + batch] for o in range(0, len(mv), batch))
            return self._put_object_streaming(bucket, object_name,
                                              chunks, opts,
                                              readahead_body=False)
        return self._put_object_bytes(bucket, object_name, data, opts)

    def _stream_batch_size(self) -> int:
        """Whole-stripe stream batch (cmd/erasure-encode.go block loop,
        widened for TPU batching): a multiple of block_size so framing
        stays batch-invariant.

        On a MESH codec the batch additionally scales with the device
        count (capped by ``pipeline.mesh_batch_bytes``): one huge
        object's stripes must fill the whole stripe axis per dispatch,
        or a 5 TiB PUT saturates one chip while the rest idle — the
        single-transfer form of ISSUE 12 tentpole c.  Framing is
        batch-invariant, so the on-disk result is bit-identical at any
        batch size (test_put_pipeline's contract)."""
        blocks = max(1, STREAM_BATCH_BYTES // self.block_size)
        codec = self._codec
        if codec is not None and codec.backend == "mesh":
            try:
                from ..parallel import mesh as pmesh
                devs = int(np.prod(list(
                    pmesh.get_active_mesh().shape.values())))
                cap = max(1, self._mesh_batch_cap // self.block_size)
                blocks = max(blocks, min(blocks * max(1, devs), cap))
            except Exception:  # noqa: BLE001 — mesh probe is advisory
                pass
        return blocks * self.block_size

    def put_object_stream(self, bucket: str, object_name: str, reader,
                          opts: Optional[PutObjectOptions] = None
                          ) -> ObjectInfo:
        opts = opts or PutObjectOptions()
        # fail BEFORE touching the body: without this a PUT to a dead
        # bucket drains a full stream batch first (the re-check inside
        # either branch below rides the TTL cache, so this costs one
        # stat fan-out per TTL, not per PUT)
        self._check_bucket(bucket)
        batch = self._stream_batch_size()
        first = _read_full(reader, batch)
        if len(first) < batch:     # whole object fits one batch
            return self._put_object_bytes(bucket, object_name, first, opts)

        def _chunks():
            c = first
            while c:
                yield c
                if len(c) < batch:
                    return
                c = _read_full(reader, batch)

        return self._put_object_streaming(bucket, object_name, _chunks(),
                                          opts, readahead_body=True)

    def _put_object_bytes(self, bucket: str, object_name: str, data: bytes,
                          opts: PutObjectOptions) -> ObjectInfo:
        self._check_bucket(bucket)
        n = len(self.disks)
        k, m = self._geometry(opts.parity)
        # Overlap the ETag md5 with erasure encode + bitrot framing:
        # hashlib releases the GIL for large buffers, and so does the
        # native gf8 matmul, so on multi-core hosts the two truly run
        # in parallel (the reference overlaps its hash.Reader with the
        # erasure goroutines the same way, pkg/hash/reader.go).  On a
        # single-core host the handoff is pure overhead — skip it.
        etag_future = None
        if (not _SINGLE_CORE and len(data) >= (1 << 20)
                and (opts.content_md5 or _strict_compat()) and m > 0):
            # md5_of routes through the lane scheduler in 1 MiB slices:
            # concurrent PUTs' ETag passes coalesce into one multi-lane
            # native call instead of running two full serial chains
            etag_future = self._pool.submit(md5fast.md5_of, data)
        etag = None if etag_future is not None \
            else self._etag_for(data, opts)
        mod_time = opts.mod_time or now_ns()
        version_id = opts.version_id or (
            str(uuid.uuid4()) if opts.versioned else "")
        distribution = meta.hash_order(f"{bucket}/{object_name}", n)
        size = len(data)

        fi = FileInfo(
            volume=bucket, name=object_name, version_id=version_id,
            data_dir=str(uuid.uuid4()), mod_time=mod_time, size=size,
            metadata={ETAG_KEY: etag, **opts.user_defined},
            parts=[ObjectPartInfo(1, size, size, etag, mod_time)],
            erasure=ErasureInfo(
                data_blocks=k, parity_blocks=m, block_size=self.block_size,
                distribution=distribution,
                checksums=[ChecksumInfo(1, self.bitrot_algo)]),
            fresh=True)

        from ..obs import stages as _stages
        with _stages.stage("encode"):
            framed = self._encode_and_frame(data, m, fi)
        inline = size <= self.inline_threshold
        shuffled = meta.shuffle_disks(self.disks, distribution)
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=True)  # cmd/erasure-object.go:729-735 nsLock
        try:
            with _stages.stage("drive_commit"):
                if etag_future is not None and not inline \
                        and self._pipeline_on():
                    # overlapped commit: the writer plane lands the
                    # part bytes in their final data dirs WHILE the
                    # md5 still runs; only the xl.meta version merge
                    # waits for the digest.  Without this the hash
                    # overlapped encode alone and the whole drive
                    # fan-out trailed it serially — the dominant
                    # serial residue of BENCH_r05.
                    return self._commit_put_overlapped(
                        bucket, object_name, fi, framed, shuffled,
                        etag_future, opts, mod_time, size)
                if etag_future is not None:
                    self._stamp_etag(fi, etag_future.result(), opts,
                                     size, mod_time)
                return self._commit_put(bucket, object_name, fi, framed,
                                        inline, shuffled)
        finally:
            lk.unlock()

    def _commit_put_overlapped(self, bucket, object_name, fi, framed,
                               shuffled, etag_future, opts, mod_time,
                               size) -> ObjectInfo:
        """Overlapped single-part commit: the usual one-call-per-drive
        write_data_commit fan-out, but each drive writes its part bytes
        FIRST and parks on an etag gate before the xl.meta merge — so
        the md5's tail runs beside the whole drive fan-out instead of
        serializing ahead of it (pkg/hash/reader.go overlap carried
        through the commit).  A pool task resolves the gate the moment
        the digest lands; by the time a drive finishes its part bytes
        the gate is normally already open.  On BadDigest every gate
        aborts before any version became visible and the orphan data
        dirs are purged — the failed PUT leaves the same nothing the
        serial path leaves."""
        import threading as _threading
        wq = self._write_quorum(fi)
        gate = _threading.Event()
        state: dict = {}
        committed = False

        def meta_gate() -> dict:
            gate.wait()
            vd = state.get("vdict")
            if vd is None:          # digest failed: leave no version
                raise serrors.StorageError("commit aborted (BadDigest)")
            return vd

        def resolve():
            try:
                self._stamp_etag(fi, etag_future.result(), opts, size,
                                 mod_time)
                state["vdict"] = fi.to_dict()
            finally:
                gate.set()

        def write_one(idx, disk):
            disk.write_data_commit(bucket, object_name, fi, framed[idx],
                                   shard_index=idx + 1,
                                   meta_gate=meta_gate)

        # the resolver is SUBMITTED AFTER the md5 task and BEFORE the
        # fan-out: FIFO start order guarantees it runs even with every
        # fan-out worker parked on the gate (and on the writer plane
        # the fan-out consumes no pool workers at all — the gate park
        # happens on the drive writer threads, where batch-mates wait
        # behind it while the resolver runs on the freed pool)
        resolver = self._pool.submit(resolve)
        try:
            errs = self._commit_fanout(write_one, shuffled, wq, framed)
            resolver.result()       # BadDigest outranks quorum errors
            try:
                meta.reduce_errs(errs, wq, WriteQuorumError)
            except serrors.VolumeNotFound:
                self._buckets_seen.pop(bucket, None)
                raise BucketNotFound(bucket) from None
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            committed = True
            if self.mrf is not None and any(e is not None for e in errs):
                self.mrf.add(bucket, object_name, fi.version_id)
            self._hot_invalidate(bucket, object_name)
            self.metacache.invalidate(bucket)
            return self._to_object_info(fi)
        finally:
            gate.set()              # parked workers must never outlive us
            if not committed and state.get("vdict") is None:
                # no xl.meta anywhere: purge the orphan data dirs (a
                # failed digest check must leave no trace; partial
                # metadata failures belong to the scanner/heal, as
                # with the non-gated path)
                ddir = f"{object_name}/{fi.data_dir}"

                def _purge(d):
                    if d is not None:
                        d.delete(bucket, ddir, recursive=True)

                self._fanout_items(_purge, shuffled)

    def health(self, maintenance: bool = False) -> dict:
        """Cluster-health heuristic (cmd/erasure-server-pool.go:1462):
        healthy iff every erasure set keeps write quorum, counting only
        online drives; under maintenance=True, LOCAL drives are
        excluded — the answer to "can this node be taken down safely".
        healing_drives counts drives mid-heal (orchestrators must not
        pull a node while its drives are being rebuilt)."""
        wq = self._write_quorum()
        up = 0
        healing = 0
        for d in self.disks:
            if d is None:
                continue
            try:
                if not d.is_online():
                    continue
            except Exception:  # noqa: BLE001 — dead drive is offline
                continue
            if getattr(d, "healing", False):
                healing += 1
            if maintenance and d.is_local():
                continue
            up += 1
        return {"healthy": up >= wq and (not maintenance or healing == 0),
                "write_quorum": wq, "healing_drives": healing,
                "online_drives": up}

    def _etag_for(self, data: bytes, opts: PutObjectOptions) -> str:
        """ETag per the reference's hash.Reader semantics: md5 when the
        client sent Content-MD5 (verified) or in strict-compat mode
        (the default, cmd/common-main.go:208); random-with-hyphen under
        --no-compat (MT_NO_COMPAT=1), skipping the md5 pass entirely
        (pkg/hash/reader.go:186, cmd/object-api-utils.go:843-855)."""
        if opts.content_md5 or (opts.preserve_etag is None
                                and _strict_compat()):
            etag = md5fast.md5(data).hexdigest()
            if opts.content_md5 and etag != opts.content_md5.lower():
                raise serrors.StorageError(
                    "Content-MD5 mismatch (BadDigest)")
            if opts.preserve_etag is None:
                return etag
        if opts.preserve_etag is not None:
            return opts.preserve_etag
        return uuid.uuid4().hex[:32] + "-1"

    def _stamp_etag(self, fi: FileInfo, md5obj, opts: PutObjectOptions,
                    size: int, mod_time: int) -> None:
        """Resolve the single-part ETag from a finished md5 (random-
        with-hyphen under --no-compat when ``md5obj`` is None), enforce
        Content-MD5 (BadDigest on mismatch), and stamp fi's size/
        metadata/parts — the ONE definition of commit-time digest
        semantics shared by the serial bytes path, the overlapped
        commit resolver, and both streaming loops."""
        if md5obj is not None:
            etag = md5obj.hexdigest()
            if opts.content_md5 and etag != opts.content_md5.lower():
                raise serrors.StorageError(
                    "Content-MD5 mismatch (BadDigest)")
        else:
            etag = uuid.uuid4().hex[:32] + "-1"
        if opts.preserve_etag is not None:
            etag = opts.preserve_etag
        fi.size = size
        fi.metadata = {ETAG_KEY: etag, **opts.user_defined}
        fi.parts = [ObjectPartInfo(1, size, size, etag, mod_time)]

    def _encode_and_frame(self, data: bytes, m: int, fi: FileInfo):
        """Erasure-encode + bitrot-frame one batch of blocks.

        Fast host path: parity and shard bytes land DIRECTLY in the
        framed on-disk layout (one copy total), digests filled in place
        by a GIL-free native pass.  Device codecs keep the fused
        TPU encode+hash pipeline; other fallbacks take the copying
        encode_object + streaming_encode_batch route."""
        ss = fi.erasure.shard_size()
        if m > 0:
            codec = self._codec_for(m)
            if (codec.backend == "mesh"
                    and self.bitrot_algo == bitrot.HIGHWAYHASH256S):
                # multi-chip fused pipeline: parity via ICI psum XOR
                # fan-in, per-shard digests all_gathered — one sharded
                # dispatch per block batch (SURVEY §2.3 contract)
                from ..ops import rs_mesh
                return list(rs_mesh.encode_object_framed_fused(
                    codec.data_blocks, m, codec.block_size, data))
            if (codec.backend == "numpy"
                    and self.bitrot_algo == bitrot.HIGHWAYHASH256S):
                from ..ops import gf8_native
                if gf8_native.available():
                    framed2d = codec.encode_object_framed(data)
                    if bitrot.fill_framed(framed2d, ss, self.bitrot_algo):
                        return list(framed2d)
            shards = codec.encode_object(data)      # ONE device dispatch
        else:
            shards = [np.frombuffer(data, dtype=np.uint8)]
        # bitrot digests fuse onto the device when the codec runs there:
        # parity + per-block HighwayHash from one pipeline (ops/hh_kernels)
        return bitrot.streaming_encode_batch(
            shards, ss, self.bitrot_algo,
            use_device=(m > 0 and codec.is_device))

    def _commit_fanout(self, write_one, shuffled, wq, framed) -> list:
        """One commit-class fan-out (one storage call per drive) with
        its quorum critical-path row.  With the pipeline on, the ops
        ride the per-drive writer plane, where CONCURRENT streams'
        commit ops coalesce into group commits — one fsync wall settles
        many streams' writes (storage/commit.py) — and the queue bound
        widens to the group batch size so one object's whole fan-out
        enqueues without parking on itself.  The staged framed bytes
        charge the memory governor (kind=commit) while queued: a burst
        of tiny PUTs sheds 503 instead of growing every drive queue
        unbounded, and the charge releases when the stream settles —
        including death by drive error or PlaneClosed (the finally) or
        an abandoned stream (Charge.__del__).  Serial/pool fan-out
        otherwise (single-core all-local hosts)."""
        if not self._pipeline_on():
            t0 = _critpath.now_ns()
            ends = [0] * len(shuffled)
            _, errs = self._fanout_indexed(
                lambda pair: write_one(pair[0], pair[1]), shuffled,
                ends=ends)
            _critpath.record("write", wq, self._drive_labels(shuffled),
                             ends, t0, errs=errs)
            return errs
        from ..storage import commit as commitcfg
        from ..utils.memgov import GOVERNOR
        charge = GOVERNOR.charge(
            sum(len(s) for s in framed) if framed is not None else 0,
            "commit")
        sw = self._write_plane.stream(shuffled)
        bound = max(self._write_plane.queue_bound(),
                    commitcfg.CONFIG.max_batch)
        t0 = _critpath.now_ns()
        try:
            for i in range(len(shuffled)):
                sw.submit(i, write_one, bound=bound)
            sw.drain()
        except BaseException:
            sw.abort()
            sw.drain(5.0)
            raise
        finally:
            charge.release()
        sw.record_gating("write", wq, t0)
        return list(sw.errs)

    def _commit_put(self, bucket, object_name, fi, framed, inline,
                    shuffled) -> ObjectInfo:
        from ..storage import commit as commitcfg
        # packed band: past the inline threshold (below it xl.meta —
        # written regardless — carries the payload for free) but small
        # enough that the per-object data-dir mkdir + part-file
        # create/fsync trio dominates the commit: the framed shard
        # rides the drive's append-only segment instead, one batched
        # fsync pair covering every packed batch-mate.  Keyed off the
        # writer plane: grouping is a concurrency play — a lone stream
        # on a serial-fanout host pays journal overhead with no group
        # to amortize it (measured slower than eager), so packing only
        # engages where batches can actually form
        packed = (not inline and self._pipeline_on()
                  and commitcfg.CONFIG.on()
                  and 0 < fi.size <= commitcfg.CONFIG.pack_threshold
                  and len(fi.parts) == 1 and bool(fi.data_dir))
        if packed:
            fi.data_dir = ""        # the segment extent replaces it
        # serialize the version ONCE; each drive patches only its shard
        # index (the fan-out previously deep-cloned FileInfo+ErasureInfo
        # per drive — pure Python overhead on the PUT hot path)
        vdict = None if inline else fi.to_dict()

        def write_one(idx, disk):
            if inline:
                dfi = FileInfo(**{**fi.__dict__})
                dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
                dfi.erasure.index = idx + 1
                blob = framed[idx]
                dfi.inline_data = blob if isinstance(blob, bytes) \
                    else bytes(memoryview(blob).cast("B"))
                dfi.data_dir = ""
                disk.write_metadata(bucket, object_name, dfi)
            elif packed:
                blob = framed[idx]
                blob = blob if isinstance(blob, bytes) \
                    else bytes(memoryview(blob).cast("B"))
                disk.write_packed(bucket, object_name, fi, blob,
                                  shard_index=idx + 1,
                                  version_dict=vdict)
            else:
                # composite commit: one storage call (one RPC on remote
                # drives), direct final-location write on local ones
                disk.write_data_commit(bucket, object_name, fi,
                                       framed[idx],
                                       shard_index=idx + 1,
                                       version_dict=vdict)
            return idx

        wq = self._write_quorum(fi)
        errs = self._commit_fanout(write_one, shuffled, wq, framed)
        try:
            meta.reduce_errs(errs, wq, WriteQuorumError)
        except serrors.VolumeNotFound:
            # bucket wiped out-of-band while the existence cache was
            # warm: evict and report what a fresh stat would have said
            self._buckets_seen.pop(bucket, None)
            raise BucketNotFound(bucket) from None
        except serrors.StorageError as e:
            raise WriteQuorumError(str(e)) from e
        # failed writes become heal candidates (MRF analog,
        # cmd/erasure-object.go:783-789): quorum met but some drive
        # missed the write — queue a prompt re-heal
        if self.mrf is not None and any(e is not None for e in errs):
            self.mrf.add(bucket, object_name, fi.version_id)
        self._hot_invalidate(bucket, object_name)
        self.metacache.invalidate(bucket)
        return self._to_object_info(fi)

    def _put_object_streaming(self, bucket: str, object_name: str,
                              chunks, opts: PutObjectOptions,
                              readahead_body: bool = True) -> ObjectInfo:
        """Block-batched streaming PUT over an iterator of body chunks
        (each chunk one stream batch; only the final chunk may be
        short).  Two data planes with bit-identical on-disk results
        (tests/test_put_pipeline.py pins the contract):

          * pipelined (default): per-drive writer queues overlap batch
            N+1's encode with batch N's create/append fan-out, the ETag
            md5 runs as a chained pool task beside both, and framed
            buffers recycle through utils/bufpool — the reference's
            hash.Reader-beside-erasure-goroutines overlap
            (pkg/hash/reader.go + cmd/erasure-encode.go:80-107
            parallelWriter), batched the TPU way;
          * serial (pipeline.depth=0, single-core all-local hosts):
            the original per-batch fan-out round-trips.

        Commit stays a single quorum rename_data at EOF
        (cmd/erasure-object.go:772-779)."""
        self._check_bucket(bucket)
        n = len(self.disks)
        k, m = self._geometry(opts.parity)
        mod_time = opts.mod_time or now_ns()
        version_id = opts.version_id or (
            str(uuid.uuid4()) if opts.versioned else "")
        distribution = meta.hash_order(f"{bucket}/{object_name}", n)
        fi = FileInfo(
            volume=bucket, name=object_name, version_id=version_id,
            data_dir=str(uuid.uuid4()), mod_time=mod_time, size=0,
            metadata={**opts.user_defined},
            erasure=ErasureInfo(
                data_blocks=k, parity_blocks=m, block_size=self.block_size,
                distribution=distribution,
                checksums=[ChecksumInfo(1, self.bitrot_algo)]),
            fresh=True)
        shuffled = meta.shuffle_disks(self.disks, distribution)
        wq = self._write_quorum(fi)
        # mesh-scaled encode batches charge the node memory governor
        # for the stream's lifetime (the PR-11 deferred follow-up):
        # ``pipeline.depth`` batches of body plus the one in hand are
        # live at once, so a mesh-widened batch is pressure the
        # watermark must admit BEFORE the body is drained (over it,
        # the S3 front sheds 503 + Retry-After instead of OOMing)
        charge = self._batch_charge(-1, slots=self._pipe_depth + 1)
        try:
            if self._pipeline_on():
                return self._stream_put_pipelined(
                    bucket, object_name, chunks, opts, fi, m, shuffled,
                    wq, mod_time, readahead_body)
            return self._stream_put_serial(
                bucket, object_name, chunks, opts, fi, m, shuffled, wq,
                mod_time, readahead_body)
        finally:
            if charge is not None:
                charge.release()

    @staticmethod
    def _md5_link(prev, h, chunk, stats) -> None:
        """One chained md5 update on the pool: waits for the previous
        link (updates are order-dependent), then hashes its chunk
        through the shared lane scheduler — concurrent streams'/parts'
        links coalesce into one multi-lane native call
        (hashing/md5fast.py; a lone stream degenerates to the plain
        fast core).  Native and hashlib updates both release the GIL,
        so the chain truly runs beside encode and the writer queues.
        The chain never deadlocks the pool: each link waits only on an
        EARLIER submission, and the executor starts tasks FIFO.

        ``md5_s`` is the link's WALL time: under concurrent streams it
        includes lane-scheduler sharing (parking while another stream's
        combiner hashes this chunk, or combining other streams'
        chunks), so per-PUT md5_s is a utilization view, not a pure
        hash cost — single-stream runs (the bench's pipelined leg) are
        unaffected."""
        if prev is not None:
            prev.result()
        t0 = time.perf_counter()
        md5fast.SCHED.update(h, chunk)
        stats["md5_s"] += time.perf_counter() - t0

    def _framed_fast_path(self, m: int) -> bool:
        """True when _encode_and_frame takes the host one-copy framed
        route (the only path worth recycling output buffers for)."""
        if m <= 0 or self.bitrot_algo != bitrot.HIGHWAYHASH256S:
            return False
        if self._codec_for(m).backend != "numpy":
            return False
        from ..hashing.highwayhash import _get_lib
        from ..ops import gf8_native
        # both natives must be present: without hh256_fill the framed
        # encode would be thrown away and re-done by the fallback
        return gf8_native.available() and _get_lib() is not None

    def _encode_framed_pooled(self, chunk, m: int, fi: FileInfo, stats):
        """Encode + frame one batch, recycling the framed 2-D buffer
        through utils/bufpool when the host fast path runs.  Returns
        (framed_rows, release_cb) — release fires once every drive
        wrote the batch (memory stays O(depth x batch))."""
        from ..obs import stages as _stages
        t0 = time.perf_counter()
        try:
            # a real stage frame (not a finally-add): time the codec
            # batcher parks inside (batch_wait) is subtracted as child
            # time, keeping the serial reconciliation exact on device
            # backends too
            with _stages.stage("encode"):
                if len(chunk) and self._framed_fast_path(m):
                    codec = self._codec_for(m)
                    buf = bufpool.GLOBAL.acquire(
                        codec.framed_shape(len(chunk)))
                    framed2d = codec.encode_object_framed(chunk,
                                                          out=buf)
                    if bitrot.fill_framed(framed2d,
                                          fi.erasure.shard_size(),
                                          self.bitrot_algo):
                        return list(framed2d), \
                            (lambda b=buf: bufpool.GLOBAL.release(b))
                    bufpool.GLOBAL.release(buf)   # native hash missing
                return self._encode_and_frame(chunk, m, fi), None
        finally:
            stats["encode_s"] += time.perf_counter() - t0

    def _pump_put_pipeline(self, chunks, sw, m, fi, md5, stats,
                           write_batch_for, wq) -> tuple[int, int]:
        """The shared stage driver of every pipelined upload (streaming
        PUT and multipart parts): chained md5 on the pool, encode into
        a recycled buffer, per-drive writer queues — batches in flight
        bounded to ``pipeline.depth`` (O(depth x batch) memory) and
        quorum re-checked as completions drain, so latched errors end
        the stream early instead of encoding the rest of a doomed body.
        ``write_batch_for(framed)`` returns the per-drive write for one
        batch's framed rows.  Returns (total_bytes, batches)."""
        n = len(self.disks)
        depth = max(1, self._pipe_depth)
        md5_links: collections.deque = collections.deque()
        inflight: collections.deque = collections.deque()
        total = batches = 0
        for chunk in chunks:
            total += len(chunk)
            batches += 1
            if md5 is not None:
                md5_links.append(self._pool.submit(
                    self._md5_link,
                    md5_links[-1] if md5_links else None,
                    md5, chunk, stats))
                while len(md5_links) > depth:
                    md5_links.popleft().result()
            framed, release = self._encode_framed_pooled(
                chunk, m, fi, stats)
            inflight.append(sw.submit_batch(write_batch_for(framed),
                                            release=release))
            while len(inflight) > depth:
                # depth-bound backpressure: the pipeline is full, the
                # request thread parks behind the writer plane
                t0 = time.perf_counter()
                inflight.popleft().done.wait()
                from ..obs import stages as _stages
                _stages.add("write_enqueue",
                            int((time.perf_counter() - t0) * 1e9))
            alive = sw.alive()
            if alive < wq:
                sw.abort()
                raise WriteQuorumError(
                    f"{alive} of {n} drives writable, need {wq}")
        for f in md5_links:
            f.result()
        return total, batches

    def _stream_put_pipelined(self, bucket, object_name, chunks, opts,
                              fi, m, shuffled, wq, mod_time,
                              readahead_body) -> ObjectInfo:
        """The pipelined loop: body readahead -> chained md5 -> encode
        into a recycled buffer -> per-drive writer queues.  Per drive
        the op order is strictly create, then appends, then rename_data
        (single writer thread per drive, FIFO queue); errors latch per
        drive and quorum is re-checked as completions drain."""
        from ..utils.readahead import readahead
        n = len(self.disks)
        tmps: list[str | None] = [None] * n
        md5 = md5fast.md5() if (opts.content_md5 or _strict_compat()) \
            else None
        stats = {"md5_s": 0.0, "encode_s": 0.0}
        depth = max(1, self._pipe_depth)
        sw = self._write_plane.stream(shuffled)
        from ..obs import stages as _stages
        src = None
        t_wall0 = time.perf_counter()
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=True)
        try:
            # started only after the lock is held and inside the try: a
            # lock failure must not leave a thread draining the body
            # socket with no close().  depth-1 queued + one in hand =
            # ``pipeline.depth`` batches of body in flight.
            src = readahead(chunks, depth=max(1, depth - 1)) \
                if readahead_body else chunks

            def write_batch_for(framed):
                def write_batch(idx, disk):
                    if tmps[idx] is None:
                        # tmp_dir here, ON the drive's writer (an RPC
                        # on remote drives): only this worker touches
                        # tmps[idx] until the stream drains
                        tmps[idx] = disk.tmp_dir()
                        disk.create_file(SYS_DIR, f"{tmps[idx]}/part.1",
                                         framed[idx])
                    else:
                        disk.append_file(SYS_DIR, f"{tmps[idx]}/part.1",
                                         framed[idx])
                return write_batch

            total, batches = self._pump_put_pipeline(
                src, sw, m, fi, md5, stats, write_batch_for, wq)
            self._stamp_etag(fi, md5, opts, total, mod_time)
            with _stages.stage("write_drain"):
                t_drain = _critpath.now_ns()
                sw.drain()
                sw.record_gating("write_drain", wq, t_drain)
            alive = sw.alive()
            if alive < wq:
                raise WriteQuorumError(
                    f"{alive} of {n} drives writable, need {wq}")
            # queues are DRAINED here: a lock whose grants lapsed while
            # the body streamed must abort before any commit op is
            # queued (drwmutex refresh-loss semantics)
            if hasattr(lk, "ensure_valid"):
                lk.ensure_valid()

            def commit_one(idx, disk):
                dfi = FileInfo(**{**fi.__dict__})
                dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
                dfi.erasure.index = idx + 1
                disk.rename_data(SYS_DIR, tmps[idx], dfi, bucket,
                                 object_name)

            with _stages.stage("drive_commit"):
                t_commit = _critpath.now_ns()
                sw.submit_batch(commit_one)
                sw.drain()
                sw.record_gating("commit", wq, t_commit)
            cerrs = list(sw.errs)
            try:
                meta.reduce_errs(cerrs, wq, WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            if self.mrf is not None and any(e is not None for e in cerrs):
                self.mrf.add(bucket, object_name, fi.version_id)
            self._hot_invalidate(bucket, object_name)
            self.metacache.invalidate(bucket)
            wall = time.perf_counter() - t_wall0
            write_s = sw.max_busy_s()
            crit = max(stats["md5_s"], stats["encode_s"], write_s)
            self._pipe_stats = {
                "wall_s": wall, "md5_s": stats["md5_s"],
                "encode_s": stats["encode_s"], "write_s": write_s,
                "batches": batches, "bytes": total,
                "overlap_efficiency": crit / wall if wall > 0 else 0.0,
            }
            return self._to_object_info(fi)
        finally:
            if src is not None and readahead_body:
                src.close()  # stop + JOIN the readahead thread: the
                             # handler reuses the body socket next
            sw.abort()
            # settle the queues before tmp cleanup — a worker must not
            # append into a dir being removed (bounded wait: a hung
            # drive op must not wedge the handler thread forever)
            sw.drain(timeout=10.0)
            lk.unlock()
            # when_drive_idle: immediate for settled drives; a drive
            # hung past the drain timeout cleans at op settlement, so
            # its resumed append (makedirs exist_ok) cannot resurrect
            # the tmp dir after the rmtree.  tmps[idx] is read at FIRE
            # time: a first-batch op still stuck inside tmp_dir() has
            # not assigned it yet — eager binding would skip the drive
            # and leak whatever the resumed op stages
            def _clean_tmp_cb(d, i):
                if tmps[i] is not None:
                    d.clean_tmp(tmps[i])

            for idx, disk in enumerate(shuffled):
                if disk is not None:
                    sw.when_drive_idle(
                        idx, lambda d=disk, i=idx: _clean_tmp_cb(d, i))

    def _stream_put_serial(self, bucket, object_name, chunks, opts, fi,
                           m, shuffled, wq, mod_time,
                           readahead_body) -> ObjectInfo:
        """The original serial loop: one synchronous fan-out round per
        batch.  Kept verbatim as the reference semantics (the pipelined
        plane must match it byte for byte) and as the single-core
        fallback."""
        n = len(self.disks)
        tmps: list[str | None] = [None] * n
        errs: list[Exception | None] = [None] * n
        # md5 only when the client sent Content-MD5 or in strict-compat
        # mode — same policy as _etag_for (pkg/hash/reader.go:186)
        md5 = md5fast.md5() if (opts.content_md5 or _strict_compat()) \
            else None
        total = 0

        # readahead on the body: the network read of batch N+1 overlaps
        # batch N's encode + drive writes (klauspost/readahead role,
        # cmd/xl-storage.go:1544-1546)
        from ..utils.readahead import readahead

        from ..obs import stages as _stages
        src = None
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=True)
        try:
            # started only after the lock is held and inside the try:
            # a lock failure must not leave a thread draining the body
            # socket with no close()
            src = readahead(chunks, depth=1) if readahead_body else chunks
            for chunk in src:
                if md5 is not None:
                    md5.update(chunk)
                total += len(chunk)
                with _stages.stage("encode"):
                    framed = self._encode_and_frame(chunk, m, fi)

                def write_batch(idx_disk):
                    idx, disk = idx_disk
                    if disk is None or errs[idx] is not None:
                        return  # dead drive: a later append would corrupt
                    if tmps[idx] is None:
                        tmps[idx] = disk.tmp_dir()
                        disk.create_file(SYS_DIR, f"{tmps[idx]}/part.1",
                                         framed[idx])
                    else:
                        disk.append_file(SYS_DIR, f"{tmps[idx]}/part.1",
                                         framed[idx])

                with _stages.stage("drive_commit"):
                    _, werrs = self._fanout_indexed(write_batch,
                                                    shuffled)
                for i, e in enumerate(werrs):
                    if e is not None and errs[i] is None:
                        errs[i] = e
                alive = sum(1 for i, d in enumerate(shuffled)
                            if d is not None and errs[i] is None)
                if alive < wq:
                    raise WriteQuorumError(
                        f"{alive} of {n} drives writable, need {wq}")
            self._stamp_etag(fi, md5, opts, total, mod_time)
            # the lock was held across the whole body stream; if its
            # grants fell below quorum meanwhile, committing would race
            # a new writer (drwmutex refresh-loss semantics)
            if hasattr(lk, "ensure_valid"):
                lk.ensure_valid()

            def commit_one(idx_disk):
                idx, disk = idx_disk
                if disk is None:
                    raise serrors.DiskNotFound("offline")
                if errs[idx] is not None:
                    raise errs[idx]
                dfi = FileInfo(**{**fi.__dict__})
                dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
                dfi.erasure.index = idx + 1
                disk.rename_data(SYS_DIR, tmps[idx], dfi, bucket,
                                 object_name)

            t0 = _critpath.now_ns()
            cends = [0] * len(shuffled)
            _, cerrs = self._fanout_indexed(commit_one, shuffled,
                                            ends=cends)
            _critpath.record("commit", wq, self._drive_labels(shuffled),
                             cends, t0, errs=cerrs)
            try:
                meta.reduce_errs(cerrs, wq, WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            if self.mrf is not None and any(e is not None for e in cerrs):
                self.mrf.add(bucket, object_name, fi.version_id)
            self._hot_invalidate(bucket, object_name)
            self.metacache.invalidate(bucket)
            return self._to_object_info(fi)
        finally:
            if src is not None and readahead_body:
                src.close()  # stop + JOIN the readahead thread: the
                             # handler reuses the body socket next
            lk.unlock()
            for idx, disk in enumerate(shuffled):
                if disk is not None and tmps[idx] is not None:
                    try:
                        disk.clean_tmp(tmps[idx])
                    except Exception:  # noqa: BLE001 — best-effort cleanup
                        pass

    # -- GET (cmd/erasure-object.go:242 getObjectWithFileInfo) -------------

    def _read_quorum_fileinfo(self, bucket: str, object_name: str,
                              version_id: Optional[str] = None
                              ) -> tuple[FileInfo, list[FileInfo | None]]:
        t0 = _critpath.now_ns()
        ends = [0] * len(self.disks)
        fis, errs = self._fanout(
            lambda d: d.read_version(bucket, object_name, version_id),
            ends=ends)
        nf = sum(1 for e in errs
                 if isinstance(e, (serrors.FileNotFound,
                                   serrors.FileVersionNotFound)))
        if nf > len(self.disks) // 2:
            if version_id is not None and any(
                    isinstance(e, serrors.FileVersionNotFound) for e in errs):
                raise VersionNotFound(f"{bucket}/{object_name}@{version_id}")
            raise ObjectNotFound(f"{bucket}/{object_name}")
        quorum = max(1, len(self.disks) // 2)
        fi = meta.find_file_info_in_quorum(fis, quorum)
        _critpath.record("read_meta", quorum,
                         self._drive_labels(self.disks), ends, t0,
                         errs=errs)
        return fi, fis

    def get_object_info(self, bucket: str, object_name: str,
                        opts: Optional[ObjectOptions] = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self._check_bucket(bucket)
        lk = self.ns_lock.new_lock(bucket, object_name)
        lk.lock(write=False)   # rlock, as GetObjectInfo does
        try:
            fi, _ = self._read_quorum_fileinfo(bucket, object_name,
                                               opts.version_id)
            return self._to_object_info(fi)
        finally:
            lk.unlock()

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[ObjectOptions] = None
                   ) -> tuple[ObjectInfo, bytes]:
        # fully-buffered read: joins immediately, so the readahead
        # thread would add overhead with zero overlap to exploit
        info, gen = self.get_object_reader(bucket, object_name, offset,
                                           length, opts, _readahead=False)
        return info, b"".join(gen)

    def get_object_reader(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          opts: Optional[ObjectOptions] = None,
                          _readahead: bool = True):
        """Range GET as (info, chunk iterator): reads ONLY the shard byte
        ranges covering the requested blocks (ShardFileOffset math,
        cmd/erasure-coding.go:134 + cmd/erasure-decode.go:229-246) and
        decodes batch-of-blocks at a time, so a 1 MiB range of a 100 GiB
        object touches one block per shard and memory stays O(batch)."""
        opts = opts or ObjectOptions()
        # hot-read plane first: concurrent readers of one window share
        # ONE drive read + decode, and hot windows serve straight from
        # the validated cache.  Every non-happy path returns None and
        # falls through here, so the reference error semantics below
        # stay the single source of truth.
        from ..obs import stages as _stages
        plane = self.hotread
        if plane is not None:
            with _stages.stage("cache"):
                served = plane.serve(bucket, object_name, offset,
                                     length, opts)
            if served is not None:
                return served
        self._check_bucket(bucket)
        # read lock for the duration of the stream (GetObjectNInfo takes
        # the nsLock RLock, cmd/erasure-object.go:136): a reader racing a
        # PUT/DELETE commit must never observe a half-renamed version set
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=False)
        try:
            fi, fis = self._read_quorum_fileinfo(bucket, object_name,
                                                 opts.version_id)
            if fi.deleted:
                raise MethodNotAllowed(f"{bucket}/{object_name} is a "
                                       "delete marker")
            # HTTP range semantics in one pass (cmd/httprange.go):
            # negative offset = suffix (last -offset bytes); length < 0 =
            # to end; overlong ranges clamp; start past EOF is invalid
            size = fi.size
            if offset < 0:
                offset = max(0, size + offset)
            if length < 0:
                length = size - offset
            if offset > size or (size > 0 and offset == size):
                from .interface import InvalidRange
                raise InvalidRange(f"{offset}+{length} vs {size}")
            length = min(length, size - offset)
            info = self._to_object_info(fi)
        except BaseException:
            lk.unlock()
            raise
        if size == 0 or length == 0:
            lk.unlock()
            return info, iter(())
        # mesh-scaled decode batches charge the node memory governor
        # for the stream's lifetime (the PR-11 deferred follow-up): a
        # GET whose batch the mesh widened past the base is real
        # memory pressure the watermark must see (release on close)
        try:
            charge = self._batch_charge(length)
        except BaseException:
            lk.unlock()
            raise
        gen = self._locked_stream(
            lk, self._stream_range(bucket, object_name, fi, fis,
                                   offset, length),
            on_close=(charge.release if charge is not None else None))
        if not _readahead:
            return info, gen
        # readahead: block batch N+1's shard reads + decode overlap the
        # consumer sending batch N (klauspost/readahead role, go.mod:39;
        # pipeline overlap of cmd/bitrot-streaming.go:74-89).  Depth
        # follows the ``pipeline.depth`` knob minus the batch in the
        # consumer's hand, so PUT and GET share one memory bound
        # (default depth 2 -> queue 1, full double-buffering at half
        # the buffered memory — the RSS gate in test_streaming bounds
        # the whole pipeline)
        from ..utils.readahead import readahead
        return info, readahead(gen, depth=max(1, self._pipe_depth - 1))

    @staticmethod
    def _locked_stream(lk, inner, on_close=None):
        """Hold a lock until the stream is exhausted or abandoned.

        NOT a generator on purpose: per PEP 342, closing/GC-ing a
        generator that was never advanced does not run its body, so a
        try/finally inside one never executes and the lock would leak
        forever (the refresh keepalive keeps the grant alive).  This
        wrapper unlocks exactly once on exhaustion, error, close(), or
        GC — advanced or not."""
        return _LockedStream(lk, inner, on_close)

    def _batch_charge(self, active_bytes: int, slots: int = 2):
        """Governor charge for one stream's batch working set — only
        when the MESH scaling widened the batch past the base
        ``STREAM_BATCH_BYTES`` (the base bound predates the governor
        and is fenced by the RSS tests; the scaled portion is the new
        pressure ``pipeline.mesh_batch_bytes`` caps but nothing
        previously accounted).  ``slots`` ≈ live copies of one batch
        (framed shards + assembled payload for GET; queued encode
        buffers for PUT).  Returns None when no charge applies; raises
        MemoryPressure past the watermark (the S3 front sheds 503)."""
        batch = self._stream_batch_size()
        if batch <= STREAM_BATCH_BYTES:
            return None
        est = batch if active_bytes < 0 else min(batch, active_bytes)
        if est <= STREAM_BATCH_BYTES:
            return None
        from ..utils.memgov import GOVERNOR
        return GOVERNOR.charge(est * max(1, slots), "pipeline")

    def _hot_fileinfo(self, bucket: str, object_name: str,
                      version_id: Optional[str]):
        """Hot-read plane validation read: one ns-read-locked quorum
        metadata pass, returning ``(fi, info)`` — the identity a cache
        hit compares before serving (diskcache.py ETag-validation
        role, quorum-consistent so a committed overwrite on ANY node
        is always seen)."""
        from ..obs import stages as _stages
        self._check_bucket(bucket)
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=False)
        try:
            fi, _ = self._read_quorum_fileinfo(bucket, object_name,
                                               version_id)
            return fi, self._to_object_info(fi)
        finally:
            lk.unlock()

    def _hot_read_window(self, bucket: str, object_name: str,
                         version_id: Optional[str], start: int,
                         wlen: int):
        """Hot-read plane leader fetch: ONE ns-read-locked pass
        resolving quorum metadata and decoding the window's plain
        bytes (inline-tiny objects serve straight from the metadata
        quorum read — ``_stream_range`` reads ``inline_data`` without
        any drive data fan-out).  Returns ``(fi, info, data)``; data
        is None for delete markers and out-of-range starts (the
        caller falls through to the reference error path)."""
        from ..obs import stages as _stages
        self._check_bucket(bucket)
        lk = self.ns_lock.new_lock(bucket, object_name)
        with _stages.stage("lock_wait"):
            lk.lock(write=False)
        try:
            fi, fis = self._read_quorum_fileinfo(bucket, object_name,
                                                 version_id)
            info = self._to_object_info(fi)
            if fi.deleted:
                return fi, info, None
            size = fi.size
            if size == 0:
                return fi, info, b""
            if start >= size:
                return fi, info, None
            n = min(wlen, size - start)
            data = b"".join(self._stream_range(bucket, object_name,
                                               fi, fis, start, n))
            return fi, info, data
        finally:
            lk.unlock()

    def _hot_invalidate(self, bucket: str, object_name: str) -> None:
        """Write-path fence: called inside every ns-write-locked
        commit section BEFORE the write is acknowledged, so cached
        windows are gone and straddling fills are refused by the time
        any client can observe the new version."""
        plane = getattr(self, "hotread", None)
        if plane is not None:
            plane.invalidate(bucket, object_name)

    def _stream_range(self, bucket: str, object_name: str, fi: FileInfo,
                      fis: list[FileInfo | None], offset: int, length: int):
        """Generator over the requested byte range, block-batch at a time.
        Shard-read failures extend into parity shards (parallelReader,
        cmd/erasure-decode.go:120-188); a failed shard stays dead for the
        remainder of the stream."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        nsh = k + m
        bs = fi.erasure.block_size
        ssize = fi.erasure.shard_size()
        algo = self.bitrot_algo
        hlen = bitrot.digest_size(algo) if bitrot.is_streaming(algo) else 0
        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        sfis = meta.shuffle_parts_metadata(fis, fi.erasure.distribution)
        # mesh codecs widen the decode batch with the device count the
        # same way the PUT batch scales (_stream_batch_size): one huge
        # GET's reconstruct dispatches fill the stripe axis
        batch_blocks = max(1, self._stream_batch_size() // bs)
        dead: set[int] = set(
            j for j in range(nsh) if shuffled[j] is None)
        end = offset + length
        part_start = 0
        for part in fi.parts:
            if part_start + part.size <= offset:
                part_start += part.size
                continue
            if part_start >= end:
                break
            p0 = max(0, offset - part_start)
            p1 = min(part.size, end - part_start)
            sfsize = fi.erasure.shard_file_size(part.size)
            b0 = p0 // bs
            bend = -(-p1 // bs)
            for bb0 in range(b0, bend, batch_blocks):
                bb1 = min(bb0 + batch_blocks, bend)
                logical_off = bb0 * ssize
                logical_end = min(bb1 * ssize, sfsize)
                seg_len = logical_end - logical_off
                framed_off = logical_off + bb0 * hlen
                framed_len = seg_len + (bb1 - bb0) * hlen
                covered = min(bb1 * bs, part.size) - bb0 * bs
                from ..obs import stages as _stages
                with _stages.stage("drive_read"):
                    shards = self._read_shard_segments(
                        bucket, object_name, fi, part, shuffled, sfis,
                        dead, framed_off, framed_len, seg_len, ssize,
                        algo)
                with _stages.stage("decode"):
                    part_bytes = self._assemble(shards, fi, covered)
                lo = max(p0 - bb0 * bs, 0)
                hi = min(p1 - bb0 * bs, covered)
                yield part_bytes[lo:hi].tobytes()
            part_start += part.size
        # shards that failed mid-stream are heal candidates
        # (on-read heal trigger, cmd/erasure-object.go:330-342)
        if self.mrf is not None and \
                any(shuffled[j] is not None for j in dead):
            self.mrf.add(bucket, object_name, fi.version_id)

    def _read_shard_segments(self, bucket, object_name, fi, part, shuffled,
                             sfis, dead: set[int], framed_off: int,
                             framed_len: int, seg_len: int, ssize: int,
                             algo: str) -> list:
        """Read one block-batch's byte range from k healthy shards,
        extending into parity on failure; returns a length-n list with
        np arrays at the indices read."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        nsh = k + m

        def read_one(j):
            disk = shuffled[j]
            dfi = sfis[j]
            if disk is None:
                raise serrors.DiskNotFound("offline")
            if dfi is not None and dfi.inline_data is not None:
                framed = dfi.inline_data[framed_off:framed_off + framed_len]
                if len(framed) < framed_len:
                    raise serrors.FileCorrupt("short inline data")
            elif dfi is not None and getattr(dfi, "seg", None):
                # packed object: the framed shard lives at an extent
                # inside the drive's segment file (storage/commit.py);
                # same window arithmetic, different backing file
                framed = disk.read_segment(
                    dfi.seg["sid"], dfi.seg["off"] + framed_off,
                    framed_len)
            else:
                framed = disk.read_file_stream(
                    bucket,
                    f"{object_name}/{fi.data_dir}/part.{part.number}",
                    framed_off, framed_len)
            try:
                # one native verify pass + one strided payload copy
                fast = bitrot.verify_extract(framed, ssize, seg_len, algo)
                if fast is not None:
                    return fast
                r = bitrot.StreamingBitrotReader(framed, ssize, algo)
                return np.frombuffer(r.read_at(0, seg_len), dtype=np.uint8)
            except bitrot.BitrotError as e:
                raise serrors.FileCorrupt(str(e)) from e

        shards: list[np.ndarray | None] = [None] * nsh
        got = 0
        t0 = _critpath.now_ns()
        ends_all = [0] * nsh
        candidates = [j for j in range(nsh) if j not in dead]
        while got < k and candidates:
            batch, candidates = candidates[:k - got], candidates[k - got:]
            bends = [0] * len(batch)
            res, errs = self._fanout_items(read_one, batch, ends=bends)
            for pos, (j, r, e) in enumerate(zip(batch, res, errs)):
                ends_all[j] = bends[pos]
                if e is None:
                    shards[j] = r
                    got += 1
                else:
                    dead.add(j)
        if got < k:
            raise ReadQuorumError(f"only {got} of {k} shards readable")
        _critpath.record("read", k, self._drive_labels(shuffled),
                         ends_all, t0,
                         errs=[True if j in dead else None
                               for j in range(nsh)])
        return shards

    def _assemble(self, shards: list[np.ndarray | None], fi: FileInfo,
                  part_size: int) -> np.ndarray:
        """Reconstruct missing data shards (batched over stripes) and
        concatenate the data blocks (writeDataBlocks analog,
        cmd/erasure-utils.go:40)."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        bs = fi.erasure.block_size
        ssize = fi.erasure.shard_size()
        nfull = part_size // bs
        tail = part_size - nfull * bs
        missing_data = [i for i in range(k) if shards[i] is None]
        if missing_data:
            if m <= 0:
                raise ReadQuorumError("no parity to reconstruct from")
            # the OBJECT's persisted geometry picks the matrix — a
            # storage-class parity differs from the layer default
            codec = self._codec_for(m)
            present = [i for i in range(k + m) if shards[i] is not None][:k]
            sfsize = fi.erasure.shard_file_size(part_size)
            mat = codec.matrix
            from ..ops import rs_kernels
            rows = rs_kernels.decode_rows(mat, k, present, missing_data)
            rebuilt_full = None
            if nfull:
                # identical survivor pattern across all full stripes ->
                # one batched reconstruction dispatch
                surv = np.stack([shards[i][: nfull * ssize]
                                 .reshape(nfull, ssize) for i in present],
                                axis=1)  # (nfull, k, ssize)
                if codec.is_device:
                    rebuilt_full = codec.apply_matrix(rows, surv)
                else:
                    rebuilt_full = np.stack(
                        [gf8.gf_matmul(rows, surv[b]) for b in range(nfull)])
            rebuilt_tail = None
            if tail:
                t_ssize = gf8.ceil_frac(tail, k)
                surv_t = np.stack(
                    [shards[i][nfull * ssize: nfull * ssize + t_ssize]
                     for i in present])  # (k, t_ssize)
                if codec.is_device:
                    rebuilt_tail = codec.apply_matrix(rows, surv_t)
                else:
                    rebuilt_tail = gf8.gf_matmul(rows, surv_t)
            for j, i in enumerate(missing_data):
                full = np.empty(sfsize, dtype=np.uint8)
                if rebuilt_full is not None:
                    full[: nfull * ssize] = rebuilt_full[:, j].reshape(-1)
                if rebuilt_tail is not None:
                    full[nfull * ssize:] = rebuilt_tail[j]
                shards[i] = full
        # concatenate data blocks, trimming per-block padding: one
        # strided copy per shard over ALL blocks (the mirror of
        # encode_object_framed's placement loop) — a per-block
        # np.concatenate costs a second full pass over the data
        out = np.empty(part_size, dtype=np.uint8)
        if nfull:
            dview = out[:nfull * bs].reshape(nfull, bs)
            for i in range(k):
                lo = i * ssize
                ln = min(ssize, max(0, bs - lo))
                if ln:
                    dview[:, lo:lo + ln] = \
                        shards[i][:nfull * ssize].reshape(
                            nfull, ssize)[:, :ln]
        if tail:
            t_ssize = gf8.ceil_frac(tail, k)
            pos = nfull * bs
            for i in range(k):
                lo = i * t_ssize
                ln = min(t_ssize, max(0, tail - lo))
                if ln:
                    out[pos + lo:pos + lo + ln] = shards[i][
                        nfull * ssize: nfull * ssize + ln]
        return out

    # -- DELETE (cmd/erasure-object.go:803-1139) ---------------------------

    def delete_object(self, bucket: str, object_name: str,
                      opts: Optional[ObjectOptions] = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self._check_bucket(bucket)
        mod_time = opts.mod_time or now_ns()
        # write lock (DeleteObject takes the nsLock, cmd/erasure-object.go
        # delete path): a delete racing a PUT commit must not interleave
        # per-drive version mutations
        lk = self.ns_lock.new_lock(bucket, object_name)
        lk.lock(write=True)
        try:
            if opts.versioned and opts.version_id is None:
                # versioned delete without a version: write a delete marker
                dm = FileInfo(volume=bucket, name=object_name,
                              version_id=str(uuid.uuid4()), deleted=True,
                              data_dir="", mod_time=mod_time)
                _, errs = self._fanout(
                    lambda d: d.delete_version(bucket, object_name, dm,
                                               force_del_marker=True))
                try:
                    meta.reduce_errs(errs, self._write_quorum(),
                                     WriteQuorumError)
                except serrors.StorageError as e:
                    raise WriteQuorumError(str(e)) from e
                oi = ObjectInfo(bucket=bucket, name=object_name,
                                version_id=dm.version_id,
                                delete_marker=True, mod_time=mod_time)
                self._hot_invalidate(bucket, object_name)
                self.metacache.invalidate(bucket)
                return oi
            # delete a concrete version (or the null version)
            vid = opts.version_id or ""
            fi = FileInfo(volume=bucket, name=object_name, version_id=vid,
                          mod_time=mod_time)
            _, errs = self._fanout(
                lambda d: d.delete_version(bucket, object_name, fi))
            nf = sum(1 for e in errs
                     if isinstance(e, (serrors.FileNotFound,
                                       serrors.FileVersionNotFound)))
            if nf > len(self.disks) // 2:
                # object absent: S3 DELETE is idempotent; return quietly
                return ObjectInfo(bucket=bucket, name=object_name,
                                  version_id=vid)
            try:
                meta.reduce_errs(errs, self._write_quorum(),
                                 WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            self._hot_invalidate(bucket, object_name)
            self.metacache.invalidate(bucket)
            return ObjectInfo(bucket=bucket, name=object_name,
                              version_id=vid)
        finally:
            lk.unlock()

    def put_object_metadata(self, bucket: str, object_name: str,
                            version_id: Optional[str],
                            updates: dict[str, str],
                            removes: tuple[str, ...] = ()) -> ObjectInfo:
        """Update user metadata on an existing version in place
        (cmd/erasure-object.go PutObjectTags / PutObjectMetadata).

        Each drive rewrites its own xl.meta entry so per-shard erasure
        indices and inline data are preserved; write quorum applies.
        """
        self._check_bucket(bucket)
        lk = self.ns_lock.new_lock(bucket, object_name)
        lk.lock(write=True)
        try:
            fi, _ = self._read_quorum_fileinfo(bucket, object_name,
                                               version_id)
            if fi.deleted:
                raise MethodNotAllowed(
                    f"{bucket}/{object_name} is a delete marker")
            # an explicit version_id (including "" = the null version) must
            # be honored as-is; only an unqualified request resolves to the
            # latest version's id
            vid = version_id if version_id is not None else \
                (fi.version_id or None)

            def update_one(disk):
                dfi = disk.read_version(bucket, object_name, vid)
                md = dict(dfi.metadata)
                for k in removes:
                    md.pop(k, None)
                md.update(updates)
                dfi.metadata = md
                disk.write_metadata(bucket, object_name, dfi)

            _, errs = self._fanout(update_one)
            try:
                meta.reduce_errs(errs, self._write_quorum(fi),
                                 WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            for k in removes:
                fi.metadata.pop(k, None)
            fi.metadata.update(updates)
            self._hot_invalidate(bucket, object_name)
            self.metacache.invalidate(bucket)
            return self._to_object_info(fi)
        finally:
            lk.unlock()

    # -- LIST (walk-merge; cmd/metacache-set.go simplified) ----------------

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000
                     ) -> ListObjectsInfo:
        """Serve from the streamed metacache blocks; the walk+resolve
        runs once per (bucket, prefix), seals fixed-size blocks as it
        resolves, and continuation pages bisect straight to their
        covering block — one block in memory per page, never the
        namespace (cmd/metacache-server-pool.go listPath +
        cmd/metacache-set.go block persistence)."""
        self._check_bucket(bucket)
        from .metacache import SnapshotGone, paginate
        for _ in range(2):
            snap = self.metacache.list_path_stream(
                bucket, prefix,
                lambda: self._gather_listing_iter(bucket, prefix))
            try:
                return paginate(snap.iter_from(marker), prefix, marker,
                                delimiter, max_keys)
            except SnapshotGone:
                # a persisted block vanished under the snapshot
                # (invalidate race / drive churn): drop it, re-walk
                self.metacache.forget(bucket, prefix)
        # twice unlucky: serve this page straight off a fresh walk
        return paginate(self._gather_listing_iter(bucket, prefix),
                        prefix, marker, delimiter, max_keys)

    def _walk_resolve(self, bucket: str, prefix: str,
                      versions: bool) -> dict[str, list]:
        """One walk stream per drive carries names AND xl.meta metadata
        (cmd/metacache-walk.go); merge into name -> per-drive FileInfo
        lists.  O(drives) streams total — never a per-key quorum read
        (the round-1 O(keys x drives) resolve, cmd/metacache-set.go:544)."""
        # confine the walk to the prefix's directory subtree so listing
        # one tenant of a huge bucket doesn't stream the whole namespace
        base_dir = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
        res, _ = self._fanout(
            lambda d: list(d.walk_entries(bucket, base_dir,
                                          versions=versions)))
        merged: dict[str, list] = {}
        for drive_entries in res:
            if not drive_entries:
                continue
            for e in drive_entries:
                name = e["name"]
                if prefix and not name.startswith(prefix):
                    continue
                merged.setdefault(name, []).append(
                    [FileInfo.from_dict(f) if isinstance(f, dict) else f
                     for f in e["fis"]])
        return merged

    def _gather_listing_iter(self, bucket: str, prefix: str):
        """STREAMED walk+resolve: one lazy walk stream per drive
        (flat key order — xl_storage.walk_dir's contract), k-way
        merged and quorum-resolved entry by entry, so memory stays
        O(drives), never O(namespace) (cmd/metacache-set.go listPath +
        metacache-entries resolve, minus the round-2 full gather)."""
        import heapq
        from itertools import groupby

        base_dir = prefix.rsplit("/", 1)[0] if "/" in prefix else ""

        def drive_stream(d):
            try:
                yield from d.walk_entries(bucket, base_dir,
                                          versions=False)
            except Exception:  # noqa: BLE001 — a dead/unreachable
                return         # drive's entries are simply missing;
                               # quorum below decides per entry

        streams = [drive_stream(d) for d in self.disks if d is not None]
        merged = heapq.merge(*streams, key=lambda e: e["name"])
        quorum = max(1, len(self.disks) // 2)
        for name, group in groupby(merged, key=lambda e: e["name"]):
            if prefix:
                if name < prefix:
                    continue
                if not name.startswith(prefix):
                    break       # sorted streams: nothing later matches
            fis = []
            for e in group:
                f = e["fis"][0]
                fis.append(FileInfo.from_dict(f)
                           if isinstance(f, dict) else f)
            try:
                fi = meta.find_file_info_in_quorum(fis, quorum)
            except ReadQuorumError:
                continue        # disagreement below quorum: skip entry
            if fi.deleted:
                continue
            yield self._to_object_info(fi)

    def list_object_versions(self, bucket: str, prefix: str = ""):
        """All versions of all objects (ListObjectVersions core) — same
        walked-metadata resolve, all versions per entry."""
        self._check_bucket(bucket)
        merged = self._walk_resolve(bucket, prefix, versions=True)
        quorum = max(1, len(self.disks) // 2)
        out: list[ObjectInfo] = []
        for name in sorted(merged):
            per_drive = merged[name]
            # resolve the version SET from the drive agreeing with the
            # quorum pick of the latest version (findFileInfoInQuorum)
            latest = [fis[0] for fis in per_drive if fis]
            try:
                fi = meta.find_file_info_in_quorum(latest, quorum)
            except ReadQuorumError:
                continue
            for fis in per_drive:
                if fis and fis[0].mod_time == fi.mod_time \
                        and fis[0].version_id == fi.version_id:
                    out.extend(self._to_object_info(v) for v in fis)
                    break
        return out

    # -- healing (delegates to objectlayer.healing) -------------------------

    def heal_object(self, bucket, object_name, version_id=None, deep=False,
                    dry_run=False, remove_dangling=False):
        from . import healing
        return healing.heal_object(self, bucket, object_name, version_id,
                                   deep, dry_run, remove_dangling)

    def heal_bucket(self, bucket: str) -> int:
        """Recreate the bucket on any drive missing it
        (healBucket, cmd/erasure-healing.go:56); returns drives touched."""
        healed = 0
        for disk in self.disks:
            if disk is None:
                continue
            try:
                disk.stat_vol(bucket)
            except serrors.StorageError:
                try:
                    disk.make_vol(bucket)
                    healed += 1
                except serrors.StorageError:
                    pass
        return healed

    # -- helpers -----------------------------------------------------------

    def _to_object_info(self, fi: FileInfo) -> ObjectInfo:
        md = dict(fi.metadata)
        return ObjectInfo(
            bucket=fi.volume, name=fi.name, mod_time=fi.mod_time,
            size=fi.size, etag=md.pop(ETAG_KEY, ""),
            version_id=fi.version_id, is_latest=fi.is_latest,
            delete_marker=fi.deleted,
            content_type=md.get("content-type", ""),
            user_defined=md, parity=fi.erasure.parity_blocks,
            data_blocks=fi.erasure.data_blocks,
            num_versions=fi.num_versions,
            parts=[(p.number, p.size) for p in fi.parts])
