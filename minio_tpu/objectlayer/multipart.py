"""Multipart uploads (cmd/erasure-multipart.go).

Uploads stage under the system volume at
``multipart/<sha256(bucket/object)>/<uploadID>/`` on every drive
(reference: .minio.sys/multipart, :36-44).  Each part is erasure-encoded
and bitrot-framed at PutObjectPart time (:342) — on TPU this is the same
single batched dispatch as whole-object PUT, so a 1 GiB multipart upload
streams through the device part by part.  CompleteMultipartUpload merges
the parts into the final version journal (:678) by renaming staged shard
files into the object's data dir and committing xl.meta with the part
table; the multipart ETag is md5(concat(part-md5s))-N.
"""

from __future__ import annotations

import hashlib
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ..hashing import md5fast
from ..storage import errors as serrors
from ..storage.datatypes import (ChecksumInfo, ErasureInfo, FileInfo,
                                 ObjectPartInfo, now_ns)
from ..storage.xl_storage import SYS_DIR
from . import metadata as meta
from .interface import (InvalidPart, InvalidPartOrder, InvalidUploadID,
                        ObjectInfo, PutObjectOptions, WriteQuorumError)

MIN_PART_SIZE = 5 * 1024 * 1024     # S3 limit (last part exempt)
MAX_PARTS = 10_000                  # docs/minio-limits.md:28-33


@dataclass
class PartInfo:
    part_number: int
    etag: str
    size: int
    actual_size: int
    mod_time: int = 0


@dataclass
class MultipartInfo:
    bucket: str
    object_name: str
    upload_id: str
    user_defined: dict[str, str] = field(default_factory=dict)


class MultipartOps:
    """Mixin for ErasureObjects: the multipart side of the ObjectLayer."""

    def _mp_dir(self, bucket: str, object_name: str, upload_id: str) -> str:
        h = hashlib.sha256(f"{bucket}/{object_name}".encode()).hexdigest()
        return f"multipart/{h}/{upload_id}"

    def new_multipart_upload(self, bucket: str, object_name: str,
                             opts: Optional[PutObjectOptions] = None) -> str:
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)
        upload_id = uuid.uuid4().hex
        mp = self._mp_dir(bucket, object_name, upload_id)
        distribution = meta.hash_order(f"{bucket}/{object_name}",
                                       len(self.disks))
        k, m = self._geometry(opts.parity)
        fi = FileInfo(
            volume=bucket, name=object_name, version_id="",
            data_dir=str(uuid.uuid4()), mod_time=now_ns(),
            metadata={**opts.user_defined,
                      "__versioned": "1" if opts.versioned else "0",
                      "__bucket": bucket, "__object": object_name},
            erasure=ErasureInfo(
                data_blocks=k, parity_blocks=m,
                block_size=self.block_size, distribution=distribution))

        def init_one(idx_disk):
            idx, disk = idx_disk
            dfi = FileInfo(**{**fi.__dict__})
            dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
            dfi.erasure.index = idx + 1
            disk.write_metadata(SYS_DIR, mp, dfi)

        shuffled = meta.shuffle_disks(self.disks, distribution)
        _, errs = self._fanout_indexed(init_one, shuffled)
        try:
            meta.reduce_errs(errs, self._write_quorum(fi), WriteQuorumError)
        except serrors.StorageError as e:
            raise WriteQuorumError(str(e)) from e
        return upload_id

    def _mp_fileinfo(self, bucket: str, object_name: str,
                     upload_id: str) -> tuple[FileInfo, list]:
        mp = self._mp_dir(bucket, object_name, upload_id)
        fis, errs = self._fanout(lambda d: d.read_version(SYS_DIR, mp))
        ok = [fi for fi in fis if fi is not None]
        if len(ok) < max(1, len(self.disks) // 2):
            raise InvalidUploadID(upload_id)
        fi = meta.find_file_info_in_quorum(fis, max(1, len(self.disks) // 2))
        return fi, fis

    def put_object_part(self, bucket: str, object_name: str, upload_id: str,
                        part_number: int, data) -> PartInfo:
        """Erasure-encode one part (PutObjectPart,
        cmd/erasure-multipart.go:342).  ``data`` is bytes or a file-like
        reader; large parts stream through the block-batched pipeline so
        memory stays O(batch) — a 5 GiB part never materializes.  Parts
        ride the same per-drive writer plane as streaming PUT (part
        md5 + encode + drive appends overlap); bytes bodies feed the
        loop zero-copy memoryview slices."""
        if not 1 <= part_number <= MAX_PARTS:
            raise InvalidPart(f"part number {part_number}")
        self._check_bucket(bucket)
        fi, _ = self._mp_fileinfo(bucket, object_name, upload_id)
        mp = self._mp_dir(bucket, object_name, upload_id)
        from .erasure_object import _read_full
        batch = self._stream_batch_size()
        if hasattr(data, "read"):
            def _chunks(reader=data):
                first = True
                while True:
                    c = _read_full(reader, batch)
                    if c or first:     # empty body still stages a part
                        yield c
                    first = False
                    if len(c) < batch:
                        return
            chunks = _chunks()
        else:
            body = data if isinstance(data, bytes) else bytes(data)
            mv = memoryview(body)
            chunks = (mv[o:o + batch]
                      for o in range(0, max(1, len(mv)), batch))
        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        wq = self._write_quorum(fi)
        # stage under a unique name, promote atomically at the end: a
        # retried or concurrent upload of the same part number must never
        # truncate a part that already verified (the reference writes
        # whole part files via tmp+rename, cmd/erasure-multipart.go:342)
        staging = f"part.{part_number}.in.{uuid.uuid4().hex[:8]}"
        if self._pipeline_on():
            return self._put_part_pipelined(
                bucket, object_name, fi, mp, staging, part_number,
                chunks, shuffled, wq)
        return self._put_part_serial(
            bucket, object_name, fi, mp, staging, part_number, chunks,
            shuffled, wq)

    def _put_part_serial(self, bucket, object_name, fi, mp, staging,
                         part_number, chunks, shuffled, wq) -> PartInfo:
        n = len(self.disks)
        errs: list[Exception | None] = [None] * n
        started = [False] * n
        md5 = md5fast.md5()
        size = 0
        try:
            for chunk in chunks:
                md5.update(chunk)
                size += len(chunk)
                # the upload's persisted geometry wins: a storage-class
                # parity chosen at initiate applies to every part.
                # Same framed fast path as single-part PUT: shard bytes
                # land once in their final frame layout, digests filled
                # by one native pass (vs the old copying
                # encode_object + streaming_encode route, ~4x slower)
                framed = self._encode_and_frame(
                    chunk, fi.erasure.parity_blocks, fi)

                def write_batch(idx_disk):
                    idx, disk = idx_disk
                    if disk is None or errs[idx] is not None:
                        return
                    if not started[idx]:
                        started[idx] = True
                        disk.create_file(SYS_DIR, f"{mp}/{staging}",
                                         framed[idx])
                    else:
                        disk.append_file(SYS_DIR, f"{mp}/{staging}",
                                         framed[idx])

                _, werrs = self._fanout_indexed(write_batch, shuffled)
                for i, e in enumerate(werrs):
                    if e is not None and errs[i] is None:
                        errs[i] = e
                alive = sum(1 for i, d in enumerate(shuffled)
                            if d is not None and errs[i] is None)
                if alive < wq:
                    raise WriteQuorumError(
                        f"{alive} of {n} drives writable, need {wq}")
            etag = md5.hexdigest()

            def promote(idx_disk):
                idx, disk = idx_disk
                if disk is None:
                    raise serrors.DiskNotFound("offline")
                if errs[idx] is not None:
                    raise errs[idx]
                # atomic promote, then the sidecar complete() verifies with
                disk.rename_file(SYS_DIR, f"{mp}/{staging}",
                                 SYS_DIR, f"{mp}/part.{part_number}")
                disk.write_all(SYS_DIR, f"{mp}/part.{part_number}.meta",
                               f"{etag}:{size}".encode())

            _, perrs = self._fanout_indexed(promote, shuffled)
            try:
                meta.reduce_errs(perrs, wq, WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            return PartInfo(part_number, etag, size, size, now_ns())
        finally:
            # drop any staging file that wasn't promoted (stream abort,
            # failed drive, lost quorum): a later retry must never see it
            def cleanup(idx_disk):
                idx, disk = idx_disk
                if disk is None or not started[idx]:
                    return
                try:
                    disk.delete(SYS_DIR, f"{mp}/{staging}")
                except Exception:  # noqa: BLE001 — already promoted/gone
                    pass

            self._fanout_indexed(cleanup, shuffled)

    def _put_part_pipelined(self, bucket, object_name, fi, mp, staging,
                            part_number, chunks, shuffled, wq) -> PartInfo:
        """Part upload on the per-drive writer plane: the shared stage
        driver (_pump_put_pipeline) overlaps chained md5, encode, and
        per-drive appends exactly like streaming PUT; the staged-name
        promote and cleanup contracts match the serial path bit for
        bit."""
        n = len(self.disks)
        m = fi.erasure.parity_blocks
        sw = self._write_plane.stream(shuffled)
        started = [False] * n
        # the lane-aware digest: concurrent parts' _md5_link chains
        # coalesce in the native multi-lane scheduler (config 2's 8+4
        # multipart uploads hash their parts side by side in one call)
        md5 = md5fast.md5()
        stats = {"md5_s": 0.0, "encode_s": 0.0}
        try:
            def write_batch_for(framed):
                def write_batch(idx, disk):
                    if not started[idx]:
                        started[idx] = True
                        disk.create_file(SYS_DIR, f"{mp}/{staging}",
                                         framed[idx])
                    else:
                        disk.append_file(SYS_DIR, f"{mp}/{staging}",
                                         framed[idx])
                return write_batch

            size, _ = self._pump_put_pipeline(
                chunks, sw, m, fi, md5, stats, write_batch_for, wq)
            etag = md5.hexdigest()
            sw.drain()
            alive = sw.alive()
            if alive < wq:
                raise WriteQuorumError(
                    f"{alive} of {n} drives writable, need {wq}")

            def promote(idx, disk):
                # atomic promote, then the sidecar complete() verifies
                # with; per-drive FIFO guarantees every append landed
                disk.rename_file(SYS_DIR, f"{mp}/{staging}",
                                 SYS_DIR, f"{mp}/part.{part_number}")
                disk.write_all(SYS_DIR, f"{mp}/part.{part_number}.meta",
                               f"{etag}:{size}".encode())

            sw.submit_batch(promote)
            sw.drain()
            try:
                meta.reduce_errs(list(sw.errs), wq, WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            return PartInfo(part_number, etag, size, size, now_ns())
        finally:
            sw.abort()
            sw.drain(timeout=10.0)   # settle queues before cleanup

            def cleanup(idx_disk):
                idx, disk = idx_disk
                if disk is None or not started[idx]:
                    return
                # settled drives delete inline (still on the parallel
                # fan-out); a drive hung past the drain timeout defers
                # to op settlement so its resumed append cannot
                # resurrect the staging file after this delete
                sw.when_drive_idle(
                    idx,
                    lambda d=disk: d.delete(SYS_DIR, f"{mp}/{staging}"))

            self._fanout_indexed(cleanup, shuffled)

    def get_multipart_info(self, bucket: str, object_name: str,
                           upload_id: str) -> MultipartInfo:
        """Upload metadata (cmd/erasure-multipart.go GetMultipartInfo) —
        the SSE path needs the sealed object key stored at initiation."""
        self._check_bucket(bucket)
        fi, _ = self._mp_fileinfo(bucket, object_name, upload_id)
        md = {k: v for k, v in fi.metadata.items()
              if not k.startswith("__")}
        return MultipartInfo(bucket, object_name, upload_id, md)

    def list_object_parts(self, bucket: str, object_name: str,
                          upload_id: str) -> list[PartInfo]:
        self._check_bucket(bucket)
        fi, _ = self._mp_fileinfo(bucket, object_name, upload_id)
        mp = self._mp_dir(bucket, object_name, upload_id)
        # merge sidecars across ALL drives: a part that met write quorum may
        # be absent from any single drive (transient per-drive failure)
        parts: dict[int, PartInfo] = {}
        found_any = False
        for disk in self.disks:
            if disk is None:
                continue
            try:
                names = disk.list_dir(SYS_DIR, mp)
                found_any = True
            except serrors.StorageError:
                continue
            for n in names:
                if not (n.startswith("part.") and n.endswith(".meta")):
                    continue
                num = int(n[5:-5])
                if num in parts:
                    continue
                try:
                    etag, size = disk.read_all(
                        SYS_DIR, f"{mp}/{n}").decode().split(":")
                except (serrors.StorageError, ValueError):
                    continue
                parts[num] = PartInfo(num, etag, int(size), int(size))
        if not found_any:
            raise InvalidUploadID(upload_id)
        return sorted(parts.values(), key=lambda p: p.part_number)

    def abort_multipart_upload(self, bucket: str, object_name: str,
                               upload_id: str) -> None:
        self._check_bucket(bucket)
        self._mp_fileinfo(bucket, object_name, upload_id)  # validates
        mp = self._mp_dir(bucket, object_name, upload_id)
        self._fanout(lambda d: d.delete(SYS_DIR, mp, recursive=True))

    def list_multipart_uploads(self, bucket: str,
                               prefix: str = "") -> list[MultipartInfo]:
        self._check_bucket(bucket)
        # merge across ALL drives: an upload that met write quorum may be
        # missing from any single drive
        out: dict[str, MultipartInfo] = {}
        for disk in self.disks:
            if disk is None:
                continue
            try:
                hashes = disk.list_dir(SYS_DIR, "multipart")
            except serrors.StorageError:
                continue
            for h in hashes:
                try:
                    uploads = disk.list_dir(SYS_DIR,
                                            f"multipart/{h.strip('/')}")
                except serrors.StorageError:
                    continue
                for u in uploads:
                    uid = u.strip("/")
                    if uid in out:
                        continue
                    try:
                        fi = disk.read_version(
                            SYS_DIR, f"multipart/{h.strip('/')}/{uid}")
                    except serrors.StorageError:
                        continue
                    obj = fi.metadata.get("__object", "")
                    if obj.startswith(prefix) and \
                            fi.metadata.get("__bucket") == bucket:
                        md = {k: v for k, v in fi.metadata.items()
                              if not k.startswith("__")}
                        out[uid] = MultipartInfo(bucket, obj, uid, md)
        return sorted(out.values(), key=lambda m: m.object_name)

    def complete_multipart_upload(self, bucket: str, object_name: str,
                                  upload_id: str,
                                  parts: list[tuple[int, str]],
                                  opts: Optional[PutObjectOptions] = None
                                  ) -> ObjectInfo:
        """parts: [(part_number, etag)] in client order; must be ascending
        (CompleteMultipartUpload, cmd/erasure-multipart.go:678).  ``opts``
        lets a rebalance/decommission move re-commit a multipart version
        under its original version_id/mod_time; same part bytes give the
        same part md5s, so the merged ETag is already bit-identical."""
        self._check_bucket(bucket)
        fi, _ = self._mp_fileinfo(bucket, object_name, upload_id)
        mp = self._mp_dir(bucket, object_name, upload_id)
        if not parts:
            raise InvalidPart("no parts specified")
        if [p[0] for p in parts] != sorted({p[0] for p in parts}):
            raise InvalidPartOrder("parts not in ascending order")
        uploaded = {p.part_number: p
                    for p in self.list_object_parts(bucket, object_name,
                                                    upload_id)}
        part_infos: list[ObjectPartInfo] = []
        md5s = b""
        total = 0
        for i, (num, etag) in enumerate(parts):
            got = uploaded.get(num)
            if got is None or got.etag != etag.strip('"'):
                raise InvalidPart(f"part {num}")
            if got.size < MIN_PART_SIZE and i != len(parts) - 1 \
                    and self.enforce_min_part_size:
                raise InvalidPart(f"part {num} too small")
            part_infos.append(ObjectPartInfo(num, got.size, got.size,
                                             got.etag, now_ns()))
            md5s += bytes.fromhex(got.etag)
            total += got.size
        etag = hashlib.md5(md5s).hexdigest() + f"-{len(parts)}"

        versioned = fi.metadata.pop("__versioned", "0") == "1"
        if opts is not None and opts.versioned:
            versioned = True
        version_id = str(uuid.uuid4()) if versioned else ""
        mod_time = now_ns()
        if opts is not None:
            version_id = opts.version_id or version_id
            mod_time = opts.mod_time or mod_time
        fi.volume, fi.name = bucket, object_name
        fi.version_id = version_id
        fi.mod_time = mod_time
        fi.size = total
        fi.parts = part_infos
        fi.metadata = {k: v for k, v in fi.metadata.items()
                       if not k.startswith("__")}
        fi.metadata["etag"] = etag
        fi.erasure.checksums = [ChecksumInfo(p.number, self.bitrot_algo)
                                for p in part_infos]
        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)

        def commit_one(idx_disk):
            idx, disk = idx_disk
            dfi = FileInfo(**{**fi.__dict__})
            dfi.erasure = ErasureInfo(**{**fi.erasure.__dict__})
            dfi.erasure.index = idx + 1
            tmp = disk.tmp_dir()
            try:
                for p in part_infos:
                    disk.rename_file(SYS_DIR, f"{mp}/part.{p.number}",
                                     SYS_DIR, f"{tmp}/part.{p.number}")
                disk.rename_data(SYS_DIR, tmp, dfi, bucket, object_name)
            finally:
                disk.clean_tmp(tmp)
            disk.delete(SYS_DIR, mp, recursive=True)

        # the commit mutates the object's version set across drives:
        # same ns write lock as PUT/DELETE (the reference's
        # CompleteMultipartUpload takes the nsLock on the object), so a
        # racing GET can never observe a half-renamed version set
        lk = self.ns_lock.new_lock(bucket, object_name)
        lk.lock(write=True)
        try:
            _, errs = self._fanout_indexed(commit_one, shuffled)
            try:
                meta.reduce_errs(errs, self._write_quorum(fi),
                                 WriteQuorumError)
            except serrors.StorageError as e:
                raise WriteQuorumError(str(e)) from e
            # hot-read fence INSIDE the locked commit section, like
            # every other write path (invalidate-before-visible)
            self._hot_invalidate(bucket, object_name)
        finally:
            lk.unlock()
        fi.is_latest = True
        self.metacache.invalidate(bucket)
        return self._to_object_info(fi)
