"""Disk cache — SSD cache layer in front of a (remote/slow) ObjectLayer
(cmd/disk-cache.go:88 cacheObjects, cmd/disk-cache-backend.go).

The reference deploys this for gateway/remote backends: GETs fill local
cache drives, subsequent reads are served locally with ETag validation
against the backend, an atime-based GC keeps usage between watermarks,
and an optional writeback mode commits PUTs to the backend
asynchronously (cmd/disk-cache.go:95 CacheCommitWriteBack).

This build keeps the same behavior: ``CacheObjects`` wraps any
ObjectLayer; cache drives are plain directories (one entry dir per
object holding ``data`` + ``cache.json``), objects map to a drive by
deterministic hash (crcHashMod analog, cmd/disk-cache.go cacheDrives).
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..hashing.siphash import sip_hash_mod
from .interface import ObjectInfo, ObjectNotFound, ObjectOptions

DEFAULT_HIGH_WATERMARK = 0.90   # start GC (config cache quota, reference
DEFAULT_LOW_WATERMARK = 0.70    # default watermarks cmd/config/cache)


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    filled: int = 0
    evicted: int = 0
    writeback_pending: int = 0

    def to_dict(self) -> dict:
        return dict(self.__dict__)


@dataclass
class CacheEntry:
    object_info: dict = field(default_factory=dict)
    etag: str = ""
    atime: float = 0.0
    size: int = 0
    # writeback: object is dirty until committed to the backend
    dirty: bool = False


class CacheDrive:
    """One cache directory (cmd/disk-cache-backend.go diskCache)."""

    def __init__(self, root: str, max_bytes: int = 0,
                 high_watermark: float = DEFAULT_HIGH_WATERMARK,
                 low_watermark: float = DEFAULT_LOW_WATERMARK):
        self.root = root
        self.max_bytes = max_bytes      # 0 = derive from fs capacity
        self.high = high_watermark
        self.low = low_watermark
        os.makedirs(root, exist_ok=True)
        self._mu = threading.Lock()

    def _entry_dir(self, bucket: str, key: str) -> str:
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.root, h[:2], h)

    # -- read/write ------------------------------------------------------

    def get(self, bucket: str, key: str
            ) -> Optional[tuple[CacheEntry, bytes]]:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "cache.json")) as f:
                meta = CacheEntry(**json.load(f))
            with open(os.path.join(d, "data"), "rb") as f:
                data = f.read()
        except (OSError, ValueError, TypeError):
            return None
        meta.atime = time.time()
        try:        # persist atime for GC ordering across restarts
            with open(os.path.join(d, "cache.json"), "w") as f:
                json.dump(meta.__dict__, f)
        except OSError:
            pass
        return meta, data

    def peek(self, bucket: str, key: str) -> Optional[CacheEntry]:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "cache.json")) as f:
                return CacheEntry(**json.load(f))
        except (OSError, ValueError, TypeError):
            return None

    def put(self, bucket: str, key: str, data: bytes, oi_doc: dict,
            dirty: bool = False) -> None:
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        entry = CacheEntry(object_info=oi_doc,
                           etag=oi_doc.get("etag", ""),
                           atime=time.time(), size=len(data),
                           dirty=dirty)
        tmp = os.path.join(d, ".data.tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(d, "data"))
        with open(os.path.join(d, "cache.json"), "w") as f:
            json.dump(entry.__dict__, f)

    def mark_clean(self, bucket: str, key: str) -> None:
        e = self.peek(bucket, key)
        if e is not None and e.dirty:
            e.dirty = False
            d = self._entry_dir(bucket, key)
            try:
                with open(os.path.join(d, "cache.json"), "w") as f:
                    json.dump(e.__dict__, f)
            except OSError:
                pass

    def delete(self, bucket: str, key: str) -> None:
        shutil.rmtree(self._entry_dir(bucket, key), ignore_errors=True)

    # -- GC --------------------------------------------------------------

    def usage_bytes(self) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def capacity_bytes(self) -> int:
        if self.max_bytes:
            return self.max_bytes
        try:
            return shutil.disk_usage(self.root).total
        except OSError:
            return 1 << 40

    def entries_by_atime(self) -> list[tuple[float, str, int, bool]]:
        """[(atime, entry_dir, size, dirty)] oldest first."""
        out = []
        for sub in os.listdir(self.root):
            subp = os.path.join(self.root, sub)
            if not os.path.isdir(subp):
                continue
            for ent in os.listdir(subp):
                d = os.path.join(subp, ent)
                try:
                    with open(os.path.join(d, "cache.json")) as f:
                        meta = json.load(f)
                    # full on-disk footprint (data + metadata), so GC's
                    # usage arithmetic matches usage_bytes()
                    size = sum(os.path.getsize(os.path.join(d, fn))
                               for fn in os.listdir(d))
                except (OSError, ValueError):
                    continue
                out.append((meta.get("atime", 0.0), d, size,
                            meta.get("dirty", False)))
        out.sort()
        return out

    def gc(self, stats: Optional[CacheStats] = None) -> int:
        """Evict least-recently-used clean entries until usage falls
        below the low watermark (cmd/disk-cache-backend.go purge)."""
        cap = self.capacity_bytes()
        used = self.usage_bytes()
        if used <= cap * self.high:
            return 0
        target = cap * self.low
        evicted = 0
        for _atime, d, size, dirty in self.entries_by_atime():
            if used <= target:
                break
            if dirty:
                continue        # never drop uncommitted writeback data
            shutil.rmtree(d, ignore_errors=True)
            used -= size
            evicted += 1
            if stats is not None:
                stats.evicted += 1
        return evicted


class CacheObjects:
    """ObjectLayer wrapper adding the cache (cmd/disk-cache.go:88).

    Every method not overridden passes straight through to the inner
    layer; GET/PUT/DELETE consult the cache.  ``writeback=True`` makes
    PUT commit to the backend asynchronously (CacheCommitWriteBack).
    """

    def __init__(self, inner, cache_dirs: list[str],
                 writeback: bool = False, max_object_size: int = 1 << 30,
                 exclude: tuple[str, ...] = (), max_bytes_per_drive: int = 0,
                 gc_interval_s: float = 0.0):
        self.inner = inner
        self.drives = [CacheDrive(d, max_bytes=max_bytes_per_drive)
                       for d in cache_dirs]
        if not self.drives:
            raise ValueError("disk cache needs at least one cache dir")
        self.writeback = writeback
        self.max_object_size = max_object_size
        self.exclude = exclude
        self.stats = CacheStats()
        self._wb_q: "queue.Queue[tuple[str, str] | None]" = queue.Queue()
        self._wb_thread: Optional[threading.Thread] = None
        self._gc_thread: Optional[threading.Thread] = None
        # event, not a bare bool: close() must WAKE a parked GC sweep
        # immediately, and both background threads key off it
        self._closed_ev = threading.Event()
        # periodic background GC (the reference's diskCache purge
        # loop, cmd/disk-cache-backend.go): 0 keeps the historical
        # inline-after-fill GC only
        self.gc_interval_s = gc_interval_s
        if gc_interval_s > 0:
            self._gc_thread = threading.Thread(
                target=self._gc_loop, daemon=True,
                name="mt-diskcache-gc")
            self._gc_thread.start()

    @property
    def _closed(self) -> bool:
        return self._closed_ev.is_set()

    # -- plumbing --------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _drive(self, bucket: str, key: str) -> CacheDrive:
        idx = sip_hash_mod(f"{bucket}/{key}", len(self.drives), b"\0" * 16)
        return self.drives[idx]

    def _excluded(self, bucket: str, key: str) -> bool:
        import fnmatch
        return any(fnmatch.fnmatch(f"{bucket}/{key}", pat)
                   for pat in self.exclude)

    @staticmethod
    def _oi_doc(oi: ObjectInfo) -> dict:
        doc = dict(oi.__dict__)
        doc["parts"] = [list(p) for p in doc.get("parts", [])]
        return doc

    @staticmethod
    def _oi_from_doc(doc: dict) -> ObjectInfo:
        doc = dict(doc)
        doc["parts"] = [tuple(p) for p in doc.get("parts", [])]
        return ObjectInfo(**doc)

    # -- GET (cmd/disk-cache.go GetObjectNInfo) --------------------------

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, opts: Optional[ObjectOptions] = None):
        opts = opts or ObjectOptions()
        if opts.version_id or self._excluded(bucket, object_name):
            return self.inner.get_object(bucket, object_name, offset,
                                         length, opts)
        drive = self._drive(bucket, object_name)
        cached = drive.get(bucket, object_name)
        if cached is not None:
            entry, data = cached
            # validate against the backend's current ETag; if the backend
            # is unreachable the cache serves anyway (reference behavior:
            # backend down -> cached data is better than an error)
            try:
                bi = self.inner.get_object_info(bucket, object_name)
                fresh = bi.etag == entry.etag
            except ObjectNotFound:
                if entry.dirty:         # not yet committed: still valid
                    fresh = True
                else:
                    drive.delete(bucket, object_name)
                    raise
            except Exception:   # noqa: BLE001 — backend down: serve cache
                fresh = True
            if fresh:
                self.stats.hits += 1
                oi = self._oi_from_doc(entry.object_info)
                if offset or length != -1:
                    end = len(data) if length == -1 else offset + length
                    return oi, data[offset:end]
                return oi, data
            drive.delete(bucket, object_name)
        self.stats.misses += 1
        oi, data = self.inner.get_object(bucket, object_name, 0, -1, opts)
        if len(data) <= self.max_object_size:
            drive.put(bucket, object_name, data, self._oi_doc(oi))
            self.stats.filled += 1
            drive.gc(self.stats)
        if offset or length != -1:
            end = len(data) if length == -1 else offset + length
            return oi, data[offset:end]
        return oi, data

    def get_object_info(self, bucket: str, object_name: str,
                        opts: Optional[ObjectOptions] = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        if not opts.version_id:
            entry = self._drive(bucket, object_name).peek(
                bucket, object_name)
            if entry is not None and entry.dirty:
                # writeback: the cache is the source of truth until commit
                return self._oi_from_doc(entry.object_info)
        return self.inner.get_object_info(bucket, object_name, opts)

    # -- PUT -------------------------------------------------------------

    def put_object(self, bucket: str, object_name: str, data: bytes,
                   opts=None) -> ObjectInfo:
        if self._excluded(bucket, object_name) or \
                len(data) > self.max_object_size:
            return self.inner.put_object(bucket, object_name, data, opts)
        drive = self._drive(bucket, object_name)
        if self.writeback:
            # commit locally, acknowledge, upload in the background
            import hashlib as _h
            oi = ObjectInfo(bucket=bucket, name=object_name,
                            size=len(data),
                            etag=_h.md5(data).hexdigest(),
                            mod_time=time.time_ns())
            drive.put(bucket, object_name, data, self._oi_doc(oi),
                      dirty=True)
            self.stats.writeback_pending += 1
            self._start_writeback()
            self._wb_q.put((bucket, object_name))
            return oi
        oi = self.inner.put_object(bucket, object_name, data, opts)
        drive.put(bucket, object_name, data, self._oi_doc(oi))
        self.stats.filled += 1
        drive.gc(self.stats)
        return oi

    def _start_writeback(self) -> None:
        if self._wb_thread is None or not self._wb_thread.is_alive():
            self._wb_thread = threading.Thread(target=self._wb_loop,
                                               daemon=True,
                                               name="mt-diskcache-wb")
            self._wb_thread.start()

    def _wb_loop(self) -> None:
        while not self._closed:
            try:
                item = self._wb_q.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:        # close() sentinel: prompt exit
                return
            bucket, key = item
            drive = self._drive(bucket, key)
            cached = drive.get(bucket, key)
            if cached is None:
                continue
            entry, data = cached
            try:
                oi = self.inner.put_object(bucket, key, data, None)
                drive.put(bucket, key, data, self._oi_doc(oi),
                          dirty=False)
                self.stats.writeback_pending -= 1
            except Exception:   # noqa: BLE001 — retry later
                self._closed_ev.wait(0.2)
                self._wb_q.put((bucket, key))

    def _gc_loop(self) -> None:
        """Periodic watermark GC (mt-diskcache-gc): sweeps every cache
        drive on the interval; close() wakes and joins it."""
        while not self._closed_ev.wait(self.gc_interval_s):
            for drive in self.drives:
                try:
                    drive.gc(self.stats)
                except Exception:  # noqa: BLE001 — one drive's sweep
                    pass           # failing must not kill the loop

    def flush_writeback(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while self.stats.writeback_pending > 0 and \
                time.monotonic() < deadline:
            time.sleep(0.02)

    # -- DELETE ----------------------------------------------------------

    def delete_object(self, bucket: str, object_name: str, opts=None):
        self._drive(bucket, object_name).delete(bucket, object_name)
        return self.inner.delete_object(bucket, object_name, opts)

    def close(self, timeout: float = 5.0) -> None:
        """Stop and JOIN the background threads (the PR-10 thread
        discipline: every mt-diskcache-* thread dies with its owner —
        S3Server.stop walks wrapped layers and calls this)."""
        self._closed_ev.set()
        try:
            self._wb_q.put_nowait(None)     # wake a parked get()
        except Exception:  # noqa: BLE001 — full queue: the 0.5s poll
            pass           # picks the closed flag up anyway
        for t in (self._wb_thread, self._gc_thread):
            if t is not None and t.is_alive():
                t.join(timeout=timeout)
