"""ILM transition (tiering) + RestoreObject.

Reference: cmd/bucket-lifecycle.go:315 `transitionObject` moves a
version's data to the configured remote target, leaving a metadata stub
whose GET returns InvalidObjectState until `RestoreObject` (POST
?restore, cmd/object-handlers.go PostRestoreObjectHandler) copies the
data back for N days; HEAD reports `x-amz-storage-class` and
`x-amz-restore` (cmd/bucket-lifecycle.go restoreTransitionedObject).

The stored stream moves to the tier *verbatim* — SSE/compression
markers stay on the stub, so a restore yields bit-identical stored
bytes and the normal decode pipeline applies unchanged.

Tier backends: S3 (a remote bucket via our own client) and Dir (a local
path — the test tier, and the NAS analog).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Optional

from ..storage.datatypes import now_ns
from .interface import (ObjectLayerError, ObjectOptions, PutObjectOptions)

# stub markers (x-minio-internal-transition* in the reference)
META_STATUS = "x-minio-internal-transition-status"      # "complete"
META_TIER = "x-minio-internal-transition-tier"          # tier name
META_KEY = "x-minio-internal-transitioned-object"       # key inside tier
META_SIZE = "x-minio-internal-transition-size"          # original size
META_ETAG = "x-minio-internal-transition-etag"          # original etag
META_RESTORE_EXPIRY = "x-minio-internal-restore-expiry"  # unix seconds

RESTORE_HDR = "x-amz-restore"
STORAGE_CLASS_HDR = "x-amz-storage-class"

TRANSITION_MARKERS = (META_STATUS, META_TIER, META_KEY, META_SIZE,
                      META_ETAG, META_RESTORE_EXPIRY)


class TierError(ObjectLayerError):
    pass


class Tier:
    """Remote tier backend (the reference's transition remote target)."""

    name = ""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError


class DirTier(Tier):
    """Local-directory tier: the test backend and the NAS-style target."""

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.path, key.replace("/", "_"))

    def put(self, key: str, data: bytes) -> None:
        tmp = self._p(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._p(key))

    def get(self, key: str) -> bytes:
        try:
            with open(self._p(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise TierError(f"tier object {key} missing") from None

    def remove(self, key: str) -> None:
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass


class S3Tier(Tier):
    """Remote S3 bucket tier (the reference's minio-go remote target)."""

    def __init__(self, name: str, endpoint: str, bucket: str,
                 access_key: str, secret_key: str, prefix: str = "",
                 region: str = "us-east-1"):
        from ..s3.client import S3Client
        self.name = name
        self.client = S3Client(endpoint, access_key, secret_key, region)
        self.bucket = bucket
        self.prefix = prefix

    def _k(self, key: str) -> str:
        return f"{self.prefix}{key}"

    def put(self, key: str, data: bytes) -> None:
        self.client.put_object(self.bucket, self._k(key), data)

    def get(self, key: str) -> bytes:
        from ..s3.client import S3ClientError
        try:
            return self.client.get_object(self.bucket, self._k(key)).body
        except S3ClientError as e:
            raise TierError(f"tier fetch failed: {e}") from e

    def remove(self, key: str) -> None:
        from ..s3.client import S3ClientError
        try:
            self.client.delete_object(self.bucket, self._k(key))
        except S3ClientError:
            pass


# -- stub state helpers ------------------------------------------------------

def _client_size(info) -> int:
    """The client-visible size of a stored object: compressed objects
    report actual size, SSE objects the decrypted size (the number HEAD
    advertises before transition and after restore)."""
    from .. import compress as mtc
    from ..crypto import sse as csse
    ud = info.user_defined
    if mtc.META_COMPRESSION in ud and csse.META_ACTUAL_SIZE in ud:
        return int(ud[csse.META_ACTUAL_SIZE])
    if csse.is_encrypted(ud):
        return csse.decrypted_size(ud, info.size, info.parts)
    return info.size


def is_transitioned(user_defined: dict) -> bool:
    return user_defined.get(META_STATUS) == "complete"


def restore_expiry(user_defined: dict) -> int:
    try:
        return int(user_defined.get(META_RESTORE_EXPIRY, "0"))
    except ValueError:
        return 0


def restore_valid(user_defined: dict) -> bool:
    return restore_expiry(user_defined) > time.time()


def restore_header(user_defined: dict) -> str:
    """x-amz-restore header value for HEAD/GET responses."""
    exp = restore_expiry(user_defined)
    if not exp:
        return ""
    if exp > time.time():
        date = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(exp))
        return f'ongoing-request="false", expiry-date="{date}"'
    return ""


class TransitionSys:
    """Transition + restore driver bound to an object layer
    (globalTransitionState analog)."""

    def __init__(self, layer):
        self.layer = layer
        self.tiers: dict[str, Tier] = {}

    def add_tier(self, tier: Tier) -> None:
        self.tiers[tier.name] = tier

    def tier_of(self, user_defined: dict) -> Optional[Tier]:
        return self.tiers.get(user_defined.get(META_TIER, ""))

    # -- transition --------------------------------------------------------

    def transition(self, bucket: str, oi) -> None:
        """Move a version's stored bytes to its rule's tier, leave a
        stub (transitionObject, cmd/bucket-lifecycle.go:315).  The
        version id threads through so noncurrent-version transitions
        never touch the live head object."""
        tier_name = getattr(oi, "transition_tier", "") or \
            oi.user_defined.get(STORAGE_CLASS_HDR, "")
        tier = self.tiers.get(tier_name)
        if tier is None:
            raise TierError(f"no tier named {tier_name!r}")
        if is_transitioned(oi.user_defined):
            return                              # already moved
        vid = getattr(oi, "version_id", "") or ""
        opts = ObjectOptions(version_id=vid or None)
        info, data = self.layer.get_object(bucket, oi.name, 0, -1, opts)
        remote_key = f"{bucket}/{oi.name}/{vid or 'null'}/" \
                     f"{uuid.uuid4().hex}"
        tier.put(remote_key, data)
        ud = dict(info.user_defined)
        ud.update({
            META_STATUS: "complete",
            META_TIER: tier.name,
            META_KEY: remote_key,
            META_SIZE: str(_client_size(info)),
            META_ETAG: info.etag,
            STORAGE_CLASS_HDR: tier.name,
        })
        ud.pop(META_RESTORE_EXPIRY, None)
        # the stub replaces the data in place; quorum commit as a write
        self.layer.put_object(bucket, oi.name, b"",
                              PutObjectOptions(user_defined=ud,
                                               version_id=vid,
                                               mod_time=info.mod_time
                                               or now_ns()))

    # -- restore -----------------------------------------------------------

    def restore(self, bucket: str, key: str, days: int,
                version_id: Optional[str] = None) -> bool:
        """Copy tiered bytes back for `days`; returns False if the
        object already holds a valid restored copy.  version_id follows
        the layer contract: None = latest, "" = the null version."""
        oi = self.layer.get_object_info(
            bucket, key, ObjectOptions(version_id=version_id))
        # write back to the version we resolved: an omitted versionId on
        # a versioned bucket must restore the latest version, not mint a
        # spurious null version
        if version_id is None:
            version_id = oi.version_id or ""
        if not is_transitioned(oi.user_defined):
            raise TierError("object is not in an archived state")
        if restore_valid(oi.user_defined):
            return False
        tier = self.tier_of(oi.user_defined)
        if tier is None:
            raise TierError(
                f"tier {oi.user_defined.get(META_TIER)!r} not configured")
        data = tier.get(oi.user_defined[META_KEY])
        ud = dict(oi.user_defined)
        ud[META_RESTORE_EXPIRY] = str(
            int(time.time()) + days * 24 * 3600)
        # keep the original mod_time: version recency (is_latest) is
        # ordered by mod_time and a restore must not reorder versions
        self.layer.put_object(
            bucket, key, data,
            PutObjectOptions(user_defined=ud, version_id=version_id,
                             mod_time=oi.mod_time))
        return True

    def sweep_expired_restores(self, bucket: str) -> int:
        """Re-stub restored copies whose window lapsed (the crawler's
        restore-expiry pass), across ALL versions.  Returns how many
        were re-stubbed."""
        n = 0
        if hasattr(self.layer, "list_object_versions"):
            versions = list(self.layer.list_object_versions(bucket))
        else:
            versions = self.layer.list_objects(
                bucket, max_keys=10 ** 6).objects
        for oi in versions:
            if getattr(oi, "delete_marker", False):
                continue
            # "" IS the null version here — `or None` would resolve the
            # latest version instead and skip expired null versions
            full = self.layer.get_object_info(
                bucket, oi.name,
                ObjectOptions(version_id=oi.version_id))
            ud = full.user_defined
            if is_transitioned(ud) and restore_expiry(ud) and \
                    not restore_valid(ud):
                stub = dict(ud)
                stub.pop(META_RESTORE_EXPIRY, None)
                self.layer.put_object(
                    bucket, oi.name, b"",
                    PutObjectOptions(user_defined=stub,
                                     version_id=full.version_id or "",
                                     mod_time=full.mod_time))
                n += 1
        return n

    def delete_tiered(self, user_defined: dict) -> None:
        """Free the remote bytes of a transitioned version being deleted
        or overwritten — otherwise the uuid-keyed tier object leaks
        forever (the reference deletes tier data on version deletion)."""
        if not is_transitioned(user_defined):
            return
        tier = self.tier_of(user_defined)
        key = user_defined.get(META_KEY, "")
        if tier is not None and key:
            try:
                tier.remove(key)
            except Exception:  # noqa: BLE001 — lossy ok; GC tolerates
                pass

    # -- persistence of tier configs (admin API) ---------------------------

    def to_json(self, redact: bool = False) -> bytes:
        """Tier configs; `redact=True` hides remote credentials (madmin
        ListTiers never returns secrets) — persistence uses the full form."""
        out = []
        for t in self.tiers.values():
            if isinstance(t, DirTier):
                out.append({"type": "dir", "name": t.name, "path": t.path})
            elif isinstance(t, S3Tier):
                out.append({"type": "s3", "name": t.name,
                            "endpoint": t.client.endpoint,
                            "bucket": t.bucket, "prefix": t.prefix,
                            "access_key": "REDACTED" if redact
                            else t.client.access_key,
                            "secret_key": "REDACTED" if redact
                            else t.client.secret_key,
                            "region": t.client.region})
        return json.dumps(out).encode()

    @classmethod
    def from_json(cls, layer, blob: bytes) -> "TransitionSys":
        sys = cls(layer)
        for d in json.loads(blob or b"[]"):
            if d.get("type") == "dir":
                sys.add_tier(DirTier(d["name"], d["path"]))
            elif d.get("type") == "s3":
                sys.add_tier(S3Tier(d["name"], d["endpoint"], d["bucket"],
                                    d["access_key"], d["secret_key"],
                                    d.get("prefix", ""),
                                    d.get("region", "us-east-1")))
        return sys


def transition_fn(tsys: TransitionSys):
    """Adapter for the crawler's transition callback: the lifecycle rule
    names the destination storage class; pass it through."""
    def fn(bucket: str, oi, storage_class: str = "") -> None:
        if storage_class:
            oi.transition_tier = storage_class
        tsys.transition(bucket, oi)
    return fn
