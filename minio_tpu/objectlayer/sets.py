"""erasureSets — set-of-sets topology (cmd/erasure-sets.go:54).

Objects distribute across ``set_count`` independent erasure sets by a
deployment-id-keyed SipHash of the object name (sipHashMod,
cmd/erasure-sets.go:629; legacy CRC mode crcHashMod :638).  Every bucket
exists on every set; object APIs route to the hashed set; listings and
heals fan out across sets and merge.
"""

from __future__ import annotations

import zlib
from typing import Optional

from ..hashing.siphash import sip_hash_mod
from ..storage.api import StorageAPI
from ..storage.format import (DISTRIBUTION_ALGO_V3,
                              load_or_init_format)
from ..storage.xl_storage import XLStorage
from . import healing
from .erasure_object import ErasureObjects
from .interface import (BucketInfo, BucketNotFound, ListObjectsInfo,
                        ObjectInfo, ObjectLayer)

DISTRIBUTION_ALGO_CRC = "CRCMOD"


class ErasureSets(ObjectLayer):
    """cmd/erasure-sets.go erasureSets."""

    def __init__(self, disks: list[Optional[StorageAPI]], set_count: int,
                 set_drive_count: int, deployment_id: str = "",
                 distribution_algo: str = DISTRIBUTION_ALGO_V3,
                 **set_kwargs):
        assert len(disks) == set_count * set_drive_count
        self.set_count = set_count
        self.set_drive_count = set_drive_count
        self.deployment_id = deployment_id
        self.distribution_algo = distribution_algo
        self.sets = [
            ErasureObjects(disks[i * set_drive_count:(i + 1) *
                                 set_drive_count], **set_kwargs)
            for i in range(set_count)]

    @classmethod
    def from_dirs(cls, dirs: list[str], set_count: int,
                  set_drive_count: int, health: bool = True,
                  **set_kwargs) -> "ErasureSets":
        """Format-aware constructor (waitForFormatErasure analog).  With
        ``health`` each drive gets the lifecycle wrapper: offline
        detection, identity-verified reconnect, heal-on-return
        (cmd/erasure-sets.go:196-332)."""
        disks = [XLStorage(d) for d in dirs]
        fmt = load_or_init_format(disks, set_count, set_drive_count)
        bind = None
        if health:
            from ..storage import health as health_mod
            disks, bind = health_mod.wrap_with_heal(disks, fmt,
                                                    set_drive_count)
        obj = cls(disks, set_count, set_drive_count,
                  deployment_id=fmt.id,
                  distribution_algo=fmt.distribution_algo, **set_kwargs)
        if bind is not None:
            bind(obj)
        return obj

    def set_for_disk(self, disk) -> "ErasureObjects | None":
        """The erasure set owning a given drive (identity match)."""
        for s in self.sets:
            if any(d is disk for d in s.disks):
                return s
        return None

    def start_drive_monitor(self, interval_s: float = 5.0):
        """Background reconnect monitor over every health-wrapped drive
        (monitorAndConnectEndpoints, cmd/erasure-sets.go:269)."""
        from ..storage.health import DriveMonitor, HealthDisk
        all_disks = [d for s in self.sets for d in s.disks
                     if isinstance(d, HealthDisk)]
        self.monitor = DriveMonitor(all_disks, interval_s=interval_s)
        self.monitor.start()
        return self.monitor

    # -- distribution (cmd/erasure-sets.go:629-661) ------------------------

    def get_hashed_set_index(self, object_name: str) -> int:
        if self.distribution_algo == DISTRIBUTION_ALGO_CRC:
            crc = zlib.crc32(object_name.encode()) & 0xFFFFFFFF
            return crc % self.set_count
        key = self.deployment_id.replace("-", "")[:32].ljust(32, "0")
        return sip_hash_mod(object_name, self.set_count,
                            bytes.fromhex(key))

    def get_hashed_set(self, object_name: str) -> ErasureObjects:
        return self.sets[self.get_hashed_set_index(object_name)]

    # -- bucket ops: fan out to every set ---------------------------------

    def make_bucket(self, bucket: str) -> None:
        self.sets[0].make_bucket(bucket)
        for s in self.sets[1:]:
            try:
                s.make_bucket(bucket)
            except Exception:  # noqa: BLE001 — partial create healed later
                pass

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.sets[0].get_bucket_info(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        return self.sets[0].list_buckets()

    def health(self, maintenance: bool = False) -> dict:
        return self.aggregate_health(self.sets, maintenance)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        """Delete across every set; if ANY set refuses (not empty), the
        sets already deleted are RESTORED so the bucket never ends up
        half-existing (cmd/erasure-sets.go DeleteBucket undo loop —
        without it a later delete reports BucketNotFound on the sets
        that went first)."""
        done = []
        for s in self.sets:
            try:
                s.delete_bucket(bucket, force)
            except Exception:
                for prev in done:
                    try:
                        prev.make_bucket(bucket)
                    except Exception:  # noqa: BLE001 — best-effort undo
                        pass
                raise
            done.append(s)

    # -- object ops: route to the hashed set ------------------------------

    def put_object(self, bucket, object_name, data, opts=None) -> ObjectInfo:
        return self.get_hashed_set(object_name).put_object(
            bucket, object_name, data, opts)

    def put_object_stream(self, bucket, object_name, reader,
                          opts=None) -> ObjectInfo:
        return self.get_hashed_set(object_name).put_object_stream(
            bucket, object_name, reader, opts)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        return self.get_hashed_set(object_name).get_object(
            bucket, object_name, offset, length, opts)

    def get_object_reader(self, bucket, object_name, offset=0, length=-1,
                          opts=None):
        return self.get_hashed_set(object_name).get_object_reader(
            bucket, object_name, offset, length, opts)

    def get_object_info(self, bucket, object_name, opts=None) -> ObjectInfo:
        return self.get_hashed_set(object_name).get_object_info(
            bucket, object_name, opts)

    def delete_object(self, bucket, object_name, opts=None) -> ObjectInfo:
        return self.get_hashed_set(object_name).delete_object(
            bucket, object_name, opts)

    def put_object_metadata(self, bucket, object_name, version_id, updates,
                            removes=()) -> ObjectInfo:
        return self.get_hashed_set(object_name).put_object_metadata(
            bucket, object_name, version_id, updates, removes)

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        """Merge per-set listings (cmd/metacache-server-pool.go analog)."""
        self.get_bucket_info(bucket)
        out = ListObjectsInfo()
        per_set = [s.list_objects(bucket, prefix, marker, delimiter,
                                  max_keys) for s in self.sets]
        objs: dict[str, ObjectInfo] = {}
        prefixes: set[str] = set()
        for res in per_set:
            for o in res.objects:
                objs.setdefault(o.name, o)
            prefixes.update(res.prefixes)
        names = sorted(objs)
        for name in names:
            out.objects.append(objs[name])
            if len(out.objects) + len(prefixes) >= max_keys:
                if name != names[-1] or any(r.is_truncated for r in per_set):
                    out.is_truncated = True
                    out.next_marker = name
                break
        out.prefixes = sorted(prefixes)
        return out

    def list_object_versions(self, bucket: str, prefix: str = ""):
        out = []
        for s in self.sets:
            out.extend(s.list_object_versions(bucket, prefix))
        return sorted(out, key=lambda o: o.name)

    # -- multipart: route to hashed set -----------------------------------

    def new_multipart_upload(self, bucket, object_name, opts=None):
        return self.get_hashed_set(object_name).new_multipart_upload(
            bucket, object_name, opts)

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        data):
        return self.get_hashed_set(object_name).put_object_part(
            bucket, object_name, upload_id, part_number, data)

    def get_multipart_info(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).get_multipart_info(
            bucket, object_name, upload_id)

    def list_object_parts(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).list_object_parts(
            bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, opts=None):
        return self.get_hashed_set(object_name).complete_multipart_upload(
            bucket, object_name, upload_id, parts, opts)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).abort_multipart_upload(
            bucket, object_name, upload_id)

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for s in self.sets:
            out.extend(s.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda m: m.object_name)

    # -- healing -----------------------------------------------------------

    def heal_object(self, bucket, object_name, version_id=None, deep=False,
                    dry_run=False, remove_dangling=False):
        return healing.heal_object(
            self.get_hashed_set(object_name), bucket, object_name,
            version_id, deep, dry_run, remove_dangling)

    def heal_bucket(self, bucket: str) -> int:
        """Recreate the bucket on any set missing it (healBucket,
        cmd/erasure-healing.go:56); returns sets touched."""
        healed = 0
        for s in self.sets:
            try:
                s.get_bucket_info(bucket)
            except BucketNotFound:
                try:
                    s.make_bucket(bucket)
                    healed += 1
                except Exception:  # noqa: BLE001 — set still down:
                    pass           # the next heal sweep retries it
        return healed

    # internal fan-out used by BucketMetadataSys
    def _fanout(self, fn):
        return self.sets[0]._fanout(fn)
