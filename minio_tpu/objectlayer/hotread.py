"""Hot-read plane — single-flight GET coalescing + the hot-object
cache (the read-side sibling of the PR-8 batching codec service).

Production read traffic is zipfian: a thousand concurrent GETs of one
hot object used to pay a thousand drive fan-outs and a thousand
erasure decodes.  This module is the third application of the
combining discipline that carried the md5 ``LaneScheduler`` (PR 6)
and the ``CodecBatcher`` (PR 8), turned toward reads:

  * **Single-flight coalescing** (:class:`SingleFlight`): concurrent
    readers of one ``(bucket, object, version, range-window)`` share
    ONE drive read + ONE erasure decode.  The first caller becomes the
    leader and executes the real read through the layer's locked
    quorum path; followers park on an event and receive zero-copy
    ``memoryview`` slices of the leader's decoded buffer.  Queues are
    bounded (``cache.singleflight_queue`` waiters per flight — an
    arrival past the bound sheds to an independent read, latency stays
    bounded), waiters can cancel out (deadline or caller death), and
    the plane owns NO threads — leaders are borrowed caller threads,
    so there is nothing to leak at shutdown.

  * **Hot-object cache** (:class:`HotObjectCache`, the promoted
    ``objectlayer/diskcache.py`` tier, memory-resident): windows a
    flight decoded are admitted when the object is HOT — per-key reads
    within the last minute reach ``cache.heat_threshold`` while the
    server's last-minute GetObject rate (the PR-2 ``api_stats`` rings,
    wired in by ``S3Server.reload_cache_config``) says the read plane
    is actually busy — or immediately when readers coalesced (
    concurrent demand is definitionally hot) or the object is
    inline-tiny (its bytes already rode the metadata quorum read).
    Cached bytes charge the PR-9 memory governor under the ``cache``
    kind (``mt_mem_inuse_bytes{kind="cache"}``) via the non-shedding
    :meth:`utils.memgov.MemoryGovernor.try_charge` — under node
    pressure the cache stops growing instead of shedding requests.

**Consistency.**  Every cache HIT revalidates against a quorum
metadata read (itself single-flighted) — the reference disk-cache
discipline (cmd/disk-cache.go GetObjectNInfo ETag validation) — so a
hit can never serve bytes a committed overwrite replaced, on any
node.  Writers additionally invalidate *before the write is
acknowledged*: every commit path bumps the key's generation inside
its ns-write-locked section, which (a) evicts cached windows, and
(b) fences in-flight fills — a fill records the generation when its
flight started and is refused if it changed, so a read that raced an
overwrite can never insert stale bytes.  Joins are safe CROSS-NODE
too, by lock serialization rather than the generation fence: a
flight is joinable only while its leader's fetch is in progress, and
the leader holds the (distributed) ns READ lock for the whole fetch
— so a conflicting overwrite on any node cannot pass its ns-write-
locked commit, let alone ack, until the leader released and the
flight stopped accepting joiners.  A reader that arrives after a
remote overwrite acked therefore always leads (or joins) a flight
whose locked read observes the new version.  Peer nodes evict
through the existing metacache-invalidate fan-out
(``peer.mark_change``); their hits were never stale anyway (quorum
validation), the eviction just frees the bytes promptly.

Config lives in the ``cache`` kvconfig subsystem (enable, max_bytes,
heat_threshold, singleflight_queue, window_bytes), live-reloadable via
admin SetConfigKV → ``S3Server.reload_cache_config``.  Every event
lands in the ``mt_singleflight_*`` / ``mt_cache_*`` metric families
(admin/metrics.py; gauges keep the idle contract — an unused plane
emits nothing).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Optional

from ..utils.locktrace import mtlock

# per-key read-heat window (seconds): touches older than this stop
# counting toward the admission threshold
_HEAT_WINDOW_S = 60.0
# generation entries older than this are prunable once the table is
# over its soft bound — far longer than any in-flight GET lives, so a
# pruned entry can never un-fence a straddling fill
_GEN_TTL_S = 120.0
_GEN_SOFT_CAP = 4096
_HEAT_SOFT_CAP = 4096


class CacheConfig:
    """Live-reloadable knobs (``cache`` kvconfig subsystem).  Reads
    env/defaults lazily on first use; the server pushes admin
    SetConfigKV values via S3Server.reload_cache_config (a fresh
    kvconfig.Config cannot see another instance's dynamic layer)."""

    def __init__(self):
        self.enable = True
        self.max_bytes = 128 << 20
        self.heat_threshold = 2
        self.singleflight_queue = 64
        self.window_bytes = 8 << 20
        # sequential hit-validation coalescing (ROADMAP item 4
        # follow-up): a validation result is reused for this many ms —
        # fenced by the key's generation, so ANY local invalidation
        # (write-path commit, peer mark_change) voids it instantly.
        # 0 disables (every hit pays its own quorum read).
        self.validate_ttl_ms = 50
        self._loaded = False

    def load(self, cfg=None) -> None:
        try:
            if cfg is None:
                from ..utils.kvconfig import Config
                cfg = Config()
            # parse ALL knobs first, assign atomically (the CodecConfig
            # discipline): a bad value in one key must not leave a
            # silently half-applied config
            enable = str(cfg.get("cache", "enable")
                         ).strip().lower() not in ("off", "0",
                                                   "false", "")
            max_bytes = max(0, int(cfg.get("cache", "max_bytes")))
            heat = max(1, int(cfg.get("cache", "heat_threshold")))
            queue = max(0, int(cfg.get("cache", "singleflight_queue")))
            window = max(64 * 1024,
                         int(cfg.get("cache", "window_bytes")))
            try:
                ttl = max(0, int(cfg.get("cache", "validate_ttl_ms")))
            except KeyError:
                # pre-PR config shape (test fakes): keep the current
                # value; a BAD value still aborts the whole load below
                ttl = self.validate_ttl_ms
            self.enable = enable
            self.max_bytes = max_bytes
            self.heat_threshold = heat
            self.singleflight_queue = queue
            self.window_bytes = window
            self.validate_ttl_ms = ttl
        except (KeyError, ValueError):
            pass
        self._loaded = True

    def on(self) -> bool:
        if not self._loaded:
            self.load()
        return self.enable


CONFIG = CacheConfig()

# every live plane, weakly referenced: operational sweeps (and test
# isolation) can release the whole process's cached bytes in one call
# without owning the layers
_PLANES: "weakref.WeakSet[HotReadPlane]" = weakref.WeakSet()


def clear_all_planes() -> None:
    """Release every plane's cached bytes back to the memory governor
    (process-wide).  Used by server shutdown paths that cannot reach a
    layer's plane directly and by the test harness between tests — a
    cache is always safe to drop."""
    for plane in list(_PLANES):
        try:
            plane.clear()
        except Exception:  # noqa: BLE001 — a dying plane must not
            pass           # block the sweep


class _Flight:
    """One in-flight leader read; waiters park on the event."""

    __slots__ = ("event", "result", "exc", "gen", "waiters", "done")

    def __init__(self, gen: int):
        self.event = threading.Event()
        self.result = None
        self.exc: BaseException | None = None
        self.gen = gen
        self.waiters = 0
        self.done = False


class SingleFlight:
    """Generic keyed single-flight executor with generation fencing.

    ``do(group, sub, fetch)`` runs ``fetch()`` once per concurrent
    ``(group, sub)`` key; followers share the leader's result (or its
    exception).  ``gen_of(group)`` fences joins: a flight started
    before ``group`` was invalidated is invisible to readers arriving
    after — they lead a fresh flight instead of riding stale bytes.
    Leaders are borrowed caller threads; the class owns none."""

    def __init__(self, gen_of: Callable[[tuple], int]):
        self._mu = mtlock("hotread.singleflight")
        self._flights: dict[tuple, _Flight] = {}
        self._gen_of = gen_of
        # lifetime totals (scrape gauges + the test/bench deltas)
        self.flights = 0
        self.coalesced = 0
        self.shed = 0
        self.cancelled = 0

    def do(self, group: tuple, sub, fetch: Callable,
           max_waiters: int = 64,
           timeout: float | None = None
           ) -> tuple[str, object, int, int]:
        """Returns ``(mode, result, gen0, followers)`` where mode is
        ``lead`` / ``join`` / ``shed`` / ``cancelled``; result is only
        valid for lead/join.  ``gen0`` is the group generation the
        flight was fenced at — a cache fill must check it is still
        current.  ``followers`` (leads only) counts the waiters the
        flight served beside the leader — the coalescing signal the
        cache admission reads as "definitionally hot"."""
        from ..admin.metrics import GLOBAL as _mtr
        key = (group, sub)
        g0 = self._gen_of(group)
        lead = False
        with self._mu:
            f = self._flights.get(key)
            if f is not None and not f.done and f.gen == g0:
                if f.waiters >= max_waiters:
                    self.shed += 1
                    f = None
                else:
                    f.waiters += 1
            else:
                f = _Flight(g0)
                self._flights[key] = f
                lead = True
        if f is None:
            _mtr.inc("mt_singleflight_shed_total")
            return "shed", None, g0, 0
        if lead:
            try:
                f.result = fetch()
            except BaseException as e:
                f.exc = e
            finally:
                f.done = True
                with self._mu:
                    if self._flights.get(key) is f:
                        del self._flights[key]
                    self.flights += 1
                    followers = f.waiters
                f.event.set()
            _mtr.inc("mt_singleflight_flights_total")
            if f.exc is not None:
                raise f.exc
            return "lead", f.result, g0, followers
        # follower: park for the leader's result.  The leader sets the
        # event in a finally, so a dead leader can never strand us; the
        # poll slice keeps caller-death (async exception) responsive.
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        try:
            while not f.event.wait(0.05):
                if deadline is not None and \
                        time.monotonic() >= deadline:
                    with self._mu:
                        f.waiters -= 1
                        self.cancelled += 1
                    _mtr.inc("mt_singleflight_cancelled_total")
                    return "cancelled", None, g0, 0
        except BaseException:
            # caller death mid-park (KeyboardInterrupt, test harness
            # timeout): cancel our seat so the shed bound stays honest,
            # then keep propagating in the thread it hit
            with self._mu:
                f.waiters -= 1
                self.cancelled += 1
            _mtr.inc("mt_singleflight_cancelled_total")
            raise
        with self._mu:
            self.coalesced += 1
        _mtr.inc("mt_singleflight_coalesced_total")
        if f.exc is not None:
            raise f.exc
        return "join", f.result, g0, 0

    def snapshot(self) -> dict:
        with self._mu:
            return {"flights": self.flights,
                    "coalesced": self.coalesced,
                    "shed": self.shed,
                    "cancelled": self.cancelled,
                    "in_flight": len(self._flights)}


class _Entry:
    """One cached window: decoded plain bytes + the identity triple
    the hit validation compares against a fresh quorum read."""

    __slots__ = ("info", "ident", "data", "charge", "size")

    def __init__(self, info, ident: tuple, data: bytes, charge):
        self.info = info
        self.ident = ident
        self.data = data
        self.charge = charge
        self.size = len(data)


class HotObjectCache:
    """Bounded LRU of decoded object windows (the memory-resident hot
    tier the disk-cache module's gateway wrapper grew into).  Keys are
    ``(bucket, object, version, window)``; bytes charge the memory
    governor (kind ``cache``) while resident and release on evict."""

    def __init__(self):
        self._mu = mtlock("hotread.cache")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._by_key: dict[tuple, set] = {}     # (b, o) -> {full keys}
        self.bytes = 0
        # lifetime totals (scrape + tests)
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidations = 0

    def get(self, ck: tuple) -> Optional[_Entry]:
        with self._mu:
            e = self._entries.get(ck)
            if e is not None:
                self._entries.move_to_end(ck)
            return e

    def record_hit(self) -> None:
        from ..admin.metrics import GLOBAL as _mtr
        with self._mu:
            self.hits += 1
        _mtr.inc("mt_cache_hits_total")

    def record_miss(self) -> None:
        from ..admin.metrics import GLOBAL as _mtr
        with self._mu:
            self.misses += 1
        _mtr.inc("mt_cache_misses_total")

    def record_invalidation(self) -> None:
        with self._mu:
            self.invalidations += 1

    def put(self, ck: tuple, info, ident: tuple, data: bytes,
            max_bytes: int) -> bool:
        """Insert one window; LRU-evicts to fit ``max_bytes`` and
        declines (False) when the governor is past its watermark or
        the window alone exceeds the budget."""
        from ..admin.metrics import GLOBAL as _mtr
        from ..utils.memgov import GOVERNOR
        n = len(data)
        if max_bytes <= 0 or n > max_bytes:
            return False
        charge = GOVERNOR.try_charge(n, "cache")
        if charge is None:
            return False            # node under pressure: don't grow
        entry = _Entry(info, ident, data, charge)
        evicted: list[_Entry] = []
        with self._mu:
            old = self._entries.pop(ck, None)
            if old is not None:
                self.bytes -= old.size
                evicted.append(old)
            while self._entries and self.bytes + n > max_bytes:
                k, e = self._entries.popitem(last=False)
                self._by_key.get(k[:2], set()).discard(k)
                self.bytes -= e.size
                evicted.append(e)
                self.evictions += 1
            if self.bytes + n > max_bytes:
                evicted.append(entry)
                entry = None
            else:
                self._entries[ck] = entry
                self._by_key.setdefault(ck[:2], set()).add(ck)
                self.bytes += n
                self.fills += 1
        for e in evicted:
            e.charge.release()
        if entry is not None:
            _mtr.inc("mt_cache_fills_total")
        return entry is not None

    def evict(self, ck: tuple) -> None:
        with self._mu:
            e = self._entries.pop(ck, None)
            if e is None:
                return
            self._by_key.get(ck[:2], set()).discard(ck)
            self.bytes -= e.size
            self.evictions += 1
        e.charge.release()

    def evict_key(self, key: tuple) -> int:
        """Drop every cached window of one ``(bucket, object)``."""
        dropped: list[_Entry] = []
        with self._mu:
            for ck in list(self._by_key.pop(key, ())):
                e = self._entries.pop(ck, None)
                if e is not None:
                    self.bytes -= e.size
                    dropped.append(e)
            self.evictions += len(dropped)
        for e in dropped:
            e.charge.release()
        return len(dropped)

    def evict_bucket(self, bucket: str) -> int:
        with self._mu:
            keys = [k for k in self._by_key if k[0] == bucket]
        return sum(self.evict_key(k) for k in keys)

    def clear(self) -> None:
        with self._mu:
            dropped = list(self._entries.values())
            self._entries.clear()
            self._by_key.clear()
            self.bytes = 0
            self.evictions += len(dropped)
        for e in dropped:
            e.charge.release()

    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "hits": self.hits, "misses": self.misses,
                    "fills": self.fills, "evictions": self.evictions,
                    "invalidations": self.invalidations}


class _HotBody:
    """Streamed body over one zero-copy slice of a plane buffer.
    Carries ``cache_status`` so the S3 handler can stamp the
    ``x-minio-tpu-cache`` response header."""

    __slots__ = ("_mv", "cache_status", "_done")

    def __init__(self, mv, cache_status: str):
        self._mv = mv
        self.cache_status = cache_status
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        self._done = True
        if not len(self._mv):
            raise StopIteration
        return self._mv

    def close(self) -> None:
        self._done = True


class HotReadPlane:
    """One erasure set's hot-read plane (constructed by
    ``ErasureObjects.__init__``; config is process-global like the
    codec batcher's).  ``serve`` returns ``(info, body)`` or ``None``
    to fall through to the uncoalesced reader — every non-happy path
    (delete markers, invalid ranges, window-spanning requests) falls
    through so the reference error semantics stay in one place."""

    def __init__(self, layer):
        self._layer = layer
        self._mu = mtlock("hotread.plane")
        self._gen_counter = 0
        self._gens: dict[tuple, tuple[int, float]] = {}
        self._heat: dict[tuple, tuple[int, float]] = {}
        # sequential hit-validation coalescing: kv -> (fi, info, gen,
        # expires_monotonic).  An entry is usable only while BOTH the
        # TTL holds and the key's generation is unchanged — a commit
        # (or peer eviction) bumps the generation inside its write-
        # locked section, so a validation cached before an overwrite
        # can never vouch for bytes after it (the stale-read-
        # impossibility regression test pins this)
        self._val_cache: dict[tuple, tuple] = {}
        self.validations_coalesced = 0
        # (b, o, vid) -> (size, monotonic): advisory routing hint so
        # full GETs of known window-spanning objects skip the plane
        # without a wasted window read
        self._sizes: dict[tuple, tuple[int, float]] = {}
        self.sf = SingleFlight(self.gen_of)
        self.cache = HotObjectCache()
        self.config = CONFIG
        # the server's last-minute GetObject rate (PR-2 api_stats),
        # injected by S3Server.reload_cache_config; None = standalone
        # layer, per-key heat alone drives admission
        self.heat_fn: Callable[[], int] | None = None
        # per-key heat from the metering plane's count-min sketch
        # (obs/metering.py key_heat), injected by the same reload;
        # None = metering disabled, the global rate above is the gate
        self.heat_key_fn: Callable[[str, str], int] | None = None
        self.used = False
        _PLANES.add(self)

    # -- generations (invalidate-before-visible fencing) -------------------

    def gen_of(self, key: tuple) -> int:
        with self._mu:
            return self._gens.get(key, (0, 0.0))[0]

    def invalidate(self, bucket: str, object_name: str) -> None:
        """Called by every write path inside its ns-write-locked
        section (and by peer mark_change): bump the fence FIRST, then
        evict — an in-flight fill that read pre-overwrite bytes is
        refused by the fence, and anything already cached is gone
        before the write is acknowledged."""
        from ..admin.metrics import GLOBAL as _mtr
        key = (bucket, object_name)
        now = time.monotonic()
        with self._mu:
            self._gen_counter += 1
            self._gens[key] = (self._gen_counter, now)
            if len(self._gens) > _GEN_SOFT_CAP:
                cut = now - _GEN_TTL_S
                for k in [k for k, (_, t) in self._gens.items()
                          if t < cut]:
                    del self._gens[k]
            for k in [k for k in self._sizes if k[:2] == key]:
                del self._sizes[k]
            for k in [k for k in self._val_cache if k[:2] == key]:
                del self._val_cache[k]
            touched = self.used
        self.cache.evict_key(key)
        self.cache.record_invalidation()
        if touched:
            _mtr.inc("mt_cache_invalidations_total")

    def invalidate_bucket(self, bucket: str) -> None:
        with self._mu:
            self._gen_counter += 1
            now = time.monotonic()
            for key in [k for k in self._gens if k[0] == bucket]:
                self._gens[key] = (self._gen_counter, now)
            for k in [k for k in self._sizes if k[0] == bucket]:
                del self._sizes[k]
            for k in [k for k in self._val_cache if k[0] == bucket]:
                del self._val_cache[k]
        self.cache.evict_bucket(bucket)

    def clear(self) -> None:
        """Release every cached byte (config disable / tests)."""
        with self._mu:
            self._val_cache.clear()
        self.cache.clear()

    # -- admission heat -----------------------------------------------------

    def _touch(self, key: tuple) -> int:
        """Record one read of ``key``; returns reads within the heat
        window (a coarse per-key last-minute ring — the api_stats
        discipline at per-object granularity)."""
        now = time.monotonic()
        with self._mu:
            n, t0 = self._heat.get(key, (0, now))
            if now - t0 > _HEAT_WINDOW_S:
                n, t0 = 0, now
            n += 1
            self._heat[key] = (n, t0)
            if len(self._heat) > _HEAT_SOFT_CAP:
                cut = now - _HEAT_WINDOW_S
                for k in [k for k, (_, t) in self._heat.items()
                          if t < cut]:
                    del self._heat[k]
            return n

    def _admit(self, touches: int, coalesced: bool, tiny: bool,
               key: tuple | None = None) -> bool:
        if tiny or coalesced:
            # concurrent demand is definitionally hot; inline-tiny
            # windows already rode the metadata quorum read
            return True
        if touches < self.config.heat_threshold:
            return False
        if key is not None and self.heat_key_fn is not None:
            # metering plane armed: THIS object's sketch heat is the
            # gate — a single hot key admits even on a quiet server,
            # and a cold key never rides another object's traffic
            try:
                return self.heat_key_fn(key[0], key[1]) >= \
                    self.config.heat_threshold
            except Exception:  # noqa: BLE001 — heat source is advisory
                return True
        if self.heat_fn is not None:
            # the stats-plane gate: a cold read plane (idle server)
            # admits nothing on per-key counts alone
            try:
                return self.heat_fn() >= self.config.heat_threshold
            except Exception:  # noqa: BLE001 — heat source is advisory
                return True
        return True

    # -- the serve path -----------------------------------------------------

    def serve(self, bucket: str, object_name: str, offset: int,
              length: int, opts) -> tuple | None:
        cfg = self.config
        if not cfg.on():
            return None
        if offset < 0:
            return None             # suffix ranges: uncoalesced path
        vid = getattr(opts, "version_id", None)
        key = (bucket, object_name)
        kv = (bucket, object_name, vid)
        W = cfg.window_bytes
        wstart = (offset // W) * W
        wend = wstart + W
        if length >= 0 and offset + length > wend:
            return None             # spans windows: uncoalesced path
        hint = self._hint(kv)
        if hint is not None:
            size = hint
            end = size if length < 0 else min(offset + length, size)
            if offset > size or (size > 0 and offset == size) or \
                    end > min(wend, size):
                return None         # error/spanning: uncoalesced path
        self.used = True
        touches = self._touch(key)
        # span = the region one flight fetches (and one cache entry
        # covers).  A COLD ranged read fetches exactly what was asked
        # — identical concurrent ranges still coalesce, with zero read
        # amplification; once the key is hot (or on full GETs, where
        # the window clamp IS the object), the fetch expands to the
        # whole window so later ranges inside it become cache hits.
        expand = length < 0 or touches >= cfg.heat_threshold
        span_win = (wstart, W)
        span_exact = (offset, length)
        for span in (span_win, span_exact):
            entry = self.cache.get((bucket, object_name, vid, span))
            if entry is None:
                continue
            fi, info = self._validate(kv)
            if fi is None or fi.deleted:
                return None
            if entry.ident != self._ident(fi):
                # a committed overwrite replaced it: drop, refill below
                self.cache.evict((bucket, object_name, vid, span))
                continue
            served = self._slice(entry.info, entry.data, span[0],
                                 offset, length, "hit")
            if served is not None:
                self.cache.record_hit()
                return served
            return None
        self.cache.record_miss()
        span = span_win if expand else span_exact
        start, wlen = (wstart, W) if expand else (offset, length)
        mode, res, g0, followers = self.sf.do(
            key, ("rd", vid, span),
            lambda: self._layer._hot_read_window(
                bucket, object_name, vid, start, wlen),
            max_waiters=cfg.singleflight_queue)
        if mode in ("shed", "cancelled") or res is None:
            return None
        fi, info, data = res
        self._note_size(kv, fi)
        if fi.deleted or data is None:
            return None             # marker / out-of-range: real path
        if length < 0 and fi.size > wend:
            return None             # full GET of a window-spanner
        served = self._slice(info, data, start, offset, length,
                             "coalesced" if mode == "join" else "miss")
        if served is None:
            return None
        if mode == "lead" and self._admit(
                touches, coalesced=followers > 0,
                tiny=fi.size <= getattr(self._layer,
                                        "inline_threshold", 0),
                key=key):
            # fence check rides the recorded generation: only insert
            # while no overwrite bumped the key since the flight
            # started (invalidate-before-visible, the stale-fill gate)
            if self.gen_of(key) == g0:
                self.cache.put((bucket, object_name, vid, span), info,
                               self._ident(fi), data, cfg.max_bytes)
        return served

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _ident(fi) -> tuple:
        return (fi.metadata.get("etag", ""), fi.version_id,
                fi.mod_time)

    def _hint(self, kv: tuple) -> int | None:
        with self._mu:
            h = self._sizes.get(kv)
            return h[0] if h is not None else None

    def _note_size(self, kv: tuple, fi) -> None:
        with self._mu:
            self._sizes[kv] = (fi.size, time.monotonic())
            if len(self._sizes) > _HEAT_SOFT_CAP:
                cut = time.monotonic() - _HEAT_WINDOW_S
                for k in [k for k, (_, t) in self._sizes.items()
                          if t < cut]:
                    del self._sizes[k]

    def _validate(self, kv: tuple):
        """Quorum-read the key's current identity.  CONCURRENT hits
        share one fan-out through the single-flight; SEQUENTIAL hits
        within ``cache.validate_ttl_ms`` reuse the last validation —
        but only while the key's generation is unchanged, so any
        committed local write or peer eviction (both bump the
        generation before the new version is observable) voids the
        reuse instantly and the next hit pays a fresh quorum read.
        Layer errors (ObjectNotFound, quorum loss) propagate exactly
        as the uncoalesced path would raise them."""
        from ..admin.metrics import GLOBAL as _mtr
        bucket, object_name, vid = kv
        key = (bucket, object_name)
        ttl_s = self.config.validate_ttl_ms / 1000.0
        if ttl_s > 0:
            with self._mu:
                e = self._val_cache.get(kv)
                gen_now = self._gens.get(key, (0, 0.0))[0]
            if e is not None and e[2] == gen_now and \
                    time.monotonic() < e[3]:
                with self._mu:
                    self.validations_coalesced += 1
                _mtr.inc("mt_cache_validations_coalesced_total")
                return e[0], e[1]
        g0 = self.gen_of(key)
        mode, res, _, _ = self.sf.do(
            key, ("info", vid),
            lambda: self._layer._hot_fileinfo(bucket, object_name,
                                              vid),
            max_waiters=self.config.singleflight_queue)
        if mode in ("shed", "cancelled"):
            res = self._layer._hot_fileinfo(bucket, object_name, vid)
        self._note_size(kv, res[0])
        if ttl_s > 0 and self.gen_of(key) == g0:
            # fence: only a validation no write raced is reusable
            with self._mu:
                self._val_cache[kv] = (res[0], res[1], g0,
                                       time.monotonic() + ttl_s)
                if len(self._val_cache) > _HEAT_SOFT_CAP:
                    now = time.monotonic()
                    for k in [k for k, v in self._val_cache.items()
                              if v[3] < now]:
                        del self._val_cache[k]
        return res

    def _slice(self, info, data, wstart: int, offset: int,
               length: int, status: str) -> tuple | None:
        size = info.size
        end = size if length < 0 else min(offset + length, size)
        if offset > size or (size > 0 and offset == size):
            return None
        lo = offset - wstart
        hi = end - wstart
        if hi > len(data):
            return None             # window didn't cover (stale hint)
        mv = memoryview(data)[lo:hi]
        return info, _HotBody(mv, status)

    def stats(self) -> dict:
        out = {"singleflight": self.sf.snapshot(),
               "cache": self.cache.stats(),
               "validations_coalesced": self.validations_coalesced}
        return out
