"""Metacache — streamed listing cache (cmd/metacache.go,
cmd/metacache-manager.go, cmd/metacache-bucket.go, cmd/metacache-set.go,
cmd/metacache-entries.go).

The reference executes each listing once per erasure set (disks walked
in agreement, entries resolved across drives), streams the result as
msgp "metacache blocks" persisted as objects under ``.minio.sys``, and
serves continuation requests from the cache instead of re-walking
(cmd/metacache-set.go:544,834).  This build keeps that shape:

* a walk streams resolved ``ObjectInfo`` entries in key order; the
  manager seals them into fixed-size sorted BLOCKS as they arrive,
  persisting each block through the per-drive ``StorageAPI`` and
  keeping only a small LRU of blocks in memory — listing a
  million-object bucket costs O(block), never the namespace;
* a manifest (id, creation time, mgr/gen stamp, last key per block)
  is written after the walk so a restarted process (or another process
  sharing the drives) reuses the persisted blocks, and pagination
  bisects the last-key index to load exactly the covering block;
* local mutations invalidate the bucket's caches immediately;
  everything expires after a TTL (the reference bounds cache life the
  same way and additionally consults the update-tracker bloom filter).

Pagination/delimiter roll-up lives here too (``paginate``, now over
any entry ITERABLE so a page streams out of one block), shared by the
erasure object layer so set/pool merges stay consistent.  V2
continuation tokens (``encode_list_token``/``decode_list_token``) are
opaque, versioned wrappers over the resume key: malformed tokens are a
clean client error, and a token that outlives its snapshot generation
resumes from the key over a fresh walk instead of failing.
"""

from __future__ import annotations

import base64
import bisect
import hashlib
import json
import time
import uuid
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Callable, Iterable, List, Optional

from .interface import ListObjectsInfo, ObjectInfo
from ..utils.locktrace import mtlock

# cache validity (seconds).  The reference keeps a metacache alive while
# clients page through it and retires it after ~2 minutes idle; writes
# here invalidate eagerly so a short-ish TTL only bounds cross-process
# staleness.
DEFAULT_TTL = 15.0
_SYS_PREFIX = "metacache"       # under the drive SYS volume

# entries per persisted metacache block (the reference's
# metacacheBlockSize role) and the per-snapshot in-memory block LRU
BLOCK_ENTRIES = 1000
CACHE_BLOCKS = 4
# rough per-entry working-set estimate the walk charges to the memory
# governor while building blocks
_EST_ENTRY_BYTES = 512


class SnapshotGone(Exception):
    """A persisted block vanished under a live snapshot (invalidate
    race, drive churn) — the caller drops the snapshot and re-walks."""


@dataclass
class Metacache:
    """Legacy single-file snapshot shape (cmd/metacache.go metacache
    struct) — kept for serialization compatibility; the manager now
    builds :class:`BlockedSnapshot` instead.

    ``mgr``/``gen`` stamp WHICH manager wrote the snapshot at WHICH
    bucket mutation generation: a loader that recognises its own mgr
    uuid rejects any snapshot from an older generation outright, so a
    stale file that slipped past the best-effort drop logic can never
    serve a stale listing locally.  Foreign snapshots (other node /
    restarted process) keep the TTL + update-tracker staleness rules."""
    id: str
    bucket: str
    prefix: str
    created: float
    entries: List[ObjectInfo] = field(default_factory=list)
    mgr: str = ""
    gen: int = -1

    def expired(self, ttl: float, now: float | None = None) -> bool:
        return ((now if now is not None else time.time())
                - self.created) > ttl


def paginate(entries: Iterable[ObjectInfo], prefix: str, marker: str,
             delimiter: str, max_keys: int) -> ListObjectsInfo:
    """Delimiter roll-up + marker pagination over sorted entries
    (cmd/metacache-entries.go filterPrefix/forwardTo).  ``entries`` is
    consumed ONCE and only far enough to fill the page — fed from a
    blocked snapshot, one page touches one block.  The marker compares
    against the rolled-up item so resuming from a CommonPrefix
    NextMarker skips the whole prefix."""
    out = ListObjectsInfo()
    prefixes: set[str] = set()
    for oi in entries:
        name = oi.name
        if prefix and not name.startswith(prefix):
            continue
        rest = name[len(prefix):]
        item = prefix + rest.split(delimiter, 1)[0] + delimiter \
            if delimiter and delimiter in rest else None
        if marker and (item or name) <= marker:
            continue
        if item is not None:
            if item in prefixes:
                continue
            prefixes.add(item)
            if len(out.objects) + len(prefixes) >= max_keys:
                out.is_truncated = True
                out.next_marker = item
                break
            continue
        out.objects.append(oi)
        if len(out.objects) + len(prefixes) >= max_keys:
            out.is_truncated = True
            out.next_marker = name
            break
    out.prefixes = sorted(prefixes)
    return out


# -- opaque V2 continuation tokens ------------------------------------------

_TOKEN_PREFIX = "mt1-"


def encode_list_token(key: str, snap_id: str = "", gen: int = -1) -> str:
    """Wrap the resume key (plus advisory snapshot id/generation) into
    the opaque NextContinuationToken clients echo verbatim."""
    doc: dict = {"k": key}
    if snap_id:
        doc["i"] = snap_id
    if gen >= 0:
        doc["g"] = gen
    raw = base64.urlsafe_b64encode(
        json.dumps(doc, separators=(",", ":")).encode()).decode()
    return _TOKEN_PREFIX + raw.rstrip("=")


def decode_list_token(token: str) -> str:
    """Resume key of a continuation token.  A token our encoder did not
    mint passes through as a raw key marker (legacy clients); a token
    WITH our prefix that fails to decode raises ValueError — the S3
    layer maps it to InvalidArgument, never a 500.  A stale snapshot
    id/generation inside is advisory only: pagination restarts from
    the key over a fresh walk."""
    if not token.startswith(_TOKEN_PREFIX):
        return token
    raw = token[len(_TOKEN_PREFIX):]
    try:
        doc = json.loads(base64.urlsafe_b64decode(
            raw + "=" * (-len(raw) % 4)))
        key = doc["k"]
        if not isinstance(key, str):
            raise TypeError(key)
    except Exception as e:  # noqa: BLE001 — any decode failure is the
        # client's malformed token, reported as such
        raise ValueError("invalid continuation token") from e
    return key


def _cache_dir(bucket: str, prefix: str) -> str:
    h = hashlib.sha256(f"{bucket}\x00{prefix}".encode()).hexdigest()[:24]
    return f"{_SYS_PREFIX}/{bucket}/{h}"


def _entries_doc(entries: List[ObjectInfo]) -> list:
    return [asdict(e) for e in entries]


def _entries_from(doc: list) -> List[ObjectInfo]:
    out = []
    for e in doc:
        e["parts"] = [tuple(p) for p in e.get("parts", [])]
        out.append(ObjectInfo(**e))
    return out


def _serialize(mc: Metacache) -> bytes:
    doc = {"id": mc.id, "bucket": mc.bucket, "prefix": mc.prefix,
           "created": mc.created, "mgr": mc.mgr, "gen": mc.gen,
           "entries": _entries_doc(mc.entries)}
    return json.dumps(doc).encode()


def _deserialize(data: bytes) -> Metacache:
    doc = json.loads(data)
    return Metacache(id=doc["id"], bucket=doc["bucket"],
                     prefix=doc["prefix"], created=doc["created"],
                     entries=_entries_from(doc["entries"]),
                     mgr=doc.get("mgr", ""), gen=doc.get("gen", -1))


def leaf_layers_of(layer) -> list:
    """Every leaf object layer under a topology (a pools layer nests
    sets which nest single-set layers) — the one traversal shared by
    cache invalidation, tracker wiring, and peer eviction."""
    if hasattr(layer, "pools"):
        return [x for p in layer.pools for x in leaf_layers_of(p)]
    if hasattr(layer, "sets"):
        return [x for s in layer.sets for x in leaf_layers_of(s)]
    return [layer]


def managers_of(layer) -> list["MetacacheManager"]:
    """Every MetacacheManager under an object-layer topology."""
    out = []
    for leaf in leaf_layers_of(layer):
        mc = getattr(leaf, "metacache", None)
        if mc is not None:
            out.append(mc)
    return out


class BlockedSnapshot:
    """One streamed listing snapshot: sorted entry blocks addressed by
    a last-key index (the reference's metacache-block shape).  Blocks
    live on a drive plus a small in-memory LRU; ``iter_from`` bisects
    the index so pagination loads one covering block per page."""

    def __init__(self, mgr: "MetacacheManager | None", bucket: str,
                 prefix: str, *, id: str, created: float, mgr_id: str,
                 gen: int):
        self._mgr = mgr
        self.bucket = bucket
        self.prefix = prefix
        self.id = id
        self.created = created
        self.mgr = mgr_id
        self.gen = gen
        self.block_keys: list[str] = []     # last key per sealed block
        self._blocks: OrderedDict[int, List[ObjectInfo]] = OrderedDict()
        self._pinned: set[int] = set()      # not on disk: never evicted
        self._disk = None                   # drive holding the blocks
        self._mu = mtlock("metacache.snapshot")

    def expired(self, ttl: float, now: float | None = None) -> bool:
        return ((now if now is not None else time.time())
                - self.created) > ttl

    # -- block access ------------------------------------------------------

    def _block_path(self, i: int) -> str:
        return f"{_cache_dir(self.bucket, self.prefix)}/{self.id}" \
               f"/b{i:06d}.json"

    def _block(self, i: int) -> List[ObjectInfo]:
        with self._mu:
            blk = self._blocks.get(i)
            if blk is not None:
                self._blocks.move_to_end(i)
                return blk
        blk = self._load_block(i)
        with self._mu:
            self._blocks[i] = blk
            self._blocks.move_to_end(i)
            self._evict_locked()
        return blk

    def _evict_locked(self) -> None:
        limit = self._mgr.cache_blocks if self._mgr is not None \
            else CACHE_BLOCKS
        evictable = [i for i in self._blocks if i not in self._pinned]
        while evictable and len(self._blocks) > limit:
            self._blocks.pop(evictable.pop(0), None)

    def _load_block(self, i: int) -> List[ObjectInfo]:
        mgr = self._mgr
        if mgr is None or not mgr._disks or not mgr._sys_volume:
            raise SnapshotGone(f"block {i} of {self.id} not in memory")
        drives = [self._disk] if self._disk is not None else []
        drives += [d for d in mgr._disks if d is not self._disk]
        path = self._block_path(i)
        for d in drives:
            try:
                doc = json.loads(d.read_all(mgr._sys_volume, path))
                if doc.get("id") != self.id:
                    continue
                return _entries_from(doc["entries"])
            except Exception:  # noqa: BLE001 — missing/corrupt: next
                continue
        raise SnapshotGone(f"block {i} of {self.id} unreadable")

    # -- iteration ---------------------------------------------------------

    def iter_from(self, marker: str = "") -> Iterable[ObjectInfo]:
        """Entries in key order starting at the first BLOCK that can
        contain keys past ``marker`` (bisect over the last-key index);
        fine-grained marker filtering stays in :func:`paginate`."""
        start = bisect.bisect_right(self.block_keys, marker) \
            if marker else 0
        for i in range(start, len(self.block_keys)):
            yield from self._block(i)

    @property
    def entries(self) -> List[ObjectInfo]:
        """Whole snapshot materialized — legacy callers/tests only."""
        return list(self.iter_from(""))

    def drop_persisted(self) -> None:
        """Best-effort removal of this snapshot's block dir."""
        mgr = self._mgr
        if mgr is None or self._disk is None:
            return
        try:
            self._disk.delete(
                mgr._sys_volume,
                f"{_cache_dir(self.bucket, self.prefix)}/{self.id}",
                recursive=True)
        except Exception:  # noqa: BLE001 — best effort
            pass


class MetacacheManager:
    """Per-object-layer cache registry (cmd/metacache-manager.go).

    ``disks`` (optional) enables persistence: blocks and the manifest
    are written to the first healthy drive's system volume and loaded
    from any drive on a cold lookup, giving restart/cross-process
    reuse the way the reference persists metacache blocks as objects.
    """

    def __init__(self, disks: Optional[list] = None,
                 ttl: float = DEFAULT_TTL, max_caches: int = 128,
                 sys_volume: str = "", block_entries: int = BLOCK_ENTRIES,
                 cache_blocks: int = CACHE_BLOCKS):
        self._caches: dict[tuple, BlockedSnapshot] = {}
        self._mu = mtlock("metacache.manager")
        self._disks = disks or []
        self._ttl = ttl
        self._max = max_caches
        self._sys_volume = sys_volume
        self.block_entries = max(1, block_entries)
        self.cache_blocks = max(1, cache_blocks)
        self.hits = 0
        self.misses = 0
        # buckets whose on-disk snapshots are KNOWN absent: a PUT-heavy
        # workload invalidates per write, and without this set each
        # invalidate pays a per-drive recursive delete (16 ENOENT
        # syscall rounds per PUT measured on the e2e bench).  A bucket
        # leaves the set when a snapshot is persisted; it (re)enters
        # after a disk-wide drop.  Snapshots written by an EARLIER
        # process are handled by the first invalidate (bucket not yet
        # in the set -> full drop runs once).
        self._clean_buckets: set = set()
        # per-bucket mutation generation: a walk that OVERLAPS a
        # mutation must not install its (possibly stale) snapshot after
        # the mutator's invalidate ran — the lost-invalidate race.  The
        # walk captures the generation first and the snapshot is cached
        # or persisted only if the bucket is untouched since.  The
        # manager uuid + gen are also stamped INTO persisted manifests
        # so _load_manifest rejects this manager's own stale files even
        # when the best-effort drop lost a race (Metacache docstring).
        self._gen: dict = {}
        self._uuid = uuid.uuid4().hex
        # optional DataUpdateTracker: when attached, cache hits consult
        # the change bloom filter so a peer's write invalidates listings
        # immediately instead of after the TTL (the reference's
        # metacache<->data-update-tracker coupling)
        self.tracker = None

    def _stale(self, snap) -> bool:
        """Update-tracker consult (cmd/metacache-bucket.go coupling):
        the cache is stale once the bucket changed at-or-after the
        snapshot's creation.  ``created`` is captured BEFORE the walk,
        so a write landing mid-walk marks a later time and the next
        lookup re-walks; >= makes the same-instant race err toward an
        extra walk, never a stale listing."""
        return self.tracker is not None and \
            self.tracker.bucket_changed_at(snap.bucket) >= snap.created

    # -- persistence -------------------------------------------------------

    def _manifest_path(self, bucket: str, prefix: str) -> str:
        return f"{_cache_dir(bucket, prefix)}/manifest.json"

    def _persist_block(self, snap: BlockedSnapshot, i: int,
                       entries: List[ObjectInfo],
                       was_clean: bool) -> bool:
        if not self._disks or not self._sys_volume:
            return False
        blob = json.dumps({"id": snap.id,
                           "entries": _entries_doc(entries)}).encode()
        if snap._disk is not None:
            drives = [snap._disk]
        else:
            drives = self._disks
        for d in drives:
            try:
                if snap._disk is None and not was_clean:
                    # first write after a non-clean state: drop the
                    # PREVIOUS snapshot's blocks so TTL-expiry rebuilds
                    # don't accrete orphan block dirs (one manifest
                    # read + recursive delete per walk, skipped on the
                    # PUT-heavy invalidate path where the drop already
                    # ran)
                    try:
                        old = json.loads(d.read_all(
                            self._sys_volume,
                            self._manifest_path(snap.bucket,
                                                snap.prefix)))
                        if old.get("id") and old["id"] != snap.id:
                            d.delete(
                                self._sys_volume,
                                f"{_cache_dir(snap.bucket, snap.prefix)}"
                                f"/{old['id']}", recursive=True)
                    except Exception:  # noqa: BLE001 — no old manifest
                        pass
                d.write_all(self._sys_volume, snap._block_path(i), blob)
                snap._disk = d
                return True
            except Exception:  # noqa: BLE001 — next drive (first block
                continue       # only; afterwards the snapshot degrades)
        return False

    def _write_manifest(self, snap: BlockedSnapshot) -> bool:
        if snap._disk is None or not self._sys_volume:
            return False
        doc = {"id": snap.id, "bucket": snap.bucket,
               "prefix": snap.prefix, "created": snap.created,
               "mgr": snap.mgr, "gen": snap.gen,
               "block_keys": snap.block_keys}
        try:
            snap._disk.write_all(
                self._sys_volume,
                self._manifest_path(snap.bucket, snap.prefix),
                json.dumps(doc).encode())
            return True
        except Exception:  # noqa: BLE001 — cold reuse lost, cache fine
            return False

    def _load_manifest(self, bucket: str,
                       prefix: str) -> Optional[BlockedSnapshot]:
        path = self._manifest_path(bucket, prefix)
        for d in self._disks:
            try:
                doc = json.loads(d.read_all(self._sys_volume, path))
                snap = BlockedSnapshot(
                    self, bucket, prefix, id=doc["id"],
                    created=doc["created"], mgr_id=doc.get("mgr", ""),
                    gen=doc.get("gen", -1))
                snap.block_keys = list(doc.get("block_keys", []))
                snap._disk = d
                if snap.mgr == self._uuid:
                    # our own snapshot: exact generation check beats
                    # any TTL heuristic
                    with self._mu:
                        if snap.gen != self._gen.get(bucket, 0):
                            return None
                if not snap.expired(self._ttl):
                    return snap
                return None
            except Exception:  # noqa: BLE001 — missing/corrupt: miss
                continue
        return None

    def _drop_persisted(self, bucket: str) -> None:
        for d in self._disks:
            try:
                d.delete(self._sys_volume, f"{_SYS_PREFIX}/{bucket}",
                         recursive=True)
            except Exception:  # noqa: BLE001 — best effort
                pass

    # -- lookup / fill -----------------------------------------------------

    def list_path(self, bucket: str, prefix: str,
                  loader: Callable[[], List[ObjectInfo]]
                  ) -> BlockedSnapshot:
        """Legacy list-loader entry point (kept for callers that gather
        eagerly): sorts the loaded entries and rides the streamed
        path."""
        return self.list_path_stream(
            bucket, prefix,
            lambda: iter(sorted(loader(), key=lambda o: o.name)))

    def list_path_stream(self, bucket: str, prefix: str,
                         loader: Callable[[], Iterable[ObjectInfo]]
                         ) -> BlockedSnapshot:
        """Snapshot for (bucket, prefix); ``loader`` returns a SORTED
        entry iterator consumed block-at-a-time on miss
        (cmd/metacache-server-pool.go listPath)."""
        key = (bucket, prefix)
        now = time.time()
        with self._mu:
            snap = self._caches.get(key)
            if snap is not None and not snap.expired(self._ttl, now) \
                    and not self._stale(snap):
                self.hits += 1
                return snap
        with self._mu:
            gen_at_load = self._gen.get(bucket, 0)
        snap = self._load_manifest(bucket, prefix)
        if snap is not None and not self._stale(snap):
            self.hits += 1
            with self._mu:
                # install only if the bucket is untouched since before
                # the disk read — an invalidate racing this load must
                # not have its cache clear overwritten by a snapshot it
                # could not see (same guard as the walk path below)
                if self._gen.get(bucket, 0) == gen_at_load:
                    self._install_locked(key, snap)
            return snap
        self.misses += 1
        return self._build(bucket, prefix, loader, now)

    def _install_locked(self, key: tuple, snap: BlockedSnapshot) -> None:
        if len(self._caches) >= self._max and key not in self._caches:
            # evict oldest (manager keeps a bounded registry)
            oldest = min(self._caches,
                         key=lambda k: self._caches[k].created)
            del self._caches[oldest]
        self._caches[key] = snap

    def _build(self, bucket: str, prefix: str,
               loader: Callable[[], Iterable[ObjectInfo]],
               now: float) -> BlockedSnapshot:
        from ..utils.memgov import GOVERNOR
        with self._mu:
            gen0 = self._gen.get(bucket, 0)
            was_clean = bucket in self._clean_buckets
            self._clean_buckets.discard(bucket)
        snap = BlockedSnapshot(self, bucket, prefix,
                               id=uuid.uuid4().hex, created=now,
                               mgr_id=self._uuid, gen=gen0)
        # governor admission for the walk's working set: the build
        # holds one filling block plus the in-memory LRU — a node past
        # its watermark sheds the listing with 503 instead of walking
        charge = GOVERNOR.charge(
            (self.cache_blocks + 1) * self.block_entries
            * _EST_ENTRY_BYTES, "listing")
        persist_ok = True
        try:
            buf: List[ObjectInfo] = []
            for oi in loader():
                buf.append(oi)
                if len(buf) >= self.block_entries:
                    persist_ok = self._seal(snap, buf, was_clean,
                                            persist_ok)
                    buf = []
            if buf:
                persist_ok = self._seal(snap, buf, was_clean,
                                        persist_ok)
        finally:
            charge.release()
        with self._mu:
            fresh = self._gen.get(bucket, 0) == gen0
        if not fresh:
            # bucket mutated mid-walk: serve the snapshot to THIS
            # caller (S3 listings are eventually consistent) but do not
            # install or keep its blocks — the next lookup re-walks.
            # Pin everything still in memory so the caller can finish
            # paging without the deleted on-disk blocks.
            with snap._mu:
                snap._pinned.update(snap._blocks)
            snap.drop_persisted()
            snap._disk = None
            return snap
        if persist_ok and snap._disk is not None:
            self._write_manifest(snap)
        with self._mu:
            if self._gen.get(bucket, 0) == gen0:
                self._install_locked((bucket, prefix), snap)
        return snap

    def _seal(self, snap: BlockedSnapshot, entries: List[ObjectInfo],
              was_clean: bool, persist_ok: bool) -> bool:
        """Seal one block: index it, persist it, keep it in the LRU.
        A persist failure degrades the snapshot to memory-pinned from
        that block on (it's a cache — never fail the listing)."""
        i = len(snap.block_keys)
        snap.block_keys.append(entries[-1].name)
        persisted = persist_ok and self._persist_block(
            snap, i, entries, was_clean)
        with snap._mu:
            snap._blocks[i] = entries
            if not persisted:
                snap._pinned.add(i)
            snap._evict_locked()
        return persisted

    def forget(self, bucket: str, prefix: str) -> None:
        """Drop one (bucket, prefix) snapshot (SnapshotGone recovery)."""
        with self._mu:
            self._caches.pop((bucket, prefix), None)

    def invalidate(self, bucket: str) -> None:
        """Drop every cache for the bucket (local mutation hook)."""
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for key in [k for k in self._caches if k[0] == bucket]:
                del self._caches[key]
            if bucket in self._clean_buckets:
                return              # nothing persisted since last drop
        self._drop_persisted(bucket)
        with self._mu:
            self._clean_buckets.add(bucket)

    def stats(self) -> dict:
        with self._mu:
            return {"caches": len(self._caches), "hits": self.hits,
                    "misses": self.misses}
