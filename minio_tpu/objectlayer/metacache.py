"""Metacache — listing cache (cmd/metacache.go, cmd/metacache-manager.go,
cmd/metacache-bucket.go, cmd/metacache-set.go, cmd/metacache-entries.go).

The reference executes each listing once per erasure set (disks walked in
agreement, entries resolved across drives), streams the result as msgp
"metacache blocks" persisted as objects under ``.minio.sys``, and serves
continuation requests from the cache instead of re-walking.  This build
keeps the same shape, host-side:

* a listing snapshot (sorted resolved ``ObjectInfo`` entries for one
  (bucket, prefix)) is gathered once, paginated from memory for
  continuation requests;
* snapshots persist through the per-drive ``StorageAPI`` into the system
  volume so a restarted process (or another process sharing the drives)
  reuses a fresh listing instead of re-walking;
* local mutations invalidate the bucket's caches immediately; everything
  expires after a TTL (the reference bounds cache life the same way and
  additionally consults the update-tracker bloom filter).

Pagination/delimiter roll-up lives here too (``paginate``), shared by the
erasure object layer so set/pool merges stay consistent.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Callable, List, Optional

from .interface import ListObjectsInfo, ObjectInfo

# cache validity (seconds).  The reference keeps a metacache alive while
# clients page through it and retires it after ~2 minutes idle; writes
# here invalidate eagerly so a short-ish TTL only bounds cross-process
# staleness.
DEFAULT_TTL = 15.0
_SYS_PREFIX = "metacache"       # under the drive SYS volume


@dataclass
class Metacache:
    """One cached listing (cmd/metacache.go metacache struct).

    ``mgr``/``gen`` stamp WHICH manager wrote the snapshot at WHICH
    bucket mutation generation: a loader that recognises its own mgr
    uuid rejects any snapshot from an older generation outright, so a
    stale file that slipped past the best-effort drop logic can never
    serve a stale listing locally.  Foreign snapshots (other node /
    restarted process) keep the TTL + update-tracker staleness rules."""
    id: str
    bucket: str
    prefix: str
    created: float
    entries: List[ObjectInfo] = field(default_factory=list)
    mgr: str = ""
    gen: int = -1

    def expired(self, ttl: float, now: float | None = None) -> bool:
        return ((now if now is not None else time.time())
                - self.created) > ttl


def paginate(entries: List[ObjectInfo], prefix: str, marker: str,
             delimiter: str, max_keys: int) -> ListObjectsInfo:
    """Delimiter roll-up + marker pagination over a sorted entry
    snapshot (cmd/metacache-entries.go filterPrefix/forwardTo).  The
    marker compares against the rolled-up item so resuming from a
    CommonPrefix NextMarker skips the whole prefix."""
    out = ListObjectsInfo()
    prefixes: set[str] = set()
    for oi in entries:
        name = oi.name
        if prefix and not name.startswith(prefix):
            continue
        rest = name[len(prefix):]
        item = prefix + rest.split(delimiter, 1)[0] + delimiter \
            if delimiter and delimiter in rest else None
        if marker and (item or name) <= marker:
            continue
        if item is not None:
            if item in prefixes:
                continue
            prefixes.add(item)
            if len(out.objects) + len(prefixes) >= max_keys:
                out.is_truncated = True
                out.next_marker = item
                break
            continue
        out.objects.append(oi)
        if len(out.objects) + len(prefixes) >= max_keys:
            out.is_truncated = True
            out.next_marker = name
            break
    out.prefixes = sorted(prefixes)
    return out


def _cache_path(bucket: str, prefix: str) -> str:
    h = hashlib.sha256(f"{bucket}\x00{prefix}".encode()).hexdigest()[:24]
    return f"{_SYS_PREFIX}/{bucket}/{h}.json"


def _serialize(mc: Metacache) -> bytes:
    doc = {"id": mc.id, "bucket": mc.bucket, "prefix": mc.prefix,
           "created": mc.created, "mgr": mc.mgr, "gen": mc.gen,
           "entries": [asdict(e) for e in mc.entries]}
    return json.dumps(doc).encode()


def _deserialize(data: bytes) -> Metacache:
    doc = json.loads(data)
    entries = []
    for e in doc["entries"]:
        e["parts"] = [tuple(p) for p in e.get("parts", [])]
        entries.append(ObjectInfo(**e))
    return Metacache(id=doc["id"], bucket=doc["bucket"],
                     prefix=doc["prefix"], created=doc["created"],
                     entries=entries, mgr=doc.get("mgr", ""),
                     gen=doc.get("gen", -1))


def leaf_layers_of(layer) -> list:
    """Every leaf object layer under a topology (a pools layer nests
    sets which nest single-set layers) — the one traversal shared by
    cache invalidation, tracker wiring, and peer eviction."""
    if hasattr(layer, "pools"):
        return [x for p in layer.pools for x in leaf_layers_of(p)]
    if hasattr(layer, "sets"):
        return [x for s in layer.sets for x in leaf_layers_of(s)]
    return [layer]


def managers_of(layer) -> list["MetacacheManager"]:
    """Every MetacacheManager under an object-layer topology."""
    out = []
    for leaf in leaf_layers_of(layer):
        mc = getattr(leaf, "metacache", None)
        if mc is not None:
            out.append(mc)
    return out


class MetacacheManager:
    """Per-object-layer cache registry (cmd/metacache-manager.go).

    ``disks`` (optional) enables persistence: snapshots are written to
    the first healthy drive's system volume and loaded from any drive on
    a cold lookup, giving restart/cross-process reuse the way the
    reference persists metacache blocks as objects.
    """

    def __init__(self, disks: Optional[list] = None,
                 ttl: float = DEFAULT_TTL, max_caches: int = 128,
                 sys_volume: str = ""):
        self._caches: dict[tuple, Metacache] = {}
        self._mu = threading.Lock()
        self._disks = disks or []
        self._ttl = ttl
        self._max = max_caches
        self._sys_volume = sys_volume
        self.hits = 0
        self.misses = 0
        # buckets whose on-disk snapshots are KNOWN absent: a PUT-heavy
        # workload invalidates per write, and without this set each
        # invalidate pays a per-drive recursive delete (16 ENOENT
        # syscall rounds per PUT measured on the e2e bench).  A bucket
        # leaves the set when a snapshot is persisted; it (re)enters
        # after a disk-wide drop.  Snapshots written by an EARLIER
        # process are handled by the first invalidate (bucket not yet
        # in the set -> full drop runs once).
        self._clean_buckets: set = set()
        # per-bucket mutation generation: a walk that OVERLAPS a
        # mutation must not install its (possibly stale) snapshot after
        # the mutator's invalidate ran — the lost-invalidate race.  The
        # walk captures the generation first and the snapshot is cached
        # or persisted only if the bucket is untouched since.  The
        # manager uuid + gen are also stamped INTO persisted snapshots
        # so _load rejects this manager's own stale files even when the
        # best-effort drop lost a race (see Metacache docstring).
        self._gen: dict = {}
        self._uuid = uuid.uuid4().hex
        # optional DataUpdateTracker: when attached, cache hits consult
        # the change bloom filter so a peer's write invalidates listings
        # immediately instead of after the TTL (the reference's
        # metacache<->data-update-tracker coupling)
        self.tracker = None

    def _stale(self, mc: Metacache) -> bool:
        """Update-tracker consult (cmd/metacache-bucket.go coupling):
        the cache is stale once the bucket changed at-or-after the
        snapshot's creation.  ``created`` is captured BEFORE the walk,
        so a write landing mid-walk marks a later time and the next
        lookup re-walks; >= makes the same-instant race err toward an
        extra walk, never a stale listing."""
        return self.tracker is not None and \
            self.tracker.bucket_changed_at(mc.bucket) >= mc.created

    # -- persistence -----------------------------------------------------

    def _persist(self, mc: Metacache, gen0: int = -1) -> None:
        if not self._disks or not self._sys_volume:
            return
        blob = _serialize(mc)
        with self._mu:
            if gen0 >= 0 and self._gen.get(mc.bucket, 0) != gen0:
                return              # bucket mutated since the walk
            self._clean_buckets.discard(mc.bucket)
        written = None
        for d in self._disks:
            try:
                d.write_all(self._sys_volume,
                            _cache_path(mc.bucket, mc.prefix), blob)
                written = d
                break               # one copy is enough; it's a cache
            except Exception:       # noqa: BLE001 — next drive
                continue
        if written is not None and gen0 >= 0:
            with self._mu:
                fresh = self._gen.get(mc.bucket, 0) == gen0
            if not fresh:
                # invalidate raced the write and may have skipped its
                # drop (clean-set fast path) — undo our own snapshot
                try:
                    written.delete(self._sys_volume,
                                   _cache_path(mc.bucket, mc.prefix))
                except Exception:   # noqa: BLE001 — best effort
                    pass

    def _load(self, bucket: str, prefix: str) -> Optional[Metacache]:
        for d in self._disks:
            try:
                blob = d.read_all(self._sys_volume,
                                  _cache_path(bucket, prefix))
                mc = _deserialize(blob)
                if mc.mgr == self._uuid:
                    # our own snapshot: exact generation check beats
                    # any TTL heuristic
                    with self._mu:
                        if mc.gen != self._gen.get(bucket, 0):
                            return None
                if not mc.expired(self._ttl):
                    return mc
                return None
            except Exception:       # noqa: BLE001 — missing/corrupt: miss
                continue
        return None

    def _drop_persisted(self, bucket: str) -> None:
        for d in self._disks:
            try:
                d.delete(self._sys_volume, f"{_SYS_PREFIX}/{bucket}",
                         recursive=True)
            except Exception:       # noqa: BLE001 — best effort
                pass

    # -- lookup / fill ---------------------------------------------------

    def list_path(self, bucket: str, prefix: str,
                  loader: Callable[[], List[ObjectInfo]]) -> Metacache:
        """Cached entries for (bucket, prefix); ``loader`` walks+resolves
        on miss (cmd/metacache-server-pool.go listPath)."""
        key = (bucket, prefix)
        now = time.time()
        with self._mu:
            mc = self._caches.get(key)
            if mc is not None and not mc.expired(self._ttl, now) \
                    and not self._stale(mc):
                self.hits += 1
                return mc
        with self._mu:
            gen_at_load = self._gen.get(bucket, 0)
        mc = self._load(bucket, prefix)
        if mc is not None and not self._stale(mc):
            self.hits += 1
            with self._mu:
                # install only if the bucket is untouched since before
                # the disk read — an invalidate racing this load must
                # not have its cache clear overwritten by a snapshot it
                # could not see (same guard as the walk path below)
                if self._gen.get(bucket, 0) == gen_at_load:
                    self._caches[key] = mc
            return mc
        self.misses += 1
        with self._mu:
            gen0 = self._gen.get(bucket, 0)
        entries = sorted(loader(), key=lambda o: o.name)
        mc = Metacache(id=uuid.uuid4().hex, bucket=bucket, prefix=prefix,
                       created=now, entries=entries, mgr=self._uuid,
                       gen=gen0)
        with self._mu:
            if self._gen.get(bucket, 0) != gen0:
                # bucket mutated mid-walk: serve the snapshot to THIS
                # caller (S3 listings are eventually consistent) but do
                # not install it — the next lookup re-walks
                return mc
            if len(self._caches) >= self._max:
                # evict oldest (manager keeps a bounded registry)
                oldest = min(self._caches, key=lambda k:
                             self._caches[k].created)
                del self._caches[oldest]
            self._caches[key] = mc
        self._persist(mc, gen0)
        return mc

    def invalidate(self, bucket: str) -> None:
        """Drop every cache for the bucket (local mutation hook)."""
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for key in [k for k in self._caches if k[0] == bucket]:
                del self._caches[key]
            if bucket in self._clean_buckets:
                return              # nothing persisted since last drop
        self._drop_persisted(bucket)
        with self._mu:
            self._clean_buckets.add(bucket)

    def stats(self) -> dict:
        with self._mu:
            return {"caches": len(self._caches), "hits": self.hits,
                    "misses": self.misses}
