"""erasureServerPools — capacity-expansion topology
(cmd/erasure-server-pool.go:41).

Multiple pools (each an ErasureSets); placement: an object goes to the pool
that already holds it, else the active pool with the most free space
(getPoolIdx :255, getAvailablePoolIdx :182).  Reads/deletes search pools in
order; lists/heals fan out and merge.

The topology is ELASTIC: a persisted pool manifest (DARE-sealed like
config, versioned, quorum-written on pool 0's system volume) records
every pool's identity (the format deployment id), dirs, geometry and
lifecycle status, so every node agrees on topology across restarts
(cmd/erasure-server-pool-decom.go poolMeta analog).  ``attach_pool``
adds a pool under live traffic; ``start_decommission`` marks a pool
draining — the router stops placing new writes there while reads and
in-flight multipart uploads keep working — and ``finish_decommission``
retires it from the manifest once the rebalancer has emptied it.
Multipart uploads stay pinned to the pool that started them via a
persisted upload→pool map, never recomputed.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field

from .interface import (BucketInfo, ListObjectsInfo, ObjectInfo,
                        ObjectLayer, ObjectNotFound, ReadQuorumError,
                        VersionNotFound)
from .sets import ErasureSets

MANIFEST_PATH = "pools/manifest.json"
UPLOADS_PREFIX = "pools/uploads"

STATUS_ACTIVE = "active"
STATUS_DRAINING = "draining"


@dataclass
class PoolSpec:
    """One manifest row: enough to re-attach the pool after a restart
    (pool_id is the pool's format deployment id — stable, derivable
    from the pool itself, so manifest rows match live pools without
    extra bookkeeping)."""
    pool_id: str
    dirs: list[str] = field(default_factory=list)
    set_count: int = 1
    set_drive_count: int = 0
    status: str = STATUS_ACTIVE
    kwargs: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        return {"id": self.pool_id, "dirs": self.dirs,
                "setCount": self.set_count,
                "setDriveCount": self.set_drive_count,
                "status": self.status, "kwargs": self.kwargs}

    @classmethod
    def from_doc(cls, doc: dict) -> "PoolSpec":
        return cls(doc.get("id", ""), list(doc.get("dirs", [])),
                   doc.get("setCount", 1), doc.get("setDriveCount", 0),
                   doc.get("status", STATUS_ACTIVE),
                   dict(doc.get("kwargs", {})))


class ErasureServerPools(ObjectLayer):
    FREE_SPACE_TTL_S = 5.0

    def __init__(self, pools: list[ErasureSets],
                 specs: list[PoolSpec] | None = None, secret: str = ""):
        assert pools
        self.pools = list(pools)
        if specs is None:
            specs = [PoolSpec(
                pool_id=getattr(p, "deployment_id", "") or f"pool-{i}",
                set_count=getattr(p, "set_count", 1),
                set_drive_count=getattr(p, "set_drive_count", 0))
                for i, p in enumerate(self.pools)]
        self.specs = specs
        self._secret = secret
        self._lock = threading.RLock()
        self._manifest_version = 0
        self._free_cache: tuple[float, list[int]] | None = None

    # -- pool manifest (persisted topology) --------------------------------

    def _seal(self, blob: bytes) -> bytes:
        if not self._secret:
            return blob
        from ..secure import configcrypt
        return configcrypt.encrypt_data(self._secret, blob)

    def _unseal(self, blob: bytes) -> bytes:
        from ..secure import configcrypt
        plain, _ = configcrypt.maybe_decrypt(
            self._secret, blob, configcrypt.old_secrets_from_env())
        return plain

    def save_manifest(self) -> None:
        """Quorum-write the manifest on pool 0's system volume — pool 0
        is the cluster's system pool (config/IAM already live there via
        ``_fanout``) and is never decommissionable, so the manifest
        survives any legal topology change."""
        with self._lock:
            self._manifest_version += 1
            doc = {"version": self._manifest_version,
                   "pools": [sp.to_doc() for sp in self.specs]}
            blob = self._seal(json.dumps(doc).encode())
            from ..storage.xl_storage import SYS_DIR
            self.pools[0]._fanout(
                lambda d: d.write_all(SYS_DIR, MANIFEST_PATH, blob))

    def load_manifest(self) -> bool:
        """Adopt the persisted topology: highest-version readable
        replica wins.  Pools recorded with dirs but missing locally are
        re-attached via ``ErasureSets.from_dirs`` (crash/restart
        resume); pools retired from the manifest are dropped; statuses
        (draining) are re-applied.  Returns True when a manifest was
        found."""
        from ..storage.xl_storage import SYS_DIR
        res, _ = self.pools[0]._fanout(
            lambda d: d.read_all(SYS_DIR, MANIFEST_PATH))
        best: dict | None = None
        for blob in res:
            if blob is None:
                continue
            try:
                doc = json.loads(self._unseal(blob))
            except Exception:  # noqa: BLE001 — torn/stale replica
                continue
            if best is None or doc.get("version", 0) > \
                    best.get("version", 0):
                best = doc
        if best is None:
            return False
        with self._lock:
            self._manifest_version = max(self._manifest_version,
                                         best.get("version", 0))
            by_id = {sp.pool_id: i for i, sp in enumerate(self.specs)}
            listed = set()
            for ent in best.get("pools", []):
                spec = PoolSpec.from_doc(ent)
                listed.add(spec.pool_id)
                if spec.pool_id in by_id:
                    i = by_id[spec.pool_id]
                    self.specs[i].status = spec.status
                    if spec.dirs:
                        self.specs[i].dirs = spec.dirs
                    continue
                if not spec.dirs:
                    continue    # remote pool: its host re-assembles it
                pool = ErasureSets.from_dirs(
                    spec.dirs, spec.set_count, spec.set_drive_count,
                    **spec.kwargs)
                self.pools.append(pool)
                self.specs.append(spec)
            # a pool absent from the winning manifest was retired by a
            # completed decommission — drop it (pool 0 never retires)
            for i in range(len(self.specs) - 1, 0, -1):
                if self.specs[i].pool_id not in listed:
                    self.pools.pop(i)
                    self.specs.pop(i)
        self._free_cache = None
        return True

    # -- elastic topology ---------------------------------------------------

    def attach_pool(self, dirs: list[str], set_count: int,
                    set_drive_count: int, **set_kwargs) -> int:
        """Attach a new pool under live traffic.  Existing buckets are
        created on it BEFORE it joins the router, so a write routed
        there never sees BucketNotFound; new writes become eligible the
        moment it lands in ``self.pools``."""
        pool = ErasureSets.from_dirs(list(dirs), set_count,
                                     set_drive_count, **set_kwargs)
        for b in self.pools[0].list_buckets():
            try:
                pool.make_bucket(b.name)
            except Exception:  # noqa: BLE001 — heal converges it
                pass
        with self._lock:
            if any(sp.pool_id == pool.deployment_id for sp in self.specs):
                raise ValueError(
                    f"pool {pool.deployment_id} already attached")
            self.pools.append(pool)
            self.specs.append(PoolSpec(
                pool.deployment_id, list(dirs), set_count,
                set_drive_count, STATUS_ACTIVE, dict(set_kwargs)))
            self.save_manifest()
        self._free_cache = None
        return len(self.pools) - 1

    def _resolve_pool(self, pool) -> int:
        """Index from an index or a pool id."""
        if isinstance(pool, int):
            if not 0 <= pool < len(self.pools):
                raise ValueError(f"no pool {pool}")
            return pool
        for i, sp in enumerate(self.specs):
            if sp.pool_id == pool:
                return i
        raise ValueError(f"no pool {pool!r}")

    def start_decommission(self, pool) -> int:
        """Mark a pool draining: the router stops placing new writes on
        it immediately; reads/deletes and pinned multipart uploads keep
        working while the rebalancer empties it."""
        with self._lock:
            idx = self._resolve_pool(pool)
            if idx == 0:
                raise ValueError(
                    "pool 0 carries the system volume (config/IAM/"
                    "manifest) and cannot be decommissioned")
            if self.specs[idx].status == STATUS_DRAINING:
                return idx
            if not [i for i in self._active_idxs() if i != idx]:
                raise ValueError("cannot drain the last active pool")
            self.specs[idx].status = STATUS_DRAINING
            self.save_manifest()
        self._free_cache = None
        return idx

    def abort_decommission(self, pool) -> int:
        with self._lock:
            idx = self._resolve_pool(pool)
            if self.specs[idx].status != STATUS_DRAINING:
                raise ValueError(f"pool {idx} is not draining")
            self.specs[idx].status = STATUS_ACTIVE
            self.save_manifest()
        self._free_cache = None
        return idx

    def decommission_pending(self, pool) -> tuple[int, int]:
        """(versions, uploads) still on the pool — the verify-empty
        probe ``finish_decommission`` gates on."""
        idx = self._resolve_pool(pool)
        p = self.pools[idx]
        versions = 0
        uploads = 0
        for b in self.list_buckets():
            versions += len(p.list_object_versions(b.name))
            uploads += len(p.list_multipart_uploads(b.name))
        return versions, uploads

    def finish_decommission(self, pool) -> None:
        """Retire a drained pool from the manifest.  Refuses while any
        version or in-flight upload remains — crash-safe: until the
        manifest write lands the pool is still draining and a restart
        resumes the drain."""
        with self._lock:
            idx = self._resolve_pool(pool)
            if self.specs[idx].status != STATUS_DRAINING:
                raise ValueError(f"pool {idx} is not draining")
            versions, uploads = self.decommission_pending(idx)
            if versions or uploads:
                raise ValueError(
                    f"pool {idx} not empty: {versions} versions, "
                    f"{uploads} uploads remain")
            self.pools.pop(idx)
            self.specs.pop(idx)
            self.save_manifest()
        self._free_cache = None

    def pool_status(self) -> list[dict]:
        frees = self._free_spaces()
        out = []
        for i, sp in enumerate(self.specs):
            out.append({
                "index": i, "id": sp.pool_id, "status": sp.status,
                "setCount": getattr(self.pools[i], "set_count",
                                    sp.set_count),
                "setDriveCount": getattr(self.pools[i], "set_drive_count",
                                         sp.set_drive_count),
                "dirs": sp.dirs, "freeBytes": frees[i]})
        return out

    # -- placement ---------------------------------------------------------

    def _active_idxs(self) -> list[int]:
        return [i for i, sp in enumerate(self.specs)
                if sp.status == STATUS_ACTIVE]

    def _free_space(self, pool: ErasureSets) -> int:
        total = 0
        for s in pool.sets:
            for d in s.disks:
                if d is not None:
                    try:
                        total += d.disk_info().free
                    except Exception:  # noqa: BLE001 — offline drive
                        pass           # counts as zero free space
        return total

    def _free_spaces(self) -> list[int]:
        """Per-pool free bytes, cached briefly: the reference batches and
        caches capacity probes rather than statvfs-ing every drive on
        every PUT (cmd/erasure-server-pool.go:182 getAvailablePoolIdx
        over cached StorageInfo)."""
        import time
        now = time.monotonic()
        if self._free_cache and now - self._free_cache[0] < \
                self.FREE_SPACE_TTL_S and \
                len(self._free_cache[1]) == len(self.pools):
            return self._free_cache[1]
        frees = [self._free_space(p) for p in self.pools]
        self._free_cache = (now, frees)
        return frees

    def get_pool_idx(self, bucket: str, object_name: str) -> int:
        """Existing location wins among ACTIVE pools; else spread new
        names across active pools proportionally to free space
        (cmd/erasure-server-pool.go:255 getPoolIdx, :182
        getAvailablePoolIdx — the reference draws a random threshold
        over total available bytes; we hash the object name instead so
        placement is deterministic per name while converging to the
        same free-space-weighted distribution).  An object living only
        on a draining pool gets its overwrite placed on an active pool
        — that IS the router refusing new writes during decommission."""
        if len(self.pools) == 1:
            return 0        # nothing to place: skip the existence probe
        active = self._active_idxs()
        for i, p in enumerate(self.pools):
            try:
                p.get_object_info(bucket, object_name)
            except (ObjectNotFound, VersionNotFound):
                continue
            # quorum/transport errors propagate: routing a PUT of an
            # existing object elsewhere would shadow it with stale data
            # once the pool recovers (getPoolIdx semantics)
            if i in active:
                return i
        frees = self._free_spaces()
        total = sum(frees[i] for i in active)
        if total <= 0:
            return active[0]
        import zlib
        frac = zlib.crc32(f"{bucket}/{object_name}".encode()) / 2**32
        choose = int(frac * total)
        for i in active:
            if choose < frees[i]:
                return i
            choose -= frees[i]
        return active[-1]

    def _find_pool(self, bucket: str, object_name: str,
                   opts=None) -> ErasureSets:
        if len(self.pools) == 1:
            # the op itself surfaces not-found; probing first would
            # double the lock + quorum-read work of every single-pool GET
            return self.pools[0]
        last: Exception = ObjectNotFound(f"{bucket}/{object_name}")
        for p in self.pools:
            try:
                p.get_object_info(bucket, object_name, opts)
                return p
            except (ObjectNotFound, VersionNotFound, ReadQuorumError) as e:
                last = e
        raise last

    def _find_pools(self, bucket: str, object_name: str,
                    opts=None) -> list[int]:
        """EVERY pool holding the object — deletes must reach all of
        them or a rebalance copy in flight would resurrect the name."""
        out = []
        for i, p in enumerate(self.pools):
            try:
                p.get_object_info(bucket, object_name, opts)
                out.append(i)
            except (ObjectNotFound, VersionNotFound, ReadQuorumError):
                continue
        return out

    # -- bucket ops --------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self.pools[0].make_bucket(bucket)
        for p in self.pools[1:]:
            try:
                p.make_bucket(bucket)
            except Exception:  # noqa: BLE001 — heal converges the
                pass           # pool that missed the bucket create

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.pools[0].get_bucket_info(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    def health(self, maintenance: bool = False) -> dict:
        return self.aggregate_health(self.pools, maintenance)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        """Delete across every pool with the erasure-sets undo loop:
        if ANY pool refuses (not empty), the pools already deleted are
        restored so the bucket never half-exists — the router spreads
        new objects across pools, so the non-empty pool is routinely
        NOT the first one."""
        done = []
        for p in self.pools:
            try:
                p.delete_bucket(bucket, force)
            except Exception:
                for prev in done:
                    try:
                        prev.make_bucket(bucket)
                    except Exception:  # noqa: BLE001 — best-effort undo
                        pass
                raise
            done.append(p)

    # -- object ops --------------------------------------------------------

    def put_object(self, bucket, object_name, data, opts=None) -> ObjectInfo:
        idx = self.get_pool_idx(bucket, object_name)
        return self.pools[idx].put_object(bucket, object_name, data, opts)

    def put_object_stream(self, bucket, object_name, reader,
                          opts=None) -> ObjectInfo:
        idx = self.get_pool_idx(bucket, object_name)
        return self.pools[idx].put_object_stream(bucket, object_name,
                                                 reader, opts)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name, opts).get_object(
            bucket, object_name, offset, length, opts)

    def get_object_reader(self, bucket, object_name, offset=0, length=-1,
                          opts=None):
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name,
                               opts).get_object_reader(
            bucket, object_name, offset, length, opts)

    def get_object_info(self, bucket, object_name, opts=None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name, opts).get_object_info(
            bucket, object_name, opts)

    def delete_object(self, bucket, object_name, opts=None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        if len(self.pools) == 1:
            return self.pools[0].delete_object(bucket, object_name, opts)
        idxs = self._find_pools(bucket, object_name)
        if not idxs:
            return self.pools[0].delete_object(bucket, object_name, opts)
        result = self.pools[idxs[0]].delete_object(bucket, object_name,
                                                   opts)
        for i in idxs[1:]:
            try:
                self.pools[i].delete_object(bucket, object_name, opts)
            except (ObjectNotFound, VersionNotFound):
                pass    # raced with the mover's own source delete
        return result

    def put_object_metadata(self, bucket, object_name, version_id, updates,
                            removes=()) -> ObjectInfo:
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name).put_object_metadata(
            bucket, object_name, version_id, updates, removes)

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        out = ListObjectsInfo()
        objs: dict[str, ObjectInfo] = {}
        prefixes: set[str] = set()
        truncated = False
        for p in self.pools:
            res = p.list_objects(bucket, prefix, marker, delimiter, max_keys)
            truncated = truncated or res.is_truncated
            for o in res.objects:
                objs.setdefault(o.name, o)
            prefixes.update(res.prefixes)
        names = sorted(objs)
        for name in names:
            out.objects.append(objs[name])
            if len(out.objects) + len(prefixes) >= max_keys:
                if name != names[-1] or truncated:
                    out.is_truncated = True
                    out.next_marker = name
                break
        out.prefixes = sorted(prefixes)
        return out

    def list_object_versions(self, bucket: str, prefix: str = ""):
        out = []
        for p in self.pools:
            out.extend(p.list_object_versions(bucket, prefix))
        # a version mid-move exists on two pools between the dest commit
        # and the source delete: merge by (name, version) so listings
        # never show the duplicate
        seen: set[tuple[str, str]] = set()
        merged = []
        for o in sorted(out, key=lambda o: o.name):
            key = (o.name, o.version_id)
            if key in seen:
                continue
            seen.add(key)
            merged.append(o)
        return merged

    # -- multipart (upload pinned to its placement pool via a persisted
    #    upload→pool record; legacy uploads fall back to probing) ----------

    def new_multipart_upload(self, bucket, object_name, opts=None):
        idx = self.get_pool_idx(bucket, object_name)
        uid = self.pools[idx].new_multipart_upload(bucket, object_name, opts)
        if len(self.pools) > 1:
            from ..storage.xl_storage import SYS_DIR
            rec = json.dumps({"pool": self.specs[idx].pool_id,
                              "bucket": bucket,
                              "object": object_name}).encode()
            self.pools[0]._fanout(lambda d: d.write_all(
                SYS_DIR, f"{UPLOADS_PREFIX}/{uid}.json", rec))
        return uid

    def _upload_pool(self, bucket, object_name, upload_id) -> ErasureSets:
        from .interface import InvalidUploadID
        if len(self.pools) > 1:
            from ..storage.xl_storage import SYS_DIR
            res, _ = self.pools[0]._fanout(lambda d: d.read_all(
                SYS_DIR, f"{UPLOADS_PREFIX}/{upload_id}.json"))
            for blob in res:
                if blob is None:
                    continue
                try:
                    pid = json.loads(blob).get("pool", "")
                except ValueError:
                    continue
                for i, sp in enumerate(self.specs):
                    if sp.pool_id == pid:
                        return self.pools[i]
                break   # pinned pool retired mid-upload: probe below
        for p in self.pools:
            try:
                p.list_object_parts(bucket, object_name, upload_id)
                return p
            except InvalidUploadID:
                continue
        raise InvalidUploadID(upload_id)

    def _drop_upload_record(self, upload_id) -> None:
        if len(self.pools) <= 1:
            return
        from ..storage.xl_storage import SYS_DIR
        try:
            self.pools[0]._fanout(lambda d: d.delete(
                SYS_DIR, f"{UPLOADS_PREFIX}/{upload_id}.json"))
        except Exception:  # noqa: BLE001 — stale record is harmless
            pass

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        data):
        return self._upload_pool(bucket, object_name, upload_id) \
            .put_object_part(bucket, object_name, upload_id, part_number,
                             data)

    def get_multipart_info(self, bucket, object_name, upload_id):
        return self._upload_pool(
            bucket, object_name, upload_id).get_multipart_info(
                bucket, object_name, upload_id)

    def list_object_parts(self, bucket, object_name, upload_id):
        return self._upload_pool(bucket, object_name, upload_id) \
            .list_object_parts(bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, opts=None):
        oi = self._upload_pool(bucket, object_name, upload_id) \
            .complete_multipart_upload(bucket, object_name, upload_id,
                                       parts, opts)
        self._drop_upload_record(upload_id)
        return oi

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        res = self._upload_pool(bucket, object_name, upload_id) \
            .abort_multipart_upload(bucket, object_name, upload_id)
        self._drop_upload_record(upload_id)
        return res

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for p in self.pools:
            out.extend(p.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda m: m.object_name)

    # -- healing -----------------------------------------------------------

    def heal_object(self, bucket, object_name, version_id=None, deep=False,
                    dry_run=False, remove_dangling=False):
        last = None
        for p in self.pools:
            try:
                return p.heal_object(bucket, object_name, version_id, deep,
                                     dry_run, remove_dangling)
            except ObjectNotFound as e:
                last = e
        raise last

    def heal_bucket(self, bucket: str) -> int:
        return sum(p.heal_bucket(bucket) for p in self.pools)

    def _fanout(self, fn):
        return self.pools[0]._fanout(fn)
