"""erasureServerPools — capacity-expansion topology
(cmd/erasure-server-pool.go:41).

Multiple pools (each an ErasureSets); placement: an object goes to the pool
that already holds it, else the pool with the most free space
(getPoolIdx :255, getAvailablePoolIdx :182).  Reads/deletes search pools in
order; lists/heals fan out and merge.
"""

from __future__ import annotations

from .interface import (BucketInfo, ListObjectsInfo, ObjectInfo,
                        ObjectLayer, ObjectNotFound, ReadQuorumError,
                        VersionNotFound)
from .sets import ErasureSets


class ErasureServerPools(ObjectLayer):
    FREE_SPACE_TTL_S = 5.0

    def __init__(self, pools: list[ErasureSets]):
        assert pools
        self.pools = pools
        self._free_cache: tuple[float, list[int]] | None = None

    # -- placement ---------------------------------------------------------

    def _free_space(self, pool: ErasureSets) -> int:
        total = 0
        for s in pool.sets:
            for d in s.disks:
                if d is not None:
                    try:
                        total += d.disk_info().free
                    except Exception:  # noqa: BLE001 — offline drive
                        pass           # counts as zero free space
        return total

    def _free_spaces(self) -> list[int]:
        """Per-pool free bytes, cached briefly: the reference batches and
        caches capacity probes rather than statvfs-ing every drive on
        every PUT (cmd/erasure-server-pool.go:182 getAvailablePoolIdx
        over cached StorageInfo)."""
        import time
        now = time.monotonic()
        if self._free_cache and now - self._free_cache[0] < \
                self.FREE_SPACE_TTL_S:
            return self._free_cache[1]
        frees = [self._free_space(p) for p in self.pools]
        self._free_cache = (now, frees)
        return frees

    def get_pool_idx(self, bucket: str, object_name: str) -> int:
        """Existing location wins; else most free space
        (cmd/erasure-server-pool.go:255,182)."""
        if len(self.pools) == 1:
            return 0        # nothing to place: skip the existence probe
        for i, p in enumerate(self.pools):
            try:
                p.get_object_info(bucket, object_name)
                return i
            except (ObjectNotFound, VersionNotFound):
                continue
            # quorum/transport errors propagate: routing a PUT of an
            # existing object elsewhere would shadow it with stale data
            # once the pool recovers (getPoolIdx semantics)
        frees = self._free_spaces()
        return max(range(len(frees)), key=frees.__getitem__)

    def _find_pool(self, bucket: str, object_name: str,
                   opts=None) -> ErasureSets:
        if len(self.pools) == 1:
            # the op itself surfaces not-found; probing first would
            # double the lock + quorum-read work of every single-pool GET
            return self.pools[0]
        last: Exception = ObjectNotFound(f"{bucket}/{object_name}")
        for p in self.pools:
            try:
                p.get_object_info(bucket, object_name, opts)
                return p
            except (ObjectNotFound, VersionNotFound, ReadQuorumError) as e:
                last = e
        raise last

    # -- bucket ops --------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self.pools[0].make_bucket(bucket)
        for p in self.pools[1:]:
            try:
                p.make_bucket(bucket)
            except Exception:  # noqa: BLE001 — heal converges the
                pass           # pool that missed the bucket create

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.pools[0].get_bucket_info(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    def health(self, maintenance: bool = False) -> dict:
        return self.aggregate_health(self.pools, maintenance)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        for p in self.pools:
            p.delete_bucket(bucket, force)

    # -- object ops --------------------------------------------------------

    def put_object(self, bucket, object_name, data, opts=None) -> ObjectInfo:
        idx = self.get_pool_idx(bucket, object_name)
        return self.pools[idx].put_object(bucket, object_name, data, opts)

    def put_object_stream(self, bucket, object_name, reader,
                          opts=None) -> ObjectInfo:
        idx = self.get_pool_idx(bucket, object_name)
        return self.pools[idx].put_object_stream(bucket, object_name,
                                                 reader, opts)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name, opts).get_object(
            bucket, object_name, offset, length, opts)

    def get_object_reader(self, bucket, object_name, offset=0, length=-1,
                          opts=None):
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name,
                               opts).get_object_reader(
            bucket, object_name, offset, length, opts)

    def get_object_info(self, bucket, object_name, opts=None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name, opts).get_object_info(
            bucket, object_name, opts)

    def delete_object(self, bucket, object_name, opts=None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        try:
            pool = self._find_pool(bucket, object_name)
        except ObjectNotFound:
            pool = self.pools[0]
        return pool.delete_object(bucket, object_name, opts)

    def put_object_metadata(self, bucket, object_name, version_id, updates,
                            removes=()) -> ObjectInfo:
        self.get_bucket_info(bucket)
        return self._find_pool(bucket, object_name).put_object_metadata(
            bucket, object_name, version_id, updates, removes)

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000) -> ListObjectsInfo:
        out = ListObjectsInfo()
        objs: dict[str, ObjectInfo] = {}
        prefixes: set[str] = set()
        truncated = False
        for p in self.pools:
            res = p.list_objects(bucket, prefix, marker, delimiter, max_keys)
            truncated = truncated or res.is_truncated
            for o in res.objects:
                objs.setdefault(o.name, o)
            prefixes.update(res.prefixes)
        names = sorted(objs)
        for name in names:
            out.objects.append(objs[name])
            if len(out.objects) + len(prefixes) >= max_keys:
                if name != names[-1] or truncated:
                    out.is_truncated = True
                    out.next_marker = name
                break
        out.prefixes = sorted(prefixes)
        return out

    def list_object_versions(self, bucket: str, prefix: str = ""):
        out = []
        for p in self.pools:
            out.extend(p.list_object_versions(bucket, prefix))
        return sorted(out, key=lambda o: o.name)

    # -- multipart (upload routed to placement pool; the upload id is
    #    looked up on every pool for the follow-up calls) ------------------

    def new_multipart_upload(self, bucket, object_name, opts=None):
        idx = self.get_pool_idx(bucket, object_name)
        uid = self.pools[idx].new_multipart_upload(bucket, object_name, opts)
        return uid

    def _upload_pool(self, bucket, object_name, upload_id) -> ErasureSets:
        from .interface import InvalidUploadID
        for p in self.pools:
            try:
                p.list_object_parts(bucket, object_name, upload_id)
                return p
            except InvalidUploadID:
                continue
        raise InvalidUploadID(upload_id)

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        data):
        return self._upload_pool(bucket, object_name, upload_id) \
            .put_object_part(bucket, object_name, upload_id, part_number,
                             data)

    def get_multipart_info(self, bucket, object_name, upload_id):
        return self._upload_pool(
            bucket, object_name, upload_id).get_multipart_info(
                bucket, object_name, upload_id)

    def list_object_parts(self, bucket, object_name, upload_id):
        return self._upload_pool(bucket, object_name, upload_id) \
            .list_object_parts(bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts):
        return self._upload_pool(bucket, object_name, upload_id) \
            .complete_multipart_upload(bucket, object_name, upload_id, parts)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self._upload_pool(bucket, object_name, upload_id) \
            .abort_multipart_upload(bucket, object_name, upload_id)

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for p in self.pools:
            out.extend(p.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda m: m.object_name)

    # -- healing -----------------------------------------------------------

    def heal_object(self, bucket, object_name, version_id=None, deep=False,
                    dry_run=False, remove_dangling=False):
        last = None
        for p in self.pools:
            try:
                return p.heal_object(bucket, object_name, version_id, deep,
                                     dry_run, remove_dangling)
            except ObjectNotFound as e:
                last = e
        raise last

    def heal_bucket(self, bucket: str) -> int:
        return sum(p.heal_bucket(bucket) for p in self.pools)

    def _fanout(self, fn):
        return self.pools[0]._fanout(fn)
