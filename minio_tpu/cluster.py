"""Multi-node cluster assembly — the distributed deployment path
(cmd/server-main.go:389 serverMain + cmd/endpoint*.go topology, rebuilt
for host-RPC + device-compute).

Each node runs: an RPC server exporting its local drives (storage service)
and lock table (lock service), plus the S3 frontend over an object layer
whose drive list mixes local XLStorage and RemoteStorage clients in the
SAME global order on every node — so quorum, distribution, and healing
agree cluster-wide.  Namespace locks are dsync DRWMutexes over every
node's locker.
"""

from __future__ import annotations

from dataclasses import dataclass

from .objectlayer.sets import ErasureSets
from .parallel.dsync import (LocalLocker, NamespaceLock, RemoteLocker,
                             register_lock_service)
from .parallel.rpc import RPCClient, RPCServer
from .storage.format import load_or_init_format
from .storage.remote import RemoteStorage, register_storage_service
from .storage.xl_storage import XLStorage


@dataclass
class NodeSpec:
    """One host in the cluster layout: (endpoint filled at runtime)."""
    node_id: str
    drive_dirs: list[str]
    endpoint: str = ""


class Node:
    """A running cluster member: RPC services + its view of the object
    layer (every node can serve any request, cmd/routers.go:30-38)."""

    def __init__(self, spec: NodeSpec, all_specs: list[NodeSpec],
                 secret: str, set_drive_count: int | None = None,
                 host: str = "127.0.0.1", port: int = 0, tls=None,
                 **set_kwargs):
        self.spec = spec
        self.secret = secret
        self.tls = tls
        if tls is not None:
            # outbound internode clients (this node's RemoteStorage /
            # RemoteLocker links) resolve their CA-pinned context +
            # client identity through the process-global registry
            from .secure import transport as _tls_transport
            _tls_transport.configure(tls)
        self.drives = {f"drive{i}": XLStorage(d)
                       for i, d in enumerate(spec.drive_dirs)}
        self.locker = LocalLocker()
        self.rpc = RPCServer(secret, host=host, port=port, tls=tls)
        register_storage_service(self.rpc, self.drives)
        register_lock_service(self.rpc, self.locker)
        # codec sidecar (BASELINE north star): peers without a chip can
        # ship shard blocks here for device encode/reconstruct
        from .parallel.codec_service import register_codec_service
        register_codec_service(self.rpc)
        self.rpc.start()
        spec.endpoint = self.rpc.endpoint
        self._all_specs = all_specs
        self._set_kwargs = set_kwargs
        self._set_drive_count = set_drive_count
        self.layer: ErasureSets | None = None

    def assemble(self) -> ErasureSets:
        """Build this node's object layer once every peer endpoint is
        known (bootstrap rendezvous, cmd/bootstrap-peer-server.go:162)."""
        disks = []
        lockers = []
        for spec in self._all_specs:
            local = spec.node_id == self.spec.node_id
            if local:
                lockers.append(self.locker)
            else:
                client = RPCClient(spec.endpoint, self.secret)
                lockers.append(RemoteLocker(client))
            for i in range(len(spec.drive_dirs)):
                if local:
                    disks.append(self.drives[f"drive{i}"])
                else:
                    disks.append(RemoteStorage(
                        RPCClient(spec.endpoint, self.secret), f"drive{i}"))
        n = len(disks)
        sdc = self._set_drive_count or n
        assert n % sdc == 0
        fmt = load_or_init_format(disks, n // sdc, sdc)
        # drive lifecycle wrappers + reconnect monitor: offline drives
        # fail fast, returned drives are identity-verified, wiped drives
        # are reformatted and the owning set healed
        # (cmd/erasure-sets.go:196-332)
        from .storage import health as health_mod
        disks, bind = health_mod.wrap_with_heal(disks, fmt, sdc)
        self.layer = ErasureSets(
            disks, n // sdc, sdc, deployment_id=fmt.id,
            distribution_algo=fmt.distribution_algo,
            ns_lock=NamespaceLock(lockers), **self._set_kwargs)
        bind(self.layer)
        self.monitor = self.layer.start_drive_monitor()
        return self.layer

    def stop(self) -> None:
        if getattr(self, "monitor", None) is not None:
            self.monitor.stop()
        self.rpc.stop()


def start_cluster(specs: list[NodeSpec], secret: str,
                  set_drive_count: int | None = None, tls=None,
                  **set_kwargs) -> list[Node]:
    """Boot all nodes, then assemble each node's layer (first node formats,
    the rest adopt — waitForFormatErasure analog).  ``tls`` (a
    secure.certs.CertManager) encrypts the whole internode plane:
    every RPC listener serves the internode identity and requires
    CA-signed client certificates, every internode client presents
    one."""
    nodes = [Node(s, specs, secret, set_drive_count, tls=tls,
                  **set_kwargs)
             for s in specs]
    for node in nodes:
        node.assemble()
    return nodes


def wait_for_peers(specs: list[NodeSpec], secret: str, self_id: str,
                   timeout: float = 60.0) -> None:
    """Poll every peer's RPC ping until the whole topology answers
    (verifyServerSystemConfig / bootstrap rendezvous,
    cmd/bootstrap-peer-server.go:162) — multi-process nodes start in any
    order and must not assemble before their peers listen."""
    import time

    from .parallel.rpc import RPCError

    deadline = time.monotonic() + timeout
    pending = [s for s in specs if s.node_id != self_id]
    while pending:
        still = []
        for spec in pending:
            try:
                c = RPCClient(spec.endpoint, secret, timeout=2.0)
                if c.call("sys", "ping") != "pong":
                    still.append(spec)
            except RPCError as e:
                if e.error_type == "AuthError":
                    # a secret mismatch never resolves by waiting —
                    # surface the misconfiguration immediately
                    raise
                still.append(spec)
            except Exception:  # noqa: BLE001 — not up yet
                still.append(spec)
        pending = still
        if pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "peers never came up: "
                    + ", ".join(s.node_id for s in pending))
            time.sleep(0.25)


def _wait_for_leader_format(leader: NodeSpec, secret: str,
                            timeout: float = 60.0) -> None:
    """Poll the leader's first drive until format.json exists."""
    import time

    from .storage.format import FORMAT_FILE
    from .storage.xl_storage import SYS_DIR

    client = RPCClient(leader.endpoint, secret)
    remote = RemoteStorage(client, "drive0")
    deadline = time.monotonic() + timeout
    while True:
        try:
            remote.read_all(SYS_DIR, FORMAT_FILE)
            return
        except Exception:  # noqa: BLE001 — leader hasn't formatted yet
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"leader {leader.node_id} never wrote format.json")
            time.sleep(0.25)


def run_node(self_id: str, specs: list[NodeSpec], secret: str,
             s3_address: str = "127.0.0.1:0",
             set_drive_count: int | None = None,
             access_key: str = "minioadmin",
             secret_key: str = "minioadmin", tls=None, **set_kwargs):
    """One real cluster member process: RPC services on the DECLARED
    endpoint (so peers can dial before rendezvous), wait for the
    topology, assemble, serve S3.  Returns (node, s3_server).

    ``tls`` may be a CertManager; when omitted, the ``tls`` kvconfig
    subsystem (env: MT_TLS_ENABLE / MT_TLS_CERTS_DIR) is consulted —
    a declared ``https://`` topology then comes up fully encrypted on
    both planes."""
    from .s3.server import S3Server

    if tls is None:
        from .secure.certs import CertManager
        from .utils.kvconfig import Config
        tls = CertManager.from_config(Config())
    spec = next(s for s in specs if s.node_id == self_id)
    if not spec.endpoint:
        raise ValueError(f"node {self_id} needs a declared endpoint")
    u = spec.endpoint.removeprefix("https://").removeprefix("http://")
    rhost, _, rport = u.rpartition(":")
    node = Node(spec, specs, secret, set_drive_count,
                host=rhost or "127.0.0.1", port=int(rport), tls=tls,
                **set_kwargs)
    # Node re-derives spec.endpoint from the bound socket; with a fixed
    # port they agree with what peers dialed
    wait_for_peers(specs, secret, self_id)
    # first-boot formatting is leader-only (waitForFormatErasure: "first
    # node creates format, others wait") — concurrent init on multiple
    # nodes would mint divergent deployment ids
    if specs[0].node_id != self_id:
        _wait_for_leader_format(specs[0], secret)
    layer = node.assemble()
    shost, _, sport = s3_address.rpartition(":")
    srv = S3Server(layer, access_key=access_key, secret_key=secret_key,
                   host=shost or "127.0.0.1", port=int(sport), tls=tls)
    srv.node_name = self_id     # traces/logs name the serving node
    srv.api_stats.label = self_id
    from .obs import trace as _obs_trace
    _obs_trace.set_node_name(self_id)   # subsystem spans too
    srv.iam.load()
    # peer control-plane service: IAM/bucket-metadata changes propagate
    # to every node immediately; trace/log streams aggregate cluster-wide
    # (cmd/peer-rest-common.go:27-61)
    from .parallel.peer import PeerNotifier, register_peer_service
    register_peer_service(node.rpc, srv)
    srv.attach_peers(PeerNotifier(
        [RPCClient(s.endpoint, secret) for s in specs
         if s.node_id != self_id]))
    # every node tracks updates (peer mark_change lands here); the
    # LEADER runs the global crawler + heal sweep — this build's walks
    # cover the whole layer, so per-node copies would duplicate scans
    # (the reference crawls per-local-drive instead,
    # cmd/server-main.go:499)
    from .background.tracker import DataUpdateTracker
    srv.attach_tracker(DataUpdateTracker())
    if specs[0].node_id == self_id:
        import os as _os

        from .background.crawler import Crawler
        from .background.heal import BackgroundHealer
        from .objectlayer.tiering import transition_fn
        srv.crawler = Crawler(
            layer, bucket_meta=srv.bucket_meta,
            interval_s=float(_os.environ.get("MT_CRAWL_INTERVAL_S",
                                             "60")),
            transition_fn=transition_fn(srv.transition),
            tracker=srv.tracker)
        srv.healer = BackgroundHealer(
            layer,
            interval_s=float(_os.environ.get("MT_HEAL_INTERVAL_S",
                                             "3600")),
            deep_every=int(_os.environ.get("MT_HEAL_DEEP_EVERY", "8")))
        srv.attach_background(srv.crawler, srv.healer)
    srv.start()
    return node, srv
