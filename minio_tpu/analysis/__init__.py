"""Concurrency analysis plane, static half: the pluggable AST lint
framework (core.py) and its rule catalog (rules.py) — the repo's
staticcheck/ruleguard stand-in, runnable three ways with identical
findings:

* ``python -m minio_tpu.analysis [--json]`` (CI gate; exit 1 on any
  finding),
* ``tests/test_static_analysis.py`` (the tier-1 shell),
* :func:`run_tree` from code.

The dynamic half — the runtime lock-order/deadlock detector — lives
in ``minio_tpu/utils/locktrace.py``.  docs/static-analysis.md is the
catalog: every rule id, the suppression grammar, and the locktrace
model.
"""

from .core import Finding, Module, Rule, run_tree  # noqa: F401 — public API
from .rules import ALL_RULES  # noqa: F401 — public API
