"""The rule catalog (docs/static-analysis.md).

First four rules are the checks absorbed verbatim from
tests/test_static_analysis.py (same messages, same file:line); the
rest are tuned to this codebase's real concurrency failure classes —
the ones the writer planes, the MD5 lane scheduler, the codec batcher,
the egress senders, and the memory governor actually hit in PRs 5-9.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Module, Rule

# -- helpers -----------------------------------------------------------------


def _last_segment(expr: ast.AST) -> str:
    """The trailing identifier of a dotted expression (``self._mu`` ->
    ``_mu``; ``SCHED`` -> ``SCHED``); empty for anything else."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _marker_reason(line: str, marker_re: str) -> str | None:
    """Reason text following a legacy suppression marker on ``line``,
    or None when the marker is absent.  An empty string means the
    marker is there but reason-less — the caller flags it."""
    m = re.search(marker_re, line)
    if m is None:
        return None
    return m.group(1).strip("—-: ").strip()


_LOCK_SEG_RE = re.compile(
    r"(?:^|_)(lock|locks|mu|mutex|rlock|cond|cv|sem|semaphore)$",
    re.I)
_COND_SEG_RE = re.compile(
    r"(?:^|_)(cond|cv|not_empty|not_full|condition)$", re.I)


def _is_lockish(expr: ast.AST) -> bool:
    return bool(_LOCK_SEG_RE.search(_last_segment(expr)))


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:   # noqa: BLE001 — best-effort label for messages
        return "<expr>"


# -- the absorbed checks -----------------------------------------------------


class BareExceptRule(Rule):
    id = "bare-except"
    description = ("``except:`` without a type swallows "
                   "KeyboardInterrupt/SystemExit — name the exception")

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield Finding(mod.rel, node.lineno, self.id,
                              "bare except")


class MutableDefaultRule(Rule):
    id = "mutable-default"
    description = ("list/dict/set literals as parameter defaults are "
                   "shared across calls")

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in list(node.args.defaults) + \
                        [d for d in node.args.kw_defaults if d]:
                    if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                        yield Finding(mod.rel, node.lineno, self.id,
                                      f"mutable default args: "
                                      f"{node.name}")


def _imported_names(node):
    """(bound name, lineno) entries."""
    if isinstance(node, ast.Import):
        for a in node.names:
            yield (a.asname or a.name.split(".")[0]), node.lineno
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return                       # flag imports bind no name
        for a in node.names:
            if a.name == "*":
                continue
            yield (a.asname or a.name), node.lineno


class UnusedImportRule(Rule):
    id = "unused-import"
    description = ("imported name never referenced (side-effect "
                   "imports carry a trailing ``# noqa``)")

    def check_module(self, mod: Module):
        used = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        # names in __all__ strings and docstring references count
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                used.update(node.value.replace(",", " ").split())
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            for name, lineno in _imported_names(node):
                reason = _marker_reason(
                    mod.line_text(lineno),
                    r"#\s*noqa[:\s]*[A-Z0-9, ]*(.*)$")
                if reason:
                    continue             # side-effect/registry import
                if reason == "" and name not in used:
                    yield Finding(mod.rel, lineno, self.id,
                                  f"unused import {name}: its noqa "
                                  f"marker needs a reason")
                elif name not in used:
                    yield Finding(mod.rel, lineno, self.id,
                                  f"unused import: {name}")


# the test/replication S3Client's whole-object API is its contract;
# everything else in the request planes must read ranged or streamed
_WHOLE_BODY_EXEMPT = ("minio_tpu/s3/client.py",)
_WHOLE_BODY_SCOPE = ("minio_tpu/s3/", "minio_tpu/s3select/")


class WholeBodyReadRule(Rule):
    id = "whole-body-read"
    description = ("unbounded-memory pattern in the S3 request planes "
                   "(rangeless get_object / argless body read() / "
                   "whole-stream b''.join materialization)")

    def check_module(self, mod: Module):
        if mod.rel in _WHOLE_BODY_EXEMPT or \
                not mod.rel.startswith(_WHOLE_BODY_SCOPE):
            return
        in_select = mod.rel.startswith("minio_tpu/s3select/")
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            reason = _marker_reason(mod.line_text(node.lineno),
                                    r"#\s*whole-body-ok\s*(.*)$")
            if reason:
                continue
            if reason == "":
                yield Finding(mod.rel, node.lineno, self.id,
                              "whole-body-ok marker without a reason "
                              "— say why this materialization is a "
                              "documented fallback")
                continue
            attr = node.func.attr
            if attr == "get_object":
                kw = {k.arg for k in node.keywords}
                if len(node.args) < 3 and \
                        not ({"offset", "length"} & kw):
                    yield Finding(mod.rel, node.lineno, self.id,
                                  "whole-object get_object (no range)")
            elif attr == "read" and not node.args and not node.keywords:
                recv = _safe_unparse(node.func.value)
                if "rfile" in recv or "body" in recv or \
                        "reader" in recv:
                    yield Finding(mod.rel, node.lineno, self.id,
                                  "unbounded request-body read()")
            elif in_select and attr == "join" and \
                    isinstance(node.func.value, ast.Constant) and \
                    node.func.value.value == b"":
                # the PR-9 materializing-fallback shape: b"".join over
                # a chunk stream rebuilds the whole decoded object in
                # memory — every site must be a documented fallback
                # (bounded comprehensions over headers/fragments are
                # the normal join idiom and stay unflagged)
                if node.args and isinstance(
                        node.args[0],
                        (ast.Name, ast.Attribute, ast.Call)):
                    yield Finding(mod.rel, node.lineno, self.id,
                                  "whole-stream join() materializes "
                                  "the object")


# -- lock discipline ---------------------------------------------------------

# dotted-name suffixes that BLOCK: sockets/RPC wire ops, subprocesses,
# thread joins, sleeps, HTTP round-trips, future results, and device
# dispatches — none of which belong inside a ``with <lock>`` body on
# the threaded data plane (they stall every other waiter)
_BLOCKING_ATTRS = {
    "sendall", "recv", "recv_into", "accept", "connect",
    "getresponse", "urlopen", "check_output", "check_call",
    "communicate", "block_until_ready", "device_put",
}
_BLOCKING_QUALIFIED = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "select.select", "socket.create_connection",
}
_THREADISH_RE = re.compile(
    r"(?:^|_)(thread|threads|worker|workers|sender|proc|t|th)\d*$",
    re.I)


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = ("bare .acquire() without a finally-paired release, "
                   "or a blocking call (socket/RPC send, subprocess, "
                   "Thread.join, sleep, HTTP, Future.result, device "
                   "dispatch) inside a ``with <lock>`` body")

    def check_module(self, mod: Module):
        yield from self._bare_acquires(mod)
        yield from self._blocking_under_lock(mod)

    # bare .acquire(): an expression statement discarding the result,
    # with no enclosing try whose finally releases the same receiver
    def _bare_acquires(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "acquire"
                    and _is_lockish(node.value.func.value)):
                continue
            recv = _safe_unparse(node.value.func.value)
            if self._finally_releases(mod, node, recv):
                continue
            yield Finding(
                mod.rel, node.lineno, self.id,
                f"bare {recv}.acquire() without a finally-paired "
                f"release — use `with {recv}:` or try/finally")

    @classmethod
    def _finally_releases(cls, mod: Module, node: ast.AST,
                          recv: str) -> bool:
        # idiom A: the acquire sits INSIDE a try whose finally releases
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.Try) and \
                    cls._releases_in(anc.finalbody, recv):
                return True
        # idiom B: ``x.acquire()`` immediately followed by
        # ``try: ... finally: x.release()`` as the NEXT statement
        parent = mod.parent_of(node)
        for body in (getattr(parent, "body", None),
                     getattr(parent, "orelse", None),
                     getattr(parent, "finalbody", None)):
            if not body or node not in body:
                continue
            i = body.index(node)
            if i + 1 < len(body) and isinstance(body[i + 1], ast.Try) \
                    and cls._releases_in(body[i + 1].finalbody, recv):
                return True
        return False

    @staticmethod
    def _releases_in(stmts, recv: str) -> bool:
        for fin in stmts or ():
            for sub in ast.walk(fin):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "release" and \
                        _safe_unparse(sub.func.value) == recv:
                    return True
        return False

    def _blocking_under_lock(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            lock_items = [i.context_expr for i in node.items
                          if _is_lockish(i.context_expr)]
            if not lock_items:
                continue
            lock_texts = {_safe_unparse(i) for i in lock_items}
            for stmt in node.body:
                yield from self._scan_locked(mod, stmt, lock_texts)

    def _scan_locked(self, mod: Module, stmt: ast.AST,
                     lock_texts: set[str]):
        # lexical body only: nested function/class bodies run later,
        # not under this lock — prune them from the walk entirely
        out: list[Finding] = []

        def visit(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Call):
                label = self._blocking_label(n, lock_texts)
                if label:
                    out.append(Finding(
                        mod.rel, n.lineno, self.id,
                        f"blocking call {label} inside a `with "
                        f"{'/'.join(sorted(lock_texts))}` body — move "
                        f"it out of the locked section"))
            for c in ast.iter_child_nodes(n):
                visit(c)

        visit(stmt)
        return out

    @staticmethod
    def _blocking_label(call: ast.Call,
                        lock_texts: set[str]) -> str | None:
        func = call.func
        dotted = _safe_unparse(func)
        if dotted in _BLOCKING_QUALIFIED or \
                any(dotted.endswith("." + q.split(".", 1)[1]) and
                    dotted.split(".")[-2:] == q.split(".")
                    for q in _BLOCKING_QUALIFIED):
            return dotted
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        recv_txt = _safe_unparse(recv)
        if attr in _BLOCKING_ATTRS:
            return f"{recv_txt}.{attr}"
        if attr == "send" and not isinstance(recv, ast.Constant):
            seg = _last_segment(recv).lower()
            if any(s in seg for s in ("sock", "conn", "client",
                                      "chan", "pipe", "wire")):
                return f"{recv_txt}.send"
        if attr == "join":
            # Thread.join, never str.join: thread-ish receiver only
            if _THREADISH_RE.search(_last_segment(recv)):
                return f"{recv_txt}.join"
        if attr == "result":
            seg = _last_segment(recv).lower()
            if "fut" in seg or "future" in seg:
                return f"{recv_txt}.result"
        if attr == "wait":
            # cond.wait() RELEASES the lock it rides — only flag
            # waiting on something that is NOT the held lock
            # (Event.wait under a mutex stalls every other waiter)
            if recv_txt in lock_texts or \
                    _COND_SEG_RE.search(_last_segment(recv)):
                return None
            return f"{recv_txt}.wait"
        return None


# -- thread discipline -------------------------------------------------------


class ThreadDisciplineRule(Rule):
    id = "thread-discipline"
    description = ("every threading.Thread must pass an explicit "
                   "daemon= and a name=\"mt-...\" so leak/soak "
                   "thread-hygiene accounting can attribute it")

    def check_module(self, mod: Module):
        thread_names = self._thread_ctor_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_thread_ctor(node.func, thread_names):
                continue
            kwargs = {k.arg for k in node.keywords}
            if None in kwargs:           # **kw: can't see inside
                continue
            if "target" not in kwargs and not node.args:
                continue                 # a Thread subclass super().__init__?
            if "daemon" not in kwargs:
                yield Finding(mod.rel, node.lineno, self.id,
                              "threading.Thread without an explicit "
                              "daemon= flag")
            name_kw = next((k for k in node.keywords
                            if k.arg == "name"), None)
            if name_kw is None:
                yield Finding(mod.rel, node.lineno, self.id,
                              "anonymous threading.Thread — pass "
                              "name=\"mt-<subsystem>-...\"")
            else:
                prefix = self._static_prefix(name_kw.value)
                if prefix is not None and not prefix.startswith("mt-"):
                    yield Finding(mod.rel, node.lineno, self.id,
                                  f"thread name {prefix!r}... must "
                                  f"start with \"mt-\"")

    @staticmethod
    def _thread_ctor_names(mod: Module) -> set[str]:
        """Local bindings of threading.Thread (``from threading
        import Thread [as X]``)."""
        names = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "threading":
                for a in node.names:
                    if a.name == "Thread":
                        names.add(a.asname or a.name)
        return names

    @staticmethod
    def _is_thread_ctor(func: ast.AST, local_names: set[str]) -> bool:
        if isinstance(func, ast.Attribute) and func.attr == "Thread":
            seg = _last_segment(func.value)
            return seg == "threading" or seg.endswith("threading") or \
                seg.lstrip("_") == "threading"
        if isinstance(func, ast.Name):
            return func.id in local_names
        return False

    @staticmethod
    def _static_prefix(value: ast.AST) -> str | None:
        """Literal prefix of a name expression, when determinable."""
        if isinstance(value, ast.Constant) and \
                isinstance(value.value, str):
            return value.value
        if isinstance(value, ast.JoinedStr) and value.values and \
                isinstance(value.values[0], ast.Constant) and \
                isinstance(value.values[0].value, str):
            return value.values[0].value
        if isinstance(value, ast.BinOp) and \
                isinstance(value.op, ast.Add) and \
                isinstance(value.left, ast.Constant) and \
                isinstance(value.left.value, str):
            return value.left.value
        return None                      # dynamic: accepted


# -- swallowed exceptions ----------------------------------------------------


class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    description = ("``except ...: pass`` with no log, counter, or "
                   "written reason hides real failures")

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                continue                 # bare-except owns that case
            if not self._broad(node.type):
                continue                 # a NARROW typed catch with
                # pass is the close-path/parse-fallback idiom; only
                # catch-alls hide unknown failures
            if not all(isinstance(s, ast.Pass) for s in node.body):
                continue                 # logs/counts/re-raises: fine
            if self._has_reason(mod, node):
                continue
            yield Finding(
                mod.rel, node.lineno, self.id,
                "swallowed exception (`except ...: pass` with no "
                "log/counter) — handle it, count it, or suppress "
                "with a reason")

    @staticmethod
    def _broad(t: ast.AST) -> bool:
        names = []
        if isinstance(t, ast.Tuple):
            names = [_last_segment(e) for e in t.elts]
        else:
            names = [_last_segment(t)]
        return any(n in ("Exception", "BaseException") for n in names)

    @staticmethod
    def _has_reason(mod: Module, node: ast.ExceptHandler) -> bool:
        """The repo's long-standing idiom — ``# noqa: BLE001 — why``
        on the except/pass line — stays honored when reason text
        follows; the mt-lint grammar is handled by the runner."""
        lines = {node.lineno}
        for s in node.body:
            lines.add(s.lineno)
        for ln in lines:
            text = mod.line_text(ln)
            m = re.search(r"#\s*noqa[:\s]*([A-Z0-9]*)\s*(.*)", text)
            if m and m.group(2).strip("—- ").strip():
                return True
        return False


# -- kvconfig drift ----------------------------------------------------------


class KvconfigDriftRule(Rule):
    id = "kvconfig-drift"
    description = ("every registered kvconfig knob must appear as "
                   "``subsys.key`` in a docs/ table and its subsystem "
                   "must be reachable from a reload/load config path "
                   "(construction-time subsystems carry a suppression "
                   "with the reason)")

    _RELOADISH_RE = re.compile(r"(?:^|_)reload|^load$|^_load")

    def check_tree(self, mods: list[Module], repo: str):
        import os
        kv = next((m for m in mods
                   if m.rel.endswith("utils/kvconfig.py")), None)
        if kv is None:
            return
        docs_text = ""
        docs_dir = os.path.join(repo, "docs")
        if os.path.isdir(docs_dir):
            for f in sorted(os.listdir(docs_dir)):
                if f.endswith(".md"):
                    with open(os.path.join(docs_dir, f),
                              encoding="utf-8") as fh:
                        docs_text += fh.read()
        reachable = self._reload_constants(mods)
        for lineno, subsys, keys in self._registrations(kv):
            for key in keys:
                token = f"{subsys}.{key}"
                if token not in docs_text:
                    yield Finding(
                        kv.rel, lineno, self.id,
                        f"knob {token} is not documented in any "
                        f"docs/*.md table (docs/config.md)")
            if not self._reachable(subsys, reachable):
                yield Finding(
                    kv.rel, lineno, self.id,
                    f"subsystem '{subsys}' is not read from any "
                    f"reload_*_config/load path — admin SetConfigKV "
                    f"changes would never land; wire a reload or "
                    f"suppress with the construction-time reason")

    @staticmethod
    def _registrations(kv: Module):
        """(lineno, subsys, [keys]) per ``register_subsys`` call with
        a literal name + defaults dict."""
        for node in ast.walk(kv.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "register_subsys"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)):
                continue
            subsys = node.args[0].value
            keys = []
            if len(node.args) > 1 and isinstance(node.args[1],
                                                 ast.Dict):
                for k in node.args[1].keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        keys.append(k.value)
            yield node.lineno, subsys, keys

    @classmethod
    def _reload_constants(cls, mods: list[Module]) -> set[str]:
        """String constants (incl. f-string fragments) inside every
        function whose name looks like a config (re)load path — plus
        one call hop (``_reload_egress_locked`` builds broker targets
        through ``target_from_config``, which owns the ``notify_*``
        subsystem strings)."""
        defs: dict[str, list] = {}
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(node)
        roots = [n for name, nodes in defs.items()
                 if cls._RELOADISH_RE.search(name) for n in nodes]
        hop = set()
        for fn in roots:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = _last_segment(sub.func)
                    if callee in defs:
                        hop.add(callee)
        consts: set[str] = set()
        for fn in roots + [n for name in hop for n in defs[name]]:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str):
                    consts.add(sub.value)
        return consts

    @staticmethod
    def _reachable(subsys: str, consts: set[str]) -> bool:
        if subsys in consts:
            return True
        # f-string prefixes ("notify_" + kind) count as reaching the
        # whole family
        return any(c and c.endswith("_") and subsys.startswith(c)
                   for c in consts)


# -- obs docs drift ----------------------------------------------------------


class ObsDocsDriftRule(Rule):
    id = "obs-docs-drift"
    description = ("every X-ray stage name emitted in code "
                   "(``_stages.stage/add/add_async`` call sites + the "
                   "``STAGE_NAMES`` catalog), every watchdog rule "
                   "name (the ``RULE_NAMES`` catalog), and every "
                   "``mt_{s3_stage,forensic,flight,quorum,drive_op,"
                   "trace_tree,alert,history,bucket,tenant,metering,"
                   "commit_group}"
                   "_*`` metric family "
                   "literal must appear in docs/observability.md — an "
                   "operator reading the stage/rule/family catalog "
                   "must be able to trust it is complete")

    _FAMILY_RE = re.compile(
        r"^mt_(?:s3_stage|forensic|flight|quorum|drive_op|trace_tree"
        r"|alert|history|bucket|tenant|metering|commit_group)_\w+$")

    def check_tree(self, mods: list[Module], repo: str):
        import os
        doc_path = os.path.join(repo, "docs", "observability.md")
        try:
            with open(doc_path, encoding="utf-8") as fh:
                doc = fh.read()
        except OSError:
            doc = ""
        for mod in mods:
            for lineno, kind, token in self._tokens(mod):
                # anchored on the catalog's own rendering (a backticked
                # token): plain substring membership would be vacuously
                # satisfied by prose ('auth' inside 'authorization')
                if f"`{token}" not in doc:
                    yield Finding(
                        mod.rel, lineno, self.id,
                        f"{kind} {token!r} is emitted here but absent "
                        f"from docs/observability.md (stage/metrics "
                        f"catalog; list it as a backticked `{token}` "
                        f"entry)")

    @classmethod
    def _tokens(cls, mod: Module):
        """(lineno, kind, token) for stage names at ``_stages.stage/
        add/add_async`` call sites, entries of the ``STAGE_NAMES`` /
        ``RULE_NAMES`` catalogs, and matching metric family literals
        (bare strings, the constant head of an f-string sample line,
        and ``# TYPE`` declarations)."""
        catalogs = {"STAGE_NAMES": "stage name",
                    "RULE_NAMES": "watchdog rule"}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("stage", "add", "add_async") and \
                    _last_segment(node.func.value).lstrip("_") \
                    == "stages" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                yield node.lineno, "stage name", node.args[0].value
            elif isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id in catalogs
                    for t in node.targets) and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                kind = next(catalogs[t.id] for t in node.targets
                            if isinstance(t, ast.Name)
                            and t.id in catalogs)
                for el in node.value.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        yield el.lineno, kind, el.value
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    not mod.rel.startswith("minio_tpu/analysis/"):
                s = node.value
                if s.startswith("# TYPE "):
                    # the family a scrape declares IS emitted — the
                    # declaration line pins it even when the sample
                    # line's name lives in an f-string head
                    parts = s.split()
                    s = parts[2] if len(parts) >= 3 else ""
                else:
                    s = s.split(" ", 1)[0].split("{", 1)[0]
                if cls._FAMILY_RE.match(s):
                    yield node.lineno, "metric family", s


# -- label cardinality -------------------------------------------------------

# request-derived label keys: their value space is controlled by
# CLIENTS (bucket names, object keys, access keys), so a family
# carrying one has unbounded cardinality unless something bounds it
_REQUEST_LABELS = frozenset(
    {"bucket", "key", "object", "access_key", "tenant", "prefix"})
# the bounded emitters: the metering registry caps its tables at
# top-K sketch membership + an ``_other`` overflow row, and the
# renderer only echoes those bounded tables (incl. the crawler's
# per-bucket usage gauges — buckets are operator-created, not
# request-minted, and the bucket table itself is capped upstream)
_LABEL_CARDINALITY_EXEMPT = (
    "minio_tpu/obs/metering.py",
    "minio_tpu/admin/metrics.py",
)
_LABEL_IN_SAMPLE_RE = re.compile(
    r"[{,](?:" + "|".join(sorted(_REQUEST_LABELS)) + r')="')


class LabelCardinalityRule(Rule):
    id = "label-cardinality"
    description = ("an ``mt_*`` metric emission carrying a request-"
                   "derived label (bucket/key/object/access_key/"
                   "tenant/prefix) outside the bounded metering "
                   "registry grows one series per distinct client "
                   "value — unbounded scrape memory; route it through "
                   "obs/metering.py (top-K sketch gating + ``_other`` "
                   "overflow) instead")

    def check_module(self, mod: Module):
        if mod.rel in _LABEL_CARDINALITY_EXEMPT:
            return
        for node in ast.walk(mod.tree):
            # shape A: counter-registry calls —
            # ``_metrics.inc("mt_x_total", {"bucket": b})``
            if isinstance(node, ast.Call):
                fam = next(
                    (a.value for a in node.args
                     if isinstance(a, ast.Constant)
                     and isinstance(a.value, str)
                     and a.value.startswith("mt_")), None)
                if fam is None:
                    continue
                dicts = [a for a in node.args
                         if isinstance(a, ast.Dict)] + \
                        [k.value for k in node.keywords
                         if isinstance(k.value, ast.Dict)]
                for d in dicts:
                    hot = sorted(
                        k.value for k in d.keys
                        if isinstance(k, ast.Constant)
                        and k.value in _REQUEST_LABELS)
                    if hot:
                        yield Finding(
                            mod.rel, node.lineno, self.id,
                            f"family {fam} labelled by request-"
                            f"derived {'/'.join(hot)} — unbounded "
                            f"cardinality; go through the metering "
                            f"registry (obs/metering.py)")
            # shape B: hand-rendered sample lines —
            # ``f'mt_x_total{{bucket="{b}"}} 1'`` (the constant head
            # of an f-string carries both the family and the label)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.startswith("mt_") and \
                    _LABEL_IN_SAMPLE_RE.search(node.value):
                yield Finding(
                    mod.rel, node.lineno, self.id,
                    f"hand-rendered sample line for "
                    f"{node.value.split('{', 1)[0]} carries a "
                    f"request-derived label — unbounded cardinality; "
                    f"go through the metering registry "
                    f"(obs/metering.py)")


# -- tls discipline ----------------------------------------------------------


class TlsDisciplineRule(Rule):
    id = "tls-discipline"
    description = ("TLS verification must never be weakened in the "
                   "production tree: ``ssl._create_unverified_context``, "
                   "``check_hostname = False`` assignments, and "
                   "``ssl.CERT_NONE`` are flagged (the runner walks "
                   "``minio_tpu`` only, so tests/ stays free to build "
                   "negative fixtures; the suppression grammar is "
                   "honored)")

    def check_module(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                if node.attr == "_create_unverified_context":
                    yield Finding(
                        mod.rel, node.lineno, self.id,
                        "ssl._create_unverified_context disables "
                        "certificate verification — build a CA-pinned "
                        "context (secure/certs.py) instead")
                elif node.attr == "CERT_NONE":
                    yield Finding(
                        mod.rel, node.lineno, self.id,
                        "ssl.CERT_NONE disables peer verification — "
                        "pin the deployment CA instead")
            elif isinstance(node, ast.Assign):
                if not (isinstance(node.value, ast.Constant)
                        and node.value.value is False):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "check_hostname":
                        yield Finding(
                            mod.rel, node.lineno, self.id,
                            "check_hostname = False defeats hostname "
                            "verification — mint certs with the right "
                            "SANs (secure/pki.py does) instead")


# -- named skip --------------------------------------------------------------


class NamedSkipRule(Rule):
    id = "named-skip"
    description = ("every pytest.skip()/pytest.mark.skipif() in "
                   "tests/ must carry a non-empty reason — a path "
                   "that degrades (no device, no compiler, no .so) "
                   "must NAME why, or a silently-skipped tier reads "
                   "as coverage it does not have")

    def check_tree(self, mods: list[Module], repo: str):
        """tests/ is outside the runner's ``minio_tpu`` walk, so this
        rule parses it directly (the kvconfig-drift/docs discipline):
        the degradation contract lives in the tests."""
        import os
        tdir = os.path.join(repo, "tests")
        if not os.path.isdir(tdir):
            return
        for fname in sorted(os.listdir(tdir)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(tdir, fname)
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue            # the parse rule owns broken files
            lines = src.splitlines()
            rel = f"tests/{fname}"
            for node in ast.walk(tree):
                # bare @pytest.mark.skip decorators (no call, so no
                # reason is even possible) are the purest silent skip
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Attribute) and \
                                _safe_unparse(dec).endswith(
                                    "mark.skip") and \
                                not self._suppressed(lines,
                                                     dec.lineno):
                            yield Finding(
                                rel, dec.lineno, self.id,
                                "@pytest.mark.skip without a reason "
                                "— name why this path degrades")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if self._suppressed(lines, node.lineno):
                    continue
                name = _safe_unparse(node.func)
                if name.endswith("pytest.skip") or name == "skip" \
                        or name.endswith("mark.skip"):
                    if not self._has_reason(node, positional=True):
                        yield Finding(
                            rel, node.lineno, self.id,
                            "pytest.skip() without a reason — name "
                            "why this path degrades")
                elif name.endswith(".skipif"):
                    if not self._has_reason(node, positional=False):
                        yield Finding(
                            rel, node.lineno, self.id,
                            "skipif without reason= — name why this "
                            "path degrades")

    @staticmethod
    def _suppressed(lines: list[str], lineno: int) -> bool:
        """tests/ sits outside the runner's suppression pass, so the
        grammar is honored here: a reasoned ``# mt-lint:
        ok(named-skip) why`` on the flagged line."""
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        return bool(re.search(
            r"#\s*mt-lint:\s*ok\([^)]*named-skip[^)]*\)\s*\S", line))

    @staticmethod
    def _has_reason(node: ast.Call, positional: bool) -> bool:
        """True when a non-empty reason is present: a non-constant
        expression counts (it evaluates to the reason at runtime, e.g.
        ``md5_device.unavailable_reason()``); only a MISSING or
        empty-literal reason is a finding."""
        cands = []
        if positional and node.args:
            cands.append(node.args[0])
        for kw in node.keywords:
            if kw.arg == "reason":
                cands.append(kw.value)
        for c in cands:
            if isinstance(c, ast.Constant):
                if isinstance(c.value, str) and c.value.strip():
                    return True
            else:
                return True
        return False


class PoolRoutingRule(Rule):
    id = "pool-routing"
    description = ("``<x>.pools[<literal int>]`` outside "
                   "objectlayer/pools.py hardwires a pool position — "
                   "elastic topology (pool add/decommission) shifts "
                   "indexes, so route through the pools layer "
                   "(get_pool_idx/_find_pool) instead")

    _EXEMPT = "minio_tpu/objectlayer/pools.py"

    def check_module(self, mod: Module):
        if mod.rel == self._EXEMPT:
            # the pools layer OWNS placement: pool 0 is its documented
            # system-volume anchor, every other index flows through it
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Subscript):
                continue
            if _last_segment(node.value) != "pools":
                continue
            idx = node.slice
            if isinstance(idx, ast.UnaryOp) and \
                    isinstance(idx.op, ast.USub):
                idx = idx.operand
            if not (isinstance(idx, ast.Constant)
                    and isinstance(idx.value, int)):
                continue             # computed indexes came FROM the router
            yield Finding(
                mod.rel, node.lineno, self.id,
                f"direct pool indexing ({_safe_unparse(node)}) — "
                "pool positions shift on add/decommission; go through "
                "the pools layer's router instead")


# -- span discipline ---------------------------------------------------------

_POOLISH_RE = re.compile(
    r"(?:^|_)(pool|pools|executor|exec|tpe|workers)\d*$", re.I)
_SPAWN_METHODS = {"submit", "map", "apply_async"}


class SpanDisciplineRule(Rule):
    id = "span-discipline"
    description = ("a function in minio_tpu/{storage,parallel,"
                   "objectlayer} that captures the request contextvar "
                   "(get_request_id) AND hands work to another thread "
                   "(threading.Thread / pool .submit/.map/.apply_async) "
                   "must also propagate the span parent "
                   "(get_span_parent / push_span_parent — the "
                   "_with_request_id shape), or the child's spans "
                   "detach from the causal tree")

    _SCOPE = ("minio_tpu/storage/", "minio_tpu/parallel/",
              "minio_tpu/objectlayer/")

    def check_module(self, mod: Module):
        if not mod.rel.startswith(self._SCOPE):
            return
        thread_names = ThreadDisciplineRule._thread_ctor_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            has_rid = has_parent = False
            spawn_line = spawn_label = None
            # lexical scan incl. nested closures: the capture usually
            # lives in an inner runner while the submit is in the
            # outer fan-out — either way, one function owns both and
            # must carry the parent alongside the request id
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = sub.func.attr \
                    if isinstance(sub.func, ast.Attribute) \
                    else (sub.func.id
                          if isinstance(sub.func, ast.Name) else "")
                if name == "get_request_id":
                    has_rid = True
                elif name in ("get_span_parent", "push_span_parent"):
                    has_parent = True
                if spawn_line is None:
                    label = self._spawn_label(sub, thread_names)
                    if label:
                        spawn_line, spawn_label = sub.lineno, label
            if has_rid and spawn_line is not None and not has_parent:
                yield Finding(
                    mod.rel, spawn_line, self.id,
                    f"{node.name} captures get_request_id() and "
                    f"spawns work ({spawn_label}) without "
                    f"propagating the span parent — carry "
                    f"get_span_parent() into the child (the "
                    f"_with_request_id shape) or its spans detach "
                    f"from the causal tree")

    @staticmethod
    def _spawn_label(call: ast.Call,
                     thread_names: set[str]) -> str | None:
        if ThreadDisciplineRule._is_thread_ctor(call.func,
                                                thread_names):
            return "threading.Thread"
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _SPAWN_METHODS:
            if call.func.attr == "apply_async" or \
                    _POOLISH_RE.search(
                        _last_segment(call.func.value)):
                return f"{_safe_unparse(call.func)}"
        return None


ALL_RULES = [
    BareExceptRule,
    MutableDefaultRule,
    UnusedImportRule,
    WholeBodyReadRule,
    LockDisciplineRule,
    ThreadDisciplineRule,
    SwallowedExceptionRule,
    KvconfigDriftRule,
    ObsDocsDriftRule,
    LabelCardinalityRule,
    TlsDisciplineRule,
    NamedSkipRule,
    PoolRoutingRule,
    SpanDisciplineRule,
]
