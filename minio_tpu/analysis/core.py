"""Pluggable AST lint framework (ruleguard.rules.go / staticcheck.conf
role, grown from tests/test_static_analysis.py's ad-hoc checks).

Every rule is a class with an ``id``, a one-line ``description``, and
a visit pass producing file:line :class:`Finding`s — either per module
(:meth:`Rule.check_module`) or once over the whole tree
(:meth:`Rule.check_tree`, for cross-file contracts like kvconfig/docs
drift).  The runner (:func:`run_tree`) parses each file once, shares
the AST across rules, applies inline suppressions, and returns the
sorted findings; ``python -m minio_tpu.analysis`` and the tier-1 test
are both thin shells over it.

Suppression grammar (docs/static-analysis.md):

    some_flagged_line()   # mt-lint: ok(<rule-id>) <reason>

The reason is MANDATORY — a suppression without one is itself a
finding (rule ``suppression``), as is one naming a rule id the runner
does not know.  Two legacy markers predating the framework stay
honored where they already applied: ``# noqa`` on an import line
(side-effect/registry imports, rule ``unused-import``) and
``# whole-body-ok`` (rule ``whole-body-read``); both also require
trailing reason text.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field


@dataclass(frozen=True, order=True)
class Finding:
    path: str                  # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line} [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "message": self.message}


# one suppression per line; ids comma-separated: mt-lint: ok(a, b) why
_SUPP_RE = re.compile(r"#\s*mt-lint:\s*ok\(([\w\-, ]*)\)\s*(.*)$")


@dataclass
class Suppression:
    rules: set[str]
    reason: str
    line: int


@dataclass
class Module:
    """One parsed source file, shared by every rule."""
    path: str                  # absolute
    rel: str                   # repo-relative
    src: str
    lines: list[str] = field(default_factory=list)
    tree: ast.AST | None = None
    suppressions: dict[int, Suppression] = field(default_factory=dict)
    parents: dict[int, ast.AST] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parent_of(self, node: ast.AST) -> ast.AST | None:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))


class Rule:
    """Base checker: subclass, set ``id``/``description``, implement
    one of the two passes."""

    id: str = ""
    description: str = ""

    def check_module(self, mod: Module):
        return ()

    def check_tree(self, mods: list[Module], repo: str):
        return ()


def _parse_suppressions(mod: Module) -> None:
    for i, text in enumerate(mod.lines, start=1):
        m = _SUPP_RE.search(text)
        if m is None:
            continue
        ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
        mod.suppressions[i] = Suppression(ids, m.group(2).strip(), i)


def load_module(path: str, repo: str) -> Module:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, repo).replace(os.sep, "/")
    mod = Module(path=path, rel=rel, src=src, lines=src.splitlines())
    _parse_suppressions(mod)
    mod.tree = ast.parse(src, filename=path)   # SyntaxError -> runner
    for parent in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(parent):
            mod.parents[id(child)] = parent
    return mod


def iter_py_files(root: str):
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def default_repo_root() -> str:
    # minio_tpu/analysis/core.py -> repo root two levels above the pkg
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_tree(repo: str | None = None, rules=None,
             subdir: str = "minio_tpu") -> list[Finding]:
    """Parse every ``.py`` under ``repo/subdir`` once, run every rule,
    apply suppressions, and return sorted findings.  A file that fails
    to parse yields a ``parse`` finding and is skipped by the other
    rules (its AST does not exist)."""
    from .rules import ALL_RULES
    repo = repo or default_repo_root()
    if rules is None:
        rules = [cls() for cls in ALL_RULES]
    # suppressions are audited against the FULL catalog — a --rule
    # subset run must not report other rules' markers as unknown
    known_ids = {cls.id for cls in ALL_RULES} | \
        {r.id for r in rules} | {"parse", "suppression"}
    findings: list[Finding] = []
    mods: list[Module] = []
    for path in iter_py_files(os.path.join(repo, subdir)):
        rel = os.path.relpath(path, repo).replace(os.sep, "/")
        try:
            mods.append(load_module(path, repo))
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 0, "parse",
                                    f"does not parse: {e.msg}"))
    raw: list[Finding] = list(findings)
    for rule in rules:
        for mod in mods:
            raw.extend(rule.check_module(mod))
        raw.extend(rule.check_tree(mods, repo))
    by_rel = {m.rel: m for m in mods}
    out: list[Finding] = []
    for f in raw:
        mod = by_rel.get(f.path)
        supp = mod.suppressions.get(f.line) if mod else None
        if supp is not None and f.rule in supp.rules:
            continue                    # suppressed (reason audited below)
        out.append(f)
    # the suppression grammar is itself linted: every mt-lint marker
    # must carry a reason and name only known rule ids
    for mod in mods:
        for supp in mod.suppressions.values():
            if not supp.reason:
                out.append(Finding(
                    mod.rel, supp.line, "suppression",
                    "suppression without a reason — say why"))
            unknown = sorted(supp.rules - known_ids)
            if unknown or not supp.rules:
                what = ", ".join(unknown) if unknown else "<empty>"
                out.append(Finding(
                    mod.rel, supp.line, "suppression",
                    f"suppression names unknown rule(s): {what}"))
    return sorted(set(out))
