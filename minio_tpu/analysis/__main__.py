"""``python -m minio_tpu.analysis`` — the CI lint gate.

Prints one ``path:line [rule] message`` per finding (or a machine-
readable report with ``--json``) and exits non-zero when anything is
flagged, so a pipeline can gate merges on it exactly like the
reference gates on staticcheck.
"""

import argparse
import json
import sys

from .core import default_repo_root, run_tree
from .rules import ALL_RULES


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m minio_tpu.analysis",
        description="AST lint over the minio_tpu tree "
                    "(docs/static-analysis.md)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only these rule ids (repeatable)")
    args = p.parse_args(argv)
    rules = [cls() for cls in ALL_RULES]
    if args.rule:
        rules = [r for r in rules if r.id in set(args.rule)]
        unknown = set(args.rule) - {r.id for r in rules}
        if unknown:
            p.error(f"unknown rule id(s): {sorted(unknown)}")
    root = args.root or default_repo_root()
    findings = run_tree(repo=root, rules=rules)
    if args.json:
        json.dump({"root": root,
                   "rules": sorted(r.id for r in rules),
                   "count": len(findings),
                   "findings": [f.as_dict() for f in findings]},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in findings:
            print(f)
        print(f"{len(findings)} finding(s) over "
              f"{len(rules)} rule(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
