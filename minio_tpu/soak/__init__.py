"""Soak plane — the continuous-verification tier (ROADMAP item 5).

PRs 1–6 built the instruments: FaultyProxy network faults, NaughtyDisk/
SlowDisk drive faults, HealthDisk offline→probe→readmit, the MRF heal
queue, last-minute latency stats, and egress dead-letter accounting.
This package is the missing proof layer that *drives* a multi-node
cluster like production and *asserts* it stays inside an SLO while
faults land:

  * :mod:`.workload` — seeded, deterministic closed-loop workers
    producing the production mixes (GET-heavy small objects, multipart
    uploads, listing-heavy, Select queries, versioned overwrite/delete
    churn) with per-op latency/error recording;
  * :mod:`.chaos` — the proxied multi-node harness (``SoakCluster``)
    plus a declarative fault timeline conductor (at t=X inject Y, heal
    at t=Z) over the existing primitives — reproducible from a seed,
    no wall-clock coin flips;
  * :mod:`.slo` — SLO budgets, last-minute p50/p99 assertions, the
    heal-convergence helper (``assert_converged``), and thread-leak
    accounting;
  * :mod:`.report` — scenario runner + the ``BENCH_*``-shaped
    ``SOAK_r*.json`` scenario-matrix report (``bench.py soak``).
"""

from .chaos import ChaosConductor, Event, SoakCluster  # noqa: F401 — public API
from .report import (Scenario, SoakStatus, run_matrix,  # noqa: F401 — public API
                     run_scenario)
from .slo import (Budget, assert_converged,  # noqa: F401 — public API
                  settled_thread_count)
from .workload import MIXES, Mix, WorkloadGenerator  # noqa: F401 — public API
