"""Chaos conductor — a declarative fault timeline over the existing
primitives, scheduled against a proxied multi-node cluster.

``SoakCluster`` assembles N in-process nodes (cluster.py Node) with one
:class:`~minio_tpu.parallel.faulty.FaultyProxy` in front of EVERY
node's RPC endpoint, so each internode link is independently
partitionable / 503-burstable, and serves S3 from node0 with the MRF
queue + background healer attached — the full production wiring the
soak workload drives.

``ChaosConductor`` replays a timeline of :class:`Event`\\ s (at t=X
inject Y, heal at t=Z) over the cluster:

  * ``drive_kill`` / ``drive_return`` — HealthDisk offline→probe→
    readmit: the drive's inner StorageAPI is swapped for a BadDisk and
    back, so every call fails deterministically and the return path
    rides the identity-verified probe + heal-on-return sweep;
  * ``drive_slow`` / ``drive_fast`` — SlowDisk latency injection that
    the slow-drive detector (storage/health.py) actually sees;
  * ``partition`` / ``blackhole`` / ``burst_503`` / ``heal_link`` —
    FaultyProxy default-fault flips plus a live-connection sever, so
    the fault applies to established flows too.

Every event fires at a programmed offset from conductor start — no
wall-clock coin flips; a scenario replays byte-for-byte from its seed.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from ..background.heal import BackgroundHealer, MRFQueue
from ..cluster import Node, NodeSpec
from ..parallel.faulty import Fault, FaultyProxy
from ..s3.server import S3Server
from ..storage.faulty import BadDisk, SlowDisk
from ..storage.health import HealthDisk


class SoakCluster:
    """N nodes x d drives, one erasure set, internode links proxied.

    With ``pools=True`` node0's layer is wrapped in an
    :class:`~minio_tpu.objectlayer.pools.ErasureServerPools` and a
    :class:`~minio_tpu.background.rebalance.Rebalancer` rides the
    background plane — the elastic-topology wiring ``pool_add`` /
    ``pool_decommission`` chaos events drive mid-storm."""

    def __init__(self, base_dir: str, *, nodes: int = 3,
                 drives_per_node: int = 2, parity: int = 2,
                 secret: str = "soak-secret", access_key: str = "soakkey",
                 secret_key: str = "soaksecret", block_size: int = 64 * 1024,
                 backend: str = "numpy", mrf_maxsize: int = 10_000,
                 tls=None, pools: bool = False):
        self.specs: list[NodeSpec] = []
        self.nodes: list[Node] = []
        self.proxies: list[FaultyProxy] = []
        self.s3: S3Server | None = None
        self.tls = tls
        self.rebalancer = None
        self._extra_pools: list = []
        self._base_dir = base_dir
        self._parity = parity
        self._block_size = block_size
        self._backend = backend
        self._saved: dict[int, object] = {}
        for n in range(nodes):
            dirs = []
            for d in range(drives_per_node):
                p = os.path.join(base_dir, f"n{n}d{d}")
                os.makedirs(p, exist_ok=True)
                dirs.append(p)
            self.specs.append(NodeSpec(node_id=f"node{n}",
                                       drive_dirs=dirs))
        sdc = nodes * drives_per_node
        try:
            # phase 1: boot every node's RPC plane on its real port
            # (with ``tls`` — a secure.certs.CertManager — BOTH planes
            # come up encrypted: internode mTLS here, the S3 front
            # below; the FaultyProxy layer is a dumb TCP relay, so
            # chaos faults land mid-handshake and mid-encrypted-frame
            # exactly as they would on a real wire)
            for s in self.specs:
                self.nodes.append(Node(s, self.specs, secret, sdc,
                                       parity=parity,
                                       block_size=block_size,
                                       backend=backend, tls=tls))
            # phase 2: interpose one FaultyProxy per node and advertise
            # the PROXY endpoint, so every cross-node client (storage +
            # locks) dials through the injectable link
            scheme = "https" if tls is not None else "http"
            for spec in self.specs:
                port = int(spec.endpoint.rsplit(":", 1)[1])
                proxy = FaultyProxy("127.0.0.1", port).start()
                spec.endpoint = f"{scheme}://127.0.0.1:{proxy.port}"
                self.proxies.append(proxy)
            # phase 3: assemble each node's layer over the proxied
            # topology
            for node in self.nodes:
                node.assemble()
            layer0 = self.nodes[0].layer
            if pools:
                from ..objectlayer.pools import ErasureServerPools
                layer0 = ErasureServerPools([layer0], secret=secret)
            self.layer = layer0
            # S3 frontend on node0 with the heal planes attached (the
            # wiring run_node gives the leader)
            self.s3 = S3Server(layer0, access_key=access_key,
                               secret_key=secret_key, tls=tls)
            self.mrf = MRFQueue(layer0, maxsize=mrf_maxsize)
            for s in self.nodes[0].layer.sets:
                s.mrf = self.mrf
            self.s3.mrf = self.mrf
            self.healer = BackgroundHealer(layer0,
                                           interval_s=24 * 3600.0)
            self.s3.healer = self.healer
            if pools:
                from ..background.rebalance import Rebalancer
                self.rebalancer = Rebalancer(layer0, interval_s=0.25,
                                             threshold=0.05)
                self.s3.rebalancer = self.rebalancer
                self.s3.attach_background(self.mrf, self.healer,
                                          self.rebalancer)
            else:
                self.s3.attach_background(self.mrf, self.healer)
            self.s3.start()
        except Exception:
            # a half-built cluster must not leak accept loops / server
            # threads into the process (the thread-hygiene SLO every
            # later scenario in this process asserts against)
            self._teardown()
            raise
        # node0's local drives, as their HealthDisk wrappers in POOL
        # ZERO of the layer (indexes stay stable across pool_add) —
        # chaos swaps .inner under them
        self.local_disks: list[HealthDisk] = [
            d for s in self.nodes[0].layer.sets for d in s.disks
            if isinstance(d, HealthDisk) and d.inner.is_local()]

    @property
    def endpoint(self) -> str:
        return self.s3.endpoint

    # -- drive faults (HealthDisk offline/return, SlowDisk) ----------------

    def drive_kill(self, idx: int) -> None:
        """Deterministic drive death: every call fails, the breaker
        marks it offline, writes queue MRF entries."""
        hd = self.local_disks[idx]
        if idx not in self._saved:
            self._saved[idx] = hd.inner
        hd.inner = BadDisk(self._saved[idx])
        hd._mark_offline()

    def drive_return(self, idx: int) -> None:
        """The drive comes back with whatever it missed; the probe
        re-admits it and heal-on-return sweeps its set."""
        hd = self.local_disks[idx]
        saved = self._saved.pop(idx, None)
        if saved is not None:
            hd.inner = saved
        hd.probe()

    def drive_slow(self, idx: int, delay_s: float = 0.05) -> None:
        hd = self.local_disks[idx]
        if idx not in self._saved:
            self._saved[idx] = hd.inner
        hd.inner = SlowDisk(self._saved[idx], delay_s=delay_s)

    def drive_fast(self, idx: int) -> None:
        hd = self.local_disks[idx]
        saved = self._saved.pop(idx, None)
        if saved is not None:
            hd.inner = saved

    # -- link faults (FaultyProxy per node) --------------------------------

    def partition(self, node: int, fault: Fault | None = None) -> None:
        """Cut the node's internode link: new connections get the
        fault (default: immediate RST), established ones are severed."""
        p = self.proxies[node]
        p.set_default(fault or Fault.reset(after_bytes=0))
        p.sever()

    def blackhole(self, node: int) -> None:
        self.partition(node, Fault.blackhole())

    def burst_503(self, node: int) -> None:
        self.partition(node, Fault.http_503())

    def heal_link(self, node: int) -> None:
        self.proxies[node].set_default(Fault.passthrough())

    # -- elastic topology (pools mode) -------------------------------------

    def pool_add(self, drives: int = 4) -> int:
        """Elastic expansion mid-storm: attach a fresh single-set pool
        (same parity/backend geometry) under whatever chaos is live,
        and kick the rebalancer so spreading starts immediately."""
        n = len(self.layer.pools)
        dirs = []
        for d in range(drives):
            p = os.path.join(self._base_dir, f"pool{n}d{d}")
            os.makedirs(p, exist_ok=True)
            dirs.append(p)
        idx = self.layer.attach_pool(dirs, 1, drives,
                                     parity=self._parity,
                                     block_size=self._block_size,
                                     backend=self._backend)
        pool = self.layer.pools[idx]
        self._extra_pools.append(pool)
        for s in pool.sets:
            s.mrf = self.mrf
        if self.rebalancer is not None:
            self.rebalancer.kick()
        return idx

    def pool_decommission(self, pool: int = 1) -> None:
        """Mark a pool draining mid-storm; the rebalancer empties it
        and retires it from the manifest once verified empty."""
        self.layer.start_decommission(pool)
        if self.rebalancer is not None:
            self.rebalancer.kick()

    # -- lifecycle ----------------------------------------------------------

    def restore_all(self) -> None:
        """Undo every live fault (scenario teardown must converge from
        a healthy substrate)."""
        for idx in list(self._saved):
            hd = self.local_disks[idx]
            hd.inner = self._saved.pop(idx)
            hd.probe()
        for i in range(len(self.proxies)):
            self.heal_link(i)

    def stop(self) -> None:
        self.restore_all()
        self._teardown()

    def _teardown(self) -> None:
        """Best-effort stop of every started component (shared by
        normal stop and mid-constructor failure cleanup)."""
        from ..storage.writers import close_write_planes
        if self.s3 is not None:
            try:
                self.s3.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        layers = [node.layer for node in self.nodes]
        # pools attached mid-run (pool_add) belong to no node — their
        # planes die here too, even if a decommission already retired
        # them from the live topology
        layers.extend(self._extra_pools)
        for node in self.nodes:
            try:
                node.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        for lay in layers:
            # the scenario OWNS its layers: their fan-out pools and
            # writer planes die with the cluster (a long soak process
            # must not accumulate one executor per scenario)
            if lay is None:
                continue
            try:
                close_write_planes(lay)
            except Exception:  # noqa: BLE001 — teardown continues past
                pass           # a plane wedged by injected faults
            for s in getattr(lay, "sets", []):
                pool = getattr(s, "_pool", None)
                if pool is not None:
                    pool.shutdown(wait=False)
        for p in self.proxies:
            try:
                p.stop()
            except Exception:  # noqa: BLE001 — teardown continues past
                pass           # a proxy that already died


@dataclass(frozen=True)
class Event:
    """One timeline entry: at ``at_s`` seconds from conductor start,
    apply ``action`` (a SoakCluster method name) to ``node``/``drive``."""
    at_s: float
    action: str              # drive_kill|drive_return|drive_slow|
    #                          drive_fast|partition|blackhole|
    #                          burst_503|heal_link|pool_add|
    #                          pool_decommission
    node: int = 1
    drive: int = 0
    delay_s: float = 0.05
    pool: int = 1            # pool index for pool_decommission

    def apply(self, cluster: SoakCluster) -> None:
        if self.action in ("drive_kill", "drive_return", "drive_fast"):
            getattr(cluster, self.action)(self.drive)
        elif self.action == "drive_slow":
            cluster.drive_slow(self.drive, self.delay_s)
        elif self.action in ("partition", "blackhole", "burst_503",
                             "heal_link"):
            getattr(cluster, self.action)(self.node)
        elif self.action == "pool_add":
            cluster.pool_add()
        elif self.action == "pool_decommission":
            cluster.pool_decommission(self.pool)
        else:
            raise ValueError(f"unknown chaos action {self.action!r}")


@dataclass
class ChaosConductor:
    """Replays a sorted fault timeline against a SoakCluster."""

    cluster: SoakCluster
    timeline: list[Event]
    applied: list[dict] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)

    def __post_init__(self):
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ChaosConductor":
        def run():
            t0 = time.monotonic()
            for ev in sorted(self.timeline, key=lambda e: e.at_s):
                wait = ev.at_s - (time.monotonic() - t0)
                if wait > 0 and self._stop.wait(wait):
                    return
                try:
                    ev.apply(self.cluster)
                    self.applied.append({
                        "at_s": round(time.monotonic() - t0, 3),
                        "action": ev.action, "node": ev.node,
                        "drive": ev.drive})
                except Exception as e:  # noqa: BLE001 — a failed
                    # injection must surface in the report, not kill
                    # the conductor mid-timeline
                    self.errors.append(f"{ev.action}@{ev.at_s}: "
                                       f"{type(e).__name__}: {e}")
        self._thread = threading.Thread(target=run, name="mt-soak-chaos",
                                        daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: float = 60.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(timeout=5.0)
